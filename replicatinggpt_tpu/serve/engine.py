"""Continuous-batching inference engine.

One pre-compiled multi-slot decode step, driven by a host-side
scheduler — the serving shape both the compiler-first O(1)-caching and
the pjit/TPU-scaling playbooks converge on (PAPERS.md): the device
program never changes at steady state, and all request-level dynamism
(arrivals, lengths, completions, cancellations) lives in cheap host
bookkeeping plus small per-step input arrays.

Per step the engine:

1. expires deadlines (queued and active),
2. admits queued prompts into free pool slots, gated on free PAGES as
   well as free slots (serve/pages.py: the KV cache is a paged pool +
   per-slot page tables with radix prefix reuse) — admission claims the
   longest cached prefix and chunked prefill
   (``models.gpt.prefill_chunk_paged``) writes only the UNCACHED tail's
   K/V through the slot's page table, under ONE compiled program
   regardless of prompt length or prefix-hit length,
3. runs ONE jitted decode dispatch over ALL slots — per-slot page
   tables, positions, active mask, RNG streams and sampling params
   (``sample.generate.sample_tokens_batched``). With
   ``EngineConfig.decode_window > 1`` the dispatch is a WINDOW of k
   decode steps rolled into one program
   (``models.gpt.decode_window_paged``: a lax.scan over the step body
   with per-slot budget/EOS masks computed ON DEVICE, so a slot
   finishing mid-window idles inside it instead of forcing an early
   exit), the step state ``(tok, pos, active, budget, rngs)`` lives on
   the device and is DONATED from window to window alongside the
   cache, and the host runs AHEAD of the device: window N+1 is
   dispatched before window N's token block is fetched (one async
   ``copy_to_host_async`` + ``np.asarray`` per window, not one
   blocking snapshot per token — the BENCH_r03 dispatch-tax fix).
   The window cadence is CONTINUOUS (ROADMAP item 4): an admission
   lands at a window boundary as host bookkeeping while window N-1 is
   still in flight, and the prompt's uncached tail prefills INSIDE
   window N as a Sarathi-style mixed prefill+decode program
   (``models.gpt.mixed_window_paged`` — new slots write prompt chunks
   while resident slots decode, one per-slot phase mask, no separate
   prefill dispatches); deadline expiry and cancels land as per-
   dispatch lifecycle masks (``_merge_lifecycle`` — the slot goes
   inactive on device, its pages free at the boundary, and a
   cancelled slot emits no tokens after the mask lands); the window
   size can AUTO-TUNE from the live host-vs-device dispatch split
   (bounded additive increase over construction-warmed buckets,
   ``decode_window_auto``). Only a speculative mode flip still drains
   the window (counted in ``window_breaks_*``). With a drafter
   attached (serve/speculative.py) the decode phase is instead ONE
   jitted ``_engine_verify``: score a static (k+1)-token drafted
   window per slot against the pooled cache and commit 1..k+1
   accepted tokens — up to k+1 tokens per slot per full-model
   forward, interleaved with chunked prefill admissions exactly like
   plain decode (and with continuous windows while speculation is
   degraded).

Zero recompiles at steady state: the decode/verify programs are keyed
only on the (static) model config, pool/page shapes, draft width and
the engine's sharding plan, the prefill program only on the chunk
shape, the COW page copy on the pool shape alone; page tables,
positions and every other request-level input are traced fixed-shape
arrays, so admissions, prefix hits, LRU evictions and copy-on-write
splits all happen without a recompile. All are module-level jits whose
cache sizes the tests assert stay flat across a long replay
(tests/test_serve.py, tests/test_speculative.py, tests/test_pages.py).

Sharded serving (``EngineConfig.mesh_data``/``mesh_model``, the
``--mesh-shape`` knob): the SAME engine runs GSPMD-partitioned over a
(data, model) mesh — params take the decode TP layout, the paged pool
shards its physical page axis over 'data' and its model dim over
'model' (parallel.mesh.page_pool_pspec, designed first per ROADMAP),
and every program above carries the engine's static
``ServeShardings`` bundle so the pool layout survives each traced body
(donation needs matching shardings to alias) while the step state and
the per-window token block stay replicated — the host fetch contract
(one ``np.asarray`` per window, reading a local shard) is unchanged.
Request-level architecture, host bookkeeping and the paged Pallas
fallback routing (ops/paged_pallas.paged_kernel_mesh_ok) are all
mesh-agnostic; greedy streams are token-identical across mesh shapes
(tests/test_serve_mesh.py).

Observability: per-request TTFT / decode tok/s / queue wait, engine
counters (admissions, rejections, completions, tokens), slot-occupancy
and queue-depth gauges, batch-fill-ratio and step-latency histograms —
through ``utils.logging.Metrics`` and ``utils.profiling.StepTimer``,
with ``annotate()`` spans around the prefill and decode phases.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass, field
from functools import partial
from typing import Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..config import ModelConfig
from ..faults.inject import fire as fault_fire
from ..faults.watchdog import (LoadShedder, ResilienceConfig, SpecHealth,
                               StepWatchdog)
from ..models.gpt import (decode_window_paged, mixed_window_paged,
                          prefill_chunk_paged, verify_step_paged)
from ..sample.generate import sample_tokens_batched
from ..utils.logging import Metrics
from ..utils.profiling import StepTimer, annotate
from ..utils.sanitize import CompileGuard, check_in_bounds, sanitize_enabled
from ..utils.telemetry import ENGINE_TRACK, NULL, SLOT_TRACK_BASE
from .pages import PagedCachePool
from .requests import (FINISH_CANCELLED, FINISH_DEADLINE, FINISH_EOS,
                       FINISH_LENGTH_CAP, FINISH_MAX_TOKENS,
                       FINISH_PREFILLED, FINISH_SHED, REJECT_BAD_REQUEST,
                       Request, RequestResult)
from .scheduler import Scheduler
from .speculative import (DraftContext, Drafter, spec_accept_and_sample,
                          timed_draft)

#: k-autotune policy (EngineConfig.decode_window_auto): consult the
#: host-vs-device dispatch split every this-many windows, and climb one
#: bucket while the host tax still exceeds this fraction of window wall
#: time. Small interval on purpose — the policy is bounded (one bucket
#: per decision, capped at decode_window) so eagerness cannot overshoot.
WINDOW_AUTOTUNE_INTERVAL = 8
WINDOW_AUTOTUNE_HOST_FRAC = 0.05

log = logging.getLogger(__name__)


@dataclass(frozen=True)
class EngineConfig:
    """Engine sizing. ``prefill_chunk=0`` auto-sizes to
    min(64, block_size): small enough that short prompts don't pay a
    huge padded chunk, large enough that long prompts take few chunk
    dispatches — and ONE compiled prefill program either way."""

    pool_size: int = 8
    max_queue: int = 64
    prefill_chunk: int = 0
    # --- paged KV cache (serve/pages.py) --------------------------------
    page_size: int = 0        # tokens per KV page; 0 = min(16, block_size)
    max_pages: int = 0        # logical pages per slot; 0 = ceil(block/page)
    n_pages: int = 0          # physical pool pages; 0 = pool_size*max_pages
                              # (the contiguous pool's HBM exactly); fewer
                              # pages shrinks HBM and admission gates on it
    prefix_cache: bool = True  # radix prefix reuse (False: pages only)
    paged_kernel: bool = False  # opt-in Pallas paged decode fast path
                                # (TPU, packed cache layout only):
                                # prefers the fused all-layers kernel
                                # (ops/decode_pallas.py), falls back to
                                # the per-layer one (ops/paged_pallas)
    decode_window: int = 1      # decode steps rolled into one dispatch
                                # at steady state (the --decode-window
                                # knob): 1 = the blocked step-per-
                                # dispatch loop; >1 enables the async
                                # double-buffered window path. The
                                # continuous-window engine keeps
                                # windows engaged through admissions
                                # (mixed prefill+decode dispatch),
                                # deadlines and cancels (on-device
                                # lifecycle masks); only speculative
                                # verify/re-probe still breaks windows
    decode_window_auto: bool = False
                                # auto-tune the window size from the
                                # live dispatch split (host-us vs
                                # device-us per window): bounded
                                # additive increase over the bucketed
                                # sizes window_buckets(), with
                                # decode_window as the cap. Every
                                # bucket's programs are compiled at
                                # engine construction, so tuning moves
                                # between ALREADY-WARM programs and can
                                # never recompile mid-traffic
    # --- serving mesh (parallel/mesh.py, the --mesh-shape knob) ---------
    mesh_data: int = 1          # 'data' axis: the paged pool's physical
                                # page axis shards across it — each chip
                                # stores n_pages/data pages, so the same
                                # per-chip HBM holds data× more
                                # aggregate pages (capacity multiplier)
    mesh_model: int = 1         # 'model' axis: Megatron TP over the
                                # decode/prefill/verify programs
                                # (attention+MLP FLOPs multiplier);
                                # params shard by the training TP specs,
                                # replicated over 'data'
    # --- quantization (replicatinggpt_tpu/quant/, the --kv-quant /
    # --weight-quant knobs) ----------------------------------------------
    kv_quant: str = "none"      # paged KV page storage: none|int8|fp8.
                                # int8/fp8 pages + per-row scale
                                # metadata halve bytes/page — at fixed
                                # HBM that doubles n_pages, the
                                # admission currency (size the pool
                                # with pages.n_pages_for_hbm)
    weight_quant: str = "none"  # block matmul kernels: none|int8|fp8,
                                # absmax-per-output-channel scales with
                                # dequant fused into the matmuls
                                # (quant/weights.py; params quantize at
                                # engine construction unless already
                                # carrying scales from a serialized
                                # calibration)
    quant_granularity: str = "page"
                                # KV scale granularity: 'page' = one
                                # f32 scale per written row, 'head' =
                                # one per (row, head) — tighter for
                                # outlier heads at H x the metadata
                                # (both granularities dequant inside
                                # the paged kernels)
    act_quant: str = "none"     # W8A8: 'int8' quantizes activation
                                # rows into the int8 weight matmuls
                                # (requires weight_quant='int8';
                                # models.gpt._wmm runs the contraction
                                # int8 x int8 -> int32, dequanted by
                                # the separable row x channel scales)

    @property
    def mesh_shape(self) -> tuple:
        return (self.mesh_data, self.mesh_model)

    def quant(self):
        """The QuantConfig this engine runs under (validated)."""
        from ..quant import QuantConfig
        q = QuantConfig(kv_dtype=self.kv_quant,
                        weight_dtype=self.weight_quant,
                        granularity=self.quant_granularity,
                        act_dtype=self.act_quant)
        q.validate()
        return q

    def chunk(self, block_size: int) -> int:
        """Effective prefill chunk — see ``cache_pool.prefill_chunk_size``
        for the divisor-rounding rule and why it is load-bearing."""
        from .cache_pool import prefill_chunk_size
        return prefill_chunk_size(self.prefill_chunk, block_size)

    def window_buckets(self) -> tuple:
        """The static window sizes this engine may dispatch, smallest
        first. Fixed small set by design: every bucket is a separate
        compiled program (the window width is static), all of them
        warmed at engine construction, so the k-autotuner's additive
        increase walks between warm programs and ``decode_window_auto``
        can never cost a mid-traffic compile. Non-auto engines own
        exactly one window program (their configured k)."""
        W = max(int(self.decode_window), 1)
        if W <= 1:
            return (1,)
        if not self.decode_window_auto:
            return (W,)
        out, b = [], 2
        while b < W:
            out.append(b)
            b *= 2
        out.append(W)
        return tuple(out)

    def warmup_tokens(self) -> int:
        """Tokens a warmup request must generate so that the
        request-driven warmup EXERCISES the steady-state window path on
        top of the admission boundary's mixed dispatch (the window
        programs themselves are compiled at engine construction —
        ``Engine._warm_windows`` — so this is a drive-through, not the
        compile). ONE definition, shared by the replay warmup and the
        worker's readiness warmup."""
        return 1 if self.decode_window <= 1 else 2 * self.decode_window + 2


@dataclass(frozen=True)
class KernelRoute:
    """The per-engine kernel-route decision, computed ONCE at
    construction (``decide_kernel_route``) and exported verbatim —
    ``metrics_summary()['kernel_route']``, the
    ``kernel_route_pallas`` Prometheus gauge and the serve bench
    artifact all read this object, so "no XLA fallback" is observable,
    not asserted.

    ``route`` is the headline: "pallas" iff EVERY hot step of this
    engine (decode windows, mixed prefill+decode windows, speculative
    verify) runs the unified Pallas kernel family; "xla" otherwise,
    with ``reasons`` naming each failed envelope check (the shared
    ``ops.paged_pallas.paged_attention_envelope`` vocabulary plus the
    engine-level gates below). ``decode`` distinguishes which decode
    kernel won: "fused" (all layers, one launch per step) vs "pallas"
    (per-layer windowed kernel) vs "xla"."""

    route: str                    # "pallas" | "xla"
    decode: str                   # "fused" | "pallas" | "xla"
    window: str                   # mixed/verify windowed steps
    sharded: bool                 # kernels run under shard_map
    mesh: tuple                   # (data, model)
    kv_quant: str
    weight_quant: str
    granularity: str
    act_quant: str
    reasons: tuple                # every failed gate ("" when pallas)

    def summary(self) -> dict:
        """The pinned ``metrics_summary()['kernel_route']`` schema."""
        return {
            "route": self.route,
            "decode": self.decode,
            "window": self.window,
            "sharded": self.sharded,
            "mesh": list(self.mesh),
            "kv_quant": self.kv_quant,
            "weight_quant": self.weight_quant,
            "granularity": self.granularity,
            "act_quant": self.act_quant,
            "reasons": list(self.reasons),
        }


def decide_kernel_route(cfg: ModelConfig, ecfg: EngineConfig, qcfg,
                        page_size: int, n_pages: int, itemsize: int,
                        n_slots: int, mesh) -> KernelRoute:
    """Route every engine step family onto the unified Pallas kernel
    family, once, statically. The ONLY gates left are real envelope
    limits (shape/VMEM/backend) and the explicit ``paged_kernel`` knob
    — mixed windows, fp8/head-granularity pools, weight-quantized
    params and >1 (data, model) meshes all route Pallas now (ISSUE 20;
    the shard_map wrapper covers sharded engines when the pool
    geometry divides, ``paged_kernel_mesh_ok``). The fused all-layers
    kernel keeps its extra gates (packed weights streamed in-kernel:
    1x1 mesh only, unquantized weights, VMEM weight budget) and wins
    over the per-layer kernel when both fit."""
    from ..ops import decode_pallas, paged_pallas
    reasons = []
    if not ecfg.paged_kernel:
        reasons.append("paged_kernel_off")
    if cfg.decode_cache_layout != "packed":
        reasons.append("cache_layout")
    if not paged_pallas._paged_attn_backend_ok():
        reasons.append("backend")
    ok_env, env_reasons = paged_pallas.paged_attention_envelope(
        cfg.n_head, cfg.head_dim, page_size, itemsize=itemsize,
        mesh=mesh, kv_quant=qcfg.kv_dtype, granularity=qcfg.granularity,
        n_pages=n_pages)
    reasons.extend(env_reasons)
    base_ok = not reasons
    use_fused = bool(
        base_ok and not qcfg.weight_enabled
        and decode_pallas.fused_paged_decode_supported(
            cfg, n_slots, page_size, itemsize, mesh=mesh,
            kv_quant=qcfg.kv_dtype, granularity=qcfg.granularity))
    use_window = bool(base_ok and paged_pallas.mixed_step_kernel_ok(
        cfg.n_head, cfg.head_dim, page_size, itemsize, mesh=mesh,
        kv_quant=qcfg.kv_dtype, granularity=qcfg.granularity,
        n_pages=n_pages))
    decode = ("fused" if use_fused
              else "pallas" if base_ok else "xla")
    window = "pallas" if use_window else "xla"
    route = "pallas" if (decode != "xla" and window != "xla") else "xla"
    return KernelRoute(
        route=route, decode=decode, window=window,
        sharded=bool(mesh is not None and mesh.size > 1
                     and decode != "xla"),
        mesh=(ecfg.mesh_data, ecfg.mesh_model),
        kv_quant=qcfg.kv_dtype, weight_quant=qcfg.weight_dtype,
        granularity=qcfg.granularity, act_quant=qcfg.act_dtype,
        reasons=tuple(reasons))


@dataclass
class _Active:
    """Host-side record of a request occupying a slot."""

    req: Request
    t_submit: float
    t_admit: float
    cap: int                      # max new tokens this slot can produce
    capped: bool                  # cap < req.max_new_tokens (context limit)
    tokens: List[int] = field(default_factory=list)
    t_first_token: float = 0.0
    t_last_token: float = 0.0


@dataclass
class _InFlight:
    """One dispatched-but-not-yet-fetched decode window. ``toks`` and
    ``emitted`` are the dispatch's (k, n_slots) device outputs; their
    host copy starts the moment the dispatch launches
    (``copy_to_host_async``) so the drain's ``np.asarray`` overlaps
    device compute instead of stalling on it."""

    toks: jax.Array               # (k, n_slots) sampled tokens
    emitted: jax.Array            # (k, n_slots) bool live-at-step mask
    k: int                        # static window width of the dispatch
    t0_us: float                  # launch timestamp (telemetry clock)
    t_wall: float                 # launch timestamp (perf_counter)
    n_active: int                 # live slots at launch
    host_s: float = 0.0           # host dispatch tax of the launch (the
                                  # numerator of the autotuner's
                                  # host-vs-device split)
    #: (slot, request_id) pairs whose in-window prefill COMPLETES in
    #: this dispatch — their radix registration (pool.commit_admission)
    #: happens at this window's drain, once the writes are known landed;
    #: the id guards against the slot having been recycled since
    pf_done: List = field(default_factory=list)


def _merge_lifecycle(tok, pos, active, budget, life, shardings):
    """Fold the boundary's host-side lifecycle deltas into the donated
    device step state AT THE TOP of a window dispatch — the mechanism
    that keeps admissions, deadlines and cancels from ever invalidating
    the device-resident state (which would force a blocking drain and a
    re-upload, the old k=1 fallback).

    ``life`` is ONE packed (5, n_slots) int32 array — a deliberate
    single device_put per boundary (per-array transfer setup, not
    bytes, dominates small-array upload cost on the hot path):

    - row 0, kill flags: slots whose request was cancelled or passed
      its deadline since the last dispatch go inactive ON DEVICE —
      their writes drop and their emissions mask off from scan step 0,
      so a cancelled slot emits no tokens after the mask lands;
    - row 1, admission flags + rows 2-4 (token, position, budget):
      slots admitted at this boundary take their host-mirror state
      (last prompt token, decode frontier P-1, full budget) and go
      active.

    A traced input, so lifecycle traffic never retraces; a quiet
    boundary passes a cached all-zero array (no device_put at all, and
    the merge folds into the window program — no extra dispatch,
    ever)."""
    kill = life[0].astype(bool)
    adm = life[1].astype(bool)
    tok = jnp.where(adm, life[2], tok)
    pos = jnp.where(adm, life[3], pos)
    budget = jnp.where(adm, life[4], budget)
    active = (active | adm) & ~kill
    if shardings is not None:
        tok, pos, active, budget = (
            jax.lax.with_sharding_constraint(a, shardings.rep)
            for a in (tok, pos, active, budget))
    return tok, pos, active, budget


@partial(jax.jit, static_argnames=("cfg", "k", "use_pallas", "use_fused",
                                   "shardings"),
         donate_argnames=("tok", "pos", "active", "budget", "cache",
                          "rngs"))
def _engine_decode_window(params, tok, pos, active, budget, eos, life,
                          tables, cache, rngs, temp, top_k, top_p,
                          greedy, cfg: ModelConfig, k: int,
                          use_pallas: bool = False,
                          use_fused: bool = False, shardings=None):
    """The steady-state program: ``k`` multi-slot PAGED decode + batched
    sample steps in ONE dispatch (``models.gpt.decode_window_paged``),
    with the whole per-slot step state ``(tok, pos, active, budget,
    rngs)`` donated alongside the cache — at k > 1 the engine feeds each
    window the previous window's returned state without ever touching
    the host, so the old buffers alias the new in place.

    All request-level inputs are small traced arrays — the (n_slots,)
    step vectors plus the (n_slots, max_pages) page tables — so
    admissions/completions/prefix-hits/evictions/COW remaps never
    retrace, and the window width is static: a slot that exhausts its
    budget or samples its eos token mid-window goes inactive ON DEVICE
    and idles for the window's remainder (partial windows are a masked
    tail, never a second program). Inactive slots run at position 0
    with their cache writes DROPPED inside ``decode_step_paged`` (a
    released slot's stale table may reference pages another request now
    owns) and their sampled token is masked to 0.

    ``shardings`` (parallel.mesh.ServeShardings; STATIC — hashable, one
    value per engine, so sharded and unsharded engines are distinct
    programs under the same budget discipline) runs the whole window on
    the serving mesh: the page pool stays pinned to its (data, model)
    PartitionSpec through every scan step (donation needs matching in/
    out shardings to alias), the step state and the (k, n_slots) token
    block leave fully replicated — the caller's ``np.asarray`` fetch is
    a local read, never a cross-device gather.
    """
    tok, pos, active, budget = _merge_lifecycle(
        tok, pos, active, budget, life, shardings)

    def sample_fn(rngs, logits):
        splits = jax.vmap(lambda r: jax.random.split(r, 2))(rngs)
        nxt = sample_tokens_batched(splits[:, 0], logits, temp, top_k,
                                    top_p, greedy)
        return nxt, splits[:, 1]

    return decode_window_paged(params, tok, pos, active, budget, eos,
                               tables, cache, rngs, cfg,
                               sample_fn=sample_fn, length=k,
                               use_pallas=use_pallas, use_fused=use_fused,
                               shardings=shardings)


@partial(jax.jit, static_argnames=("cfg", "k", "use_kernel", "shardings"),
         donate_argnames=("tok", "pos", "active", "budget", "cache",
                          "rngs"))
def _engine_mixed_window(params, tok, pos, active, budget, eos, life,
                         pfc, pf_toks, tables, cache, rngs,
                         temp, top_k, top_p, greedy, cfg: ModelConfig,
                         k: int, use_kernel: bool = False,
                         shardings=None):
    """The mixed steady-state program: ``models.gpt.mixed_window_paged``
    behind the same lifecycle merge, donation set and sampling closure
    as ``_engine_decode_window`` — dispatched instead of the pure decode
    window whenever an admission left prompt chunks to write, so newly
    admitted slots prefill while resident slots decode and the window
    cadence never breaks. One compiled program per window bucket (the
    prefill chunk width and pool shapes are static); the per-slot phase
    mask, chunk cursors and chunk payloads are all traced inputs, so
    WHICH slots prefill and how much never retraces. ``use_kernel``
    (STATIC; the engine gates it on
    ``ops.paged_pallas.mixed_step_kernel_ok``) routes every step's
    windowed forward through the unified paged Pallas kernel —
    prefilling slots scatter chunk rows through their page tables and
    decoding slots do the verify<->decode row math in the SAME launch
    (the seam PR 12 documented, now flipped). ``pfc`` packs the three
    (n_slots,) prefill cursors — chunks-this-window / next write
    position / true prompt length — into one (3, n_slots) upload,
    like ``life``."""
    tok, pos, active, budget = _merge_lifecycle(
        tok, pos, active, budget, life, shardings)

    def sample_fn(rngs, logits):
        splits = jax.vmap(lambda r: jax.random.split(r, 2))(rngs)
        nxt = sample_tokens_batched(splits[:, 0], logits, temp, top_k,
                                    top_p, greedy)
        return nxt, splits[:, 1]

    return mixed_window_paged(params, tok, pos, active, budget, eos,
                              pfc[0], pfc[1], pfc[2], pf_toks,
                              tables, cache, rngs, cfg,
                              sample_fn=sample_fn, length=k,
                              shardings=shardings, use_kernel=use_kernel)


@partial(jax.jit, static_argnames=("cfg", "shardings"),
         donate_argnames=("cache",))
def _engine_prefill(params, chunk, offset, limit, table_row, cache,
                    cfg: ModelConfig, shardings=None):
    return prefill_chunk_paged(params, chunk, offset, limit, table_row,
                               cache, cfg, shardings=shardings)


@partial(jax.jit, static_argnames=("cfg", "use_kernel", "shardings"),
         donate_argnames=("cache", "rngs"))
def _engine_verify(params, window, pos, m, active, tables, cache, rngs,
                   temp, top_k, top_p, greedy, cfg: ModelConfig,
                   use_kernel: bool = False, shardings=None):
    """The speculative steady-state program: ONE target forward over a
    static (n_slots, k+1) window against the PAGED pool + per-position
    acceptance. Draft count k is carried by the window's static width,
    so a fixed --spec-k means exactly one extra compiled program next
    to decode/prefill. All request-level inputs — positions, valid-
    draft counts, page tables, sampling params, the drafted tokens —
    are traced fixed-shape arrays, so acceptance outcomes never
    retrace. Inactive slots run at position 0 with zero valid drafts
    and dropped writes; their outputs are masked. ``shardings`` runs
    the verify forward on the serving mesh (pool pinned per layer) with
    the acceptance outputs replicated for the host commit.
    """
    logits, cache = verify_step_paged(params, window, pos, m, active,
                                      tables, cache, cfg,
                                      shardings=shardings,
                                      use_kernel=use_kernel)
    m_eff = jnp.where(active, m, 0)
    n_acc, out, rngs = spec_accept_and_sample(rngs, logits, window, m_eff,
                                              temp, top_k, top_p, greedy)
    n_acc = jnp.where(active, n_acc, 0)
    out = jnp.where(active[:, None], out, 0)
    if shardings is not None:
        n_acc = jax.lax.with_sharding_constraint(n_acc, shardings.rep)
        out = jax.lax.with_sharding_constraint(out, shardings.rep)
        rngs = jax.lax.with_sharding_constraint(rngs, shardings.rep)
    return n_acc, out, cache, rngs


@partial(jax.jit, static_argnames=("shardings",),
         donate_argnames=("cache",))
def _engine_page_copy(cache, src, dst, shardings=None):
    """Copy-on-write page split: duplicate physical page ``src`` into
    ``dst`` across all layers of EVERY pool array — the quantized
    pool's ``ks``/``vs`` scale arrays share the page axis (axis 1), so
    a COW split carries a page's scales with its rows for free. One
    program for any (src, dst) — both traced scalars — warmed at
    engine construction so the first real COW mid-replay cannot cost a
    compile. The caller bounds dst host-side (check_in_bounds below
    no-ops on tracers). On a serving mesh the copy crosses data shards
    when src and dst land on different chips — GSPMD inserts the
    collective; each output stays pinned to its entry's spec
    (models.gpt.pool_entry_sharding) so the donated buffers alias."""
    from ..models.gpt import pool_entry_sharding
    out = {}
    for name, arr in cache.items():
        check_in_bounds(dst, 1, arr.shape[1], what="COW page copy")
        page = jax.lax.dynamic_index_in_dim(arr, src, 1, keepdims=True)
        new = jax.lax.dynamic_update_slice_in_dim(arr, page, dst, axis=1)
        if shardings is not None:
            new = jax.lax.with_sharding_constraint(
                new, pool_entry_sharding(shardings, name))
        out[name] = new
    return out


@jax.jit
def _engine_page_export(pool_entries, src):
    """Disaggregated transfer, source side (serve/disagg.py): slice
    physical page ``src`` out of every pool entry — K/V rows at the
    storage dtype AND the quantized pool's per-row scale arrays, which
    share the page axis (axis 1), so an int8/fp8 page's scales leave
    with its rows for free. One program for any page (``src`` traced),
    warmed at engine construction next to the COW copy; the caller
    batches every requested page's dispatch before its single
    ``device_get`` sync. A READ of the pool, never an update — the
    pool must survive, so nothing donates (hence ``pool_entries``,
    not the update programs' donated ``cache``)."""
    return {name: jax.lax.dynamic_index_in_dim(arr, src, 1, keepdims=True)
            for name, arr in pool_entries.items()}


@partial(jax.jit, static_argnames=("shardings",),
         donate_argnames=("cache",))
def _engine_page_install(cache, dst, blocks, shardings=None):
    """Disaggregated transfer, destination side: scatter one
    transferred page's blocks (the exact per-entry slices
    ``_engine_page_export`` produced, round-tripped through the RPC
    byte codec) into physical page ``dst`` of the local pool. Same
    shape/dtype discipline as the COW copy — ``dst`` is a traced
    scalar and the blocks are fixed-shape, so installing any page into
    any slot of the pool is ONE compiled program, warmed at engine
    construction (a transfer mid-traffic can never cost a compile).
    The table rebase the tentpole names happens host-side: installed
    pages enter the local radix (``PagedCachePool.commit_install``)
    and the next admission's claim maps logical prompt pages to these
    LOCAL physical indices through the ordinary chain walk."""
    from ..models.gpt import pool_entry_sharding
    out = {}
    for name, arr in cache.items():
        check_in_bounds(dst, 1, arr.shape[1], what="page install")
        new = jax.lax.dynamic_update_slice_in_dim(arr, blocks[name], dst,
                                                  axis=1)
        if shardings is not None:
            new = jax.lax.with_sharding_constraint(
                new, pool_entry_sharding(shardings, name))
        out[name] = new
    return out


def engine_summary_block(engine: "Engine") -> dict:
    """The per-replica block of the fleet summary — ONE definition
    consumed by both sides of the process boundary (the in-process
    ``router.Replica.summary_block`` and the worker's ``summary`` RPC),
    so the multiproc bench artifact can never silently diverge in
    shape from the in-process one."""
    s = engine.metrics_summary()
    return {
        "occupancy_mean": round(
            s["histograms"].get("batch_fill_ratio", {})
            .get("mean", 0.0), 4),
        "n_steps": engine.n_steps,
        "pages": s["pages"],
        "finished": {k: int(v) for k, v in
                     engine.metrics.counters.items()
                     if k.startswith("finished_")},
    }


def compile_counts() -> Dict[str, int]:
    """Process-wide compiled-program counts for the engine entry points
    (module-level jits, so they accumulate across engines), including
    the speculative verify step, the COW page copy, and the model
    drafter's two programs. The replay driver's before/after
    bookkeeping reads these; the *live* steady-state enforcement is
    per-engine via :class:`CompileGuard` (utils.sanitize), which raises
    from the offending step instead of reporting after the fact."""
    from .speculative import _draft_decode_k, _draft_prefill
    return {"decode": _engine_decode_window._cache_size(),
            "mixed": _engine_mixed_window._cache_size(),
            "prefill": _engine_prefill._cache_size(),
            "verify": _engine_verify._cache_size(),
            "page_copy": _engine_page_copy._cache_size(),
            "page_export": _engine_page_export._cache_size(),
            "page_install": _engine_page_install._cache_size(),
            "draft_decode": _draft_decode_k._cache_size(),
            "draft_prefill": _draft_prefill._cache_size()}


class Engine:
    """Continuous-batching engine over a pooled KV cache.

    Host API (single-threaded by design — drive it from one loop):

    - ``submit(req)`` -> None (accepted) or a rejected ``RequestResult``
      (backpressure / validation, with the reason as finish_reason);
    - ``cancel(request_id)`` -> bool;
    - ``step()`` -> list of requests finishing this step;
    - ``drain()`` -> run steps until idle, return all finishes;
    - ``metrics_summary()`` -> counters/gauges/histograms + step-latency
      percentiles.
    """

    def __init__(self, params, cfg: ModelConfig,
                 ecfg: EngineConfig = EngineConfig(),
                 clock: Callable[[], float] = time.monotonic,
                 drafter: Optional[Drafter] = None,
                 rcfg: Optional[ResilienceConfig] = None,
                 journal=None, telemetry=None, track_base: int = 0,
                 track_label: str = ""):
        """``rcfg`` (faults.watchdog.ResilienceConfig) opts into the
        self-healing policies — stall watchdog, speculative auto-disable
        with re-probe, load shedding; None/all-zero changes nothing.
        ``journal`` (serve.journal.RequestJournal) records accepted and
        finished requests for restart recovery. ``telemetry`` (a
        utils.telemetry.Telemetry, ideally sharing this engine's
        ``clock`` so request envelopes and step spans land on one
        timeline) opts into request-lifecycle tracing: one span tree
        per request on per-slot tracks plus step/draft spans and
        prefix-hit/COW/eviction/recovery instants; None means the
        zero-cost NULL recorder and changes nothing. ``track_base``
        offsets every track id this engine emits on — the fleet router
        gives replica ``i`` base ``i * REPLICA_TRACK_STRIDE`` so N
        replicas share one recorder without colliding tracks
        (``track_label`` prefixes the human-readable track names)."""
        cfg.validate()
        self.params = params
        # quantization (replicatinggpt_tpu/quant/): weight-side params
        # quantize HERE, before any mesh placement, unless the caller
        # handed in an already-quantized tree (a serialized calibration
        # applied at the CLI layer — quant/weights.py load_calibration)
        self.qcfg = ecfg.quant()
        if self.qcfg.act_enabled and cfg.act_quant != self.qcfg.act_dtype:
            # W8A8 threads through ModelConfig (models.gpt._wmm reads
            # it) — replace() keeps the caller's cfg untouched; the
            # field is part of the fleet shape hash via asdict(cfg)
            import dataclasses as _dc
            cfg = _dc.replace(cfg, act_quant=self.qcfg.act_dtype)
        self.cfg = cfg
        self.ecfg = ecfg
        if self.qcfg.weight_enabled:
            from ..quant.weights import quantize_params
            self.params = quantize_params(self.params,
                                          self.qcfg.weight_dtype)
        self.clock = clock
        self.drafter = drafter
        self.tel = telemetry or NULL
        self._tb = track_base
        if self.tel.enabled:
            self.tel.name_track(self._tb + ENGINE_TRACK,
                                f"{track_label}engine")
            for s in range(ecfg.pool_size):
                self.tel.name_track(self._tb + SLOT_TRACK_BASE + s,
                                    f"{track_label}slot {s}")
        if drafter is not None:
            dcfg = getattr(drafter, "cfg", None)
            if dcfg is not None:       # model drafter: pools must line up
                assert dcfg.vocab_size == cfg.vocab_size, \
                    "draft model must share the target vocab"
                assert dcfg.block_size == cfg.block_size, \
                    "draft model must share the target block_size"
                assert drafter.pool_size == ecfg.pool_size, \
                    "draft pool must match the engine pool"
        # serving mesh (parallel/mesh.py): params take the decode TP
        # layout (Megatron over 'model', replicated over 'data'), the
        # page pool its (data, model) PartitionSpec — both placed ONCE
        # here; every jitted program then carries the same static
        # ServeShardings bundle, so GSPMD runs the whole engine sharded
        # without any program gaining a second compiled variant.
        # Drafter params/caches stay single-device (they are separate
        # jits over separate state — prefix reuse logic is unchanged).
        self.mesh = None
        self._plan = None
        if ecfg.mesh_data > 1 or ecfg.mesh_model > 1:
            from ..parallel.mesh import (make_serve_mesh,
                                         serve_param_shardings,
                                         serve_shardings)
            from .pages import pool_geometry
            self.mesh = make_serve_mesh(ecfg.mesh_data, ecfg.mesh_model)
            _, _, n_pages_eff = pool_geometry(
                cfg, ecfg.pool_size, ecfg.page_size, ecfg.max_pages,
                ecfg.n_pages)
            self._plan = serve_shardings(self.mesh, cfg, n_pages_eff,
                                         ecfg.mesh_data, ecfg.mesh_model)
            self.params = jax.device_put(
                self.params,
                serve_param_shardings(cfg, self.mesh, ecfg.mesh_model,
                                      params=self.params))
        self._rep = self._plan.rep if self._plan is not None else None
        self.pool = PagedCachePool(
            cfg, ecfg.pool_size, page_size=ecfg.page_size,
            max_pages=ecfg.max_pages, n_pages=ecfg.n_pages,
            prefix_cache=ecfg.prefix_cache, telemetry=self.tel,
            sharding=(self._plan.cache if self._plan is not None
                      else None),
            scale_sharding=(self._plan.scale if self._plan is not None
                            else None),
            mesh_shape=(ecfg.mesh_data, ecfg.mesh_model),
            quant=(self.qcfg if self.qcfg.kv_enabled else None))
        self.scheduler = Scheduler(ecfg.max_queue, cfg.block_size,
                                   clock=clock)
        self.metrics = Metrics()
        self.step_timer = StepTimer()
        P = ecfg.pool_size
        self._chunk = ecfg.chunk(cfg.block_size)
        self._window = max(int(ecfg.decode_window), 1)
        # bucketed window sizes + the autotune cursor: _window_cur is
        # the size the next steady-state dispatch uses; the additive-
        # increase policy (_maybe_autotune) only ever moves it UP the
        # bucket list, and every bucket's programs compile at
        # construction (_warm_windows), so a bucket move is free
        self._buckets = ecfg.window_buckets()
        self._wk = 0
        self._window_cur = self._buckets[0]
        self._at_host = 0.0           # autotune accumulators: host
        self._at_wall = 0.0           # dispatch tax vs window wall time
        self._at_n = 0                # over windows since last decision
        # Kernel route: decided ONCE, statically, for every step family
        # (decode windows, mixed prefill+decode windows, speculative
        # verify) — decide_kernel_route() above; the decision is logged,
        # exported through metrics_summary()["kernel_route"], and
        # mirrored as the kernel_route_pallas Prometheus gauge. The
        # FUSED all-layers kernel is preferred for pure decode; the
        # per-layer windowed kernel (and its shard_map wrapper on a >1
        # mesh) carries everything else.
        itemsize = jnp.dtype(self.pool.cache["k"].dtype).itemsize
        self.kernel_route = decide_kernel_route(
            cfg, ecfg, self.qcfg, self.pool.page_size,
            self.pool.cache["k"].shape[1], itemsize, P, self.mesh)
        self._use_fused = self.kernel_route.decode == "fused"
        self._use_pallas = self.kernel_route.decode == "pallas"
        self._use_window_kernel = self.kernel_route.window == "pallas"
        self.metrics.gauge("kernel_route_pallas",
                           1.0 if self.kernel_route.route == "pallas"
                           else 0.0)
        log.info("kernel route: %s (decode=%s window=%s sharded=%s%s)",
                 self.kernel_route.route, self.kernel_route.decode,
                 self.kernel_route.window, self.kernel_route.sharded,
                 (" reasons=" + ",".join(self.kernel_route.reasons)
                  if self.kernel_route.reasons else ""))
        self._tok = np.zeros((P,), np.int32)
        # ALIAS of pool.positions (one host buffer): the pool exposes the
        # committed frontier to drafters, the engine advances it in place
        self._pos = self.pool.positions
        self._active = np.zeros((P,), bool)
        self._budget = np.zeros((P,), np.int32)   # tokens still allowed
        self._eos = np.full((P,), -1, np.int32)   # per-slot stop token
        # lifecycle masks (continuous windows): per-slot deadline
        # precomputed at admission (vectorized expiry check, no dict
        # walk), the pending-kill map feeding the per-dispatch kill
        # flags, and the admission-merge mask — all consumed by
        # _merge_lifecycle at the top of the next window dispatch
        self._deadline = np.full((P,), np.inf)
        self._kill: Dict[str, str] = {}           # request_id -> reason
        self._adm_mask = np.zeros((P,), bool)
        # in-window prefill cursors (mixed steps): chunks left to write,
        # next absolute write position, true prompt length, and the
        # pending padded prompt tails — consumption is deterministic
        # (min(k, pf_left) chunks per window), so the host tracks the
        # cursor without ever fetching device state
        self._pf_left = np.zeros((P,), np.int32)
        self._pf_off = np.zeros((P,), np.int32)
        self._pf_limit = np.zeros((P,), np.int32)
        self._pf_tail: Dict[int, np.ndarray] = {}
        self._temp = np.ones((P,), np.float32)
        self._top_k = np.zeros((P,), np.int32)
        self._top_p = np.zeros((P,), np.float32)
        self._greedy = np.zeros((P,), bool)
        # launch-invariant device inputs (eos / page tables / sampling
        # params), converted ONCE per change instead of once per
        # dispatch — a window dispatch's host tax is mostly device_put
        # calls, so re-uploading arrays that only change at admission/
        # finish boundaries would tax exactly the steady state the
        # window amortizes (None = rebuild at the next launch); plus
        # shared all-zero lifecycle masks for quiet boundaries
        self._li = None
        self._z_life = jnp.zeros((5, P), jnp.int32)
        # async window machinery: the device-resident donated step state
        # (tok, pos, active, budget) between window dispatches — None
        # means "host mirrors are authoritative, re-upload at the next
        # launch" — and the in-flight dispatch whose token block has
        # not been fetched yet (double buffering: window N+1 launches
        # before window N's block is read)
        self._dev_state = None
        self._inflight: Optional[_InFlight] = None
        # committed up front for the same jit-key stability reason as
        # CachePool.cache (the array becomes a committed jit output
        # after the first step)
        from .cache_pool import commit_default
        # rng streams are (P, 2): their bootstrap commit must use the
        # rank-2 replicated REPRESENTATION (ServeShardings.rep2) — the
        # jit cache key is representational, and the window programs
        # propagate the rng state out rank-matched
        self._rngs = commit_default(
            jnp.stack([jax.random.PRNGKey(i) for i in range(P)]),
            sharding=(self._plan.rep2 if self._plan is not None
                      else None))
        self._slots: Dict[int, _Active] = {}
        self._pending: List[RequestResult] = []  # cancellations between steps
        self.n_steps = 0
        # the steady-state contract, enforced live: each entry point may
        # compile ONE program for this engine's shapes (counted relative
        # to engine construction — the module jit caches accumulate
        # across engines); a second compile raises RecompileError from
        # the step that caused it. Replaces the ad-hoc two-program
        # bookkeeping the first serving PR shipped (compile_counts()
        # remains for offline summaries).
        # a windowed engine owns one decode-window program and one mixed
        # prefill+decode program PER BUCKET (the admission path is a
        # mixed window, never a k=1 fallback — the blocked program only
        # exists on decode_window=1 engines)
        self._decode_guard = CompileGuard(
            _engine_decode_window, "serve/decode",
            max_programs=len(self._buckets))
        self._mixed_guard = CompileGuard(
            _engine_mixed_window, "serve/mixed",
            max_programs=len(self._buckets))
        self._prefill_guard = CompileGuard(_engine_prefill, "serve/prefill")
        self._verify_guard = CompileGuard(_engine_verify, "serve/verify")
        self._copy_guard = CompileGuard(_engine_page_copy, "serve/page-copy")
        self._export_guard = CompileGuard(_engine_page_export,
                                          "serve/page-export")
        self._install_guard = CompileGuard(_engine_page_install,
                                           "serve/page-install")
        # warm the COW program NOW (page 0 onto itself — a value no-op):
        # the first real copy-on-write happens mid-replay, where a
        # compile would break the pinned-flat compile_counts invariant
        self.pool.cache = self._copy_guard(self.pool.cache, jnp.int32(0),
                                           jnp.int32(0),
                                           shardings=self._plan)
        # warm the disaggregated-transfer pair the same way: export page
        # 0, round-trip its blocks through host memory (matching the
        # live path's placement — uncommitted uploads — so the warm
        # program IS the steady-state program), install them back onto
        # page 0. A value no-op; the first real transfer lands
        # mid-traffic on either tier.
        blocks = {name: np.asarray(arr) for name, arr in
                  self._export_guard(self.pool.cache,
                                     jnp.int32(0)).items()}
        self.pool.cache = self._install_guard(
            self.pool.cache, jnp.int32(0),
            {name: jnp.asarray(arr) for name, arr in blocks.items()},
            shardings=self._plan)
        if self._window > 1:
            # compile every bucketed window program up front (masked
            # no-op dispatches) — admissions, lifecycle masks and
            # autotune bucket moves then always hit a warm program
            self._warm_windows()
        self._sanitize = sanitize_enabled()
        # self-healing (faults.watchdog): all policies opt-in via rcfg.
        # Degraded transitions move between the two already-budgeted
        # steady-state programs (verify <-> decode), so CompileGuard
        # keeps enforcing zero recompiles through every mode switch.
        self.rcfg = rcfg or ResilienceConfig()
        self.journal = journal
        self._spec_active = drafter is not None
        self._watchdog = (StepWatchdog(self.rcfg, telemetry=self.tel)
                          if self.rcfg.watchdog_on else None)
        self._spec_health = (SpecHealth(self.rcfg, telemetry=self.tel)
                             if (self.rcfg.spec_guard_on
                                 and drafter is not None) else None)
        self._shedder = (LoadShedder(self.rcfg, telemetry=self.tel)
                         if self.rcfg.shed_on else None)
        self._probe_pending = False
        self._spec_pinned = False     # operator pin (set_spec_active)
        #: host-side log of resilience events (bounded — see _event),
        #: for tests/ops
        self.events: List[str] = []

    # ---------------------------------------------------------------- API

    def submit(self, req: Request) -> Optional[RequestResult]:
        self.metrics.inc("requests_submitted")
        if (self.pool.slot_of(req.id) is not None
                or self.scheduler.contains(req.id)):
            # an id must be unique among in-flight requests: results,
            # cancellation, the journal and the pool's reverse index all
            # key on it
            self.metrics.inc(REJECT_BAD_REQUEST)
            return RequestResult(id=req.id, tokens=[],
                                 finish_reason=REJECT_BAD_REQUEST)
        eos = req.eos_token_id
        if eos is not None and not (0 <= int(eos) < self.cfg.vocab_size):
            # the device-side stop mask compares sampled ids against
            # this value; an out-of-vocab eos can never match and is a
            # caller bug — reject it loudly
            self.metrics.inc(REJECT_BAD_REQUEST)
            return RequestResult(id=req.id, tokens=[],
                                 finish_reason=REJECT_BAD_REQUEST)
        reason = self.scheduler.submit(req)
        if reason is not None:
            # an expired-at-submit deadline is a terminal finish, not a
            # backpressure rejection — count it with the finishes
            self.metrics.inc("finished_" + reason
                             if reason == FINISH_DEADLINE else reason)
            return RequestResult(id=req.id, tokens=[], finish_reason=reason)
        if self.journal is not None:
            self.journal.record_submit(req)
        return None

    def cancel(self, request_id: str, migrated: bool = False) -> bool:
        """Cancel a queued or running request. The terminal
        ``RequestResult`` (with any tokens already produced) surfaces
        from the next ``step()``; True iff the request was found.

        On a windowed engine a plain cancel is a LIFECYCLE MASK, not a
        window break: the request id joins the pending-kill map, the
        kill flag rides the next window dispatch (deactivating the slot
        on device from its first scan step — the slot emits nothing
        after the mask lands), and the slot + pages release at that
        boundary, right after the in-flight window's already-committed
        tokens are fetched to ride the terminal result. A cancel racing
        a window that already finished the request surfaces the natural
        finish. On blocked (k=1) engines, and for ``migrated=True`` —
        the fleet router's re-route path, where the id must be
        releasable BEFORE the router resubmits it elsewhere — the old
        drain-now semantics hold: fetch the in-flight window, finish
        and free immediately (counted as a ``cancel`` window break).
        ``migrated=True`` closes the telemetry envelope tagged
        ``migrated`` (a non-terminal segment, see tools/trace_check.py)
        and still journals a finish so THIS replica's journal replay
        never resurrects the id."""
        now = self.clock()
        if self.scheduler.cancel(request_id):
            self.metrics.inc("finished_" + FINISH_CANCELLED)
            self._journal_finish(request_id, FINISH_CANCELLED)
            self._pending.append(RequestResult(
                id=request_id, tokens=[], finish_reason=FINISH_CANCELLED))
            return True
        slot = self.pool.slot_of(request_id)
        if slot is None:
            return False
        if self._window > 1 and not migrated:
            self._kill[request_id] = FINISH_CANCELLED
            return True
        self._pending.extend(self._drain_pending("cancel"))
        slot = self.pool.slot_of(request_id)
        if slot is None:
            # the drained window finished it naturally; its terminal
            # result is already pending
            return True
        self._pending.append(self._finish_slot(slot, FINISH_CANCELLED, now,
                                               migrated=migrated))
        return True

    def partial_tokens(self, request_id: str) -> Optional[List[int]]:
        """Tokens committed so far for an ACTIVE request (host list
        copy; None when the request holds no slot — still queued, or
        already finished). The streaming front door (serve/http.py) and
        the fleet router's delivery dedupe poll this between steps."""
        slot = self.pool.slot_of(request_id)
        if slot is None or slot not in self._slots:
            return None
        return list(self._slots[slot].tokens)

    def in_flight_ids(self) -> List[str]:
        """Every accepted-but-unfinished request id: queued first (in
        arrival order), then active slots. The router's re-route path
        reads this for a wedged replica (for a DEAD one it replays the
        journal instead — host memory died with the replica)."""
        queued = self.scheduler.ids()
        active = [self._slots[s].req.id for s in sorted(self._slots)]
        return queued + active

    def slot_track(self, slot: int) -> int:
        """Telemetry track id of a slot (``track_base``-offset) — the
        router closes a killed replica's open request envelopes on the
        right tracks."""
        return self._tb + SLOT_TRACK_BASE + slot

    # ------------------------------------------- disaggregated transfer

    def export_pages(self, pages: List[int]) -> List[Dict[str, np.ndarray]]:
        """Fetch physical pages to host memory for a cross-tier
        transfer (serve/disagg.py): one warmed jitted slice per page,
        each a dict of per-entry blocks — K/V rows plus any quantized
        scale rows, exactly what ``install_pages`` scatters on the far
        side. Every page's slice is dispatched before the single
        ``device_get`` sync fetches the whole batch. The caller pins
        the pages first (``pool.pin_prefix``) so LRU eviction cannot
        recycle one mid-copy."""
        out = []
        for p in pages:
            check_in_bounds(int(p), 1, self.pool.n_pages,
                            what="page export")
            out.append(self._export_guard(self.pool.cache, jnp.int32(p)))
        self.pool.pages_exported += len(pages)
        return jax.device_get(out)

    def install_pages(self, pages: List[int],
                      blocks: List[Dict[str, np.ndarray]]) -> None:
        """Scatter transferred page blocks into local physical pages
        (allocated + pinned by ``pool.install_prefix``) through the
        construction-warmed install program — zero recompiles, any
        traffic. Shapes/dtypes must match this pool's entries exactly;
        the engine-shape hash both tiers agreed on at registration
        guarantees that, and the assert keeps a codec bug loud."""
        cache = self.pool.cache
        for p, blk in zip(pages, blocks):
            check_in_bounds(int(p), 1, self.pool.n_pages,
                            what="page install")
            dev = {}
            for name, arr in cache.items():
                want = (arr.shape[0], 1) + tuple(arr.shape[2:])
                b = blk[name]
                assert b.shape == want and b.dtype == arr.dtype, (
                    f"page block {name!r}: got {b.shape}/{b.dtype}, "
                    f"pool wants {want}/{arr.dtype}")
                dev[name] = jnp.asarray(b)
            cache = self._install_guard(cache, jnp.int32(p), dev,
                                        shardings=self._plan)
        self.pool.cache = cache

    @property
    def idle(self) -> bool:
        return (not self._active.any() and len(self.scheduler) == 0
                and not self._pending and self._inflight is None)

    def step(self) -> List[RequestResult]:
        """One scheduling iteration: expire -> shed -> admit -> decode,
        with the self-healing policies (watchdog / speculative health /
        shedding) folded around the decode phase when configured.

        With ``decode_window > 1`` the steady-state decode phase is the
        CONTINUOUS window path: dispatch the NEXT k-step window, then
        fetch the previous one's token block — the host stays one
        window ahead of the device, and host-side request dynamism
        rides the dispatch instead of breaking it. Admissions land at
        window boundaries: page tables, COW copies and slot mirrors are
        written host-side while window N-1 is still in flight, and the
        prompt's uncached tail prefills INSIDE window N as a mixed
        prefill+decode program (``_engine_mixed_window``). Deadline
        expiry and cancels land as per-dispatch lifecycle masks
        (``_merge_lifecycle``): the slot goes inactive on device, its
        in-flight tokens ride the terminal result, and its pages free
        at the boundary. Only a speculative verify / re-probe still
        drains the window and leaves the path (counted in the
        ``window_breaks_*`` counters); queued-deadline expiry and
        overload shedding are host-only and never touch it."""
        finished: List[RequestResult] = self._pending
        self._pending = []
        now = self.clock()
        t_wall = time.perf_counter()
        t_step_us = self.tel.now_us() if self.tel.enabled else 0.0

        for req, t_submit, reason in self.scheduler.drain_expired(now):
            finished.append(self._finish_unstarted(req, t_submit, reason,
                                                   now))
        if self._shedder is not None:
            n_shed = self._shedder.observe(self.scheduler.depth,
                                           self.ecfg.max_queue)
            if n_shed:
                for req, t_submit in self.scheduler.shed(n_shed):
                    finished.append(self._finish_unstarted(
                        req, t_submit, FINISH_SHED, now))
                self.metrics.inc("shed_requests", n_shed)
                self._event(f"step {self.n_steps}: shed {n_shed} "
                                   f"queued request(s) under sustained "
                                   f"overload")

        # active-deadline expiry against the per-slot deadline mirror
        # precomputed at admission (one vectorized compare, no dict
        # walk). On the windowed path these become lifecycle-mask kills.
        expired = [int(s) for s in
                   np.flatnonzero(self._active & (self._deadline <= now))
                   if int(s) in self._slots]

        # speculative re-probe countdown while degraded (auto-disabled
        # only: an operator pin via set_spec_active(False) must stick)
        reprobe = False
        if (self.drafter is not None and not self._spec_active
                and not self._spec_pinned
                and self._spec_health is not None
                and self._active.any()):
            reprobe = self._spec_health.tick_disabled()

        use_spec = (self.drafter is not None
                    and (self._spec_active or reprobe))
        # the continuous-window steady state: everything except a
        # speculative mode flip stays on the window path — admissions
        # become mixed dispatches, deadlines/cancels become masks
        windowed = (self._window > 1 and not use_spec
                    and (bool(self._active.any()) or bool(self._kill)
                         or bool(expired) or self._head_admissible()))

        if windowed:
            for slot in expired:
                self._kill.setdefault(self._slots[slot].req.id,
                                      FINISH_DEADLINE)
        else:
            # a speculative transition (or a blocked k=1 engine): fetch
            # the in-flight window first — its tokens commit now,
            # finished slots' pages and slots free at this boundary
            finished.extend(self._drain_pending(
                "reprobe" if reprobe else "spec" if use_spec else
                "deadline" if expired else "cancel" if self._kill else
                "admit"))
            # any slot still mid-prefill (its chunks were riding the
            # mixed windows this branch just abandoned) completes
            # host-side NOW: the verify/decode paths assume every
            # admitted slot's prompt pages are fully written
            self._flush_prefill()
            # kills deferred while windows were engaged resolve here the
            # old way (host-initiated finish; the device state rebuilds
            # from mirrors at the next upload)
            for rid, reason in list(self._kill.items()):
                slot = self.pool.slot_of(rid)
                if slot is not None and slot in self._slots:
                    finished.append(self._finish_slot(slot, reason, now))
            self._kill.clear()
            for slot in expired:
                if slot in self._slots:   # may have finished in the drain
                    finished.append(self._finish_slot(
                        slot, FINISH_DEADLINE, now))
            if reprobe:
                self.set_spec_active(True)
                self._probe_pending = True
                self.metrics.inc("spec_reprobes")
                self._event(f"step {self.n_steps}: re-probing "
                                   f"speculative decoding")
            self._admit_queue(now, finished, self._admit)

        self.metrics.gauge("queue_depth", self.scheduler.depth)
        self.metrics.gauge("slots_active", int(self._active.sum()))
        self.metrics.gauge("slot_occupancy", self.pool.occupancy)
        self.metrics.gauge("pages_in_use", self.pool.alloc.pages_in_use)

        # chaos seam: an artificially slow/stuck step (no-op without an
        # installed FaultPlan) — what the watchdog must catch
        flt = fault_fire("serve/step", index=self.n_steps)
        if flt is not None and flt.kind == "delay":
            time.sleep(flt.arg)

        ran_decode = False
        if windowed:
            with annotate("serve/decode"):
                self._window_step(now, finished)
            ran_decode = True
        elif self._active.any():
            spec_now = self.drafter is not None and self._spec_active
            finished.extend(self._verify_once() if spec_now
                            else self._decode_once())
            ran_decode = True
        elif self._inflight is not None:
            # endgame: every slot finished while a window was in flight
            # — fetch it (it emits nothing) so drain() reaches idle
            finished.extend(self._drain_pending())
        if ran_decode and self._watchdog is not None:
            dur = time.perf_counter() - t_wall
            if self._watchdog.observe(dur):
                self.metrics.inc("watchdog_stalls")
                self.metrics.gauge("last_stall_s", dur)
                self._event(f"step {self.n_steps}: stall — "
                                   f"{dur * 1e3:.1f} ms step against "
                                   f"a p99-derived budget")
        if self.tel.enabled:
            self.tel.complete("engine_step", self._tb + ENGINE_TRACK,
                              t_step_us,
                              self.tel.now_us() - t_step_us,
                              step=self.n_steps,
                              queue_depth=self.scheduler.depth,
                              n_active=int(self._active.sum()),
                              n_finished=len(finished))
        return finished

    def _window_step(self, now: float, finished: List[RequestResult]
                     ) -> None:
        """One continuous-window boundary: resolve pending kills into
        this dispatch's flag array, admit the queue head(s) host-side
        (their prefill chunks ride the dispatch), launch window N,
        fetch window N-1, then finish masked-out slots — whose pages
        are safe to release while window N flies, because the kill flag
        already deactivated them on device (writes dropped, reads
        masked) before the launch."""
        k = self._window_cur
        P = self.ecfg.pool_size
        kill_arr = np.zeros((P,), bool)
        kills: List = []
        for rid, reason in self._kill.items():
            slot = self.pool.slot_of(rid)
            if slot is not None and slot in self._slots:
                kill_arr[slot] = True
                kills.append((slot, reason))
        # admissions at the boundary: host bookkeeping only (window N-1
        # is still in flight); slots freed by this boundary's kills
        # become available at the NEXT one
        self._admit_queue(now, finished, self._admit_windowed)
        adm_any = bool(self._adm_mask.any())
        live = self._active & ~kill_arr
        live_any = bool(live.any())
        if kills or adm_any:
            if live_any:
                # the masks/merge must land on device: dispatch window N
                # (kill flags + admission merge ride it), then fetch
                # N-1 so a killed slot's already-committed tokens ride
                # its terminal result
                nxt = self._launch(k, kill=kill_arr)
                finished.extend(self._drain_pending())
                for slot, reason in kills:
                    if slot in self._slots:   # may have finished in N-1
                        finished.append(self._finish_slot(
                            slot, reason, now, masked=True))
                self._inflight = nxt
            else:
                # the kills empty the engine: nothing left to dispatch,
                # so no mask ever lands — finish host-side (invalidates
                # the device state; the next upload rebuilds it)
                finished.extend(self._drain_pending())
                for slot, reason in kills:
                    if slot in self._slots:
                        finished.append(self._finish_slot(slot, reason,
                                                          now))
            self._kill.clear()
        elif live_any:
            # remaining work per slot in window steps: pending prefill
            # chunks + the decode budget. When it all fits one more
            # window, that window is the LAST (barring eos, which only
            # ends sooner): no point dispatching blind past it.
            rem = np.where(live, self._pf_left + self._budget, 0)
            last = int(rem.max()) <= k
            if self._inflight is not None and last:
                # the in-flight window already finishes everything
                finished.extend(self._drain_pending())
            elif last:
                finished.extend(self._drain_window(self._launch(k)))
            else:
                # double buffering: launch window N BEFORE fetching
                # window N-1's token block
                nxt = self._launch(k)
                finished.extend(self._drain_pending())
                self._inflight = nxt
        else:
            finished.extend(self._drain_pending())
            self._kill.clear()   # stale ids whose requests already ended

    def set_spec_active(self, active: bool) -> None:
        """Flip speculative decoding between its verify program and the
        plain decode program (both CompileGuard-budgeted — no new
        compilations at steady state). Re-enabling resyncs stateful
        drafters from host-side histories: tokens committed while
        degraded never went through the drafter's cache. A manual
        disable through this method PINS the degraded mode — the
        auto-re-probe policy leaves it alone until set_spec_active(True)
        lifts the pin (the auto-disable path flips ``_spec_active``
        directly and stays re-probeable)."""
        active = active and self.drafter is not None
        if active and not self._spec_active:
            # an in-flight decode window holds tokens the drafters'
            # resync must see — fetch it before reading histories; a
            # slot still mid-prefill completes host-side (the verify
            # path attends its whole prompt range)
            self._pending.extend(self._drain_pending("spec"))
            self._flush_prefill()
            hists = self._histories()
            for slot in self._slots:
                if self._active[slot] and hists[slot] is not None:
                    self.drafter.resync(slot, hists[slot])
        self._spec_pinned = not active and self.drafter is not None
        self._spec_active = active

    @property
    def spec_active(self) -> bool:
        return self._spec_active

    def _journal_finish(self, request_id: str, reason: str) -> None:
        if self.journal is not None:
            self.journal.record_finish(request_id, reason)

    def _event(self, msg: str) -> None:
        # a soak run with recurring degradations must not grow host
        # memory without bound (the Metrics reservoir rationale)
        self.events.append(msg)
        if len(self.events) > 256:
            del self.events[:len(self.events) - 256]

    def drain(self, max_steps: int = 1_000_000) -> List[RequestResult]:
        out: List[RequestResult] = []
        for _ in range(max_steps):
            if self.idle:
                return out
            out.extend(self.step())
        raise RuntimeError(f"engine did not drain in {max_steps} steps")

    def metrics_summary(self) -> dict:
        s = self.metrics.summary()
        s["step_latency"] = self.step_timer.summary(skip=1)
        s["n_steps"] = self.n_steps
        s["compile_counts"] = compile_counts()
        # kernel-route decision: static per engine, schema pinned in
        # tests/test_pages.py (bench serve artifacts carry it verbatim)
        s["kernel_route"] = self.kernel_route.summary()
        s["compile_guards"] = {"decode": self._decode_guard.stats(),
                               "mixed": self._mixed_guard.stats(),
                               "prefill": self._prefill_guard.stats(),
                               "verify": self._verify_guard.stats(),
                               "page_copy": self._copy_guard.stats(),
                               "page_export": self._export_guard.stats(),
                               "page_install": self._install_guard.stats()}
        # paged-pool health: bench dashboards key on this block (schema
        # pinned in tests/test_pages.py)
        s["pages"] = self.pool.stats()
        # dispatch amortization: the host tax per dispatch vs per token
        # (the serve-side analogue of the train bench's dispatch split;
        # BENCH_r03 measured 77.4 ms blocked vs 12.1 ms/step amortized)
        c = self.metrics.counters
        disp = self.metrics.hist_summary("decode_dispatch_s")
        n_disp = int(c.get("decode_dispatches", 0))
        dec_tokens = int(c.get("dispatch_tokens", 0))
        mean_ms = disp.get("mean", 0.0) * 1e3
        s["dispatch"] = {
            "window_k": self._window_cur,
            "window_k_max": self._window,
            "autotune": bool(self.ecfg.decode_window_auto),
            "autotune_increases": int(
                c.get("autotune_window_increases", 0)),
            "dispatches": n_disp,
            "mean_dispatch_ms": round(mean_ms, 4),
            "host_dispatch_ms_per_token": (
                round(mean_ms * n_disp / dec_tokens, 4)
                if dec_tokens else 0.0),
        }
        # window-break observability (continuous windows): which host
        # mutations still force the engine off the window path. Post
        # continuous-windows only the speculative reasons should move
        # on a healthy engine — admit/deadline/cancel ride the window.
        s["window_breaks"] = {
            r: int(c.get("window_breaks_" + r, 0))
            for r in ("admit", "deadline", "cancel", "spec", "reprobe")}
        c = self.metrics.counters
        s["recovery"] = {
            "watchdog_stalls": int(c.get("watchdog_stalls", 0)),
            "spec_disables": int(c.get("spec_disables", 0)),
            "spec_reprobes": int(c.get("spec_reprobes", 0)),
            "shed_requests": int(c.get("shed_requests", 0)),
            "spec_active": self._spec_active,
            "events": list(self.events[-32:]),
        }
        if self.drafter is not None:
            c = self.metrics.counters
            drafted = c.get("spec_draft_tokens", 0)
            slot_steps = c.get("slot_steps", 0)
            s["speculative"] = {
                "drafter": self.drafter.name,
                "k": self.drafter.k,
                "accept_rate": (round(c.get("spec_accepted_tokens", 0)
                                      / drafted, 4) if drafted else 0.0),
                "mean_tokens_per_step": (round(c.get("decode_tokens", 0)
                                               / slot_steps, 3)
                                         if slot_steps else 0.0),
                "draft_overhead_s":
                    self.metrics.hist_summary("draft_overhead_s"),
            }
        return s

    # ----------------------------------------------------------- internals

    def _cap(self, req: Request) -> int:
        """Decode budget for a request: decode step i runs at position
        P-1+i (the first rewrites the last prompt position), so a slot
        supports S - P + 1 new tokens before the write position would
        leave the logical buffer. A ``prefill_only`` request budgets
        exactly ONE decode token — enough to rewrite position P-1 and
        finalize the last full prompt page for registration — so the
        prefill tier reserves prompt pages only, never a decode
        budget's worth."""
        if req.prefill_only:
            return 1
        return min(req.max_new_tokens,
                   self.pool.seq_len - int(req.prompt.size) + 1)

    def _fits(self, req: Request) -> bool:
        """Admission gate beyond free slots: enough free (or LRU-
        reclaimable) pages for the request's WHOLE lifetime — prompt
        minus cached prefix plus the full decode budget, reserved
        eagerly so an admitted request can never strand mid-decode."""
        return self.pool.can_admit(req.prompt, self._cap(req))

    def _admit(self, req: Request, t_submit: float, now: float) -> None:
        P = int(req.prompt.size)
        cap = self._cap(req)
        t_admit_us = self.tel.now_us() if self.tel.enabled else 0.0
        # acquire claims the longest radix-cached prefix, reserves the
        # remaining pages, and sets pool.positions[slot] = P - 1 (which
        # self._pos aliases — the first decode rewrites the last prompt
        # index)
        adm = self.pool.acquire(req.id, req.prompt, cap)
        assert adm is not None, "scheduler admitted past pool capacity"
        slot = adm.slot
        tid = self._tb + SLOT_TRACK_BASE + slot
        if self.tel.enabled:
            # the request's span tree opens BACKDATED to its submit
            # time (viewers sort by ts, so out-of-order emission is
            # fine); the queue phase closes it out to this admission
            ts_sub = self.tel.ts_us(t_submit)
            self.tel.begin("request", tid, ts_us=ts_sub, request=req.id,
                           prompt_tokens=P, max_new_tokens=cap)
            self.tel.complete("queue", tid, ts_sub,
                              self.tel.ts_us(now) - ts_sub,
                              request=req.id)
        for src, dst in adm.cow:
            # copy-on-write split of a fully-cached prompt's frontier
            # page; program warmed at construction (budget 1)
            check_in_bounds(dst, 1, self.pool.n_pages, what="COW page")
            self.tel.instant("cow_split", tid, src=src, dst=dst,
                             request=req.id)
            self.pool.cache = self._copy_guard(self.pool.cache,
                                               jnp.int32(src),
                                               jnp.int32(dst),
                                               shardings=self._plan)
        claimed = adm.claimed
        S = self.pool.seq_len
        if claimed < P:
            chunk = self._chunk
            n_chunks = -(-(P - claimed) // chunk)
            # host-side bound for the jitted prefill (offset traced):
            # every REAL token position must sit inside the logical
            # buffer — padded tail positions are routed to scatter-drop
            # inside prefill_chunk_paged, so only [claimed, P) matters
            check_in_bounds(claimed, P - claimed, S,
                            what=f"prefill of {P}-token prompt from "
                                 f"{claimed} in {chunk}-chunks")
            padded = np.zeros((n_chunks * chunk,), np.int32)
            padded[:P - claimed] = req.prompt[claimed:]
            table_row = jnp.asarray(self.pool.tables[slot])
            cache = self.pool.cache
            with annotate("serve/prefill"):
                for c in range(n_chunks):
                    tc_us = (self.tel.now_us() if self.tel.enabled
                             else 0.0)
                    cache = self._prefill_guard(
                        self.params,
                        jnp.asarray(padded[None,
                                           c * chunk:(c + 1) * chunk]),
                        jnp.int32(claimed + c * chunk), jnp.int32(P),
                        table_row, cache, self.cfg,
                        shardings=self._plan)
                    if self.tel.enabled:
                        # host dispatch time (the device runs async);
                        # a jax.profiler capture of the same run shows
                        # the device-side cost under serve/prefill
                        self.tel.complete(
                            "prefill_chunk", tid, tc_us,
                            self.tel.now_us() - tc_us, chunk=c,
                            n_chunks=n_chunks, request=req.id)
            self.pool.cache = cache
        # registration AFTER the prefill wrote the pages: a same-step
        # neighbor may claim them the moment they hit the radix
        self.pool.commit_admission(slot)
        # host mirrors changed: the next window launch re-uploads them
        # (blocked-path admission only runs with no dispatch in flight)
        self._dev_state = None
        self._admit_finalize(req, t_submit, now, slot, cap, claimed,
                             t_admit_us)

    def _admit_windowed(self, req: Request, t_submit: float, now: float
                        ) -> None:
        """Admission at a CONTINUOUS window boundary: identical host
        bookkeeping to ``_admit`` — page acquisition, COW copies, slot
        mirrors — but the prompt's uncached tail is NOT dispatched as
        separate prefill programs: its chunks are queued on the
        in-window prefill cursors and ride the next MIXED window
        dispatch, and the slot's state enters the donated device loop
        through the admission-merge mask instead of invalidating it
        (``_merge_lifecycle``). Window N-1 stays in flight throughout:
        the COW copy and the coming prefill writes consume its output
        cache, so device dispatch order sequences them after it. Radix
        registration is DEFERRED until the window that finishes the
        prefill drains (``_InFlight.pf_done``) — registering pages a
        still-flying window is writing would let a same-boundary
        neighbor attend garbage."""
        P = int(req.prompt.size)
        cap = self._cap(req)
        t_admit_us = self.tel.now_us() if self.tel.enabled else 0.0
        adm = self.pool.acquire(req.id, req.prompt, cap,
                                defer_commit=True)
        assert adm is not None, "scheduler admitted past pool capacity"
        slot = adm.slot
        tid = self._tb + SLOT_TRACK_BASE + slot
        if self.tel.enabled:
            ts_sub = self.tel.ts_us(t_submit)
            self.tel.begin("request", tid, ts_us=ts_sub, request=req.id,
                           prompt_tokens=P, max_new_tokens=cap)
            self.tel.complete("queue", tid, ts_sub,
                              self.tel.ts_us(now) - ts_sub,
                              request=req.id)
        for src, dst in adm.cow:
            check_in_bounds(dst, 1, self.pool.n_pages, what="COW page")
            self.tel.instant("cow_split", tid, src=src, dst=dst,
                             request=req.id)
            self.pool.cache = self._copy_guard(self.pool.cache,
                                               jnp.int32(src),
                                               jnp.int32(dst),
                                               shardings=self._plan)
        claimed = adm.claimed
        S = self.pool.seq_len
        if claimed < P:
            chunk = self._chunk
            n_chunks = -(-(P - claimed) // chunk)
            # host-side bound for the traced in-window prefill writes:
            # every REAL position sits inside the logical buffer;
            # padded tail positions scatter-drop past pf_limit
            check_in_bounds(claimed, P - claimed, S,
                            what=f"windowed prefill of {P}-token prompt "
                                 f"from {claimed} in {chunk}-chunks")
            padded = np.zeros((n_chunks * chunk,), np.int32)
            padded[:P - claimed] = req.prompt[claimed:]
            self._pf_tail[slot] = padded
            self._pf_left[slot] = n_chunks
            self._pf_off[slot] = claimed
            self._pf_limit[slot] = P
        else:
            # fully-cached prompt (COW split aside): nothing to write —
            # the slot decodes from its first window step, and the
            # claim registers immediately (its pages were written and
            # registered by previous owners)
            self.pool.commit_admission(slot)
        self._adm_mask[slot] = True
        self._admit_finalize(req, t_submit, now, slot, cap, claimed,
                             t_admit_us)

    def _admit_finalize(self, req: Request, t_submit: float, now: float,
                        slot: int, cap: int, claimed: int,
                        t_admit_us: float) -> None:
        """Mirror/record/telemetry bookkeeping shared by the blocked
        and windowed admission paths — ONE definition so the two can
        never drift on a per-slot field (the deadline mirror and the
        rng reset are both parity-load-bearing)."""
        P = int(req.prompt.size)
        tid = self._tb + SLOT_TRACK_BASE + slot
        if self.drafter is not None:
            # drafters keep their own (unpaged) cache and see the full
            # prompt — prefix reuse is a target-pool concern
            self.drafter.on_admit(slot, req.prompt)
        self._tok[slot] = req.prompt[-1]
        self._active[slot] = True
        self._budget[slot] = cap
        self._eos[slot] = (-1 if req.eos_token_id is None
                           else int(req.eos_token_id))
        # deadline precomputed at admission into the vectorized expiry
        # mirror (inf = none): the step loop's check is one compare
        # (req.deadline is a host float already — no conversion)
        self._deadline[slot] = (np.inf if req.deadline is None
                                else req.deadline)
        sp = req.sampling
        self._temp[slot] = sp.temperature
        self._top_k[slot] = sp.top_k
        self._top_p[slot] = sp.top_p
        self._greedy[slot] = sp.greedy
        self._rngs = self._rngs.at[slot].set(jax.random.PRNGKey(req.rng_seed))
        self._li = None           # eos/tables/sampling mirrors changed
        self._slots[slot] = _Active(req=req, t_submit=t_submit, t_admit=now,
                                    cap=cap,
                                    capped=cap < req.max_new_tokens)
        if self.tel.enabled:
            self.tel.complete("admit", tid, t_admit_us,
                              self.tel.now_us() - t_admit_us,
                              request=req.id, cached_tokens=claimed,
                              prefill_tokens=P - claimed)
        self.metrics.inc("requests_admitted")
        self.metrics.inc("prefill_tokens", P - claimed)
        self.metrics.inc("prefix_hit_tokens", claimed)
        self.metrics.observe("queue_wait_s", now - t_submit)

    def _admit_queue(self, now: float, finished: List[RequestResult],
                     admit_fn) -> None:
        """One-at-a-time admission off the queue head — ONE definition
        of the FIFO protocol for the blocked (``_admit``) and windowed
        (``_admit_windowed``) paths: each admission changes page
        availability, so the fits check must see fresh allocator state
        per request, and a head that does not fit BLOCKS the queue
        rather than being skipped (big requests cannot starve)."""
        while self.pool.n_free > 0:
            admitted, dropped = self.scheduler.admit(1, now,
                                                     fits=self._fits)
            for req, t_submit, reason in dropped:
                finished.append(self._finish_unstarted(req, t_submit,
                                                       reason, now))
            if not admitted:
                break
            req, t_submit = admitted[0]
            admit_fn(req, t_submit, now)

    def _flush_prefill(self) -> None:
        """Complete any still-pending in-window prefill through the
        blocked prefill program — called whenever the engine LEAVES the
        windowed path with chunks outstanding (a speculative
        verify/re-probe transition, which only exists on drafter
        engines, whose warmup compiles ``_engine_prefill``): the
        verify/decode paths attend each admitted slot's full prompt
        range, so abandoning unwritten chunks would read never-written
        pages. The deferred radix registration commits here too — the
        writes are enqueued ahead of any later dispatch."""
        for slot in np.flatnonzero(self._pf_left > 0):
            slot = int(slot)
            chunk = self._chunk
            tail = self._pf_tail.pop(slot)
            n = int(self._pf_left[slot])
            off = int(self._pf_off[slot])
            limit = int(self._pf_limit[slot])
            table_row = jnp.asarray(self.pool.tables[slot])
            cache = self.pool.cache
            with annotate("serve/prefill"):
                for c in range(n):
                    cache = self._prefill_guard(
                        self.params,
                        jnp.asarray(tail[None,
                                         c * chunk:(c + 1) * chunk]),
                        jnp.int32(off + c * chunk), jnp.int32(limit),
                        table_row, cache, self.cfg,
                        shardings=self._plan)
            self.pool.cache = cache
            self._pf_left[slot] = 0
            self._pf_off[slot] = 0
            self._pf_limit[slot] = 0
            if slot in self._slots:
                self.pool.commit_admission(slot)

    def _head_admissible(self) -> bool:
        """Whether this step could admit: a free slot AND a queued,
        unexpired head that fits the page gate. While False, a backlog
        does not break decode windows — arrivals batch at window
        boundaries (the scheduler's strict FIFO is unchanged: only the
        HEAD is consulted, exactly like the admission loop)."""
        if self.pool.n_free <= 0:
            return False
        head = self.scheduler.peek()
        return head is not None and self._fits(head[0])

    def _warm_windows(self) -> None:
        """Compile every bucketed window program — the pure decode
        window AND the mixed prefill+decode window at each
        ``window_buckets()`` size — with masked no-op dispatches at
        construction: all slots inactive, all masks False, so writes
        drop, emissions mask off and the step-state values pass through
        unchanged (the donated cache/rng buffers are threaded through
        and reassigned). After this, admissions, lifecycle masks and
        k-autotune bucket moves always land on a warm program; the
        request-driven replay/worker warmups merely EXERCISE the paths.
        Per-slot rng streams are reset at admission, so the decode
        windows' unconditional in-scan splits here cannot perturb any
        request's sampled stream."""
        P = self.ecfg.pool_size
        from .cache_pool import commit_default
        zi = np.zeros((P,), np.int32)
        zb = np.zeros((P,), bool)
        state = tuple(commit_default(jnp.asarray(a), sharding=self._rep)
                      for a in (zi, zi, zb, zi))
        cache, rngs = self.pool.cache, self._rngs
        eos_d, tables_d, *sample = self._launch_inputs()
        for k in self._buckets:
            out = self._decode_guard(
                self.params, *state, eos_d, self._z_life,
                tables_d, cache, rngs, *sample,
                self.cfg, k=k, use_pallas=self._use_pallas,
                use_fused=self._use_fused, shardings=self._plan)
            _, _, t_, p_, a_, b_, cache, rngs = out
            state = (t_, p_, a_, b_)
            out = self._mixed_guard(
                self.params, *state, eos_d, self._z_life,
                jnp.zeros((3, P), jnp.int32),
                jnp.zeros((k, P, self._chunk), jnp.int32),
                tables_d, cache, rngs, *sample,
                self.cfg, k=k, use_kernel=self._use_window_kernel,
                shardings=self._plan)
            _, _, t_, p_, a_, b_, cache, rngs = out
            state = (t_, p_, a_, b_)
        self.pool.cache = cache
        self._rngs = rngs
        # mirrors stay authoritative: the warm state is discarded, the
        # first real launch re-uploads (values were untouched anyway)

    def _launch_inputs(self) -> tuple:
        """Device copies of the launch-invariant per-slot inputs (eos,
        page tables, sampling params), rebuilt only when an admission
        or finish dirtied them (``self._li = None``) — at steady state
        a window dispatch re-uses them with zero device_put calls,
        which is most of the host tax the window amortizes."""
        if self._li is None:
            self._li = (jnp.asarray(self._eos),
                        jnp.asarray(self.pool.tables),
                        jnp.asarray(self._temp),
                        jnp.asarray(self._top_k),
                        jnp.asarray(self._top_p),
                        jnp.asarray(self._greedy))
        return self._li

    def _launch(self, k: int, kill: Optional[np.ndarray] = None
                ) -> _InFlight:
        """Dispatch one ``k``-step window WITHOUT fetching its results
        — the pure decode-window program, or the MIXED prefill+decode
        program whenever any slot still has prompt chunks to write.
        The donated device step state from the previous dispatch feeds
        straight back in (``_dev_state``); boundary lifecycle traffic —
        ``kill`` flags and the admission-merge mask — rides the
        dispatch as small traced inputs (``_merge_lifecycle``) instead
        of invalidating it. Only a host-initiated finish outside the
        mask path forces a mirror re-upload. The token block's
        device->host copy starts immediately (``copy_to_host_async``),
        so by the time ``_drain_window`` reads it the transfer has been
        overlapping device compute."""
        t0_us = self.tel.now_us() if self.tel.enabled else 0.0
        t_wall = time.perf_counter()
        P = self.ecfg.pool_size
        if kill is None:
            kill = np.zeros((P,), bool)
        n_active = int((self._active & ~kill).sum())
        if self._dev_state is None:
            # host-side bound for the traced window writes: every REAL
            # write position (bounded by the per-slot budget — the
            # admission cap's pos + budget <= seq_len invariant) stays
            # inside the logical buffer
            check_in_bounds(
                np.where(self._active,
                         self._pos + np.minimum(
                             np.maximum(self._budget, 1), k) - 1, 0),
                1, self.pool.seq_len, what="decode window write")
            # committed, like every engine-owned jit input: the state
            # must enter this call exactly as it leaves the donated
            # steady-state loop (a committed output), or the jit cache
            # keys the two placements as two programs — on a mesh that
            # means replicated over every device (the constrained
            # window output's placement), not one chip
            from .cache_pool import commit_default
            state = tuple(commit_default(jnp.asarray(a),
                                         sharding=self._rep) for a in
                          (self._tok, self._pos, self._active,
                           self._budget))
        else:
            state = self._dev_state
        tok, pos, active, budget = state
        eos_d, tables_d, temp_d, top_k_d, top_p_d, greedy_d = \
            self._launch_inputs()
        # lifecycle inputs: quiet boundaries (the steady state) reuse
        # the cached all-zero pack — no device_put; a boundary with
        # kills or admissions uploads ONE (5, P) array (the admission
        # merge reads the host mirrors directly, which were written at
        # this boundary's admissions)
        adm = self._adm_mask
        if kill.any() or adm.any():
            life_np = np.zeros((5, P), np.int32)
            life_np[0] = kill
            life_np[1] = adm
            life_np[2] = self._tok
            life_np[3] = self._pos
            life_np[4] = self._budget
            life = jnp.asarray(life_np)
        else:
            life = self._z_life
        pf = np.flatnonzero((self._pf_left > 0) & ~kill)
        if pf.size:
            # mixed window: lay each still-prefilling slot's next
            # min(k, pf_left) chunks into the scan's per-step payload;
            # consumption is deterministic, so the cursors advance
            # host-side with no fetch
            chunk = self._chunk
            pf_toks = np.zeros((k, P, chunk), np.int32)
            pfc = np.zeros((3, P), np.int32)
            pfc[1] = self._pf_off
            pfc[2] = self._pf_limit
            pf_done: List = []
            for slot in pf:
                slot = int(slot)
                n = min(k, int(self._pf_left[slot]))
                pfc[0, slot] = n
                pf_toks[:n, slot, :] = \
                    self._pf_tail[slot][:n * chunk].reshape(n, chunk)
            out = self._mixed_guard(
                self.params, tok, pos, active, budget, eos_d, life,
                jnp.asarray(pfc), jnp.asarray(pf_toks),
                tables_d, self.pool.cache, self._rngs,
                temp_d, top_k_d, top_p_d, greedy_d, self.cfg, k=k,
                use_kernel=self._use_window_kernel,
                shardings=self._plan)
            for slot in pf:
                slot = int(slot)
                n = int(pfc[0, slot])
                self._pf_left[slot] -= n
                self._pf_off[slot] += n * chunk
                if self._pf_left[slot] <= 0:
                    self._pf_tail.pop(slot, None)
                    pf_done.append((slot, self._slots[slot].req.id))
                else:
                    self._pf_tail[slot] = self._pf_tail[slot][n * chunk:]
        else:
            pf_done = []
            out = self._decode_guard(
                self.params, tok, pos, active, budget, eos_d, life,
                tables_d, self.pool.cache, self._rngs,
                temp_d, top_k_d, top_p_d, greedy_d, self.cfg, k=k,
                use_pallas=self._use_pallas, use_fused=self._use_fused,
                shardings=self._plan)
        toks, emitted, tok, pos, active, budget, cache, rngs = out
        self.pool.cache = cache
        self._rngs = rngs
        self._dev_state = (tok, pos, active, budget)
        self._adm_mask[:] = False       # the merge landed with this launch
        for out_arr in (toks, emitted):
            copy_async = getattr(out_arr, "copy_to_host_async", None)
            if copy_async is not None:
                copy_async()
        # the host-side dispatch tax this PR amortizes: arg conversion +
        # trace-cache lookup + enqueue, all BEFORE any device wait (the
        # bench dispatch-split line and the k-autotuner read this)
        host_s = time.perf_counter() - t_wall
        self.metrics.inc("decode_dispatches")
        self.metrics.observe("decode_dispatch_s", host_s)
        return _InFlight(toks=toks, emitted=emitted, k=k, t0_us=t0_us,
                         t_wall=t_wall, n_active=n_active, host_s=host_s,
                         pf_done=pf_done)

    def _drain_pending(self, break_reason: str = "") -> List[RequestResult]:
        """Fetch the in-flight window, if any. A non-empty
        ``break_reason`` marks this drain as a WINDOW BREAK — the
        continuous-window path had to be abandoned for a host mutation
        — and feeds the ``window_breaks_{reason}`` counters
        (admit|deadline|cancel|spec|reprobe), the PR's before/after
        observability: post-continuous-windows only the speculative
        reasons should ever move on a healthy engine."""
        if self._inflight is None:
            return []
        if break_reason and self._window > 1:
            self.metrics.inc("window_breaks_" + break_reason)
        w, self._inflight = self._inflight, None
        return self._drain_window(w)

    def _commit_tokens(self, slot: int, st: _Active, committed: List[int],
                       now: float, t0_us: float, dur_us: float) -> None:
        """Append a dispatch's committed tokens to a slot's host record
        — ONE definition for the decode-window and speculative-verify
        drains: TTFT on the first token, one ``token`` telemetry
        instant per committed token interpolated across the dispatch
        span (indices are the request's running count — the strictly-
        increasing contract tools/trace_check.py enforces), and the
        ``_tok``/``_pos``/``_budget`` mirrors advanced."""
        tid = self._tb + SLOT_TRACK_BASE + slot
        first = not st.tokens
        base = len(st.tokens)
        st.tokens.extend(committed)
        if self.tel.enabled:
            n = len(committed)
            for j in range(n):
                self.tel.instant("token", tid,
                                 ts_us=t0_us + dur_us * (j + 1) / n,
                                 request=st.req.id, index=base + j + 1)
        if first:
            st.t_first_token = now
            self.metrics.observe("ttft_s", now - st.t_submit)
        st.t_last_token = now
        self._tok[slot] = st.tokens[-1]
        self._pos[slot] += len(committed)
        self._budget[slot] = st.cap - len(st.tokens)

    def _drain_window(self, w: _InFlight) -> List[RequestResult]:
        """Fetch one dispatched window's token block (ONE host snapshot
        per window — ``np.asarray`` on the async-copied outputs) and run
        the host bookkeeping: append tokens, advance the mirrors,
        finish slots whose budget ran out or whose eos landed. Slots
        that finished mid-window already idled on device; their pages
        and slot free HERE, at the window boundary."""
        toks = np.asarray(w.toks)
        emitted = np.asarray(w.emitted)
        now = self.clock()
        self.n_steps += 1
        self.step_timer.laps.append(time.perf_counter() - w.t_wall)
        n_tok = int(emitted.sum())
        if self._sanitize:
            # GRAFT_SANITIZE: sampled ids must be valid vocab entries
            # (an out-of-range id would clamp in the next embedding
            # gather and silently decode garbage)
            live = toks[emitted]
            bad = (live < 0) | (live >= self.cfg.vocab_size)
            if bad.any():
                raise FloatingPointError(
                    f"sanitize: decode produced out-of-range token(s) "
                    f"{live[bad][:4].tolist()} (vocab "
                    f"{self.cfg.vocab_size})")
        self.metrics.observe("batch_fill_ratio",
                             w.n_active / self.ecfg.pool_size)
        self.metrics.inc("decode_steps")
        self.metrics.inc("decode_tokens", n_tok)
        # plain-decode tokens only (decode_tokens also counts verify
        # commits): the denominator of host_dispatch_ms_per_token —
        # dispatch time is only accumulated on this path, so a
        # spec-enabled run must not dilute the ratio
        self.metrics.inc("dispatch_tokens", n_tok)
        tel_on = self.tel.enabled
        # span end at ts_us(now) — the same clock reading the finish
        # path stamps on a request's E event, so a slot's last decode
        # span never spills past its request envelope
        dur_us = (self.tel.ts_us(now) - w.t0_us) if tel_on else 0.0
        if tel_on:
            self.tel.complete("decode_step", self._tb + ENGINE_TRACK,
                              w.t0_us, dur_us, step=self.n_steps,
                              n_active=w.n_active, k=w.k, tokens=n_tok)
        # windowed-admission radix registration: a slot whose in-window
        # prefill COMPLETED in this dispatch has verifiably written its
        # prompt pages — they become claimable from this boundary on
        # (never earlier: a same-window neighbor sharing a page still
        # being written would attend garbage). The id guards against
        # the slot having been killed and recycled since the launch.
        for slot, rid in w.pf_done:
            st = self._slots.get(slot)
            if st is not None and st.req.id == rid:
                self.pool.commit_admission(slot)
        finished: List[RequestResult] = []
        for slot in list(self._slots):
            # emitted[:, slot] is a RUN mask: False while the slot
            # prefills its admission chunks (mixed windows), True from
            # its first decode step, False again once it deactivates —
            # commit by mask, not by count
            mask = emitted[:, slot]
            n_emit = int(mask.sum())
            if n_emit == 0:
                continue
            st = self._slots[slot]
            if tel_on:
                self.tel.complete("decode",
                                  self._tb + SLOT_TRACK_BASE + slot,
                                  w.t0_us, dur_us,
                                  step=self.n_steps, request=st.req.id,
                                  k=w.k, tokens=n_emit)
            self._commit_tokens(slot, st,
                                [int(t) for t in toks[mask, slot]],
                                now, w.t0_us, dur_us)
            eos = int(self._eos[slot])
            if eos >= 0 and st.tokens[-1] == eos:
                # the device deactivated the slot the step its eos
                # landed (emission stops right there — the eos token is
                # the stream's last)
                finished.append(self._finish_slot(
                    slot, FINISH_EOS, now, device_stopped=True))
            elif self._budget[slot] <= 0:
                reason = (FINISH_LENGTH_CAP if st.capped
                          else FINISH_MAX_TOKENS)
                finished.append(self._finish_slot(
                    slot, reason, now, device_stopped=True))
        # deferred radix registration: the full prompt page holding
        # position P-1 becomes shareable once the frontier passed it
        self.pool.flush_pending()
        # k-autotune: accumulate this window's host-vs-device split and
        # let the bounded additive-increase policy climb the buckets
        if w.k > 1:
            self._at_host += w.host_s
            self._at_wall += self.step_timer.laps[-1]
            self._at_n += 1
            self._maybe_autotune()
        return finished

    def _maybe_autotune(self) -> None:
        """Bounded additive-increase window sizing from the live
        dispatch split: every ``WINDOW_AUTOTUNE_INTERVAL`` windows,
        when the host dispatch tax is still more than
        ``WINDOW_AUTOTUNE_HOST_FRAC`` of window wall time, move ONE
        bucket up (never down, never past ``decode_window``). Every
        bucket's programs compiled at construction, so a move is a
        warm-cache dispatch-size change — zero recompiles by design."""
        if (not self.ecfg.decode_window_auto
                or self._wk >= len(self._buckets) - 1
                or self._at_n < WINDOW_AUTOTUNE_INTERVAL):
            return
        host_frac = self._at_host / max(self._at_wall, 1e-9)
        if host_frac > WINDOW_AUTOTUNE_HOST_FRAC:
            self._wk += 1
            self._window_cur = self._buckets[self._wk]
            self.metrics.inc("autotune_window_increases")
            self.metrics.gauge("decode_window_k", self._window_cur)
            self._event(
                f"step {self.n_steps}: autotune k -> {self._window_cur} "
                f"(host dispatch {host_frac:.1%} of window wall over "
                f"{self._at_n} windows)")
        self._at_host = self._at_wall = 0.0
        self._at_n = 0

    def _decode_once(self) -> List[RequestResult]:
        """Blocked k=1 decode: dispatch one step and immediately fetch
        it — the fallback around host-side state mutations (admission,
        deadline, cancel, speculative transitions)."""
        with annotate("serve/decode"):
            return self._drain_window(self._launch(1))

    def _histories(self) -> List[Optional[np.ndarray]]:
        """Per-slot prompt+generated token history — pure host data (the
        engine appends every committed token), so drafters never pay a
        device sync for it."""
        out: List[Optional[np.ndarray]] = [None] * self.ecfg.pool_size
        for slot, st in self._slots.items():
            # fromiter, not asarray: tokens is a host list of ints — no
            # device round-trip here, and the conversion can't be
            # mistaken (by reader or linter) for one
            out[slot] = np.concatenate(
                [st.req.prompt,
                 np.fromiter(st.tokens, np.int32, len(st.tokens))])
        return out

    def _verify_once(self) -> List[RequestResult]:
        """One speculative step: host-side draft -> ONE jitted verify
        over all slots -> commit 1..k+1 tokens per slot. The drafter's
        proposals are clamped per slot by cache room (the window's last
        REAL write position must stay inside the slot buffer) and by
        the remaining token budget, both host-side — the device program
        only ever sees traced (n_slots,)-sized inputs."""
        k = self.drafter.k
        S = self.pool.seq_len
        P = self.ecfg.pool_size
        # verify works off the host mirrors and advances them below:
        # any device-resident window state is stale after this step
        self._dev_state = None
        ctx = DraftContext(
            tok=self._tok, pos=self._pos, active=self._active,
            histories=(self._histories() if self.drafter.needs_history
                       else None))
        draft_toks, draft_len, dt = timed_draft(
            self.drafter, ctx, self.cfg.vocab_size, tel=self.tel,
            track=self._tb + ENGINE_TRACK)
        self.metrics.observe("draft_overhead_s", dt)
        t0_us = self.tel.now_us() if self.tel.enabled else 0.0
        m = np.zeros((P,), np.int32)
        for slot, st in self._slots.items():
            if not self._active[slot]:
                continue
            room = S - 1 - int(self._pos[slot])
            budget = st.cap - len(st.tokens) - 1
            m[slot] = max(0, min(int(draft_len[slot]), k, room, budget))
        window = np.zeros((P, k + 1), np.int32)
        window[:, 0] = self._tok
        window[:, 1:] = draft_toks
        # the host-side bound the traced verify writes rely on: every
        # ACTIVE slot's real window positions (j <= m) stay inside the
        # slot buffer; padding positions route to an explicit
        # scatter-drop (GL006). Scoped to active slots: a released
        # slot's stale frontier can legitimately sit at S (a request
        # that finished by filling its buffer), and the verify program
        # runs those slots at position 0 anyway.
        check_in_bounds(np.where(self._active, self._pos + m, 0), 1, S,
                        what="speculative verify window")
        with annotate("serve/verify"):
            self.step_timer.start()
            n_acc, out, cache, rngs = self._verify_guard(
                self.params, jnp.asarray(window), jnp.asarray(self._pos),
                jnp.asarray(m), jnp.asarray(self._active),
                jnp.asarray(self.pool.tables), self.pool.cache,
                self._rngs, jnp.asarray(self._temp),
                jnp.asarray(self._top_k), jnp.asarray(self._top_p),
                jnp.asarray(self._greedy), self.cfg,
                use_kernel=self._use_window_kernel,
                shardings=self._plan)
            self.step_timer.lap(n_acc)
        self.pool.cache = cache
        self._rngs = rngs
        # ONE host snapshot per verify step for every slot's outcome
        # (np.asarray, not jax.device_get: the engine's step loop is
        # GL004-clean — syncs happen once per dispatch, never per token)
        n_acc_h = np.asarray(n_acc)
        out_h = np.asarray(out)
        if self._sanitize:
            bad = (out_h < 0) | (out_h >= self.cfg.vocab_size)
            if bad.any():
                raise FloatingPointError(
                    f"sanitize: verify produced out-of-range token(s) "
                    f"{out_h[bad][:4].tolist()} (vocab "
                    f"{self.cfg.vocab_size})")
        now = self.clock()
        self.n_steps += 1
        n_active = int(self._active.sum())
        drafted = int(m.sum())
        accepted = int(n_acc_h.sum())
        emitted = accepted + n_active          # +1 correction/bonus each
        self.metrics.observe("batch_fill_ratio", n_active / P)
        self.metrics.inc("decode_steps")
        self.metrics.inc("decode_tokens", emitted)
        self.metrics.inc("slot_steps", n_active)
        self.metrics.inc("spec_draft_tokens", drafted)
        self.metrics.inc("spec_accepted_tokens", accepted)
        if drafted:
            self.metrics.observe("accept_rate", accepted / drafted)
        self.metrics.observe("tokens_per_slot_step", emitted / n_active)
        tel_on = self.tel.enabled
        dur_us = (self.tel.ts_us(now) - t0_us) if tel_on else 0.0
        if tel_on:
            self.tel.complete("verify_step", self._tb + ENGINE_TRACK,
                              t0_us, dur_us,
                              step=self.n_steps, n_active=n_active,
                              drafted=drafted, accepted=accepted)
        if self._spec_health is not None:
            if self._spec_health.observe(drafted, accepted):
                # the drafter is a pure tax at this accept rate: fall
                # back to plain decode (same shapes, already-budgeted
                # program) and re-probe later with backoff
                self._spec_active = False
                self._probe_pending = False
                self._spec_health.on_disable()
                self.metrics.inc("spec_disables")
                self._event(
                    f"step {self.n_steps}: speculative decoding disabled "
                    f"(windowed accept rate below "
                    f"{self.rcfg.spec_disable_threshold})")
            elif (self._probe_pending
                  and len(self._spec_health.window)
                  >= self.rcfg.spec_window):
                self._probe_pending = False
                self._spec_health.on_reenable()
                self._event(f"step {self.n_steps}: speculative "
                                   f"re-probe healthy; backoff reset")
        finished: List[RequestResult] = []
        for slot in list(self._slots):
            if not self._active[slot]:
                continue
            st = self._slots[slot]
            n_emit = int(n_acc_h[slot]) + 1
            committed = [int(t) for t in out_h[slot, :n_emit]]
            eos = int(self._eos[slot])
            if eos >= 0 and eos in committed:
                # a drafted/accepted eos ends the stream there — drop
                # whatever the verify window committed past it
                n_emit = committed.index(eos) + 1
                committed = committed[:n_emit]
            if tel_on:
                self.tel.complete("verify",
                                  self._tb + SLOT_TRACK_BASE + slot,
                                  t0_us, dur_us, step=self.n_steps,
                                  request=st.req.id, drafted=int(m[slot]),
                                  committed=n_emit)
            self._commit_tokens(slot, st, committed, now, t0_us, dur_us)
            if eos >= 0 and st.tokens[-1] == eos:
                finished.append(self._finish_slot(slot, FINISH_EOS, now))
            elif len(st.tokens) >= st.cap:
                reason = (FINISH_LENGTH_CAP if st.capped
                          else FINISH_MAX_TOKENS)
                finished.append(self._finish_slot(slot, reason, now))
        self.pool.flush_pending()
        return finished

    def _finish_slot(self, slot: int, reason: str, now: float,
                     migrated: bool = False,
                     device_stopped: bool = False,
                     masked: bool = False) -> RequestResult:
        st = self._slots.pop(slot)
        if st.req.prefill_only and reason in (
                FINISH_MAX_TOKENS, FINISH_LENGTH_CAP, FINISH_EOS):
            # disaggregated prefill completed: the prompt's full pages
            # are final (the 1-token budget rewrote position P-1) and
            # registered for export; the envelope closes migrated — the
            # decode tier's segment is the terminal one. Deadline /
            # cancel / shed outcomes keep their reason: those ARE
            # terminal for the request.
            reason = FINISH_PREFILLED
            migrated = True
        self._active[slot] = False
        self._deadline[slot] = np.inf
        self._adm_mask[slot] = False
        self._li = None           # release zeroes the slot's table row
        self._pf_left[slot] = 0
        self._pf_off[slot] = 0
        self._pf_limit[slot] = 0
        self._pf_tail.pop(slot, None)
        if not (device_stopped or masked):
            # a host-initiated finish outside the mask path (a migrated
            # cancel, or any finish on a blocked engine): the device-
            # resident step state still believes the slot is live —
            # rebuild from the mirrors at the next launch. Budget/eos
            # finishes flipped the slot off ON DEVICE, and masked
            # kills landed through the kill flags of a dispatch that
            # has already launched, so both leave the state donatable.
            self._dev_state = None
        if self.tel.enabled:
            extra = {"migrated": True} if migrated else {}
            self.tel.end("request", self._tb + SLOT_TRACK_BASE + slot,
                         ts_us=self.tel.ts_us(now), request=st.req.id,
                         reason=reason, n_tokens=len(st.tokens), **extra)
        self.pool.release(slot)
        if self.drafter is not None:
            self.drafter.on_release(slot)
        n = len(st.tokens)
        decode_tps = 0.0
        if n > 1 and st.t_last_token > st.t_first_token:
            decode_tps = (n - 1) / (st.t_last_token - st.t_first_token)
        res = RequestResult(
            id=st.req.id, tokens=st.tokens, finish_reason=reason,
            queue_wait_s=st.t_admit - st.t_submit,
            ttft_s=(st.t_first_token - st.t_submit) if n else 0.0,
            decode_tokens_per_s=decode_tps, total_s=now - st.t_submit)
        self.metrics.inc(f"finished_{reason}")
        self._journal_finish(st.req.id, reason)
        if decode_tps:
            self.metrics.observe("decode_tokens_per_s", decode_tps)
        return res

    def _finish_unstarted(self, req: Request, t_submit: float, reason: str,
                          now: float) -> RequestResult:
        # never admitted -> no slot track and no open envelope; one
        # instant marks the terminal outcome on the engine timeline
        self.tel.instant("request_unstarted", self._tb + ENGINE_TRACK,
                         ts_us=(self.tel.ts_us(now) if self.tel.enabled
                                else None),
                         request=req.id, reason=reason)
        self.metrics.inc(f"finished_{reason}")
        self._journal_finish(req.id, reason)
        return RequestResult(id=req.id, tokens=[], finish_reason=reason,
                             queue_wait_s=now - t_submit,
                             total_s=now - t_submit)
