"""Length-prefixed JSON RPC: the wire between the router and worker
processes.

PR 8's fleet lives in one interpreter — every "replica death" it
survives is simulated. This module is the seam that makes the fleet
real: a worker process (serve/worker.py) owns one Engine and speaks
this protocol over a loopback socket; the router holds one
:class:`RpcClient` per worker and drives it with the same verbs the
in-process host API has.

Framing: one message = an 8-byte header (4-byte big-endian unsigned
length + 4-byte big-endian CRC32 of the body) + that many bytes of
UTF-8 JSON. The checksum is the corruption fence: a flipped bit
anywhere in the body fails the CRC on the far side and surfaces as a
typed :class:`RpcProtocolError` — never a mis-decoded result quietly
poisoning a stream. Requests are ``{"op": <verb>, ...args}``;
responses are ``{"ok": true, ...result}`` or ``{"ok": false,
"error": msg}``. Stdlib only (socket/asyncio/json/zlib) — the
zero-egress image adds no dependency for its own fleet.

Two envelope keys ride OUTSIDE the per-verb payload: ``idem`` (a
per-logical-call idempotency key on mutating verbs — the worker's
dispatch consults a bounded reply cache so a duplicated or
blindly-retried frame returns the cached reply, marked
``idem_hit: true``, instead of re-executing) and ``gen`` (the
generation fence: a worker rejects calls stamped with a generation
other than its own with a typed "stale generation" protocol error, so
a router still talking to a partitioned-then-replaced incarnation can
never mutate the wrong process).

Verbs (dispatched in serve/worker.py):

- ``register`` — sent BY the worker TO the router's registration
  listener (:class:`RpcListener`, owned by faults/procsup.py) right
  after it binds its serving socket: ``{port, pid, gen, replayed,
  worker_idx, proto, shape_hash}``. This replaces PR 9's ready files —
  the handshake crosses the network, not a shared filesystem, so a
  worker is placeable on any host that can reach ``--router-addr``.
  ``proto`` (:data:`PROTO_VERSION`) and ``shape_hash``
  (:func:`engine_shape_hash`) are checked at registration: a
  mismatched worker build is rejected with a typed
  :class:`RpcProtocolError` *before* it takes traffic, instead of
  failing mid-stream on a codec or engine-shape drift;
- ``submit``   — route one request into the worker's engine;
- ``step``     — run ONE engine scheduling iteration; the response
  carries every not-yet-acknowledged finished result (redelivered
  until the router acks it in a later ``step``/``ack`` — a response
  lost to a timeout or a router crash must not lose a finish), the
  committed-token lists for every active slot (the stream-drain
  piggyback the delivery ledger reads), and the health gauges;
- ``stream_drain`` — just the committed-token lists (reconciliation
  after a reconnect, without forcing a step);
- ``cancel``   — cancel one request (``migrated`` closes it as a
  non-terminal segment and journals a finish so the worker's own
  journal replay never resurrects it);
- ``drain``    — stop admitting (submits now refuse) and cancel every
  in-flight request ``migrated`` — the rolling-restart drain;
- ``health``   — liveness/readiness probe: pid, warmed, idle, queue
  depth, slots, pages, prefix-hit counters, in-flight ids;
- ``journal_drain`` — stream the worker's LOCAL crash-journal state to
  the router in bounded frames (``cursor``/``limit`` paging, ``eof``
  flag): condensed finish records ``{id, reason}`` plus the
  still-unfinished requests as wire docs. This is how
  ``Router.attach_replica`` reconciles across machines — the journal
  never leaves the worker's filesystem; its *content* rides the RPC
  channel;
- ``page_transfer`` — the disaggregation verb (serve/disagg.py): move
  a prompt's finished KV pages between tiers in bounded frames. One
  verb, six kinds: ``export_begin`` pins the prompt's radix-cached
  full pages on the prefill worker and answers with the page count;
  ``export_chunk`` pages the pinned pages out as base64 raw bytes —
  every pool entry per page (int8/fp8/bf16 K/V rows AND the quantized
  per-row scale arrays, which share the page axis), chunked so each
  frame stays under :data:`MAX_FRAME`; ``export_end`` drops the pin.
  On the decode worker ``install_begin`` allocates + pins local
  physical pages, ``install_chunk`` scatters arriving blocks through
  the engine's construction-warmed install program, and
  ``install_commit`` registers the chain into the local radix (the
  page-table rebase: the next admission maps the prompt to these
  LOCAL physical indices through an ordinary prefix claim) — or,
  with ``abort: true``, unpins and frees the staged pages (the
  driver lost the source mid-transfer; a half-landed chain must
  never enter the radix). Shapes
  and dtypes are never carried per frame — the engine-shape hash both
  tiers presented at registration already guarantees page-geometry
  agreement, so the receiver decodes against its own pool's template
  (:func:`page_block_template`);
- ``summary``  — the engine ``metrics_summary()`` block the fleet
  summary aggregates;
- ``shutdown`` — close the journal and exit 0 (the graceful half of a
  rolling restart; SIGKILL is the other half).

Failure model on the client: a socket timeout raises
:class:`RpcTimeout` (the worker may still execute the call — SIGSTOP
looks exactly like this), a connection that dies BETWEEN frames raises
:class:`RpcDown` (connection refused/reset — the process is gone or
restarting), and a stream-integrity violation — a checksum mismatch,
a connection dying MID-frame, a generation fence rejection — raises
:class:`RpcProtocolError` (the stream is poisoned; the only safe move
is close + reconnect, and the router's retry-once path re-sends with
the SAME idempotency key so a maybe-executed mutation cannot double).
All three close the connection; the next call reconnects. The caller
decides what each means: the router's wedge probe treats timeouts as
slow steps, the supervisor treats refused connections as a death to
restart.
"""

from __future__ import annotations

import json
import socket
import time
import zlib
from typing import Callable, Optional, Tuple

import numpy as np

from .requests import Request, RequestResult, SamplingParams

#: frame-size sanity bound (a corrupt length prefix must not allocate
#: gigabytes); generous for block_size-scale prompt lists
MAX_FRAME = 16 << 20

#: wire protocol version, carried in every ``register`` handshake: the
#: router rejects a worker speaking a different framing/codec dialect
#: at registration time (RpcProtocolError) instead of corrupting a
#: stream mid-traffic. Bump on any incompatible change to the frame
#: layout or the request/result wire codecs.
#: v2: checksummed framing (4-byte length + 4-byte CRC32 header) plus
#: the ``idem``/``gen`` envelope keys.
PROTO_VERSION = 2

#: frame header: 4-byte big-endian length + 4-byte big-endian CRC32
HEADER_BYTES = 8

#: journal_drain paging bound: records per frame (a frame of 256
#: condensed records stays far under MAX_FRAME at block_size-scale
#: prompts)
JOURNAL_DRAIN_LIMIT = 256


class RpcError(Exception):
    """The worker answered with ok=false (an application error)."""


class RpcTimeout(RpcError):
    """No response within the timeout — the worker may be hung
    (SIGSTOP, wedged device) and may still execute the call."""


class RpcDown(RpcError):
    """Connection refused/reset/closed — the worker process is gone."""


class RpcProtocolError(RpcError):
    """The protocol itself was violated — two flavors, one type:

    - at REGISTRATION: protocol version or engine shape hash mismatch.
      The worker build cannot safely join this fleet — it must exit
      (and be rebuilt), not retry;
    - on the DATA PLANE: stream integrity lost — a frame checksum
      mismatch, a connection dying mid-frame, or a generation fence
      rejection. The connection is poisoned: close, reconnect, and (on
      the router) retry ONCE with the same idempotency key — the reply
      cache makes that safe even if the original call executed."""


def engine_shape_hash(mcfg, ecfg) -> str:
    """Fingerprint of everything that must agree between the router's
    expectation and a worker's engine for the fleet to be coherent:
    the full model architecture plus the engine-shape knobs that size
    the pool/pages/window. Two builds with the same hash produce
    token-identical streams for the same request; a worker whose hash
    differs is a DIFFERENT model or engine and is rejected at
    registration (docs/serving.md#deployment)."""
    import dataclasses
    import hashlib
    doc = {
        "proto": PROTO_VERSION,
        "model": {k: str(v) for k, v in
                  sorted(dataclasses.asdict(mcfg).items())},
        "engine": {k: str(getattr(ecfg, k)) for k in
                   ("pool_size", "max_queue", "prefill_chunk",
                    "page_size", "max_pages", "n_pages", "prefix_cache",
                    "decode_window", "mesh_data", "mesh_model",
                    # quantization knobs (quant/): a worker serving a
                    # different KV/weight precision is a DIFFERENT
                    # model numerically — mismatched fleets must
                    # reject at registration, never mix streams
                    "kv_quant", "weight_quant", "quant_granularity",
                    "act_quant")},
    }
    return hashlib.sha256(
        json.dumps(doc, sort_keys=True).encode()).hexdigest()[:16]


# --------------------------------------------------------------- framing

def encode_frame(obj: dict) -> bytes:
    data = json.dumps(obj).encode()
    if len(data) > MAX_FRAME:
        raise ValueError(f"frame too large: {len(data)} bytes")
    crc = zlib.crc32(data) & 0xFFFFFFFF
    return (len(data).to_bytes(4, "big") + crc.to_bytes(4, "big")
            + data)


def decode_header(header: bytes) -> Tuple[int, int]:
    """(body length, expected CRC32) from an 8-byte frame header. An
    insane length is a loud error — a corrupt prefix must never
    allocate gigabytes or desync the stream quietly."""
    n = int.from_bytes(header[:4], "big")
    if n > MAX_FRAME:
        raise ValueError(f"frame too large: {n} bytes")
    return n, int.from_bytes(header[4:HEADER_BYTES], "big")


def crc_ok(body: bytes, crc: int) -> bool:
    return (zlib.crc32(body) & 0xFFFFFFFF) == crc


# ---------------------------------------------------------- wire codecs

def request_to_wire(req: Request, now: float) -> dict:
    """Request -> JSON-safe dict. Deadlines cross the process boundary
    as *remaining seconds* (an absolute timestamp on the router's
    monotonic clock is meaningless on the worker's)."""
    sp = req.sampling
    return {
        "id": req.id,
        "prompt": np.asarray(req.prompt).tolist(),
        "max_new_tokens": int(req.max_new_tokens),
        "rng_seed": int(req.rng_seed),
        "temperature": float(sp.temperature), "top_k": int(sp.top_k),
        "top_p": float(sp.top_p), "greedy": bool(sp.greedy),
        "deadline_rel": (None if req.deadline is None
                         else max(req.deadline - now, 0.0)),
        "eos_token_id": (None if req.eos_token_id is None
                         else int(req.eos_token_id)),
        "prefill_only": bool(req.prefill_only),
    }


def request_from_wire(doc: dict, now: float) -> Request:
    deadline = None
    if doc.get("deadline_rel") is not None:
        deadline = now + float(doc["deadline_rel"])
    # host JSON list -> host array; no device involved
    prompt = np.asarray(doc["prompt"],
                        np.int32)
    return Request(
        id=doc["id"], prompt=prompt,
        max_new_tokens=int(doc["max_new_tokens"]),
        sampling=SamplingParams(
            temperature=float(doc["temperature"]),
            top_k=int(doc["top_k"]), top_p=float(doc["top_p"]),
            greedy=bool(doc["greedy"])),
        deadline=deadline, rng_seed=int(doc["rng_seed"]),
        eos_token_id=(None if doc.get("eos_token_id") is None
                      else int(doc["eos_token_id"])),
        prefill_only=bool(doc.get("prefill_only", False)))


def result_to_wire(res: RequestResult) -> dict:
    return {
        "id": res.id, "tokens": list(res.tokens),
        "finish_reason": res.finish_reason,
        "queue_wait_s": res.queue_wait_s, "ttft_s": res.ttft_s,
        "decode_tokens_per_s": res.decode_tokens_per_s,
        "total_s": res.total_s,
    }


def result_from_wire(doc: dict) -> RequestResult:
    return RequestResult(
        id=doc["id"], tokens=list(doc["tokens"]),
        finish_reason=doc["finish_reason"],
        queue_wait_s=float(doc.get("queue_wait_s", 0.0)),
        ttft_s=float(doc.get("ttft_s", 0.0)),
        decode_tokens_per_s=float(doc.get("decode_tokens_per_s", 0.0)),
        total_s=float(doc.get("total_s", 0.0)))


# ------------------------------------------------------ page transfer codec

#: raw bytes of page blocks per ``export_chunk`` frame: base64 expands
#: 4/3 and the JSON envelope adds entry names, so 8 MiB of raw page
#: bytes stays comfortably under the 16 MiB MAX_FRAME bound. A single
#: page larger than this still ships (one page per frame is the floor);
#: that needs a model far past anything this repo sizes.
PAGE_CHUNK_BYTES = 8 << 20


def page_block_template(cache) -> dict:
    """Per-entry (shape, dtype) of ONE page's export blocks, derived
    from a pool's cache dict — the receiver-side decode key. Never
    serialized: both tiers derive it from their own pool, and the
    engine-shape hash agreed at registration guarantees the two match
    byte-for-byte."""
    return {name: ((arr.shape[0], 1) + tuple(arr.shape[2:]),
                   np.dtype(arr.dtype))
            for name, arr in cache.items()}


def page_wire_bytes(template: dict) -> int:
    """Raw bytes one page occupies on the wire (all entries)."""
    total = 0
    for shape, dtype in template.values():
        n = 1
        for d in shape:
            n *= int(d)
        total += n * dtype.itemsize
    return total


def page_block_to_wire(block: dict) -> dict:
    """One page's export blocks -> {entry: base64 raw bytes}. Raw
    bytes, not token lists: int8/fp8 pages round-trip exactly, and the
    f32 scale rows ride as their IEEE bytes (bit-exact — a lossy float
    repr here would silently perturb dequantization on the far tier)."""
    import base64
    return {name: base64.b64encode(
                np.ascontiguousarray(arr).tobytes()).decode("ascii")
            for name, arr in block.items()}


def page_block_from_wire(doc: dict, template: dict) -> dict:
    """{entry: base64} -> one page's blocks, decoded against the LOCAL
    pool's template. A byte-length mismatch is a loud error: it means
    the shape-hash handshake let a geometry drift through, which must
    never be papered over with a reshape."""
    import base64
    out = {}
    for name, (shape, dtype) in template.items():
        raw = base64.b64decode(doc[name])
        n = 1
        for d in shape:
            n *= int(d)
        if len(raw) != n * dtype.itemsize:
            raise ValueError(
                f"page block {name!r}: {len(raw)} bytes on the wire, "
                f"local pool wants {n * dtype.itemsize} "
                f"(shape {shape}, dtype {dtype})")
        out[name] = np.frombuffer(raw, dtype=dtype).reshape(shape)
    return out


# ---------------------------------------------------------- sync client

class RpcClient:
    """Blocking single-connection client (the router and supervisor are
    single-threaded loops — one in-flight call at a time by design).
    Connects lazily; a timeout or connection failure closes the socket
    so the next call reconnects from a clean state (a half-read
    response from a timed-out call can never be mistaken for the next
    call's)."""

    def __init__(self, host: str, port: int, timeout_s: float = 10.0):
        self.host = host
        self.port = port
        self.timeout_s = timeout_s
        self._sock: Optional[socket.socket] = None
        #: chaos/test seams (faults/netchaos.py): a transform applied
        #: to the encoded request frame before send (corrupt-frame
        #: injection), and ``(chunk_bytes, pause_s)`` pacing that drips
        #: the frame onto the wire (trickle injection). Both None in
        #: production — the send path is one ``sendall``.
        self.frame_filter: Optional[Callable[[bytes], bytes]] = None
        self.send_chunking: Optional[Tuple[int, float]] = None

    def connect(self) -> None:
        if self._sock is not None:
            return
        try:
            self._sock = socket.create_connection(
                (self.host, self.port), timeout=self.timeout_s)
            self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY,
                                  1)
        except OSError as e:
            self._sock = None
            raise RpcDown(f"connect {self.host}:{self.port}: {e}") from e

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def _recv_exact(self, n: int, mid_frame: bool = False) -> bytes:
        """Read exactly ``n`` bytes. EOF classification (the S-series
        contract): a clean close BETWEEN frames — zero bytes read,
        header position — is :class:`RpcDown` (the peer went away; the
        next call reconnects); a close MID-frame — a partial header or
        anywhere inside a body (``mid_frame``) — is
        :class:`RpcProtocolError` (the stream died with bytes in
        flight; whatever was being framed is unrecoverable)."""
        buf = b""
        while len(buf) < n:
            # budget-bounded: call() sets sock.settimeout from its
            # timeout_s before every frame, so this recv cannot hang
            chunk = self._sock.recv(n - len(buf))  # graftlint: disable=GL019
            if not chunk:
                if buf or mid_frame:
                    raise RpcProtocolError(
                        f"connection closed mid-frame "
                        f"({len(buf)}/{n} bytes)")
                raise RpcDown("connection closed")
            buf += chunk
        return buf

    def _send_frame(self, frame: bytes) -> None:
        if self.frame_filter is not None:
            frame = self.frame_filter(frame)
        pacing = self.send_chunking
        if pacing is None:
            self._sock.sendall(frame)
            return
        chunk, pause = pacing
        for i in range(0, len(frame), chunk):
            self._sock.sendall(frame[i:i + chunk])
            time.sleep(pause)  # graftlint: disable=GL019 — chaos injection: the trickle IS the fault

    def call(self, op: str, timeout_s: Optional[float] = None,
             **kwargs) -> dict:
        """One request/response exchange; returns the response dict
        (``ok`` stripped). Raises RpcTimeout / RpcDown /
        RpcProtocolError / RpcError."""
        self.connect()
        self._sock.settimeout(timeout_s if timeout_s is not None
                              else self.timeout_s)
        try:
            self._send_frame(encode_frame({"op": op, **kwargs}))
            n, crc = decode_header(self._recv_exact(HEADER_BYTES))
            body = self._recv_exact(n, mid_frame=True)
        except socket.timeout as e:
            self.close()
            raise RpcTimeout(f"{op}: no response") from e
        except RpcProtocolError:
            self.close()
            raise
        except RpcDown:
            self.close()
            raise
        except (OSError, ValueError) as e:
            self.close()
            raise RpcDown(f"{op}: {e}") from e
        if not crc_ok(body, crc):
            # a corrupt RESPONSE frame: never decode it — a flipped bit
            # in a token list would otherwise become a silent wrong
            # answer. Poisoned stream: close, typed error, reconnect.
            self.close()
            raise RpcProtocolError(
                f"{op}: response frame checksum mismatch")
        try:
            doc = json.loads(body)
        except ValueError as e:
            self.close()
            raise RpcDown(f"{op}: undecodable response: {e}") from e
        if not doc.get("ok"):
            if doc.get("kind") == "protocol":
                # either end declared the stream unsafe (checksum
                # reject, generation fence): drop the connection too —
                # a retry must start from a clean socket
                self.close()
                raise RpcProtocolError(
                    doc.get("error", "protocol mismatch"))
            raise RpcError(doc.get("error", "unknown worker error"))
        return doc


# --------------------------------------------------------- async server

async def serve_connection(reader, writer, dispatch) -> None:
    """One worker-side connection loop: read frame -> dispatch -> write
    response, until the peer goes away. ``dispatch`` is a synchronous
    callable ``(doc) -> dict`` running in the event loop — the engine
    host API is single-threaded by design, and the loop IS that one
    thread. Dispatch exceptions become ok=false responses; transport
    errors end the connection quietly (the router reconnects)."""
    import asyncio
    try:
        while True:
            try:
                header = await reader.readexactly(HEADER_BYTES)
                n, crc = decode_header(header)
                body = await reader.readexactly(n)
            except (asyncio.IncompleteReadError, ConnectionError):
                return
            except ValueError:
                # an insane length prefix: framing is lost on this
                # connection — drop it, the client reconnects clean
                return
            if not crc_ok(body, crc):
                # corrupt REQUEST frame: answer typed (the client's
                # retry-once path needs to know this was a protocol
                # failure, not an application error), then drop the
                # connection — the stream cannot be trusted past a
                # failed checksum
                try:
                    writer.write(encode_frame(
                        {"ok": False, "kind": "protocol",
                         "error": "request frame checksum mismatch"}))
                    await writer.drain()
                except (ConnectionError, OSError):
                    pass
                return
            try:
                doc = json.loads(body)
                resp = {"ok": True, **(dispatch(doc) or {})}
            except SystemExit:
                raise
            except Exception as e:  # noqa: BLE001 — the one process
                # boundary: any dispatch failure must become a framed
                # error, not a dropped socket the router misreads as a
                # death
                resp = {"ok": False,
                        "error": f"{type(e).__name__}: {e}"}
                if isinstance(e, RpcProtocolError):
                    # typed on the wire so the far client re-raises
                    # RpcProtocolError (terminal) rather than RpcError
                    resp["kind"] = "protocol"
            try:
                writer.write(encode_frame(resp))
                await writer.drain()
            except (ConnectionError, OSError):
                return
    finally:
        try:
            writer.close()
        except (ConnectionError, OSError):
            pass


# ------------------------------------------------------ poll listener

class RpcListener:
    """Poll-driven frame endpoint for the fleet's registration channel.

    The supervisor's control loop is single-threaded by design (ticked
    from the router's driver), so the registration endpoint cannot be
    a blocking server: this listener accepts whatever connections are
    pending, reads ONE frame from each, answers with the handler's
    response, and returns — all inside one :meth:`poll` call. A worker
    sends its ``register`` frame immediately after connecting and
    blocks on the response, so a short per-connection read budget
    suffices; anything slower is dropped and the worker retries.

    The handler receives ``(doc, peer_host)`` — the peer address is
    how the router learns which HOST a remote worker lives on (the
    worker only knows its bound port; the network knows the rest).
    A handler raising :class:`RpcProtocolError` answers with
    ``kind="protocol"`` so the worker's client raises the typed error
    and exits instead of retrying."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 read_timeout_s: float = 2.0):
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(16)
        self._sock.setblocking(False)
        self.read_timeout_s = read_timeout_s

    @property
    def addr(self) -> str:
        h, p = self._sock.getsockname()
        return f"{h}:{p}"

    @property
    def port(self) -> int:
        return self._sock.getsockname()[1]

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass

    @staticmethod
    def _recv_exact(conn: socket.socket, n: int,
                    mid_frame: bool = False) -> bytes:
        """Same EOF classification as the client's: clean close at a
        frame boundary is RpcDown, mid-frame is RpcProtocolError."""
        buf = b""
        while len(buf) < n:
            chunk = conn.recv(n - len(buf))
            if not chunk:
                if buf or mid_frame:
                    raise RpcProtocolError(
                        f"connection closed mid-frame "
                        f"({len(buf)}/{n} bytes)")
                raise RpcDown("connection closed")
            buf += chunk
        return buf

    def poll(self, handler) -> int:
        """Serve every pending connection one request/response frame;
        returns how many frames were handled. Never blocks longer than
        ``read_timeout_s`` per ready connection; transport failures
        drop that connection only."""
        handled = 0
        while True:
            try:
                conn, peer = self._sock.accept()
            except (BlockingIOError, InterruptedError):
                return handled
            except OSError:
                return handled
            try:
                conn.settimeout(self.read_timeout_s)
                n, crc = decode_header(
                    self._recv_exact(conn, HEADER_BYTES))
                body = self._recv_exact(conn, n, mid_frame=True)
                if not crc_ok(body, crc):
                    conn.sendall(encode_frame(
                        {"ok": False, "kind": "protocol",
                         "error": "request frame checksum mismatch"}))
                    continue
                doc = json.loads(body)
                try:
                    resp = {"ok": True, **(handler(doc, peer[0]) or {})}
                except RpcProtocolError as e:
                    resp = {"ok": False, "kind": "protocol",
                            "error": str(e)}
                except Exception as e:  # noqa: BLE001 — same boundary
                    # as serve_connection: a handler failure must frame
                    # an error, not drop the worker's handshake socket
                    resp = {"ok": False,
                            "error": f"{type(e).__name__}: {e}"}
                conn.sendall(encode_frame(resp))
            except (OSError, ValueError, RpcError):
                pass
            finally:
                try:
                    conn.close()
                except OSError:
                    pass
            handled += 1


#: a submit refused because the worker is unreachable or draining —
#: NOT deterministic across replicas (another replica may accept), so
#: the router's candidate loop falls through to the next one
REJECT_REPLICA_DOWN = "rejected_replica_down"
