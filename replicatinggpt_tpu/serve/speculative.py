"""Speculative decoding for the serving engine: drafters + acceptance.

The engine's steady-state cost is one full-model forward per token per
slot (serve/engine.py). Speculative decoding turns that into one
full-model forward per *window*: a cheap drafter proposes up to k
tokens per slot, the target model scores the whole ``[last_committed,
draft_1..draft_k]`` window in ONE jitted pass (``_engine_verify`` in
engine.py over ``models.gpt.verify_step_multi``), and per-position
acceptance commits between 1 and k+1 tokens per slot per step. Draft-k
is static, so the verify program compiles exactly once and the
zero-recompile steady-state contract holds unchanged.

Acceptance rule (this module's ``spec_accept_and_sample``): drafters
propose DETERMINISTIC token sequences — a point-mass proposal q. With
q a point mass at d, standard speculative rejection sampling reduces
to: accept d with probability p(d) under the target's fully-filtered
per-slot distribution (temperature -> top-k -> top-p, the exact
``sample.generate`` pipeline via ``filter_logits_batched``); on the
first rejection, resample from p with d masked out, renormalized.
This preserves the target distribution EXACTLY for any drafter, and
for greedy slots degenerates to argmax equality — which is why greedy
speculative output is token-for-token the non-speculative stream
(pinned in tests/test_speculative.py).

Two drafters behind one host-side interface:

- :class:`NGramDrafter` — prompt-lookup drafting: match the slot's
  trailing n-gram against its own prompt+generated history and propose
  the continuation of the most recent earlier occurrence. Zero
  parameters, zero device work; pays off on repetitive text (and on
  greedy loops, where it converges to accept-rate ~1).
- :class:`ModelDrafter` — a second, smaller ``ModelConfig`` + params
  with its own pooled KV cache, drafting k tokens greedily via one
  jitted k-step scan per engine step. Same slot ids as the engine's
  pool; its cache stays consistent for free because accepted tokens
  are exactly the tokens it drafted (stale K/V past the committed
  frontier is overwritten before ever being attended — the standing
  pool invariant).
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from functools import partial
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config import ModelConfig
from ..faults.inject import fire as fault_fire
from ..models.gpt import (decode_step_multi, init_kv_cache, param_count,
                          prefill_chunk_into_slot)
from ..ops.attention import NEG_INF
from ..sample.generate import filter_logits_batched
from ..utils.sanitize import CompileGuard, check_in_bounds
from ..utils.telemetry import ENGINE_TRACK, NULL
from .cache_pool import commit_default, prefill_chunk_size


# ---------------------------------------------------------------------------
# device-side acceptance (traced inside the engine's verify jit)
# ---------------------------------------------------------------------------

def spec_accept_and_sample(rngs: jnp.ndarray, logits: jnp.ndarray,
                           window: jnp.ndarray, n_valid: jnp.ndarray,
                           temperature: jnp.ndarray, top_k: jnp.ndarray,
                           top_p: jnp.ndarray, greedy: jnp.ndarray
                           ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Per-slot speculative acceptance + the committed-token layout.

    logits: (B, W, V) f32 from ``verify_step_multi`` (position j scores
    the token after window token j); window: (B, W) int32; n_valid:
    (B,) int32 — drafts beyond it are padding; per-slot sampling params
    as in ``sample_tokens_batched``; rngs: (B, key) per-slot streams.

    Returns ``(n_acc, out, new_rngs)``: ``n_acc[b]`` accepted drafts
    (0..n_valid[b]); ``out[b, :n_acc[b]+1]`` the committed tokens —
    accepted drafts followed by the correction token (resampled from
    the draft-masked renormalized target at the first rejection) or the
    bonus token (sampled from the full target after total acceptance).
    Greedy rows use raw-logits argmax for acceptance AND for the
    correction/bonus token, exactly ``sample_tokens_batched``'s greedy
    mode — so a greedy slot's stream is the non-speculative stream.
    """
    B, W, V = logits.shape
    offs = jnp.arange(W, dtype=jnp.int32)[None, :]          # (1, W)
    # candidate at logits position j is window token j+1 (pad last col)
    cand = jnp.concatenate(
        [window[:, 1:], jnp.zeros((B, 1), window.dtype)], axis=1)
    next_raw = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # (B, W)

    flat = logits.reshape(B * W, V)
    rep = lambda a: jnp.repeat(jnp.asarray(a), W)           # noqa: E731
    f = filter_logits_batched(flat, rep(temperature), rep(top_k),
                              rep(top_p)).reshape(B, W, V)
    logp = jax.nn.log_softmax(f, axis=-1)
    p_acc = jnp.exp(jnp.take_along_axis(
        logp, cand[..., None].astype(jnp.int32), axis=-1))[..., 0]

    def per_slot(key):
        ku, kc, kb, knext = jax.random.split(key, 4)
        return jax.random.uniform(ku, (W,)), kc, kb, knext

    u, ckeys, bkeys, new_rngs = jax.vmap(per_slot)(rngs)
    greedy_b = jnp.asarray(greedy, bool)[:, None]
    accept = jnp.where(greedy_b, next_raw == cand, u < p_acc)
    valid = offs < n_valid[:, None]
    chain = jnp.cumprod((accept & valid).astype(jnp.int32), axis=1)
    n_acc = jnp.sum(chain, axis=1).astype(jnp.int32)

    # only position r = n_acc per row emits a sampled token, so gather
    # its distribution first and draw ONE correction + ONE bonus
    # categorical per row (not per window position)
    take = lambda a: jnp.take_along_axis(a, n_acc[:, None], axis=1)[:, 0]  # noqa: E731
    f_r = jnp.take_along_axis(
        f, n_acc[:, None, None], axis=1)[:, 0, :]            # (B, V)
    cand_r, raw_r = take(cand), take(next_raw)
    # correction: target with the rejected draft masked out, renormalized
    # (NEG_INF, not -inf: a fully-masked row must stay NaN-free; it is
    # only reachable when acceptance was certain, so it is never used)
    masked_r = jnp.where(
        jax.lax.broadcasted_iota(jnp.int32, (B, V), 1)
        == cand_r[:, None], NEG_INF, f_r)
    cat = jax.vmap(jax.random.categorical)
    corr = cat(ckeys, masked_r).astype(jnp.int32)
    bonus = cat(bkeys, f_r).astype(jnp.int32)
    final = jnp.where(jnp.asarray(greedy, bool), raw_r,
                      jnp.where(n_acc < n_valid, corr, bonus))
    out = jnp.where(offs < n_acc[:, None], cand,
                    jnp.where(offs == n_acc[:, None], final[:, None], 0)
                    ).astype(jnp.int32)
    return n_acc, out, new_rngs


# ---------------------------------------------------------------------------
# host-side drafter interface
# ---------------------------------------------------------------------------

@dataclass
class DraftContext:
    """Per-step host snapshot handed to ``Drafter.draft`` — built ONCE
    per engine step from the engine's host-side state (no per-slot
    device syncs: token histories are host bookkeeping and positions
    live in ``CachePool.positions``)."""

    tok: np.ndarray                    # (P,) int32 last committed token
    pos: np.ndarray                    # (P,) int32 per-slot positions
    active: np.ndarray                 # (P,) bool
    histories: Optional[List[Optional[np.ndarray]]] = None
    # per-slot prompt+generated token history; only materialized when
    # the drafter sets ``needs_history`` (the n-gram drafter)


class Drafter:
    """Host-side proposal source for speculative decoding.

    ``draft`` returns ``(tokens (P, k) int32, lens (P,) int32)`` —
    deterministic proposals per slot; the engine further clamps lens by
    cache room and token budget. Lifecycle hooks mirror slot admission
    so stateful drafters (the model drafter's pooled KV cache) stay in
    sync with the engine's pool.
    """

    name = "base"
    needs_history = False

    def __init__(self, k: int):
        assert k >= 1, k
        self.k = k

    def on_admit(self, slot: int, prompt: np.ndarray) -> None:
        pass

    def on_release(self, slot: int) -> None:
        pass

    def resync(self, slot: int, history: np.ndarray) -> None:
        """Rebuild the drafter's per-slot state from the slot's full
        committed history (prompt + generated). The engine calls this
        when re-enabling a drafter after a degraded window: tokens were
        committed by the plain decode path while the drafter sat idle,
        so a stateful drafter's cache is behind the frontier. The
        default treats the history as a fresh admission — which is
        exactly a chunked re-prefill for the model drafter and a no-op
        for the stateless n-gram drafter."""
        self.on_admit(slot, history)

    def draft(self, ctx: DraftContext) -> Tuple[np.ndarray, np.ndarray]:
        raise NotImplementedError


class NGramDrafter(Drafter):
    """Prompt-lookup drafting: propose the continuation of the most
    recent earlier occurrence of the slot's trailing n-gram, falling
    back to shorter n-grams down to 1; no match (or a <2-token history)
    proposes nothing. Pure host numpy over histories <= block_size —
    microseconds next to a model forward."""

    name = "ngram"
    needs_history = True

    def __init__(self, k: int, ngram: int = 3):
        super().__init__(k)
        assert ngram >= 1, ngram
        self.ngram = ngram

    def _lookup(self, history: np.ndarray) -> np.ndarray:
        L = int(history.size)
        for n in range(min(self.ngram, L - 1), 0, -1):
            pat = history[L - n:]
            win = np.lib.stride_tricks.sliding_window_view(history, n)
            hits = np.nonzero((win == pat).all(axis=1))[0]
            hits = hits[hits < L - n]          # exclude the suffix itself
            if hits.size:
                i = int(hits[-1])
                cont = history[i + n:i + n + self.k]
                if cont.size:
                    return cont.astype(np.int32)
        return np.empty((0,), np.int32)

    def draft(self, ctx: DraftContext) -> Tuple[np.ndarray, np.ndarray]:
        P = ctx.tok.shape[0]
        toks = np.zeros((P, self.k), np.int32)
        lens = np.zeros((P,), np.int32)
        for slot in range(P):
            if not ctx.active[slot] or ctx.histories[slot] is None:
                continue
            cont = self._lookup(ctx.histories[slot])
            toks[slot, :cont.size] = cont
            lens[slot] = cont.size
        return toks, lens


# module-level jits (like the engine's): programs accumulate across
# drafter instances, steady-state enforcement is per-drafter CompileGuard
@partial(jax.jit, static_argnames=("cfg",), donate_argnames=("cache",))
def _draft_prefill(params, chunk, offset, slot, cache, cfg: ModelConfig):
    return prefill_chunk_into_slot(params, chunk, offset, slot, cache, cfg)


@partial(jax.jit, static_argnames=("cfg", "k"), donate_argnames=("cache",))
def _draft_decode_k(params, tok, pos, active, cache, cfg: ModelConfig,
                    k: int):
    """k greedy draft proposals per slot in ONE dispatch (lax.scan over
    ``decode_step_multi``). Greedy on purpose: proposals are point-mass,
    which keeps the acceptance rule exact for every target sampling
    mode (module docstring).

    The scan runs k+1 iterations, not k: iteration j writes K/V for
    window token j at pos+j, so stopping at k would leave the k-th
    proposal's K/V unwritten — and after a FULL acceptance the engine's
    frontier jumps past that position, which the draft cache would then
    hold stale prefill-padding for, silently degrading every later
    proposal for the request (exactly in the drafter's best case). The
    extra iteration commits d_k's K/V, making the draft cache's writes
    mirror the verify window's; its own proposal is discarded. Slots
    whose positions run off the cache buffer mid-scan write nothing
    (scatter drops out-of-bounds updates) and their surplus proposals
    are clamped away host-side."""
    pos0 = jnp.where(active, pos, 0)

    def body(carry, _):
        tok, pos, cache = carry
        logits, cache = decode_step_multi(params, tok, pos, cache, cfg)
        nxt = jnp.where(active, jnp.argmax(logits, axis=-1)
                        .astype(jnp.int32), 0)
        return (nxt, pos + 1, cache), nxt

    (_, _, cache), toks = jax.lax.scan(
        body, (tok, pos0, cache), None, length=k + 1)
    return toks[:k].T, cache                   # (B, k)


class ModelDrafter(Drafter):
    """Small-model drafter: a second ``ModelConfig`` + params with its
    own pooled KV cache, same slot ids as the engine pool. Per engine
    step it drafts k tokens per slot greedily in one jitted scan; per
    admission it chunk-prefills the prompt into its own slot region.
    The draft cache needs no post-verification repair: accepted tokens
    ARE the drafted tokens and the draft scan writes K/V for the whole
    window [tok, d_1..d_k] (see ``_draft_decode_k``'s k+1-iteration
    note), so K/V up to and including each slot's committed frontier is
    always for the committed stream; everything past it is overwritten
    before being attended (pool invariant). With draft params == target
    params this makes greedy acceptance exact — pinned as a regression
    test for the cache-alignment property."""

    name = "model"

    def __init__(self, params, cfg: ModelConfig, k: int, pool_size: int,
                 prefill_chunk: int = 0):
        super().__init__(k)
        cfg.validate()
        self.params = params
        self.cfg = cfg
        self.pool_size = pool_size
        self._chunk = prefill_chunk_size(prefill_chunk, cfg.block_size)
        self.cache = commit_default(init_kv_cache(cfg, pool_size))
        self._decode_guard = CompileGuard(_draft_decode_k, "spec/draft")
        self._prefill_guard = CompileGuard(_draft_prefill,
                                           "spec/draft-prefill")

    @property
    def n_params(self) -> int:
        return param_count(self.params)

    def on_admit(self, slot: int, prompt: np.ndarray) -> None:
        P = int(prompt.size)
        S = self.cfg.block_size
        chunk = self._chunk
        n_chunks = -(-P // chunk)
        # same clamp-corruption bound as Engine._admit (lint GL006)
        check_in_bounds((n_chunks - 1) * chunk, chunk, S,
                        what=f"draft prefill of {P}-token prompt")
        padded = np.zeros((n_chunks * chunk,), np.int32)
        padded[:P] = prompt
        cache = self.cache
        for c in range(n_chunks):
            cache = self._prefill_guard(
                self.params,
                jnp.asarray(padded[None, c * chunk:(c + 1) * chunk]),
                jnp.int32(c * chunk), jnp.int32(slot), cache, self.cfg)
        self.cache = cache

    def draft(self, ctx: DraftContext) -> Tuple[np.ndarray, np.ndarray]:
        toks, cache = self._decode_guard(
            self.params, jnp.asarray(ctx.tok), jnp.asarray(ctx.pos),
            jnp.asarray(ctx.active), self.cache, self.cfg, self.k)
        self.cache = cache
        out = np.asarray(toks)                 # one snapshot per step
        lens = np.where(ctx.active, self.k, 0).astype(np.int32)
        return out, lens

    def compile_stats(self) -> dict:
        return {"decode": self._decode_guard.stats(),
                "prefill": self._prefill_guard.stats()}


# ---------------------------------------------------------------------------
# construction helpers (CLI / bench / replay)
# ---------------------------------------------------------------------------

def draft_config_from_preset(target: ModelConfig,
                             preset: str) -> ModelConfig:
    """A drafter ``ModelConfig`` from a named preset, forced compatible
    with the target: same vocab (proposals must be valid target ids),
    same block_size (slot regions line up), same compute dtype and
    cache layout (one set of engine invariants)."""
    import dataclasses

    from ..config import get_config
    base = get_config(preset).model
    return dataclasses.replace(
        base, vocab_size=target.vocab_size, block_size=target.block_size,
        dtype=target.dtype, decode_cache_layout=target.decode_cache_layout)


def make_drafter(mode: str, k: int, ngram: int, pool_size: int,
                 draft_params=None, draft_cfg: Optional[ModelConfig] = None,
                 prefill_chunk: int = 0) -> Optional[Drafter]:
    """Drafter factory: ``mode`` is 'off' | 'ngram' | 'model'. The model
    mode needs ``draft_params``/``draft_cfg`` (see
    ``draft_config_from_preset``). Called once per Engine — drafters
    are stateful (per-slot caches, compile guards)."""
    if mode in ("off", "", None):
        return None
    if mode == "ngram":
        return NGramDrafter(k, ngram=ngram)
    if mode == "model":
        if draft_params is None or draft_cfg is None:
            raise ValueError("mode='model' needs draft_params and draft_cfg")
        return ModelDrafter(draft_params, draft_cfg, k, pool_size,
                            prefill_chunk=prefill_chunk)
    raise ValueError(f"unknown drafter mode {mode!r}")


def timed_draft(drafter: Drafter, ctx: DraftContext,
                vocab_size: int = 0, tel=NULL,
                track: int = ENGINE_TRACK
                ) -> Tuple[np.ndarray, np.ndarray, float]:
    """``drafter.draft`` + wall-clock overhead (seconds) — the engine
    records it per step so the drafter's cost is visible next to the
    verify step it amortizes. ``tel`` (utils.telemetry) additionally
    records the draft phase as a span on the engine track, so the
    drafter's host cost sits on the same timeline as the verify step
    it feeds.

    Chaos seam ``spec/draft`` (kind ``collapse``): shifts every proposed
    token by one (mod the vocab), turning the drafter's proposals into
    deterministic garbage — the accept rate collapses toward zero while
    every token stays a valid vocab id, which is exactly the failure the
    engine's speculative auto-disable must catch. No-op without an
    installed FaultPlan."""
    t0_us = tel.now_us() if tel.enabled else 0.0
    t0 = time.perf_counter()
    toks, lens = drafter.draft(ctx)
    f = fault_fire("spec/draft")
    if f is not None and f.kind == "collapse" and vocab_size > 1:
        toks = (toks + 1) % vocab_size
    dt = time.perf_counter() - t0
    if tel.enabled:
        tel.complete("draft", track, t0_us, dt * 1e6,
                     drafter=drafter.name, k=drafter.k)
    return toks, lens, dt
