"""HTTP/SSE front door: the fleet's network edge, stdlib-asyncio only.

Everything used to enter the engine through replay traces; this module
is the real ingress path — a thin asyncio HTTP server that maps
directly onto the router/engine host API, adding **no new scheduling
semantics**: backpressure is the Scheduler's bounded queue surfacing as
429s, deadlines are request fields, cancellation (explicit or by client
disconnect mid-stream) is ``Router.cancel`` — which releases the
request's slot and KV pages immediately — and crash recovery is the
per-replica journals behind the router. The zero-egress image cannot
take outside traffic, so the server binds loopback and is exercised by
tier-1 tests speaking real HTTP over real sockets.

Endpoints (all JSON unless noted):

- ``POST /v1/submit`` — body ``{"prompt": [ids], "id"?, "max_new_tokens"?,
  "temperature"?, "top_k"?, "top_p"?, "greedy"?, "rng_seed"?,
  "eos_token_id"?,
  "deadline_s"?}``; 200 ``{"id", "status": "accepted"}`` or an error
  status from the rejection reason (429 backpressure, 400 validation,
  413 prompt too long, 504 dead-on-arrival deadline).
- ``GET /v1/stream/{id}`` — ``text/event-stream``: one ``data:
  {"token": t, "i": n}`` event per token as steps commit them, then
  ``event: done`` with the terminal summary. Exactly-once across a
  replica kill mid-stream (the router's delivery ledger). One consumer
  per request id — the ledger is the dedupe state.
- ``POST /v1/generate`` — submit + stream in one round trip.
- ``POST /v1/cancel/{id}`` — ``{"cancelled": bool}``.
- ``GET /v1/result/{id}`` — non-streaming terminal result (202 while
  running; popping it frees the id).
- ``GET /healthz`` — **liveness**: 200 whenever the server process is
  up and answering (per-replica detail rides along). A process that
  cannot answer this is dead; restart it.
- ``GET /readyz`` — **readiness**: 200 iff ≥ 1 routable *warmed*
  replica can take traffic, else 503 — including during a rolling
  restart's last-survivor drain window. External supervisors gate
  traffic on THIS, not on /healthz (a live router with zero ready
  workers must be drained from the load balancer, not restarted).
- ``GET /metrics`` — Prometheus text exposition of the router metrics
  (fleet counters + per-replica gauges; utils.telemetry).

The server is single-threaded asyncio on purpose: the engine/router
host API is single-threaded by design, and one driver task calling
``router.step()`` between socket reads is exactly the replay loop with
sockets for arrivals. A step blocks the loop for one dispatch — the
same latency floor every request already pays. In multi-process mode
the same driver task also ticks the process supervisor
(faults/procsup.py) after every step, so worker restarts progress
even while the fleet is idle.

Untrusted-peer hygiene: a client that opens a connection and never
completes its headers (slow-loris), stalls mid-body, or stops
consuming its SSE stream is dropped after ``idle_timeout_s`` — a
handler task and its buffers are capacity, and a peer that is not
making progress does not get to pin them forever.

Per-client rate limiting (:class:`RateLimitConfig`): the submit paths
(``/v1/submit``, ``/v1/generate``) meter a token bucket per client id
(the ``x-client-id`` header; missing header = one shared anonymous
bucket) BEFORE parsing the body — an over-rate client gets 429 with a
``Retry-After`` header and never costs a JSON parse or a router
submit. This is *fairness* backpressure (one greedy tenant must not
consume every queue slot), distinct from the Scheduler's *capacity*
backpressure (a full queue 429s everyone).
"""

from __future__ import annotations

import asyncio
import itertools
import json
import math
from dataclasses import dataclass
from typing import Dict, Optional, Tuple

import numpy as np

from ..utils.telemetry import prometheus_text
from .requests import (FINISH_DEADLINE, REJECT_BAD_REQUEST,
                       REJECT_PROMPT_TOO_LONG, REJECT_QUEUE_FULL, Request,
                       SamplingParams)
from .router import (REJECT_FLEET_CAPACITY, REJECT_REPLICA_TIMEOUT,
                     Router)
from .rpc import REJECT_REPLICA_DOWN

#: rejection reason -> HTTP status for the submit path
REASON_STATUS = {
    REJECT_QUEUE_FULL: 429,
    REJECT_FLEET_CAPACITY: 429,
    REJECT_BAD_REQUEST: 400,
    REJECT_PROMPT_TOO_LONG: 413,
    FINISH_DEADLINE: 504,
    # every candidate replica unreachable/hung at submit time: a
    # try-later server condition, not a client error
    REJECT_REPLICA_DOWN: 503,
    REJECT_REPLICA_TIMEOUT: 503,
}

_STATUS_TEXT = {200: "OK", 202: "Accepted", 400: "Bad Request",
                404: "Not Found", 405: "Method Not Allowed",
                408: "Request Timeout", 413: "Payload Too Large",
                429: "Too Many Requests",
                500: "Internal Server Error", 503: "Service Unavailable",
                504: "Gateway Timeout"}


@dataclass(frozen=True)
class RateLimitConfig:
    """Per-client token-bucket sizing for the submit paths. ``rps`` is
    the sustained refill rate (0 disables the limiter entirely);
    ``burst`` the bucket capacity — how many submits a quiet client may
    fire back-to-back. ``header`` names the client-id header; a request
    without it shares one anonymous bucket (anonymous traffic competes
    with itself, never with identified tenants). ``max_clients`` bounds
    the bucket table — the id is an UNTRUSTED string, and without a cap
    a peer minting fresh ids per request would grow the table without
    limit."""

    rps: float = 0.0
    burst: float = 10.0
    header: str = "x-client-id"
    max_clients: int = 4096


class _TokenBuckets:
    """The bucket table: lazily-refilled continuous token buckets keyed
    by client id. ``take`` returns 0.0 on admit (one token consumed) or
    the seconds until a token accrues (the Retry-After value). Stale
    entries (fully refilled = client gone quiet) are reclaimed when the
    table hits ``max_clients``; if every entry is active the OLDEST
    refill is dropped — an attacker minting ids can only evict its own
    churn, an active tenant's bucket refills on its next request at
    worst."""

    def __init__(self, cfg: RateLimitConfig, clock):
        self.cfg = cfg
        self.clock = clock
        self._b: Dict[str, Tuple[float, float]] = {}  # id -> (tokens, t)

    def take(self, client: str) -> float:
        now = self.clock()
        tokens, t = self._b.get(client, (self.cfg.burst, now))
        tokens = min(self.cfg.burst,
                     tokens + (now - t) * self.cfg.rps)
        if tokens >= 1.0:
            if client not in self._b and \
                    len(self._b) >= self.cfg.max_clients:
                self._evict(now)
            self._b[client] = (tokens - 1.0, now)
            return 0.0
        self._b[client] = (tokens, now)
        return (1.0 - tokens) / max(self.cfg.rps, 1e-9)

    def _evict(self, now: float) -> None:
        full = [k for k, (tok, t) in self._b.items()
                if tok + (now - t) * self.cfg.rps >= self.cfg.burst]
        if full:
            for k in full:
                del self._b[k]
            return
        del self._b[min(self._b, key=lambda k: self._b[k][1])]


def request_from_json(body: dict, default_id: str, clock,
                      vocab: int = 0) -> Tuple[Optional[Request],
                                               Optional[str]]:
    """Build a :class:`Request` from a submit body; (None, error) on a
    malformed one. Validation beyond shape (empty prompt, too-long
    prompt) is the Scheduler's job — the front door only refuses what
    it cannot even construct. ``vocab`` bounds the token ids (0 skips
    the check): this is the first untrusted boundary, and an
    out-of-range id would otherwise be silently clamped by the
    embedding gather into a 200 with garbage output."""
    prompt = body.get("prompt")
    if (not isinstance(prompt, list) or
            not all(isinstance(t, int) and not isinstance(t, bool)
                    and 0 <= t and (not vocab or t < vocab)
                    for t in prompt)):
        return None, ("prompt must be a list of token ids in "
                      f"[0, {vocab})" if vocab else
                      "prompt must be a list of non-negative token ids")
    rid = body.get("id", default_id)
    if not isinstance(rid, str) or not rid:
        return None, "id must be a non-empty string"
    try:
        deadline = None
        if body.get("deadline_s"):
            deadline = clock() + float(body["deadline_s"])
        req = Request(
            id=rid, prompt=np.asarray(prompt, np.int32),
            max_new_tokens=int(body.get("max_new_tokens", 16)),
            sampling=SamplingParams(
                temperature=float(body.get("temperature", 1.0)),
                top_k=int(body.get("top_k", 0)),
                top_p=float(body.get("top_p", 0.0)),
                greedy=bool(body.get("greedy", False))),
            deadline=deadline,
            rng_seed=int(body.get("rng_seed", 0)),
            eos_token_id=(None if body.get("eos_token_id") is None
                          else int(body["eos_token_id"])))
    except (TypeError, ValueError) as e:
        return None, f"bad request field: {e}"
    return req, None


class ServeApp:
    """The front door: one router, one asyncio server, one driver task.

    ``step_wait_s`` bounds how long an SSE handler waits for the next
    step wakeup before re-checking terminal state (a safety net around
    missed wakeups, not a poll interval); ``idle_sleep_s`` is the
    driver's sleep when the fleet is idle. ``idle_timeout_s`` is the
    slow-loris budget: a peer that stalls mid-headers, mid-body, or
    mid-SSE-consumption is dropped after it (0 disables).
    ``supervisor`` (faults.procsup.ProcSupervisor) is ticked by the
    driver after every step — multi-process fleets only.
    """

    def __init__(self, router: Router, idle_sleep_s: float = 0.002,
                 step_wait_s: float = 0.5,
                 idle_timeout_s: float = 30.0, supervisor=None,
                 rate_limit: Optional[RateLimitConfig] = None):
        self.router = router
        self.idle_sleep_s = idle_sleep_s
        self.step_wait_s = step_wait_s
        self.idle_timeout_s = idle_timeout_s
        self.supervisor = supervisor
        self.rate_limit = rate_limit
        self._buckets = (_TokenBuckets(rate_limit, router.clock)
                         if rate_limit and rate_limit.rps > 0 else None)
        self._vocab: Optional[int] = None
        self._ids = itertools.count()
        self._running = False
        self._step_fut: Optional[asyncio.Future] = None
        self._server: Optional[asyncio.AbstractServer] = None
        self._driver: Optional[asyncio.Future] = None
        #: ids whose client disconnected mid-stream: nobody will ever
        #: pop their terminal result, so the driver pops it the moment
        #: it surfaces (pop_result's no-unbounded-growth invariant)
        self._abandoned: set = set()

    # ------------------------------------------------------------- driver

    async def _drive(self) -> None:
        """Step the router whenever it has work; wake SSE streams after
        every step (they read the delivery ledger, not engine state)."""
        loop = asyncio.get_running_loop()
        self._step_fut = loop.create_future()
        while self._running:
            if self.router.idle:
                # restarts/backoffs must progress while the fleet waits
                if self.supervisor is not None:
                    self.supervisor.tick()
                await asyncio.sleep(self.idle_sleep_s)
                continue
            self.router.step()
            if self.supervisor is not None:
                self.supervisor.tick()
            for rid in [r for r in self._abandoned
                        if not self.router.knows(r)
                        or self.router.result(r) is not None]:
                self.router.pop_result(rid)
                self._abandoned.discard(rid)
            fut, self._step_fut = self._step_fut, loop.create_future()
            fut.set_result(None)
            await asyncio.sleep(0)         # let handlers consume

    async def _next_step(self) -> None:
        fut = self._step_fut
        if fut is None:
            await asyncio.sleep(self.idle_sleep_s)
            return
        try:
            await asyncio.wait_for(asyncio.shield(fut),
                                   timeout=self.step_wait_s)
        except asyncio.TimeoutError:
            pass

    # ------------------------------------------------------------- server

    async def start(self, host: str = "127.0.0.1",
                    port: int = 0) -> Tuple[str, int]:
        """Bind + start serving; returns the bound (host, port)
        (port 0 = ephemeral, for tests)."""
        self._running = True
        self._server = await asyncio.start_server(self._handle, host,
                                                  port)
        self._driver = asyncio.ensure_future(self._drive())
        self._driver.add_done_callback(self._on_driver_done)
        sock = self._server.sockets[0].getsockname()
        return sock[0], sock[1]

    def _on_driver_done(self, fut: asyncio.Future) -> None:
        """A dead driver is a dead server: without this callback an
        exception from ``router.step()`` sits in the never-awaited
        future while the server keeps accepting connections that can
        never complete. Surface it loudly and fail every waiter."""
        if fut.cancelled():
            return
        exc = fut.exception()
        if exc is None:
            return
        import sys
        import traceback
        self._running = False
        print("serve driver task died; shutting down:", file=sys.stderr)
        traceback.print_exception(type(exc), exc, exc.__traceback__,
                                  file=sys.stderr)
        # wake every SSE handler blocked on the next step with the
        # failure (they fail their connection instead of spinning on
        # the step_wait_s timeout forever)
        if self._step_fut is not None and not self._step_fut.done():
            self._step_fut.set_exception(exc)
        if self._server is not None:
            self._server.close()

    async def stop(self) -> None:
        self._running = False
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
        try:
            if self._driver is not None:
                self._driver.cancel()
                try:
                    await self._driver
                except asyncio.CancelledError:
                    pass
        finally:
            # a driver that died re-raises above — the journals still
            # close
            self.router.close()

    async def serve_forever(self, host: str, port: int) -> None:
        h, p = await self.start(host, port)
        import sys
        print(f"serving on http://{h}:{p} "
              f"({self.router.rcfg.n_replicas} replica(s))",
              file=sys.stderr)
        async with self._server:
            await self._server.serve_forever()

    # ----------------------------------------------------------- handlers

    async def _read_request(self, reader: asyncio.StreamReader):
        """Read one request (start line + headers + body); None on an
        unparseable start line. Raises ValueError on malformed framing,
        IncompleteReadError/ConnectionError on a vanished peer."""
        line = await reader.readline()
        parts = line.decode("latin-1").split()
        if len(parts) < 2:
            return None
        method, path = parts[0].upper(), parts[1]
        headers = {}
        while True:
            h = await reader.readline()
            if h in (b"\r\n", b"\n", b""):
                break
            k, _, v = h.decode("latin-1").partition(":")
            headers[k.strip().lower()] = v.strip()
        n = int(headers.get("content-length", "0") or 0)
        body = b""
        if n:
            body = await reader.readexactly(n)
        return method, path, body, headers

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        try:
            try:
                # the slow-loris budget: a peer must DELIVER a complete
                # request within idle_timeout_s or lose the connection —
                # half-sent headers / a stalled body must not pin this
                # handler task forever
                req = await asyncio.wait_for(
                    self._read_request(reader),
                    self.idle_timeout_s or None)
            except asyncio.TimeoutError:
                await self._json(writer, 408,
                                 {"error": "request idle timeout"})
                return
            except ValueError:
                # a request/header line over the StreamReader limit
                # (readline raises ValueError) or a non-numeric
                # Content-Length — answer 400, don't drop the socket
                await self._json(writer, 400,
                                 {"error": "malformed request"})
                return
            if req is None:
                return
            method, path, body, headers = req
            await self._dispatch(method, path.split("?", 1)[0], body,
                                 writer, headers)
        except (ConnectionError, asyncio.IncompleteReadError):
            pass
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except (ConnectionError, OSError):
                pass

    async def _dispatch(self, method: str, path: str, body: bytes,
                        writer: asyncio.StreamWriter,
                        headers: Optional[dict] = None) -> None:
        if (self._buckets is not None and method == "POST"
                and path in ("/v1/submit", "/v1/generate")):
            client = (headers or {}).get(self.rate_limit.header,
                                         "") or "anonymous"
            wait_s = self._buckets.take(client)
            if wait_s > 0:
                self.router.metrics.inc("http_rate_limited")
                await self._json(
                    writer, 429,
                    {"error": "rate limited",
                     "client": client,
                     "retry_after_s": round(wait_s, 3)},
                    extra_headers={"Retry-After":
                                   str(max(1, math.ceil(wait_s)))})
                return
        if path == "/healthz" and method == "GET":
            # liveness: answering at all IS the signal — always 200
            await self._json(writer, 200, self.router.healthz())
        elif path == "/readyz" and method == "GET":
            r = self.router.readyz()
            await self._json(writer, 200 if r["ok"] else 503, r)
        elif path in ("/metrics", "/v1/metrics") and method == "GET":
            text = prometheus_text(self.router.metrics,
                                   prefix="tpu_gpt_fleet")
            await self._raw(writer, 200, text.encode(),
                            "text/plain; version=0.0.4")
        elif path == "/v1/submit" and method == "POST":
            rid, err = self._submit(body)
            if err is not None:
                await self._json(writer, err[0], {"error": err[1]})
            else:
                await self._json(writer, 200,
                                 {"id": rid, "status": "accepted"})
        elif path == "/v1/generate" and method == "POST":
            rid, err = self._submit(body)
            if err is not None:
                await self._json(writer, err[0], {"error": err[1]})
            else:
                await self._stream(rid, writer)
        elif path.startswith("/v1/stream/") and method == "GET":
            rid = path[len("/v1/stream/"):]
            if (not self.router.knows(rid)):
                await self._json(writer, 404, {"error": "unknown id"})
            else:
                await self._stream(rid, writer)
        elif path.startswith("/v1/cancel/") and method == "POST":
            rid = path[len("/v1/cancel/"):]
            await self._json(writer, 200,
                             {"id": rid,
                              "cancelled": self.router.cancel(rid)})
        elif path.startswith("/v1/result/") and method == "GET":
            rid = path[len("/v1/result/"):]
            res = self.router.result(rid)
            if res is not None:
                self.router.pop_result(rid)
                await self._json(writer, 200,
                                 {**res.to_dict(), "tokens": res.tokens})
            elif self.router.knows(rid):
                await self._json(writer, 202, {"id": rid,
                                               "status": "running"})
            else:
                await self._json(writer, 404, {"error": "unknown id"})
        else:
            await self._json(writer, 404 if method in ("GET", "POST")
                             else 405, {"error": f"no route {method} "
                                                 f"{path}"})

    def _vocab_size(self) -> int:
        """Token-id bound for ingress validation. Local replicas carry
        an engine; remote workers report it over the health RPC once
        (cached — 0, skipping the check, only if no worker has ever
        been reachable)."""
        if self._vocab:
            return self._vocab
        for rep in self.router.replicas:
            if rep.is_local:
                self._vocab = int(rep.engine.cfg.vocab_size)
                return self._vocab
            try:
                # short budget: this runs inside a submit handler on
                # the single-threaded loop — a hung worker must not
                # stall every connection for the full RPC timeout
                self._vocab = int(rep.refresh_health(timeout_s=1.0)
                                  .get("vocab_size", 0))
                if self._vocab:
                    return self._vocab
            except Exception:  # noqa: BLE001 — unreachable worker;
                continue       # try the next, or skip the check
        return 0

    def _submit(self, body: bytes):
        """Parse + route one submit; returns (id, None) or
        (None, (status, message))."""
        try:
            doc = json.loads(body.decode() or "{}")
        except (ValueError, UnicodeDecodeError):
            return None, (400, "body is not valid JSON")
        if not isinstance(doc, dict):
            return None, (400, "body must be a JSON object")
        req, perr = request_from_json(
            doc, f"h{next(self._ids):06d}", self.router.clock,
            vocab=self._vocab_size())
        if req is None:
            return None, (400, perr)
        rej = self.router.submit(req)
        if rej is not None:
            status = REASON_STATUS.get(rej.finish_reason, 400)
            return None, (status, rej.finish_reason)
        return req.id, None

    def _emit_new_tokens(self, rid: str,
                         writer: asyncio.StreamWriter, i: int) -> int:
        """Drain the delivery ledger into SSE events; returns the next
        event index."""
        for t in self.router.take_new_tokens(rid):
            writer.write(f"data: {json.dumps({'token': t, 'i': i})}"
                         f"\n\n".encode())
            i += 1
        return i

    async def _drain_sse(self, writer: asyncio.StreamWriter) -> None:
        """drain() with the idle budget: an SSE consumer that stopped
        reading (buffer past the high-water mark, drain suspended
        forever) is indistinguishable from a vanished one — treat it
        as one instead of pinning the handler and the send buffer."""
        try:
            await asyncio.wait_for(writer.drain(),
                                   self.idle_timeout_s or None)
        except asyncio.TimeoutError:
            raise ConnectionError("SSE consumer stalled past the idle "
                                  "budget") from None

    async def _stream(self, rid: str,
                      writer: asyncio.StreamWriter) -> None:
        """SSE token stream through the router's exactly-once delivery
        ledger; a client disconnect mid-stream cancels the request —
        its slot and KV pages free immediately, not at completion."""
        writer.write(b"HTTP/1.1 200 OK\r\n"
                     b"Content-Type: text/event-stream\r\n"
                     b"Cache-Control: no-cache\r\n"
                     b"Connection: close\r\n\r\n")
        i = 0
        try:
            await self._drain_sse(writer)
            while True:
                i = self._emit_new_tokens(rid, writer, i)
                await self._drain_sse(writer)
                res = self.router.result(rid)
                if res is not None:
                    # final ledger drain: the request may have finished
                    # (with more tokens) while we were suspended in
                    # drain() above — those must go out before `done`
                    i = self._emit_new_tokens(rid, writer, i)
                    done = {"finish_reason": res.finish_reason,
                            "n_tokens": len(res.tokens),
                            "ttft_s": round(res.ttft_s, 6),
                            "total_s": round(res.total_s, 6)}
                    writer.write(f"event: done\ndata: "
                                 f"{json.dumps(done)}\n\n".encode())
                    await self._drain_sse(writer)
                    self.router.pop_result(rid)
                    return
                if not self.router.knows(rid):
                    writer.write(b"event: error\ndata: "
                                 b"{\"error\": \"request lost\"}\n\n")
                    await self._drain_sse(writer)
                    return
                await self._next_step()
        except (ConnectionError, OSError):
            # client went away mid-stream: release the slot/pages NOW,
            # and hand the id to the driver's abandoned sweep — the
            # cancelled (or already-terminal) result must still be
            # popped or the results/ledger maps grow per disconnect
            if self.router.pop_result(rid) is None:
                self.router.cancel(rid)
                self._abandoned.add(rid)

    async def _json(self, writer, status: int, obj: dict,
                    extra_headers: Optional[dict] = None) -> None:
        await self._raw(writer, status,
                        (json.dumps(obj) + "\n").encode(),
                        "application/json", extra_headers)

    async def _raw(self, writer, status: int, payload: bytes,
                   ctype: str,
                   extra_headers: Optional[dict] = None) -> None:
        extra = "".join(f"{k}: {v}\r\n"
                        for k, v in (extra_headers or {}).items())
        writer.write(
            f"HTTP/1.1 {status} {_STATUS_TEXT.get(status, '')}\r\n"
            f"Content-Type: {ctype}\r\n"
            f"Content-Length: {len(payload)}\r\n"
            f"{extra}"
            f"Connection: close\r\n\r\n".encode())
        writer.write(payload)
        await writer.drain()
