"""Paged KV cache with radix prefix reuse: the serving engine's memory
model.

The contiguous ``CachePool`` gives every slot a full ``block_size`` KV
buffer for its whole lifetime, so HBM — not compute — caps concurrent
occupancy, and every request pays full prefill even when thousands share
one system prompt. Here device KV storage is a pool of fixed-size PAGES
(``models.gpt.init_paged_kv_pool``) and each slot holds a fixed-shape
``(max_pages,)`` int32 page table: host-mirrored, device-fed as a traced
per-step input, so admissions / prefix hits / evictions / copy-on-write
never change a compiled program's shape (the zero-recompile steady state
survives paging — pinned in tests/test_pages.py).

Three host-side pieces:

- :class:`PageAllocator` — refcounted acquire/release of physical
  pages. A page's refcount counts SLOT references; pages referenced by
  the radix index alone (refcount 0) are the prefix cache, reclaimed
  LRU when allocation runs dry.
- :class:`RadixIndex` — a prefix tree over FULL pages of prompt tokens
  (node key = (parent, page-token bytes), so lookups are exact, not
  hash-collision-prone). Admission walks it to claim the longest cached
  prefix; chunked prefill then starts at the first uncached token.
- :class:`PagedCachePool` — the engine-facing pool: slot bookkeeping
  (drop-in for ``CachePool``'s host API) + page tables + the device
  page arrays.

Sharing discipline (what makes copy-on-write rare and safe): a full
prompt page is registered into the radix only once its owner's next
write position is PAST the page — the first decode step rewrites prompt
position P-1, so the page containing it is deferred until that write
lands. Shared pages are therefore never written through... with ONE
exception: a claimer whose ENTIRE prompt is cached starts decoding at
P-1, inside the last claimed page. That admission gets a copy-on-write
split — a fresh page, a device page copy, a remapped table entry — and
the shared original stays intact for the next claimer.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..config import ModelConfig
from ..models.gpt import init_paged_kv_pool
from ..utils.telemetry import NULL
from .cache_pool import commit_default


def default_page_size(requested: int, block_size: int) -> int:
    """Effective page size: the requested (0 = the vLLM-conventional 16)
    clamped to block_size. No divisibility requirement — the paged
    programs route every write per-position and drop out-of-range
    padding, so a ragged last logical page just holds fewer usable
    positions."""
    return min(requested or 16, block_size)


def pool_geometry(cfg: ModelConfig, n_slots: int, page_size: int = 0,
                  max_pages: int = 0,
                  n_pages: int = 0) -> Tuple[int, int, int]:
    """Resolve the (page_size, max_pages_per_slot, n_pages) triple from
    the EngineConfig knobs — ONE definition shared by the pool's
    constructor and the sharded engine, which must size the page pool's
    PartitionSpec (parallel.mesh.page_pool_pspec divisibility) BEFORE
    the pool allocates its device arrays."""
    psz = default_page_size(page_size, cfg.block_size)
    mp = max_pages or -(-cfg.block_size // psz)
    return psz, mp, (n_pages or n_slots * mp)


def page_bytes(cfg: ModelConfig, page_size: int, kv_quant: str = "none",
               granularity: str = "page") -> int:
    """HBM bytes ONE physical page occupies across all layers: K + V
    rows at the storage dtype, plus the per-row f32 scale metadata a
    quantized pool carries (quant/kv.py). This is the denominator of
    the admission-capacity claim: page count is the admission currency,
    so at a fixed HBM budget ``n_pages = budget // page_bytes`` — int8
    storage roughly halves this number vs bf16 (2·C bytes/token
    -> C + 8/page_size... the scale overhead is 8 bytes/token/layer at
    page granularity), roughly doubling the pool."""
    from ..quant.kv import kv_itemsize, scale_bytes_per_token
    per_tok = (2 * cfg.n_embd * kv_itemsize(kv_quant, cfg)
               + scale_bytes_per_token(kv_quant, granularity,
                                       cfg.n_head))
    return cfg.n_layer * page_size * per_tok


def n_pages_for_hbm(hbm_bytes: int, cfg: ModelConfig, page_size: int,
                    kv_quant: str = "none",
                    granularity: str = "page") -> int:
    """Physical pages a fixed HBM budget holds at the given KV storage
    mode — the fixed-HBM capacity comparison the quantization A/B
    (bench --quant-ab) and the pool-geometry acceptance test size
    their pools with."""
    return max(int(hbm_bytes) // page_bytes(cfg, page_size, kv_quant,
                                            granularity), 1)


class _RadixNode:
    __slots__ = ("id", "page", "parent", "key", "n_children", "last_use")

    def __init__(self, nid: int, page: int, parent: int,
                 key: Tuple[int, bytes]):
        self.id = nid
        self.page = page
        self.parent = parent
        self.key = key
        self.n_children = 0
        self.last_use = 0


class RadixIndex:
    """Prefix tree over full-page token runs -> physical pages.

    Every node is one FULL page of prompt tokens hanging off its
    parent's chain; edges are keyed by the page's exact token bytes
    (prefix identity, not a lossy hash). ``lookup`` walks the longest
    cached chain; eviction removes childless nodes only, so a surviving
    node's whole ancestry stays reachable.
    """

    ROOT = 0

    def __init__(self):
        self.nodes: Dict[int, _RadixNode] = {}
        self._edges: Dict[Tuple[int, bytes], int] = {}
        self._next_id = 1
        self._tick = 0

    def __len__(self) -> int:
        return len(self.nodes)

    def _touch(self, node: _RadixNode) -> None:
        self._tick += 1
        node.last_use = self._tick

    def lookup(self, prompt: np.ndarray, page_size: int,
               touch: bool = True) -> List[_RadixNode]:
        """Longest chain of cached full pages prefixing ``prompt`` (in
        order). ``touch`` refreshes LRU stamps — peeks (admission
        gating) pass False so a queued-but-unadmittable request cannot
        pin pages it never claims."""
        out: List[_RadixNode] = []
        parent = self.ROOT
        for g in range(int(prompt.size) // page_size):
            key = (parent, prompt[g * page_size:(g + 1) * page_size]
                   .tobytes())
            nid = self._edges.get(key)
            if nid is None:
                break
            node = self.nodes[nid]
            if touch:
                self._touch(node)
            out.append(node)
            parent = nid
        return out

    def insert(self, parent: int, tok_bytes: bytes,
               page: int) -> Tuple[_RadixNode, bool]:
        """Insert a full page under ``parent``; returns (node, inserted).
        An existing identical chain wins (two slots racing to register
        the same prompt): the caller's physical copy simply stays
        private and frees with its slot."""
        key = (parent, tok_bytes)
        nid = self._edges.get(key)
        if nid is not None:
            node = self.nodes[nid]
            self._touch(node)
            return node, False
        node = _RadixNode(self._next_id, page, parent, key)
        self._next_id += 1
        self.nodes[node.id] = node
        self._edges[key] = node.id
        if parent != self.ROOT:
            self.nodes[parent].n_children += 1
        self._touch(node)
        return node, True

    def remove(self, node: _RadixNode) -> None:
        assert node.n_children == 0, "evicting a non-leaf radix node"
        del self.nodes[node.id]
        del self._edges[node.key]
        if node.parent != self.ROOT and node.parent in self.nodes:
            self.nodes[node.parent].n_children -= 1


@dataclass
class PageClaim:
    """One slot's page reservation: the physical page per logical page
    (claimed prefix pages first, then fresh pages covering the prompt
    tail and the whole decode budget — reserved eagerly so an admitted
    request can never strand mid-decode on an empty pool)."""

    pages: List[int]
    claimed_tokens: int
    chain: List[int]                 # radix node ids along the prefix
    cow: List[Tuple[int, int]]       # (src, dst) device copies to apply
    prompt: np.ndarray
    next_reg: int                    # next full prompt page to register


class PageAllocator:
    """Refcounted physical-page allocator + radix prefix cache + LRU
    eviction. Pure host state — the device pool is the pool's concern —
    which is what makes the fuzz harness (tests/test_pages.py) cheap.
    """

    def __init__(self, n_pages: int, page_size: int,
                 prefix_cache: bool = True, telemetry=None):
        assert n_pages >= 1 and page_size >= 1
        self.n_pages = n_pages
        self.page_size = page_size
        self.prefix_cache = prefix_cache
        # prefix-hit / eviction instants on the request timeline
        # (utils.telemetry); NULL by default — zero cost, zero state
        self.tel = telemetry or NULL
        self._free: List[int] = list(range(n_pages - 1, -1, -1))
        self.ref = np.zeros((n_pages,), np.int32)
        self.radix = RadixIndex()
        self.page_node: Dict[int, _RadixNode] = {}   # phys -> radix node
        # counters surfaced through Engine.metrics_summary()["pages"]
        self.prefix_lookups = 0
        self.prefix_hits = 0
        self.prefix_hit_tokens = 0
        self.prompt_tokens = 0
        self.evictions = 0
        self.cow_copies = 0

    # ------------------------------------------------------------ sizing

    @property
    def pages_free(self) -> int:
        return len(self._free)

    @property
    def pages_in_use(self) -> int:
        return self.n_pages - len(self._free)

    def in_use_by_block(self, n_blocks: int) -> List[int]:
        """Pages in use per contiguous block of the physical page axis
        — exactly per-CHIP occupancy when the pool's page axis shards
        over the serving mesh's 'data' axis (NamedSharding assigns
        contiguous blocks), so the router's least-loaded signal and the
        Prometheus gauges stay meaningful on a mesh. 'In use' matches
        ``pages_in_use``: slot-referenced pages AND radix-held
        refcount-0 prefix pages (both occupy HBM)."""
        free = np.zeros((self.n_pages,), bool)
        free[np.fromiter(self._free, np.int64, len(self._free))] = True
        blk = -(-self.n_pages // n_blocks)
        return [int((~free[i * blk:(i + 1) * blk]).sum())
                for i in range(n_blocks)]

    def n_pages_for(self, n_prompt: int, cap: int) -> int:
        """Logical pages a request needs END TO END: the last write
        position is P-1 + cap-1 (decode rewrites the last prompt index
        first), so reserve ceil((P + cap - 1) / page)."""
        return -(-(n_prompt + cap - 1) // self.page_size)

    def _reclaimable(self, protect) -> int:
        """Pages reclaimable by cascaded LRU eviction: every refcount-0
        radix page not protected. (Claims cover whole prefixes, so
        ref[parent] >= ref[child] along any chain — a refcount-0 node
        heads a fully refcount-0 subtree and leaf-first eviction always
        reaches it.)"""
        return sum(1 for page in self.page_node
                   if self.ref[page] == 0 and page not in protect)

    def _evict_one(self, protect) -> Optional[int]:
        best: Optional[Tuple[int, _RadixNode]] = None
        for page, node in self.page_node.items():
            if node.n_children or self.ref[page] or page in protect:
                continue
            if best is None or node.last_use < best[1].last_use:
                best = (page, node)
        if best is None:
            return None
        page, node = best
        self.radix.remove(node)
        del self.page_node[page]
        self._free.append(page)
        self.evictions += 1
        self.tel.instant("page_evict", page=page)
        return page

    # ----------------------------------------------------------- acquire

    def _plan(self, prompt: np.ndarray, cap: int, touch: bool):
        chain = (self.radix.lookup(prompt, self.page_size, touch=touch)
                 if self.prefix_cache else [])
        need = self.n_pages_for(int(prompt.size), cap) - len(chain)
        # full-prompt hit: the first decode write (position P-1) lands
        # inside the last claimed page -> copy-on-write needs one more
        cow = bool(chain) and len(chain) * self.page_size == prompt.size
        if cow:
            need += 1
        return chain, need, cow

    def can_acquire(self, prompt: np.ndarray, cap: int) -> bool:
        chain, need, _ = self._plan(prompt, cap, touch=False)
        claimed = {n.page for n in chain}
        return need <= len(self._free) + self._reclaimable(claimed)

    def acquire(self, prompt: np.ndarray, cap: int) -> Optional[PageClaim]:
        """Claim the longest cached prefix + fresh pages for the rest of
        the request's lifetime; None when even LRU eviction cannot free
        enough pages. A failed acquire refreshes NO LRU stamps (the plan
        walks untouched; touching happens only on commit) — a caller
        probing with acquire() directly cannot pin prefix pages it never
        claims."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        chain, need, cow_needed = self._plan(prompt, cap, touch=False)
        protect = {n.page for n in chain}
        while len(self._free) < need:
            if self._evict_one(protect) is None:
                return None
        for node in chain:
            self.radix._touch(node)
        self.prefix_lookups += 1
        self.prompt_tokens += int(prompt.size)
        pages = [n.page for n in chain]
        for p in pages:
            self.ref[p] += 1
        cow: List[Tuple[int, int]] = []
        if cow_needed:
            dst = self._free.pop()
            src = pages[-1]
            self.ref[src] -= 1
            self.ref[dst] = 1
            pages[-1] = dst
            cow.append((src, dst))
            self.cow_copies += 1
        n_total = self.n_pages_for(int(prompt.size), cap)
        for _ in range(n_total - len(pages)):
            p = self._free.pop()
            self.ref[p] = 1
            pages.append(p)
        claimed_tokens = len(chain) * self.page_size
        if chain:
            self.prefix_hits += 1
            self.tel.instant("prefix_hit", pages=len(chain),
                             tokens=claimed_tokens)
        self.prefix_hit_tokens += claimed_tokens
        return PageClaim(pages=pages, claimed_tokens=claimed_tokens,
                         chain=[n.id for n in chain], cow=cow,
                         prompt=prompt.copy(), next_reg=len(chain))

    # ------------------------------------------------- register / release

    def register(self, claim: PageClaim, next_write_pos: int) -> None:
        """Insert the claim's FINALIZED full prompt pages into the radix.
        A page is final once the slot's next write position is past it —
        which defers exactly the page containing prompt position P-1
        (rewritten by the first decode step) until that write lands, so
        no registered page is ever written by its owner again."""
        if not self.prefix_cache:
            return
        psz = self.page_size
        n_full = int(claim.prompt.size) // psz
        while (claim.next_reg < n_full
               and (claim.next_reg + 1) * psz <= next_write_pos):
            g = claim.next_reg
            parent = claim.chain[-1] if claim.chain else RadixIndex.ROOT
            node, inserted = self.radix.insert(
                parent, claim.prompt[g * psz:(g + 1) * psz].tobytes(),
                claim.pages[g])
            if inserted:
                self.page_node[claim.pages[g]] = node
            claim.chain.append(node.id)
            claim.next_reg += 1

    def pending_registration(self, claim: PageClaim) -> bool:
        return (self.prefix_cache
                and claim.next_reg < int(claim.prompt.size)
                // self.page_size)

    def release(self, claim: PageClaim) -> None:
        """Drop the claim's references; refcount-0 pages return to the
        free list unless the radix holds them (then they ARE the prefix
        cache, reclaimed later by LRU eviction)."""
        for p in claim.pages:
            self.ref[p] -= 1
            assert self.ref[p] >= 0, f"page {p} refcount underflow"
            if self.ref[p] == 0 and p not in self.page_node:
                self._free.append(p)


@dataclass
class Admission:
    """What the engine needs from a successful ``acquire``: the slot,
    how many prompt tokens the prefix cache already holds (prefill
    starts there), and the device page copies to apply before any
    compute touches the slot (copy-on-write splits)."""

    slot: int
    claimed: int
    cow: List[Tuple[int, int]]


class PagedCachePool:
    """Paged drop-in for ``CachePool``: same host API (acquire/release/
    slot_of/positions/occupancy), backed by a page pool + per-slot page
    tables instead of contiguous slot buffers."""

    def __init__(self, cfg: ModelConfig, n_slots: int, *,
                 page_size: int = 0, max_pages: int = 0, n_pages: int = 0,
                 prefix_cache: bool = True, dtype=None, telemetry=None,
                 sharding=None, scale_sharding=None,
                 mesh_shape: Tuple[int, int] = (1, 1), quant=None):
        """``sharding`` (a NamedSharding from
        ``parallel.mesh.serve_shardings().cache``) commits the page
        pool onto the serving mesh instead of one device: the physical
        page axis shards over 'data' (each chip stores
        ceil(n_pages / data) pages — the capacity multiplier) and the
        model dim over 'model'. All HOST state here (allocator, radix,
        tables) is mesh-agnostic: page ids are logical either way.
        ``mesh_shape`` is carried for stats()/gauges only.

        ``quant`` (a quant.QuantConfig with ``kv_dtype`` set) stores
        pages in int8/fp8 with per-row scale metadata riding the pool
        dict (``ks``/``vs``) — halving bytes/page, which at fixed HBM
        doubles the page count this pool can be sized with
        (``n_pages_for_hbm``). ``scale_sharding``
        (``ServeShardings.scale``) commits the scale arrays with their
        page axis over 'data' alongside the pool's; every host-side
        invariant (allocator, radix, COW planning) is byte-for-byte
        unchanged — a page is its rows plus their scales."""
        assert n_slots >= 1, n_slots
        self.cfg = cfg
        self.n_slots = n_slots
        self.quant = quant
        self.page_size, self.max_pages, self.n_pages = pool_geometry(
            cfg, n_slots, page_size, max_pages, n_pages)
        assert self.max_pages * self.page_size >= cfg.block_size, (
            f"max_pages={self.max_pages} x page_size={self.page_size} "
            f"cannot hold block_size={cfg.block_size}")
        # default physical pool = the contiguous pool's HBM exactly;
        # fewer pages is the point (admission then gates on free pages)
        assert self.n_pages >= self.max_pages, (
            "pool smaller than one slot's worst case")
        self.mesh_shape = (int(mesh_shape[0]), int(mesh_shape[1]))
        # effective shard count of the PAGE axis (may be 1 when the
        # page count was not divisible and the spec dropped the axis)
        self._page_shards = 1
        if sharding is not None and len(sharding.spec) > 1 \
                and sharding.spec[1] is not None:
            self._page_shards = int(
                sharding.mesh.shape[sharding.spec[1]])
        self.alloc = PageAllocator(self.n_pages, self.page_size,
                                   prefix_cache=prefix_cache,
                                   telemetry=telemetry)
        pool = init_paged_kv_pool(cfg, self.n_pages, self.page_size,
                                  dtype=dtype, quant=quant)
        # per-entry placement: K/V take the pool spec, scale arrays
        # (different rank) their own page-axis spec
        self.cache: Dict = {
            name: commit_default(
                arr, sharding=(scale_sharding if name in ("ks", "vs")
                               else sharding))
            for name, arr in pool.items()}
        # host-mirrored, device-fed each step (fixed shape: the paged
        # programs never retrace on table contents)
        self.tables = np.zeros((n_slots, self.max_pages), np.int32)
        self.positions = np.zeros((n_slots,), np.int32)
        self._free_slots: List[int] = list(range(n_slots - 1, -1, -1))
        self._owner: Dict[int, str] = {}
        self._slot_by_request: Dict[str, int] = {}   # reverse index: O(1)
        self._claims: Dict[int, PageClaim] = {}
        # slots admitted with defer_commit=True (in-window prefill):
        # their radix registration is gated on commit_admission — the
        # engine calls it only once the writes are known landed, so
        # flush_pending can never pre-register a page a still-flying
        # window is writing
        self._deferred: set = set()
        # disaggregation (serve/disagg.py): pages refcount-pinned under
        # a transfer key — an export pin keeps radix prefix pages alive
        # while their bytes stream out; an install pin holds freshly
        # allocated pages until commit_install registers them
        self._pins: Dict[str, List[int]] = {}
        self._installs: Dict[str, Tuple[np.ndarray, int, List[int]]] = {}
        self.pages_exported = 0
        self.pages_installed = 0

    # ---------------------------------------------------------- geometry

    @property
    def seq_len(self) -> int:
        """LOGICAL per-slot capacity (positions are bounded by the
        learned positional table regardless of page count)."""
        return self.cfg.block_size

    @property
    def n_free(self) -> int:
        return len(self._free_slots)

    @property
    def n_used(self) -> int:
        return self.n_slots - len(self._free_slots)

    @property
    def occupancy(self) -> float:
        return self.n_used / self.n_slots

    # ------------------------------------------------------ slot lifecycle

    def can_admit(self, prompt: np.ndarray, cap: int) -> bool:
        return bool(self._free_slots) and self.alloc.can_acquire(
            np.asarray(prompt, np.int32), cap)

    def cached_prefix_tokens(self, prompt: np.ndarray) -> int:
        """Longest radix-cached prefix of ``prompt`` in TOKENS, without
        touching LRU stamps or claiming anything — the fleet router's
        affinity probe (route a session to the replica that already
        owns its prefix). 0 with the prefix cache off."""
        if not self.alloc.prefix_cache:
            return 0
        chain = self.alloc.radix.lookup(
            np.asarray(prompt, np.int32).reshape(-1), self.page_size,
            touch=False)
        return len(chain) * self.page_size

    def acquire(self, request_id: str, prompt: np.ndarray,
                cap: int, defer_commit: bool = False
                ) -> Optional[Admission]:
        """``defer_commit=True`` (the engine's windowed-admission path)
        holds the slot OUT of radix registration — including
        ``flush_pending`` — until ``commit_admission``: its prompt
        pages are being written by an in-flight mixed window, and a
        registered page must never be claimable before its writes have
        landed in dispatch order."""
        if not self._free_slots:
            return None
        claim = self.alloc.acquire(prompt, cap)
        if claim is None:
            return None
        slot = self._free_slots.pop()
        if defer_commit:
            self._deferred.add(slot)
        self._owner[slot] = request_id
        self._slot_by_request[request_id] = slot
        self._claims[slot] = claim
        row = self.tables[slot]
        row[:] = 0
        row[:len(claim.pages)] = claim.pages
        self.positions[slot] = int(prompt.size) - 1
        return Admission(slot=slot, claimed=claim.claimed_tokens,
                         cow=list(claim.cow))

    def commit_admission(self, slot: int) -> None:
        """Register the slot's already-final full prompt pages (called
        after prefill wrote them — registration order is what lets a
        same-step neighbor claim them safely). Lifts a
        ``defer_commit`` hold."""
        self._deferred.discard(slot)
        self.alloc.register(self._claims[slot], int(self.positions[slot]))

    def flush_pending(self) -> None:
        """Advance deferred registrations (the page containing prompt
        position P-1 becomes shareable once the first decode write
        passed it). Called once per engine step — cheap: at most one
        page per slot ever waits. Slots under a ``defer_commit`` hold
        are skipped: their prompt writes may still be in flight."""
        for slot, claim in self._claims.items():
            if slot in self._deferred:
                continue
            if self.alloc.pending_registration(claim):
                self.alloc.register(claim, int(self.positions[slot]))

    def release(self, slot: int) -> None:
        self._deferred.discard(slot)
        owner = self._owner.pop(slot, None)
        assert owner is not None, f"slot {slot} double-free"
        # conditional: duplicate request ids are rejected at submit, but
        # the reverse index must never KeyError another slot's mapping
        if self._slot_by_request.get(owner) == slot:
            del self._slot_by_request[owner]
        self.alloc.release(self._claims.pop(slot))
        self.tables[slot, :] = 0
        self._free_slots.append(slot)

    def owner(self, slot: int) -> Optional[str]:
        return self._owner.get(slot)

    def slot_of(self, request_id: str) -> Optional[int]:
        return self._slot_by_request.get(request_id)

    # ------------------------------------------- disaggregated transfer
    #
    # The page-level API serve/disagg.py moves KV between tiers with.
    # Export side: a prefill worker's finished prompt pages live in its
    # radix as refcount-0 prefix cache — pin_prefix refcounts them for
    # the duration of the copy-out so LRU eviction cannot reclaim a
    # page mid-transfer. Install side: install_prefix allocates fresh
    # physical pages (pinned, so nothing evicts them before their
    # bytes land), the engine's jitted scatter writes the transferred
    # blocks, and commit_install registers the chain into the local
    # radix keyed by the prompt's token bytes — after which a NORMAL
    # admission claims the prefix exactly like a locally warmed one
    # (table rebase to local physical indices is the radix chain
    # itself). Every failure path degrades to "prefix not cached":
    # the request re-prefills locally, token-identically.

    def pin_prefix(self, key: str, prompt: np.ndarray) -> List[int]:
        """Refcount-pin the radix-cached full prompt pages of
        ``prompt`` under ``key``; returns the physical pages in prefix
        order (possibly empty). Pin keys are single-owner: re-pinning
        an active key is a bug."""
        assert key not in self._pins, f"transfer pin {key!r} already held"
        chain = self.alloc.radix.lookup(
            np.asarray(prompt, np.int32).reshape(-1), self.page_size,
            touch=True)
        pages = [n.page for n in chain]
        for p in pages:
            self.alloc.ref[p] += 1
        self._pins[key] = pages
        return pages

    def unpin(self, key: str) -> None:
        """Drop a transfer pin (export finished, or install aborted).
        Pages whose refcount hits 0 return to the free list unless the
        radix holds them — same discipline as claim release."""
        self._installs.pop(key, None)
        for p in self._pins.pop(key, []):
            self.alloc.ref[p] -= 1
            assert self.alloc.ref[p] >= 0, f"page {p} pin underflow"
            if self.alloc.ref[p] == 0 and p not in self.alloc.page_node:
                self.alloc._free.append(p)

    def install_prefix(self, key: str, prompt: np.ndarray,
                       from_page: int,
                       n_pages: int) -> Optional[List[int]]:
        """Allocate ``n_pages`` fresh physical pages (pinned under
        ``key``) to receive transferred KV blocks for prompt pages
        ``from_page .. from_page+n_pages``. Requires the local radix to
        already hold the first ``from_page`` pages (the chain the
        placement probe saw) — if that prefix shrank since (eviction),
        returns None and the caller falls back to local prefill."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        chain = self.alloc.radix.lookup(prompt, self.page_size,
                                        touch=True)
        if len(chain) < from_page:
            return None
        protect = {n.page for n in chain}
        taken: List[int] = []
        for _ in range(n_pages):
            if not self.alloc._free and \
                    self.alloc._evict_one(protect) is None:
                for p in taken:                      # unwind: no pin
                    self.alloc.ref[p] = 0
                    self.alloc._free.append(p)
                return None
            p = self.alloc._free.pop()
            self.alloc.ref[p] = 1
            taken.append(p)
        self._pins[key] = list(taken)
        self._installs[key] = (prompt.copy(), int(from_page), taken)
        return taken

    def commit_install(self, key: str) -> int:
        """Register an installed chain into the radix (the transferred
        blocks are known landed — the caller sequences this after the
        scatter's result is committed) and drop the pin. Returns the
        number of pages that entered the radix; pages whose edge
        already existed (a concurrent local prefill won the race) stay
        private and free with the pin."""
        prompt, g0, pages = self._installs.pop(key)
        psz = self.page_size
        chain = self.alloc.radix.lookup(prompt, psz, touch=True)
        if len(chain) < g0 or not self.alloc.prefix_cache:
            self.unpin(key)
            return 0
        parent = chain[g0 - 1].id if g0 else RadixIndex.ROOT
        registered = 0
        for i, page in enumerate(pages):
            g = g0 + i
            node, inserted = self.alloc.radix.insert(
                parent, prompt[g * psz:(g + 1) * psz].tobytes(), page)
            if inserted:
                self.alloc.page_node[page] = node
                registered += 1
            parent = node.id
        self.pages_installed += registered
        self.unpin(key)
        return registered

    # ----------------------------------------------------------- metrics

    def stats(self) -> dict:
        a = self.alloc
        # mesh accounting: n_pages is the AGGREGATE admission currency
        # (the allocator is mesh-agnostic); each chip along the data
        # axis physically stores pages_per_chip of it, so per-chip
        # occupancy is what a capacity dashboard / the router's
        # least-loaded signal should watch on a mesh (on 1x1 the
        # per-chip numbers degenerate to the aggregate ones)
        d = self._page_shards
        by_chip = a.in_use_by_block(d)
        per_chip = -(-self.n_pages // d)
        kv_quant = (self.quant.kv_dtype
                    if self.quant is not None and self.quant.kv_enabled
                    else "none")
        gran = (self.quant.granularity if kv_quant != "none" else "page")
        return {
            "page_size": self.page_size,
            "max_pages_per_slot": self.max_pages,
            "n_pages": self.n_pages,
            # quantization gauges (ISSUE 15): bytes_per_page is the
            # admission-capacity denominator the fixed-HBM A/B keys on;
            # kv_quant_bits is the numeric Prometheus-friendly spelling
            # of the mode (8 = quantized storage)
            "kv_quant": kv_quant,
            "quant_granularity": gran,
            "bytes_per_page": page_bytes(self.cfg, self.page_size,
                                         kv_quant, gran),
            "kv_quant_bits": 8 * self.cache["k"].dtype.itemsize,
            "pages_in_use": a.pages_in_use,
            "pages_free": a.pages_free,
            "page_utilization": round(a.pages_in_use / self.n_pages, 4),
            "mesh_shape": list(self.mesh_shape),
            "aggregate_pages": self.n_pages,
            "pages_per_chip": per_chip,
            "pages_in_use_by_chip": by_chip,
            "page_utilization_by_chip": [round(c / per_chip, 4)
                                         for c in by_chip],
            "radix_pages": len(a.page_node),
            "prefix_cache": a.prefix_cache,
            "prefix_lookups": a.prefix_lookups,
            "prefix_hits": a.prefix_hits,
            "prefix_hit_tokens": a.prefix_hit_tokens,
            "prefix_hit_rate": (round(a.prefix_hit_tokens
                                      / a.prompt_tokens, 4)
                                if a.prompt_tokens else 0.0),
            "evictions": a.evictions,
            "cow_copies": a.cow_copies,
            # disaggregated transfer counters (serve/disagg.py)
            "pages_exported": self.pages_exported,
            "pages_installed": self.pages_installed,
            "transfer_pins": len(self._pins),
        }
