"""Host-side request journal: restart recovery for the serving engine.

The engine's device state (pooled KV cache) is disposable — every
request regenerates deterministically from its prompt + sampling params
+ rng_seed (per-request RNG streams make output independent of slot and
neighbors). What a crash actually loses is the *host* bookkeeping:
which requests were in flight. The journal closes that gap with an
append-only JSONL file: one ``submit`` record when the engine accepts a
request, one ``finish`` record when its terminal ``RequestResult``
exists. After a crash/restart, :func:`unfinished` replays the journal
and returns the accepted-but-unfinished requests for requeueing into a
fresh engine — every admitted request is eventually served (or
explicitly shed), across restarts.

Records are flushed per write: a journal that lags the engine would
silently drop the most recent admissions, which is exactly the window a
crash hits. One fsync-free flush per request (not per token) is host
noise next to a model forward. Two multi-process knobs harden this for
journals on shared storage (the fleet's worker processes,
serve/worker.py):

- ``fsync_finish=True`` fsyncs after every ``finish`` record — a
  finish that only reached the page cache when the machine (not just
  the process) died would make the restarted worker re-decode and
  re-deliver a request the client already saw complete. Submits stay
  flush-only: losing a submit record loses at most an un-started
  request the router will retry, never a duplicate delivery.
- ``lock=True`` takes an exclusive ``flock`` on the journal file at
  open, so two processes can never append to the same journal (a
  supervisor racing a not-quite-dead worker, a misconfigured second
  worker on one journal path). The kernel drops the lock when the
  holder dies — including ``kill -9`` — so a restarted worker never
  waits on its own corpse. A held lock raises
  :class:`JournalBusyError` instead of blocking.

The reader contract is unchanged by both: readers never lock (they
tolerate a concurrent appender), and the torn final line a crash can
leave is skipped by the shared ``utils.jsonl`` reader — fsync narrows
the torn-tail window, it does not remove the reader's obligation to
tolerate one.

Deadlines are *not* recovered: they are absolute timestamps on the dead
engine's monotonic clock, meaningless after restart. A recovered
request runs deadline-free (operators re-impose one at requeue time if
the workload needs it — docs/robustness.md).
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, TextIO

import numpy as np

from ..utils.jsonl import load_jsonl_if_exists
from .requests import Request, SamplingParams


class JournalBusyError(RuntimeError):
    """Another live process holds this journal's exclusive write lock."""


class RequestJournal:
    """Append-only submit/finish journal (one writer — the engine)."""

    def __init__(self, path: str, fsync_finish: bool = False,
                 lock: bool = False):
        self.path = os.path.abspath(path)
        self.fsync_finish = fsync_finish
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        # a previous incarnation may have died MID-RECORD (the torn
        # tail the readers tolerate) — appending straight after it
        # would merge this writer's first record into the torn
        # fragment, corrupting a GOOD record. Terminate the fragment
        # first: it becomes one complete invalid line the tolerant
        # reader skips, and every new record stays intact.
        needs_nl = False
        try:
            with open(self.path, "rb") as rf:
                rf.seek(-1, os.SEEK_END)
                needs_nl = rf.read(1) != b"\n"
        except (OSError, ValueError):
            pass                   # missing or empty file
        self._f: Optional[TextIO] = open(self.path, "a")
        if lock:
            import fcntl
            try:
                fcntl.flock(self._f.fileno(),
                            fcntl.LOCK_EX | fcntl.LOCK_NB)
            except OSError as e:
                self._f.close()
                self._f = None
                raise JournalBusyError(
                    f"journal {self.path} is locked by another live "
                    f"process") from e
        if needs_nl:               # after the flock: only the ONE
            self._f.write("\n")    # legitimate writer repairs the tail
            self._f.flush()

    def _write(self, obj: dict, fsync: bool = False) -> None:
        assert self._f is not None, "journal is closed"
        self._f.write(json.dumps(obj) + "\n")
        self._f.flush()
        if fsync:
            # the durability point: a finish ack must not race the
            # record to disk, so this stall is the contract, not a bug
            os.fsync(self._f.fileno())  # graftlint: disable=GL019

    def record_submit(self, req: Request) -> None:
        sp = req.sampling
        self._write({
            "ev": "submit", "id": req.id,
            "prompt": np.asarray(req.prompt).tolist(),
            "max_new_tokens": int(req.max_new_tokens),
            "rng_seed": int(req.rng_seed),
            "temperature": float(sp.temperature), "top_k": int(sp.top_k),
            "top_p": float(sp.top_p), "greedy": bool(sp.greedy),
            # eos is part of the stop condition: a replay that decodes
            # past it would NOT be token-identical to the original
            **({"eos": int(req.eos_token_id)}
               if req.eos_token_id is not None else {}),
        })

    def record_finish(self, request_id: str, reason: str) -> None:
        self._write({"ev": "finish", "id": request_id, "reason": reason},
                    fsync=self.fsync_finish)

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None

    @staticmethod
    def unfinished(path: str, telemetry=None) -> List[Request]:
        """Replay a journal (possibly from a dead engine) and rebuild the
        accepted-but-unfinished requests, in admission order. Tolerates a
        torn final line (the crash may have landed mid-write).
        ``telemetry`` (utils.telemetry) marks the replay as an instant
        on the recovered engine's timeline — restart recovery shows up
        next to the requeued requests' span trees."""
        submits: Dict[str, Request] = {}
        order: List[str] = []
        # torn-tail tolerance lives in utils.jsonl (shared with the
        # telemetry sink readers and the fleet router's journal replay)
        for rec in load_jsonl_if_exists(path):
            if rec.get("ev") == "submit":
                rid = rec["id"]
                if rid not in submits:
                    order.append(rid)
                submits[rid] = Request(
                    id=rid,
                    # host JSON list -> host array; no device involved
                    prompt=np.asarray(rec["prompt"],  # graftlint: disable=GL004
                                      np.int32),
                    max_new_tokens=rec["max_new_tokens"],
                    sampling=SamplingParams(
                        temperature=rec["temperature"],
                        top_k=rec["top_k"], top_p=rec["top_p"],
                        greedy=rec["greedy"]),
                    rng_seed=rec["rng_seed"],
                    eos_token_id=rec.get("eos"))
            elif rec.get("ev") == "finish":
                submits.pop(rec["id"], None)
        # an id can appear in `order` twice (finished, then a fresh
        # request reused the id and was journaled again) — emit each
        # unfinished id exactly ONCE or the caller would requeue and
        # decode it twice
        out, seen = [], set()
        for rid in order:
            if rid in submits and rid not in seen:
                seen.add(rid)
                out.append(submits[rid])
        if telemetry is not None and telemetry.enabled:
            telemetry.instant("journal_replay", requeued=len(out))
        return out
