"""Request/response types for the continuous-batching serving engine.

The offline ``sample.generate`` path takes one fixed prompt batch per
call; a serving engine instead deals in *requests* — independent
prompts arriving at independent times with independent sampling params,
lengths, and deadlines. These types are the host-side contract between
the admission queue (serve/scheduler.py), the slot pool
(serve/cache_pool.py), and the engine loop (serve/engine.py).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

from ..sample.generate import GenerateConfig


@dataclass(frozen=True)
class SamplingParams:
    """Per-request sampling controls — the same knobs as
    ``sample.GenerateConfig`` (temperature/top-k/top-p/greedy), minus
    the length/chunking fields that belong to the request/engine."""

    temperature: float = 1.0
    top_k: int = 0
    top_p: float = 0.0
    greedy: bool = False

    @classmethod
    def from_generate_config(cls, g: GenerateConfig) -> "SamplingParams":
        return cls(temperature=g.temperature, top_k=g.top_k, top_p=g.top_p,
                   greedy=g.greedy)


# Finish reasons (RequestResult.finish_reason). String constants, not an
# enum: they go straight into metrics counter names and JSON summaries.
FINISH_MAX_TOKENS = "max_tokens"        # produced request.max_new_tokens
FINISH_LENGTH_CAP = "length_cap"        # hit the slot's context capacity
                                        # (block_size) before max_new_tokens
FINISH_EOS = "eos"                      # sampled request.eos_token_id (the
                                        # eos token is the stream's last;
                                        # detected ON DEVICE inside decode
                                        # windows, so a stopped slot idles
                                        # to the window boundary)
FINISH_DEADLINE = "deadline"            # deadline expired (at submit,
                                        # queued, or active)
FINISH_CANCELLED = "cancelled"          # caller cancelled (queued or active)
FINISH_SHED = "shed"                    # dropped by overload shedding
                                        # (faults.watchdog.LoadShedder)
FINISH_PREFILLED = "prefilled"          # prefill-tier completion of a
                                        # ``prefill_only`` request: the
                                        # prompt's KV pages are warm in
                                        # this engine's radix, ready for
                                        # export (serve/disagg.py); NOT
                                        # a client-visible terminal —
                                        # the fleet router diverts it
                                        # into the page transfer and the
                                        # decode tier produces the real
                                        # stream
REJECT_QUEUE_FULL = "rejected_queue_full"      # backpressure at submit
REJECT_PROMPT_TOO_LONG = "rejected_prompt_too_long"  # prompt > block_size
REJECT_BAD_REQUEST = "rejected_bad_request"    # empty prompt / bad lengths


@dataclass
class Request:
    """One generation request.

    ``deadline`` is an absolute timestamp on the engine's clock
    (``time.monotonic`` unless the engine was given another clock);
    None = no deadline. ``rng_seed`` keys the request's private sampling
    stream (per-slot RNG in the batched sampler), so a request's
    stochastic output is independent of which slot it lands in and of
    its neighbors in the batch.
    """

    id: str
    prompt: np.ndarray                      # (P,) int32 token ids, P >= 1
    max_new_tokens: int = 16
    sampling: SamplingParams = field(default_factory=SamplingParams)
    deadline: Optional[float] = None
    rng_seed: int = 0
    #: stop token: generation ends the step this id is sampled (it IS
    #: emitted, as the last token, finish_reason ``eos``); None = run to
    #: max_new_tokens. Must be a valid vocab id — the engine rejects
    #: out-of-range values at submit.
    eos_token_id: Optional[int] = None
    #: disaggregated prefill (serve/disagg.py): run the prompt through
    #: admission + chunked prefill normally, finish after the FIRST
    #: decode token (which rewrites prompt position P-1, finalizing the
    #: last full page for radix registration), report finish_reason
    #: ``prefilled`` with the telemetry envelope closed ``migrated`` —
    #: a non-terminal segment; the decode tier owns the stream.
    prefill_only: bool = False

    def __post_init__(self):
        self.prompt = np.asarray(self.prompt, np.int32).reshape(-1)


@dataclass
class RequestResult:
    """Terminal record for a request — produced exactly once, whether it
    completed, was cancelled, expired, or was rejected at the door."""

    id: str
    tokens: List[int]
    finish_reason: str
    # timings (engine clock, seconds); 0.0 when the phase never ran
    queue_wait_s: float = 0.0               # submit -> admission
    ttft_s: float = 0.0                     # submit -> first new token
    decode_tokens_per_s: float = 0.0        # steady-state decode rate
    total_s: float = 0.0                    # submit -> finish

    @property
    def ok(self) -> bool:
        return self.finish_reason in (FINISH_MAX_TOKENS, FINISH_LENGTH_CAP,
                                      FINISH_EOS)

    def to_dict(self) -> Dict:
        return {"id": self.id, "n_tokens": len(self.tokens),
                "finish_reason": self.finish_reason,
                "queue_wait_s": round(self.queue_wait_s, 6),
                "ttft_s": round(self.ttft_s, 6),
                "decode_tokens_per_s": round(self.decode_tokens_per_s, 2),
                "total_s": round(self.total_s, 6)}
