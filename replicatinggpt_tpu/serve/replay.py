"""Offline serving-trace replay: a synthetic Poisson workload through
the engine.

The zero-egress image cannot take real traffic, so the serving story is
proven the way load tests do it: a seeded Poisson arrival process over
random prompts/lengths/budgets is replayed in wall-clock time through
the engine, and the metrics summary (TTFT, decode tok/s, occupancy,
batch fill, step latency, recompiles-after-warmup) is the artifact.
Drives both ``python -m replicatinggpt_tpu serve-replay`` and
``bench.py --mode serve``.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import List, Optional, Tuple

import numpy as np

from ..config import ModelConfig
from ..utils.sanitize import sanitized
from ..utils.telemetry import MetricsTimeline, Telemetry, prometheus_text
from .engine import Engine, EngineConfig, compile_counts
from .requests import Request, RequestResult, SamplingParams
from .speculative import make_drafter


@dataclass(frozen=True)
class ReplayConfig:
    n_requests: int = 64
    rate: float = 200.0            # mean arrivals/sec (Poisson)
    seed: int = 0
    prompt_len_min: int = 1
    prompt_len_max: int = 32
    max_new_tokens: int = 16
    greedy: bool = False
    temperature: float = 1.0
    top_k: int = 20
    top_p: float = 0.0
    deadline_s: float = 0.0        # per-request deadline after arrival; 0=off
    prompt_mode: str = "random"    # 'random' | 'repeat' (tiled small
                                   # pattern — the speculative bench trace)
                                   # | 'shared_prefix' (every prompt =
                                   # one common random prefix + a random
                                   # suffix — the system-prompt traffic
                                   # shape the radix prefix cache serves)
    shared_prefix_len: int = 0     # 'shared_prefix' common-prefix length;
                                   # 0 = prompt_len_max // 2
    spec: str = "off"              # drafter: 'off' | 'ngram' | 'model'
    spec_k: int = 4                # drafted tokens per slot per step
    spec_ngram: int = 3            # n-gram drafter match width


def make_trace(mcfg: ModelConfig, rcfg: ReplayConfig
               ) -> List[Tuple[float, Request]]:
    """Seeded (arrival_time, request) list: exponential inter-arrivals,
    uniform prompt lengths (clamped to block_size), uniform token ids —
    or, with ``prompt_mode='repeat'``, each prompt a tiled random <=4
    token pattern (repetitive text is the n-gram drafter's favorable
    regime; the serve-spec bench row uses this trace)."""
    rng = np.random.default_rng(rcfg.seed)
    hi = min(rcfg.prompt_len_max, mcfg.block_size)
    lo = min(rcfg.prompt_len_min, hi)
    shared = None
    if rcfg.prompt_mode == "shared_prefix":
        n_shared = min(rcfg.shared_prefix_len or max(hi // 2, 1), hi - 1)
        shared = rng.integers(0, mcfg.vocab_size, (n_shared,),
                              dtype=np.int64)
    t = 0.0
    trace = []
    sp = SamplingParams(temperature=rcfg.temperature, top_k=rcfg.top_k,
                        top_p=rcfg.top_p, greedy=rcfg.greedy)
    for i in range(rcfg.n_requests):
        # host numpy RNG: float() here is not a device round-trip
        t += float(rng.exponential(1.0 / max(rcfg.rate, 1e-9)))  # graftlint: disable=GL004
        if shared is not None:
            # the system-prompt shape: identical prefix + unique tail
            # (>= 1 token so requests are distinct streams); total
            # length still honors the [lo, hi] knobs
            P = int(rng.integers(max(lo, shared.size + 1), hi + 1))
            prompt = np.concatenate([
                shared, rng.integers(0, mcfg.vocab_size,
                                     (P - shared.size,), dtype=np.int64)])
        elif rcfg.prompt_mode == "repeat":
            P = int(rng.integers(lo, hi + 1))
            pat = rng.integers(0, mcfg.vocab_size,
                               (min(int(rng.integers(1, 5)), P),),
                               dtype=np.int64)
            prompt = np.tile(pat, -(-P // pat.size))[:P]
        else:
            P = int(rng.integers(lo, hi + 1))
            prompt = rng.integers(0, mcfg.vocab_size, (P,), dtype=np.int64)
        trace.append((t, Request(
            id=f"r{i:04d}", prompt=prompt.astype(np.int32),
            max_new_tokens=rcfg.max_new_tokens, sampling=sp,
            rng_seed=rcfg.seed * 100_003 + i)))
    return trace


def run_replay(params, mcfg: ModelConfig, rcfg: ReplayConfig,
               ecfg: EngineConfig, warmup: bool = True,
               draft_params=None,
               draft_cfg: Optional[ModelConfig] = None,
               resilience=None, journal=None,
               trace_out: Optional[str] = None,
               metrics_timeline: Optional[str] = None,
               metrics_timeline_interval_s: float = 0.5,
               metrics_out: Optional[str] = None,
               profile_dir: Optional[str] = None,
               profile_start: int = 10,
               profile_steps: int = 5,
               trace: Optional[List[Tuple[float, Request]]] = None,
               cancels: Optional[List[Tuple[float, str]]] = None,
               deadlines: Optional[dict] = None) -> dict:
    """Replay the trace in wall-clock time; returns the summary dict.

    ``warmup`` first pushes one tiny request through a throwaway engine
    of the same shapes so the device programs (including the
    speculative verify step and the model drafter's two programs, when
    configured) compile outside the timed replay — the summary's
    ``recompiles_after_warmup`` then asserts the steady-state claim
    (0 on a healthy run). With a drafter configured the warmup also
    runs the plain-decode path once: the speculative auto-disable
    policy (``resilience``, a faults.watchdog.ResilienceConfig) may
    legitimately switch to it mid-replay, and a degraded transition
    must not cost a compile. ``rcfg.spec`` selects the drafter; the
    'model' mode additionally needs ``draft_params``/``draft_cfg``
    (see ``speculative.draft_config_from_preset``). Drafters are
    stateful, so each engine gets its own. ``journal`` (a
    serve.journal.RequestJournal) is handed to the replay engine for
    restart-recovery coverage.

    Observability outputs (utils.telemetry; all off by default):
    ``trace_out`` writes a Perfetto-loadable Chrome trace of the whole
    replay (one span tree per request on per-slot tracks, recovery /
    prefix-hit / COW / eviction instants); ``metrics_timeline`` writes
    a JSONL time series of the engine's Metrics every
    ``metrics_timeline_interval_s`` (plus one snapshot at attach and a
    forced final one — >= 2 points always); ``metrics_out`` writes the
    end-of-run Prometheus text exposition. ``profile_dir`` captures a
    ``jax.profiler`` device trace of engine steps [profile_start,
    profile_start + profile_steps) — the device-side half of the
    timeline, with host spans linked by ``annotate`` region names.
    Paths of everything written land in the summary's ``artifacts``
    block (bench.py attaches it to the artifact JSON).

    ``trace`` replays a PREBUILT (arrival_time, request) list instead of
    ``make_trace(mcfg, rcfg)`` — the admission-storm preset
    (serve/loadgen.admission_storm) enters here. ``cancels`` is a
    time-sorted [(t, request_id), ...] schedule issued through
    ``engine.cancel`` as the replay clock passes each t (a cancel for a
    request that already finished is a no-op), and ``deadlines`` maps
    request ids to RELATIVE deadlines applied at submit (per-request,
    where ``rcfg.deadline_s`` is uniform).
    """
    def drafter():
        return make_drafter(rcfg.spec, rcfg.spec_k, rcfg.spec_ngram,
                            ecfg.pool_size, draft_params, draft_cfg,
                            ecfg.prefill_chunk)

    def tiny(rid):
        # long enough to EXERCISE the steady-state window path past the
        # admission boundary's mixed dispatch (the window programs
        # themselves compile at engine construction —
        # Engine._warm_windows; EngineConfig.warmup_tokens is one
        # definition shared with the worker's readiness warmup)
        return Request(id=rid, prompt=np.zeros((1,), np.int32),
                       max_new_tokens=ecfg.warmup_tokens(),
                       sampling=SamplingParams(greedy=True))

    if warmup:
        w = Engine(params, mcfg, ecfg, drafter=drafter())
        w.submit(tiny("warmup"))
        w.drain()
        if w.drafter is not None:
            # compile the degraded (plain decode) program too — see above
            w.set_spec_active(False)
            w.submit(tiny("warmup-degraded"))
            w.drain()
    warm = compile_counts()

    tel = Telemetry() if trace_out else None
    engine = Engine(params, mcfg, ecfg, drafter=drafter(),
                    rcfg=resilience, journal=journal, telemetry=tel)
    timeline = None
    if metrics_timeline:
        timeline = MetricsTimeline(engine.metrics, metrics_timeline,
                                   interval_s=metrics_timeline_interval_s)
        timeline.snapshot(step=0)          # the t=0 anchor point
    from ..utils.profiling import trace_window
    profiler = trace_window(profile_dir, start=profile_start,
                            n_steps=profile_steps)
    if trace is None:
        trace = make_trace(mcfg, rcfg)
    cancels = sorted(cancels) if cancels else []
    results: List[RequestResult] = []
    i = 0
    ci = 0
    n_trace_events = 0
    t0 = time.monotonic()
    # GRAFT_SANITIZE=1 runs the whole replay under jax's tracer-leak +
    # NaN checks (no-op context otherwise). Cleanup rides a finally: a
    # replay that dies mid-run (injected fault, sanitize trip, Ctrl-C)
    # must still stop the jax profiler (a started trace poisons the
    # next start_trace in this process) and flush the trace/timeline
    # artifacts — the crash window is exactly when they matter.
    try:
        with sanitized():
            while len(results) < len(trace):
                now = time.monotonic() - t0
                while i < len(trace) and trace[i][0] <= now:
                    arr_t, req = trace[i]
                    if deadlines and req.id in deadlines:
                        req.deadline = (time.monotonic()
                                        + deadlines[req.id])
                    elif rcfg.deadline_s > 0:
                        req.deadline = time.monotonic() + rcfg.deadline_s
                    rej = engine.submit(req)
                    if rej is not None:
                        results.append(rej)
                    i += 1
                while ci < len(cancels) and cancels[ci][0] <= now:
                    # mid-flight cancel traffic (the storm trace); a
                    # cancel for an already-finished id is a no-op
                    engine.cancel(cancels[ci][1])
                    ci += 1
                if engine.idle:
                    if i >= len(trace):
                        break
                    # nothing in flight: sleep to the next arrival
                    time.sleep(min(max(trace[i][0] - now, 0.0), 0.05))
                    continue
                profiler.step(engine.n_steps)
                results.extend(engine.step())
                if timeline is not None:
                    timeline.maybe_snapshot(step=engine.n_steps)
    finally:
        profiler.close()
        if tel is not None:
            n_trace_events = tel.export_chrome_trace(trace_out)
            tel.close()
        if timeline is not None:
            timeline.close(step=engine.n_steps)  # forced end-of-run point
    wall_s = time.monotonic() - t0

    done = compile_counts()
    ok = [r for r in results if r.ok]
    gen_tokens = sum(len(r.tokens) for r in results)
    summary = engine.metrics_summary()
    summary.update({
        "n_requests": len(trace),
        "n_completed": len(ok),
        "n_rejected": sum(r.finish_reason.startswith("rejected")
                          for r in results),
        "generated_tokens": gen_tokens,
        "wall_s": round(wall_s, 3),
        "aggregate_tokens_per_s": round(gen_tokens / wall_s, 1)
        if wall_s > 0 else 0.0,
        "recompiles_after_warmup": sum(done.values()) - sum(warm.values()),
    })
    artifacts = {}
    if tel is not None:
        artifacts["trace_out"] = trace_out
        artifacts["trace_events"] = n_trace_events
    if timeline is not None:
        artifacts["metrics_timeline"] = metrics_timeline
        artifacts["metrics_timeline_snapshots"] = timeline.n_snapshots
    if metrics_out:
        pages = summary.get("pages", {})
        with open(metrics_out, "w") as f:
            f.write(prometheus_text(
                engine.metrics,
                extra_gauges={k: pages[k] for k in
                              ("pages_in_use", "page_utilization",
                               "prefix_hit_rate", "radix_pages",
                               "pages_per_chip", "aggregate_pages",
                               # quantization gauges (ISSUE 15): the
                               # capacity denominator + numeric mode
                               "bytes_per_page", "kv_quant_bits")
                              if k in pages}))
        artifacts["metrics_out"] = metrics_out
    if profile_dir:
        artifacts["profile_dir"] = profile_dir
    if artifacts:
        summary["artifacts"] = artifacts
    return summary


def format_summary(s: dict) -> str:
    """Human-readable metrics block (the serve-replay stdout report)."""
    h = s["histograms"]

    def pct(name, scale=1.0, unit=""):
        d = h.get(name, {})
        return (f"p50 {d.get('p50', 0) * scale:.2f}{unit} / "
                f"p90 {d.get('p90', 0) * scale:.2f}{unit} / "
                f"p99 {d.get('p99', 0) * scale:.2f}{unit}")

    sl = s["step_latency"]
    lines = [
        f"requests: {s['n_requests']} submitted, {s['n_completed']} "
        f"completed, {s['n_rejected']} rejected",
        f"tokens: {s['generated_tokens']} generated in {s['wall_s']}s "
        f"-> {s['aggregate_tokens_per_s']} tok/s aggregate",
        f"TTFT: {pct('ttft_s', 1e3, ' ms')}",
        f"decode rate/request: {pct('decode_tokens_per_s', 1.0, ' tok/s')}",
        f"step latency: p50 {sl['p50_s'] * 1e3:.2f} ms / "
        f"p90 {sl['p90_s'] * 1e3:.2f} ms over {s['n_steps']} steps",
        f"batch fill: mean {h.get('batch_fill_ratio', {}).get('mean', 0):.2f}"
        f" (pool), queue wait {pct('queue_wait_s', 1e3, ' ms')}",
        f"recompiles after warmup: {s['recompiles_after_warmup']}",
    ]
    dp = s.get("dispatch")
    if dp and dp.get("dispatches"):
        auto = (f" (autotuned from {dp['window_k_max']} cap, "
                f"{dp['autotune_increases']} increase(s))"
                if dp.get("autotune") else "")
        lines.insert(4, (
            f"dispatch split: window k={dp['window_k']}{auto}, "
            f"{dp['dispatches']} dispatches, host "
            f"{dp['mean_dispatch_ms']:.3f} ms/dispatch -> "
            f"{dp['host_dispatch_ms_per_token']:.3f} ms/token"))
        wb = s.get("window_breaks") or {}
        if dp.get("window_k_max", dp["window_k"]) > 1:
            lines.insert(5, (
                "window breaks: "
                + " ".join(f"{r}={wb.get(r, 0)}" for r in
                           ("admit", "deadline", "cancel", "spec",
                            "reprobe"))))
    pg = s.get("pages")
    if pg:
        if pg.get("kv_quant", "none") != "none":
            lines.insert(2, (
                f"quant: KV {pg['kv_quant']} "
                f"({pg['quant_granularity']}-granularity scales), "
                f"{pg['bytes_per_page']} bytes/page"))
        lines.insert(2, (
            f"pages: {pg['pages_in_use']}/{pg['n_pages']} in use "
            f"({pg['page_size']} tok/page, util "
            f"{pg['page_utilization']:.2f}), prefix hits "
            f"{pg['prefix_hits']}/{pg['prefix_lookups']} "
            f"({pg['prefix_hit_tokens']} tok, rate "
            f"{pg['prefix_hit_rate']:.2f}), {pg['evictions']} evictions, "
            f"{pg['cow_copies']} COW copies"))
        if pg.get("mesh_shape", [1, 1]) != [1, 1]:
            d, m = pg["mesh_shape"]
            lines.insert(2, (
                f"mesh: {d}x{m} (data x model), "
                f"{pg['pages_per_chip']} pages/chip of "
                f"{pg['aggregate_pages']} aggregate, per-chip in use "
                f"{pg['pages_in_use_by_chip']}"))
    sp = s.get("speculative")
    if sp:
        lines.insert(2, (
            f"speculative ({sp['drafter']}, k={sp['k']}): accept rate "
            f"{sp['accept_rate']:.3f}, {sp['mean_tokens_per_step']:.2f} "
            f"tokens/slot-step, draft overhead p50 "
            f"{sp['draft_overhead_s'].get('p50', 0) * 1e3:.2f} ms"))
    return "\n".join(lines)
