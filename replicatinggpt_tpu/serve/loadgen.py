"""Multi-turn session load generator + fleet replay driver.

The single-engine replay (serve/replay.py) proves the engine against a
Poisson trace of independent one-shot prompts. Real front-door traffic
is *sessions*: a user opens a conversation under one of a few system
prompts, and each turn re-enters the engine with the whole history as
its prompt — exactly the shape the radix prefix cache and the router's
prefix affinity exist for. This module generates that traffic and
drives it through a :class:`~.router.Router`:

- ``n_prefix_groups`` shared system prefixes (the "system prompt"
  population); each session draws one and opens with it;
- turn ``k``'s prompt = the full prior context (previous prompt +
  generated tokens) + fresh user tokens — submitted only after turn
  ``k-1`` finished (closed-loop per session, open-loop Poisson across
  session starts);
- the ``fleet/session`` chaos seam (faults/fleet.py,
  ``hot_key_skew``) collapses sessions onto group 0 with the planned
  probability, turning the mix into hot-key traffic;
- the driver consumes tokens through the router's delivery ledger
  (``take_new_tokens``) every step, so a soak with replica kills
  asserts the exactly-once stream property end to end.

Deterministic by construction: all randomness is seeded, and with
``virtual_dt`` set the driver runs on a virtual clock (arrivals and
deadlines in virtual seconds, one tick per router step) so a chaos
test's admission order cannot wobble with host load. Wall-clock mode
(``virtual_dt=0``) is what ``bench.py --mode fleet`` measures.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Dict, List, Optional

import numpy as np

from ..config import ModelConfig
from ..faults.fleet import session_skew
from ..utils.telemetry import MetricsTimeline, Telemetry, prometheus_text
from .engine import Engine, EngineConfig, compile_counts
from .requests import Request, RequestResult, SamplingParams
from .router import Router, RouterConfig


@dataclass(frozen=True)
class SessionLoadConfig:
    """Session-traffic shape. Sizing must fit the model's block_size:
    ``prefix_len + turns * (user_len_max + max_new_tokens)`` is the
    worst-case final context (validated in :func:`make_sessions`)."""

    n_sessions: int = 8
    turns: int = 3
    n_prefix_groups: int = 2
    prefix_len: int = 12
    user_len_min: int = 2
    user_len_max: int = 4
    max_new_tokens: int = 6
    rate: float = 100.0            # session-start arrivals/sec (Poisson)
    think_time_s: float = 0.0      # finish -> next-turn gap
    greedy: bool = True
    seed: int = 0
    #: the autoscaler acceptance trace: session arrivals run in three
    #: phases — the first third at ``rate``, the middle third at
    #: 2x ``rate`` (the load DOUBLES mid-run: sustained backlog, the
    #: scale-up signal), the final third at ``rate``/2 (the load
    #: HALVES: sustained lull, the scale-down signal). Same Poisson
    #: draws, phase-scaled — seeded and deterministic like everything
    #: else here.
    load_step: bool = False
    #: mixed long+short traffic (the disaggregation A/B trace,
    #: ``bench.py --mode fleet --disagg``): every ``long_every``-th
    #: session (sid % long_every == 0) opens with a UNIQUE
    #: ``long_prefix_len``-token prompt instead of its group prefix —
    #: no radix sharing, a guaranteed full prefill that monopolizes
    #: prompt budget. 0 disables. Both A/B arms replay the same lcfg,
    #: so the long/short mix is identical by construction.
    long_every: int = 0
    long_prefix_len: int = 0


def session_is_long(sid: int, lcfg: SessionLoadConfig) -> bool:
    """Whether session ``sid`` is a long-prompt session under the
    mixed trace rule (bench partitions TTFT by this)."""
    return lcfg.long_every > 0 and sid % lcfg.long_every == 0


@dataclass
class _Session:
    sid: int
    group: int
    context: np.ndarray            # tokens so far (prompt + generated)
    user_turns: List[np.ndarray]   # pre-drawn user tokens per turn
    next_turn: int = 0
    due_t: float = 0.0             # when the next turn submits
    waiting_on: Optional[str] = None


@dataclass(frozen=True)
class AdmissionStormConfig:
    """The admission-heavy saturating trace (the continuous-window
    acceptance workload): arrivals outpace the pool so nearly EVERY
    window boundary has an admissible head, prompts are short (admission
    cost dominates decode), and a slice of the traffic carries tight
    deadlines or mid-stream cancels — exactly the request dynamism that
    used to collapse the async engine to blocked k=1 dispatches. A
    continuous-window engine must hold its idle-trace dispatch
    amortization (>= 90%) through this storm; the pre-PR engine drops
    to 1.0x by construction."""

    n_requests: int = 96
    rate: float = 50_000.0         # arrivals/sec — saturating by design
    prompt_len_min: int = 2
    prompt_len_max: int = 8
    max_new_min: int = 6
    max_new_max: int = 14
    deadline_frac: float = 0.2     # fraction with a tight deadline
    deadline_s: float = 0.05       # relative deadline for that slice
    cancel_frac: float = 0.15      # fraction cancelled mid-flight
    cancel_after_s: float = 0.02   # cancel issued this long after arrival
    greedy: bool = True
    seed: int = 0


def admission_storm(mcfg: ModelConfig, scfg: AdmissionStormConfig
                    ) -> tuple:
    """Build the storm: returns ``(trace, cancels, deadlines)`` —
    ``trace`` is the (arrival_time, request) list ``run_replay`` takes,
    ``cancels`` a time-sorted [(t, request_id), ...] schedule the replay
    issues through ``engine.cancel``, and ``deadlines`` a
    {request_id: relative_deadline_s} map applied at submit. All draws
    seeded; the deadline/cancel slices are disjoint (a cancelled
    request's terminal reason must be unambiguous in the artifact)."""
    rng = np.random.default_rng(scfg.seed)
    hi = min(scfg.prompt_len_max, mcfg.block_size)
    lo = min(scfg.prompt_len_min, hi)
    sp = SamplingParams(greedy=scfg.greedy)
    n = scfg.n_requests
    # all scalar randomness drawn vectorized, converted once (host
    # numpy; .tolist() keeps the per-request loop free of per-item
    # float()/int() conversions per GL004)
    gaps = rng.exponential(1.0 / max(scfg.rate, 1e-9), n)
    arrivals = np.cumsum(gaps).tolist()
    lens = rng.integers(lo, hi + 1, n).tolist()
    budgets = rng.integers(scfg.max_new_min, scfg.max_new_max + 1,
                           n).tolist()
    lanes = rng.random(n).tolist() # [0, deadline_frac) -> deadline,
                                   # [deadline_frac, +cancel_frac) -> cancel
    trace, cancels, deadlines = [], [], {}
    for i in range(n):
        rid = f"storm{i:04d}"
        prompt = rng.integers(0, mcfg.vocab_size, (lens[i],),
                              dtype=np.int64).astype(np.int32)
        trace.append((arrivals[i], Request(
            id=rid, prompt=prompt, max_new_tokens=budgets[i],
            sampling=sp, rng_seed=scfg.seed * 100_003 + i)))
        if lanes[i] < scfg.deadline_frac:
            deadlines[rid] = scfg.deadline_s
        elif lanes[i] < scfg.deadline_frac + scfg.cancel_frac:
            cancels.append((arrivals[i] + scfg.cancel_after_s, rid))
    cancels.sort()
    return trace, cancels, deadlines


class StepClock:
    """Injectable virtual clock for deterministic fleet replays: the
    driver advances it one ``dt`` per router step, so arrival order,
    TTFT buckets and deadline math are identical run to run."""

    def __init__(self, t0: float = 0.0):
        self.t = t0

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def make_sessions(mcfg: ModelConfig, lcfg: SessionLoadConfig
                  ) -> List[_Session]:
    """Seeded session population: group prefixes, per-session start
    times (Poisson), per-turn user token draws. The ``hot_key_skew``
    chaos seam is consulted per session — with a plan installed, a
    session collapses onto group 0 with the planned probability."""
    worst_prefix = max(lcfg.prefix_len,
                       lcfg.long_prefix_len if lcfg.long_every else 0)
    worst = (worst_prefix
             + lcfg.turns * (lcfg.user_len_max + lcfg.max_new_tokens))
    assert worst <= mcfg.block_size, (
        f"session worst-case context {worst} exceeds block_size "
        f"{mcfg.block_size}: shrink turns/user_len/max_new_tokens")
    rng = np.random.default_rng(lcfg.seed)
    prefixes = [rng.integers(0, mcfg.vocab_size, (lcfg.prefix_len,),
                             dtype=np.int64).astype(np.int32)
                for _ in range(lcfg.n_prefix_groups)]
    # all scalar randomness drawn vectorized up front (host numpy, but
    # keeps the per-session loop free of float()/asarray per GL004)
    gaps = rng.exponential(1.0 / max(lcfg.rate, 1e-9), lcfg.n_sessions)
    if lcfg.load_step:
        # base -> 2x -> 0.5x arrival rate by thirds: a gap at k times
        # the rate is the base gap divided by k
        third = max(lcfg.n_sessions // 3, 1)
        gaps[third:2 * third] /= 2.0
        gaps[2 * third:] *= 2.0
    starts = np.cumsum(gaps)
    groups = rng.integers(0, lcfg.n_prefix_groups, lcfg.n_sessions)
    skew_draws = rng.random(lcfg.n_sessions)
    out: List[_Session] = []
    for sid in range(lcfg.n_sessions):
        group = int(groups[sid])
        skew = session_skew(sid)
        if skew > 0 and skew_draws[sid] < skew:
            group = 0              # the hot key
        turns = []
        for _ in range(lcfg.turns):
            n = int(rng.integers(lcfg.user_len_min,
                                 lcfg.user_len_max + 1))
            turns.append(rng.integers(0, mcfg.vocab_size, (n,),
                                      dtype=np.int64).astype(np.int32))
        if session_is_long(sid, lcfg):
            ctx = rng.integers(0, mcfg.vocab_size,
                               (lcfg.long_prefix_len,),
                               dtype=np.int64).astype(np.int32)
        else:
            ctx = prefixes[group].copy()
        out.append(_Session(sid=sid, group=group, context=ctx,
                            user_turns=turns, due_t=starts[sid]))
    return out


def session_request(s: _Session, lcfg: SessionLoadConfig) -> Request:
    """Build turn ``s.next_turn``'s request: full context + this turn's
    user tokens, with a per-(session, turn) rng seed so regeneration
    after a requeue is exact."""
    prompt = np.concatenate([s.context, s.user_turns[s.next_turn]])
    return Request(
        id=f"s{s.sid:03d}t{s.next_turn}", prompt=prompt,
        max_new_tokens=lcfg.max_new_tokens,
        sampling=SamplingParams(greedy=lcfg.greedy),
        rng_seed=lcfg.seed * 1_000_003 + s.sid * 101 + s.next_turn)


def run_fleet_replay(params, mcfg: ModelConfig,
                     lcfg: SessionLoadConfig,
                     rcfg: RouterConfig = RouterConfig(),
                     ecfg: EngineConfig = EngineConfig(),
                     warmup: bool = True,
                     virtual_dt: float = 0.0,
                     collect_streams: bool = False,
                     trace_out: Optional[str] = None,
                     metrics_timeline: Optional[str] = None,
                     metrics_timeline_interval_s: float = 0.5,
                     metrics_out: Optional[str] = None,
                     max_steps: int = 1_000_000,
                     router: Optional[Router] = None,
                     supervisor=None) -> dict:
    """Drive the session workload through a router fleet; returns the
    fleet summary (per-replica occupancy + pages, requeue counters,
    fleet TTFT distribution, aggregate prefix-hit rate,
    recompiles-after-warmup) plus per-session completion stats.

    ``virtual_dt > 0`` runs the whole replay on a :class:`StepClock`
    (deterministic chaos tests); 0 replays in wall-clock time (bench).
    ``collect_streams`` returns every request's router-delivered token
    stream under ``"streams"`` — the exactly-once-across-migration
    evidence the fleet chaos tests assert on. Observability artifacts
    (``trace_out`` Perfetto trace with router + per-replica tracks,
    ``metrics_timeline`` JSONL series of the ROUTER's metrics,
    ``metrics_out`` Prometheus text with per-replica gauges) mirror
    serve/replay.py's contract; paths land in ``summary["artifacts"]``.

    Pass ``router`` (and its ``supervisor``) to replay through an
    ALREADY-BUILT fleet instead of constructing one — the
    multi-process path (``faults.procsup.spawn_fleet``): ``params`` /
    ``ecfg`` / ``warmup`` / ``virtual_dt`` are ignored (each worker
    owns its model and warms itself; remote replays run in wall-clock
    time), the supervisor is ticked after every router step and while
    idle (worker restarts must progress while the fleet waits), and
    the CALLER keeps ownership of shutdown (``supervisor.stop_all()``
    then ``router.close()``). For a trace, attach a ``Telemetry`` at
    ``spawn_fleet`` time — ``trace_out`` exports the router's own
    recorder."""
    own_router = router is None
    if own_router:
        if warmup:
            w = Engine(params, mcfg, ecfg)
            w.submit(Request(id="warmup",
                             prompt=np.zeros((1,), np.int32),
                             max_new_tokens=1,
                             sampling=SamplingParams(greedy=True)))
            w.drain()
    warm = compile_counts()

    if own_router:
        clock = StepClock() if virtual_dt > 0 else time.monotonic
        tel = Telemetry(clock=clock) if trace_out else None
        router = Router(params, mcfg, rcfg, ecfg, clock=clock,
                        telemetry=tel)
    else:
        virtual_dt = 0.0
        clock = router.clock
        tel = router.tel if (trace_out and router.tel.enabled) else None
    timeline = None
    if metrics_timeline:
        timeline = MetricsTimeline(router.metrics, metrics_timeline,
                                   interval_s=metrics_timeline_interval_s,
                                   clock=clock)
        timeline.snapshot(step=0)
    sessions = make_sessions(mcfg, lcfg)
    streams: Dict[str, List[int]] = {}
    inflight_ids: List[str] = []
    results: Dict[str, RequestResult] = {}
    turns_done = 0
    t0 = clock()
    steps = 0
    try:
        while True:
            # the runaway guard counts EVERY loop iteration, idle
            # branch included — a stall where the router reports idle
            # but sessions still wait must raise, not spin forever
            steps += 1
            if steps > max_steps:
                raise RuntimeError(
                    f"fleet replay did not finish in {max_steps} steps")
            now = clock()
            for s in sessions:
                if (s.waiting_on is None and s.next_turn < lcfg.turns
                        and s.due_t <= now - t0):
                    req = session_request(s, lcfg)
                    s.waiting_on = req.id
                    streams.setdefault(req.id, [])
                    rej = router.submit(req)
                    if rej is not None:
                        results[req.id] = rej
                        s.waiting_on = None
                        s.next_turn = lcfg.turns    # session abandoned
                    else:
                        inflight_ids.append(req.id)
            pending_turns = any(
                s.next_turn < lcfg.turns for s in sessions)
            if router.idle:
                if not pending_turns:
                    break
                if supervisor is not None:
                    supervisor.tick()
                # nothing in flight: run the clock to the next arrival
                if virtual_dt > 0:
                    clock.advance(virtual_dt)
                else:
                    # default: no session is submit-ready (a stuck
                    # state) — spin to the max_steps RuntimeError
                    # instead of dying on min() of an empty sequence
                    nxt = min((s.due_t for s in sessions
                               if s.waiting_on is None
                               and s.next_turn < lcfg.turns),
                              default=now - t0)
                    time.sleep(min(max(nxt - (now - t0), 0.0), 0.05))
                continue
            finished = router.step()
            if supervisor is not None:
                supervisor.tick()
            # deliver: the ONE consumption path (exactly-once ledger)
            inflight_ids = [rid for rid in inflight_ids
                            if rid not in results]
            for rid in inflight_ids:
                streams[rid].extend(router.take_new_tokens(rid))
            for res in finished:
                results[res.id] = res
                streams[res.id].extend(router.take_new_tokens(res.id))
                turns_done += 1
                for s in sessions:
                    if s.waiting_on == res.id:
                        s.waiting_on = None
                        if res.ok:
                            # next turn re-enters with the WHOLE history
                            # (previous prompt + generated) — the
                            # prefix-cache / affinity traffic shape
                            prev = np.concatenate(
                                [s.context, s.user_turns[s.next_turn]])
                            s.context = np.concatenate(
                                [prev,
                                 np.fromiter(res.tokens, np.int32,
                                             count=len(res.tokens))])
                            s.next_turn += 1
                            s.due_t = ((clock() - t0)
                                       + lcfg.think_time_s)
                        else:
                            # cancelled / shed / expired / capacity:
                            # the session has no coherent history to
                            # continue from — it ends here
                            s.next_turn = lcfg.turns
                        break
            if timeline is not None:
                timeline.maybe_snapshot(step=router.n_steps)
            if virtual_dt > 0:
                clock.advance(virtual_dt)
    finally:
        if tel is not None:
            n_trace_events = tel.export_chrome_trace(trace_out)
            if own_router:
                tel.close()
        if timeline is not None:
            timeline.close(step=router.n_steps)
        if own_router:
            router.close()
    wall_s = clock() - t0

    done = compile_counts()
    summary = router.fleet_summary()
    ok = [r for r in results.values() if r.ok]
    summary.update({
        "n_sessions": lcfg.n_sessions,
        "turns_per_session": lcfg.turns,
        "n_requests": len(results),
        "turns_finished": turns_done,
        "n_completed": len(ok),
        "n_rejected": sum(r.finish_reason.startswith("rejected")
                          for r in results.values()),
        "generated_tokens": sum(len(r.tokens)
                                for r in results.values()),
        "wall_s": round(wall_s, 3),
        "recompiles_after_warmup": (sum(done.values())
                                    - sum(warm.values())),
    })
    artifacts = {}
    if tel is not None:
        artifacts["trace_out"] = trace_out
        artifacts["trace_events"] = n_trace_events
    if timeline is not None:
        artifacts["metrics_timeline"] = metrics_timeline
        artifacts["metrics_timeline_snapshots"] = timeline.n_snapshots
    if metrics_out:
        with open(metrics_out, "w") as f:
            f.write(prometheus_text(router.metrics, prefix="tpu_gpt_fleet"))
        artifacts["metrics_out"] = metrics_out
    if artifacts:
        summary["artifacts"] = artifacts
    if collect_streams:
        summary["streams"] = streams
        summary["results"] = results
    return summary
