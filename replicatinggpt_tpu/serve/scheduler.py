"""Admission control for the serving engine.

A bounded FIFO queue with backpressure: ``submit`` either enqueues or
rejects-with-reason immediately (never blocks, never grows without
bound — the "heavy traffic" failure mode is a queue that silently eats
RAM while latency compounds). Each engine step, ``admit`` hands over as
many queued requests as there are free pool slots, in arrival order,
dropping queued requests whose deadline already expired (no point
prefilling work that is already late).
"""

from __future__ import annotations

import time
from collections import deque
from typing import Callable, Deque, List, Optional, Tuple

from .requests import (FINISH_DEADLINE, REJECT_BAD_REQUEST,
                       REJECT_PROMPT_TOO_LONG, REJECT_QUEUE_FULL, Request)


class Scheduler:
    """Bounded FIFO admission queue + per-step admission decisions."""

    def __init__(self, max_queue: int, block_size: int,
                 clock: Callable[[], float] = time.monotonic):
        assert max_queue >= 1, max_queue
        self.max_queue = max_queue
        self.block_size = block_size
        self.clock = clock
        self._queue: Deque[Tuple[Request, float]] = deque()  # (req, t_submit)

    def __len__(self) -> int:
        return len(self._queue)

    @property
    def depth(self) -> int:
        return len(self._queue)

    def contains(self, request_id: str) -> bool:
        """Whether ``request_id`` is still queued (submit-time duplicate
        check; O(queue), which is bounded by max_queue)."""
        return any(req.id == request_id for req, _ in self._queue)

    def ids(self) -> List[str]:
        """Queued request ids in arrival order — the fleet router's
        re-route path enumerates a wedged replica's backlog with this
        (bounded by max_queue, like ``contains``)."""
        return [req.id for req, _ in self._queue]

    def peek(self) -> Optional[Tuple[Request, float]]:
        """The queue head WITHOUT popping it — the engine's window path
        asks "could this step admit?" before deciding whether to break
        a multi-token decode window for the admission (strict FIFO: the
        head is the only candidate, exactly as in ``admit``). While the
        head cannot fit, queued arrivals batch up and are admitted
        together at a later window boundary."""
        return self._queue[0] if self._queue else None

    def submit(self, req: Request) -> Optional[str]:
        """Enqueue ``req``; returns None on acceptance or a rejection
        reason (backpressure / validation) — the caller must surface
        rejections to the client instead of retrying blindly."""
        if req.prompt.size < 1 or req.max_new_tokens < 1:
            return REJECT_BAD_REQUEST
        if req.prompt.size > self.block_size:
            return REJECT_PROMPT_TOO_LONG
        if req.deadline is not None and self.clock() >= req.deadline:
            # already dead on arrival: queueing it would burn a queue
            # slot and a prefill on work nobody can use
            return FINISH_DEADLINE
        if len(self._queue) >= self.max_queue:
            return REJECT_QUEUE_FULL
        self._queue.append((req, self.clock()))
        return None

    def shed(self, n: int) -> List[Tuple[Request, float]]:
        """Drop up to ``n`` requests from the queue TAIL (newest first —
        the oldest are closest to service and fresh arrivals are the
        cheapest to turn away). Overload-shedding support
        (faults.watchdog.LoadShedder drives the policy)."""
        out: List[Tuple[Request, float]] = []
        while self._queue and len(out) < n:
            out.append(self._queue.pop())
        return out

    def cancel(self, request_id: str) -> bool:
        """Remove a still-queued request; True if it was found (an
        already-admitted request is the engine's to cancel)."""
        for i, (req, _) in enumerate(self._queue):
            if req.id == request_id:
                del self._queue[i]
                return True
        return False

    def admit(self, n_free: int, now: Optional[float] = None,
              fits: Optional[Callable[[Request], bool]] = None
              ) -> Tuple[List[Tuple[Request, float]],
                         List[Tuple[Request, float, str]]]:
        """Pop up to ``n_free`` admissible requests (arrival order).

        ``fits`` is the engine's resource gate beyond free slots (the
        paged pool's free-page check): a head that does not fit BLOCKS
        the queue rather than being skipped — strict FIFO, so a large
        request cannot be starved by a stream of small ones slipping
        past it. Returns (admitted, dropped): admitted as
        (request, t_submit) pairs; dropped as (request, t_submit,
        reason) for queued requests whose deadline expired before a
        slot freed up.
        """
        if now is None:
            now = self.clock()
        admitted: List[Tuple[Request, float]] = []
        dropped: List[Tuple[Request, float, str]] = []
        while self._queue and len(admitted) < n_free:
            req, t_submit = self._queue[0]
            if req.deadline is not None and now >= req.deadline:
                self._queue.popleft()
                dropped.append((req, t_submit, FINISH_DEADLINE))
                continue
            if fits is not None and not fits(req):
                break
            self._queue.popleft()
            admitted.append((req, t_submit))
        return admitted, dropped

    def drain_expired(self, now: Optional[float] = None
                      ) -> List[Tuple[Request, float, str]]:
        """Drop every queued request whose deadline has passed (called
        even when no slot is free, so expired work never occupies queue
        capacity)."""
        if now is None:
            now = self.clock()
        dropped, keep = [], deque()
        for req, t_submit in self._queue:
            if req.deadline is not None and now >= req.deadline:
                dropped.append((req, t_submit, FINISH_DEADLINE))
            else:
                keep.append((req, t_submit))
        self._queue = keep
        return dropped
