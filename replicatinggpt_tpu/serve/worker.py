"""Worker process: one Engine behind the serve/rpc.py socket protocol.

``python -m replicatinggpt_tpu serve-worker`` is the unit a real
deployment schedules — on THIS machine or any other that can reach
the router: it owns one engine (its own params, KV pool, compile
caches — a whole interpreter whose death takes nothing else with it),
an exclusively-locked crash journal on its own PRIVATE disk, and an
RPC socket the router drives. Nothing here assumes a filesystem
shared with the router: the worker announces itself over the network
(``register``), and its journal's content crosses the wire
(``journal_drain``). The router process (serve/router.py,
:class:`~.router.RemoteReplica`) holds the in-flight ledger (mirrored
to the router's OWN crash journal); the supervisor
(faults/procsup.py) holds the restart + autoscale policy; this
process holds the only thing that is actually expensive — the
compiled model — and the journal that makes losing it survivable.
Losing the journal TOO (host loss) is survivable one level up, from
the router's ledger.

Startup sequence (the order is the crash-recovery contract):

1. build + **warm** the engine (one throwaway greedy token through the
   decode path, un-journaled) — readiness means "the next request pays
   no compile";
2. open the journal with ``lock=True`` (flock: a not-quite-dead
   previous incarnation still holding it fails THIS process loudly
   rather than interleaving two writers) and ``fsync_finish`` on. The
   journal is **worker-local** storage: the router never opens it —
   its content crosses the network through the ``journal_drain`` RPC;
3. **replay** the journal: every accepted-but-unfinished request from
   the previous incarnation is resubmitted into the fresh engine — it
   regenerates deterministically from token 0, and the router's
   delivery ledger suppresses the prefix the client already saw
   (exactly-once across ``kill -9``, pinned in
   tests/test_fleet_multiproc.py). Requests the admission queue cannot
   hold yet stay in a pending list retried before every step;
4. bind the RPC server (port 0 = ephemeral) and **register** with the
   fleet over the network: one ``register`` frame to ``--router-addr``
   carrying ``{port, pid, gen, replayed, worker_idx, proto,
   shape_hash}`` (serve/rpc.py). The supervisor's
   :class:`~..serve.rpc.RpcListener` answers and attaches the router —
   only now is the worker routable. No ready files, no shared
   filesystem: this is the handshake that makes the worker placeable
   on any host that can reach the router. A protocol-version or
   engine-shape mismatch is rejected HERE with a typed
   :class:`~..serve.rpc.RpcProtocolError` (exit code 3), never
   mid-traffic.

The worker never steps itself: the router's ``step`` RPC is the one
driver, so fleet scheduling stays single-threaded and deterministic
across the process boundary exactly as it is within one. Finished
results are buffered until the router acks them (serve/rpc.py's
redelivery contract); committed tokens for active slots piggyback on
every step response (the stream-drain the delivery ledger reads).
"""

from __future__ import annotations

import asyncio
import json
import os
import random
import signal
import sys
import time
from collections import OrderedDict
from typing import Dict, List, Optional

from .engine import Engine
from .journal import RequestJournal
from .requests import FINISH_CANCELLED, Request, RequestResult
from .rpc import (HEADER_BYTES, JOURNAL_DRAIN_LIMIT, PROTO_VERSION,
                  REJECT_REPLICA_DOWN, RpcProtocolError, crc_ok,
                  decode_header, encode_frame, request_from_wire,
                  request_to_wire, result_to_wire, serve_connection)


#: re-registration pacing (ROADMAP 3a remainder): a worker that
#: registered once but then hears NOTHING from the router for
#: REREGISTER_IDLE_S seconds assumes the router (or its listener)
#: restarted and lost the attachment — it re-announces itself with
#: bounded exponential backoff until a listener answers again. A
#: healthy router drives the worker every step, so silence IS the
#: signal; re-registering an already-attached worker is idempotent
#: (the supervisor's handler re-attaches at the same gen).
REREGISTER_IDLE_S = 5.0
REREGISTER_BACKOFF_S = 0.5
REREGISTER_BACKOFF_CAP_S = 10.0

#: Mutating verbs whose dispatch consults the reply cache (graftlint
#: GL024 holds this tuple against the registry in
#: analysis/contracts.py): a duplicated or blindly-retried frame
#: carrying an ``idem`` key the worker has already answered returns
#: the CACHED reply (marked ``idem_hit``) instead of re-executing —
#: the worker-side half of exactly-once under duplication. Read-only
#: verbs (step has its own ack/redeliver protocol; health, prefix,
#: summary, stream_drain are pure reads) stay uncached.
IDEMPOTENT_VERBS = ("submit", "page_transfer", "journal_drain")

#: bounded reply cache: plenty for every in-flight retry window (a
#: duplicate older than 256 mutating calls is not a retry, it is a
#: bug), small enough to never matter in memory
REPLY_CACHE_SIZE = 256


class WorkerServer:
    """Dispatch table around one engine (single-threaded: runs inside
    the asyncio loop, which is the worker's only thread of control)."""

    def __init__(self, engine: Engine,
                 journal: Optional[RequestJournal],
                 clock=time.monotonic):
        self.engine = engine
        self.journal = journal
        self.clock = clock
        self.draining = False
        self.warmed = False
        #: this incarnation's generation (faults/procsup.py assigns it
        #: at spawn; -1 = unfenced, for direct-embedding tests). The
        #: dispatch gate rejects calls stamped with any OTHER
        #: generation — a router still holding a connection to a
        #: partitioned-then-replaced incarnation gets a typed "stale
        #: generation" protocol error, never a quiet wrong-process
        #: mutation.
        self.gen = -1
        #: monotonic timestamp of the last inbound router RPC — the
        #: re-registration loop's silence detector
        self.last_contact = time.monotonic()
        #: idempotency reply cache (bounded, insertion-ordered): the
        #: last reply per idem key on mutating verbs — dispatch
        #: consults it so duplicated frames answer without re-executing
        self._replies: "OrderedDict[str, dict]" = OrderedDict()
        self.stop_event = asyncio.Event()
        #: finished results not yet acked by the router — redelivered
        #: in every step response until an ack prunes them (a response
        #: lost to a timeout/reconnect must not lose a finish)
        self._finished: Dict[str, RequestResult] = {}
        #: journal-replayed requests the admission queue could not hold
        #: yet (retried before every step)
        self._replay_pending: List[Request] = []
        #: journal_drain paging snapshot (one disk read per drain
        #: session; reset at eof / a fresh cursor-0 call)
        self._drain_snapshot: Optional[List[dict]] = None
        self.n_replayed = 0

    # ------------------------------------------------------------ replay

    def replay_journal(self, path: str) -> int:
        """Resubmit the previous incarnation's unfinished requests."""
        pending = RequestJournal.unfinished(path)
        self.n_replayed = len(pending)
        for req in pending:
            rej = self.engine.submit(req)
            if rej is not None:
                self._replay_pending.append(req)
        return self.n_replayed

    def _retry_replays(self) -> None:
        still: List[Request] = []
        for req in self._replay_pending:
            if self.engine.submit(req) is not None:
                still.append(req)
        self._replay_pending = still

    # ---------------------------------------------------------- dispatch

    def dispatch(self, doc: dict) -> dict:
        op = doc.get("op")
        fn = getattr(self, f"op_{op}", None)
        if fn is None:
            raise ValueError(f"unknown op {op!r}")
        self.last_contact = time.monotonic()
        gen = doc.get("gen")
        if gen is not None and self.gen >= 0 and int(gen) != self.gen:
            # the generation fence: a caller stamped with another
            # incarnation's gen is talking to the wrong process —
            # typed rejection, never execution (the router classifies
            # the "stale generation" marker and re-resolves)
            raise RpcProtocolError(
                f"stale generation {gen} (worker at gen {self.gen})")
        idem = doc.get("idem")
        if idem is not None and op in IDEMPOTENT_VERBS:
            cached = self._replies.get(idem)
            if cached is not None:
                # a duplicated/retried mutating frame: answer from the
                # reply cache — the original execution's exact
                # response, marked so the router's suppression counter
                # can account for it
                return {**cached, "idem_hit": True}
            resp = fn(doc) or {}
            self._replies[idem] = resp
            while len(self._replies) > REPLY_CACHE_SIZE:
                self._replies.popitem(last=False)
            return resp
        return fn(doc)

    def _in_flight_ids(self) -> List[str]:
        return (self.engine.in_flight_ids()
                + [r.id for r in self._replay_pending])

    def _gauges(self) -> dict:
        eng = self.engine
        a = eng.pool.alloc
        return {
            "queue_depth": eng.scheduler.depth,
            "slots_active": int(eng._active.sum()),
            "pages_in_use": a.pages_in_use,
            "prefix_hit_tokens": a.prefix_hit_tokens,
            "prompt_tokens": a.prompt_tokens,
            "n_steps": eng.n_steps,
            "idle": (eng.idle and not self._replay_pending
                     and not self._finished),
            "warmed": self.warmed,
        }

    def _partials(self) -> Dict[str, List[int]]:
        out: Dict[str, List[int]] = {}
        for rid in self.engine.in_flight_ids():
            toks = self.engine.partial_tokens(rid)
            if toks is not None:
                out[rid] = toks
        return out

    def op_submit(self, doc: dict) -> dict:
        if self.draining:
            return {"accepted": False,
                    "rejection": result_to_wire(RequestResult(
                        id=doc["req"]["id"], tokens=[],
                        finish_reason=REJECT_REPLICA_DOWN))}
        req = request_from_wire(doc["req"], self.clock())
        rej = self.engine.submit(req)
        if rej is None:
            return {"accepted": True}
        return {"accepted": False, "rejection": result_to_wire(rej)}

    def op_step(self, doc: dict) -> dict:
        for rid in doc.get("acks", []):
            self._finished.pop(rid, None)
        self._retry_replays()
        for res in self.engine.step():
            self._finished[res.id] = res
        return {
            "finished": [result_to_wire(r)
                         for r in self._finished.values()],
            "partials": self._partials(),
            **self._gauges(),
        }

    def op_stream_drain(self, doc: dict) -> dict:
        return {"partials": self._partials(), **self._gauges()}

    def op_cancel(self, doc: dict) -> dict:
        rid = doc["id"]
        migrated = bool(doc.get("migrated"))
        found = self.engine.cancel(rid, migrated=migrated)
        if not found:
            # a replay-pending id is in flight too (journal says so):
            # cancelling it must journal a finish or a future restart
            # would resurrect it
            for i, req in enumerate(self._replay_pending):
                if req.id == rid:
                    del self._replay_pending[i]
                    if self.journal is not None:
                        self.journal.record_finish(rid, FINISH_CANCELLED)
                    found = True
                    break
        return {"found": found}

    def op_prefix(self, doc: dict) -> dict:
        import numpy as np
        prompt = np.asarray(doc["prompt"],
                            np.int32)
        return {"tokens": int(
            self.engine.pool.cached_prefix_tokens(prompt))}

    def op_page_transfer(self, doc: dict) -> dict:
        """The disaggregation verb (serve/disagg.py): this worker is
        the source (export_* kinds, prefill tier) or the sink
        (install_* kinds, decode tier) of one prefix transfer. State
        between kinds lives in the Local* adapters, lazily built —
        a worker that never disaggregates never touches them."""
        import numpy as np

        from .disagg import LocalPageSink, LocalPageSource
        from .rpc import page_block_to_wire
        if not hasattr(self, "_xfer_src"):
            self._xfer_src = LocalPageSource(self.engine)
            self._xfer_sink = LocalPageSink(self.engine)
        kind, key = doc["kind"], doc["key"]
        if kind == "export_begin":
            n = self._xfer_src.begin(
                key, np.asarray(doc["prompt"], np.int32),
                int(doc["from_page"]))
            return {"pages": n,
                    "page_bytes": self._xfer_src.page_bytes}
        if kind == "export_chunk":
            blocks, cursor, done = self._xfer_src.chunk(
                key, int(doc["cursor"]), int(doc.get("limit", 0)))
            return {"blocks": [page_block_to_wire(b) for b in blocks],
                    "cursor": cursor, "done": done}
        if kind == "export_end":
            self._xfer_src.end(key)
            return {}
        if kind == "install_begin":
            if self.draining:
                return {"accepted": False}
            return {"accepted": self._xfer_sink.begin(
                key, np.asarray(doc["prompt"], np.int32),
                int(doc["from_page"]), int(doc["n_pages"]))}
        if kind == "install_chunk":
            self._xfer_sink.chunk(key, doc["blocks"])
            return {}
        if kind == "install_commit":
            if doc.get("abort"):
                self._xfer_sink.abort(key)
                return {"registered": 0}
            return {"registered": self._xfer_sink.commit(key)}
        raise ValueError(f"unknown page_transfer kind {kind!r}")

    def op_health(self, doc: dict) -> dict:
        return {
            "pid": os.getpid(),
            "vocab_size": int(self.engine.cfg.vocab_size),
            "in_flight": self._in_flight_ids(),
            "replayed": self.n_replayed,
            "draining": self.draining,
            "counters": {k: int(v) for k, v in
                         self.engine.metrics.counters.items()},
            **self._gauges(),
        }

    def op_summary(self, doc: dict) -> dict:
        from .engine import engine_summary_block
        return {"block": engine_summary_block(self.engine)}

    def _journal_view(self) -> List[dict]:
        """Condensed journal state for ``journal_drain``: the last
        finish reason per id (in journal order), then the
        still-unfinished requests as wire docs. Computed fresh per
        drain — the file is worker-local and the reader is the shared
        torn-tail-tolerant one, so a drain racing an append sees a
        consistent prefix."""
        if self.journal is None:
            return []
        from ..utils.jsonl import load_jsonl_if_exists
        reasons: Dict[str, str] = {}
        for rec in load_jsonl_if_exists(self.journal.path):
            if rec.get("ev") == "finish":
                reasons[rec["id"]] = rec.get("reason", "")
        now = self.clock()
        return ([{"kind": "finished", "id": rid, "reason": reason}
                 for rid, reason in reasons.items()]
                + [{"kind": "unfinished",
                    "req": request_to_wire(req, now)}
                   for req in RequestJournal.unfinished(
                       self.journal.path)])

    def op_journal_drain(self, doc: dict) -> dict:
        """Stream the local journal's condensed state in bounded
        frames: the router pages with ``cursor`` until ``eof``. This
        replaces the shared-filesystem journal read PR 9's
        ``attach_replica`` did — reconciliation state crosses the RPC
        channel, so the worker's disk can live on another machine.

        The view is SNAPSHOTTED at ``cursor == 0`` and later frames
        page over that snapshot: one disk read per drain session (not
        per frame — a long journal would make reconcile O(R^2)), and
        a record appended mid-drain can never shift the paging under
        the reader. ``kinds`` filters the snapshot (the router's
        attach only needs the finish records; the unfinished half
        exists for a router rebuilding from nothing)."""
        cursor = max(int(doc.get("cursor", 0)), 0)
        limit = max(1, min(int(doc.get("limit", JOURNAL_DRAIN_LIMIT)),
                           JOURNAL_DRAIN_LIMIT))
        kinds = doc.get("kinds")
        if cursor == 0 or self._drain_snapshot is None:
            records = self._journal_view()
            if kinds:
                records = [r for r in records if r["kind"] in kinds]
            self._drain_snapshot = records
        records = self._drain_snapshot
        frame = records[cursor:cursor + limit]
        eof = cursor + len(frame) >= len(records)
        if eof:
            self._drain_snapshot = None
        return {"records": frame, "cursor": cursor + len(frame),
                "eof": eof}

    def op_drain(self, doc: dict) -> dict:
        """Rolling-restart drain: refuse new submits, cancel everything
        in flight as migrated (the journal records the finishes, so the
        NEXT incarnation's replay resurrects none of it)."""
        self.draining = True
        ids = self._in_flight_ids()
        for rid in list(self.engine.in_flight_ids()):
            self.engine.cancel(rid, migrated=True)
        for req in self._replay_pending:
            if self.journal is not None:
                self.journal.record_finish(req.id, FINISH_CANCELLED)
        self._replay_pending = []
        return {"cancelled": ids}

    def op_shutdown(self, doc: dict) -> dict:
        asyncio.get_running_loop().call_soon(self.stop_event.set)
        return {"stopping": True}


async def _register_attempt(router_addr: str, doc: dict) -> dict:
    """ONE register frame to the fleet's RpcListener. Returns the ok
    response; raises :class:`RpcProtocolError` on a typed rejection
    (a version/shape-mismatched build must exit, not retry) and
    :class:`ConnectionError` on transport failure or any other
    rejection (the caller owns the retry/backoff policy)."""
    host, _, port = router_addr.rpartition(":")
    writer = None
    try:
        reader, writer = await asyncio.open_connection(
            host or "127.0.0.1", int(port))
        writer.write(encode_frame({"op": "register", **doc}))
        await writer.drain()
        header = await asyncio.wait_for(
            reader.readexactly(HEADER_BYTES), 15.0)
        n, crc = decode_header(header)
        body = await asyncio.wait_for(reader.readexactly(n), 15.0)
        if not crc_ok(body, crc):
            raise ConnectionError(
                "registration response checksum mismatch")
        resp = json.loads(body)
    except RpcProtocolError:
        raise
    except (OSError, ValueError, asyncio.IncompleteReadError,
            asyncio.TimeoutError, ConnectionError) as e:
        raise ConnectionError(f"{type(e).__name__}: {e}") from e
    finally:
        if writer is not None:
            writer.close()
    if resp.get("ok"):
        return resp
    if resp.get("kind") == "protocol":
        raise RpcProtocolError(resp.get("error", "protocol mismatch"))
    raise ConnectionError(resp.get("error", "rejected"))


async def _register_with_router(router_addr: str, doc: dict,
                                budget_s: float = 120.0) -> dict:
    """Startup registration: ``_register_attempt`` retried until the
    listener answers (it polls from the router's single-threaded loop,
    so the response may lag a tick). Transport failures retry;
    :class:`RpcProtocolError` propagates — a mismatched build exits."""
    deadline = time.monotonic() + budget_s
    last = "no attempt"
    while time.monotonic() < deadline:
        try:
            return await _register_attempt(router_addr, doc)
        except ConnectionError as e:
            last = str(e)
        await asyncio.sleep(0.2)
    raise RuntimeError(
        f"registration with {router_addr} failed: {last}")


async def _reregister_loop(worker, router_addr: str, doc: dict,
                           idle_s: float = REREGISTER_IDLE_S,
                           backoff_s: float = REREGISTER_BACKOFF_S,
                           backoff_cap_s: float =
                           REREGISTER_BACKOFF_CAP_S,
                           on_reregister=None, rng=None) -> None:
    """Keep the worker attached across router restarts (ROADMAP 3a
    remainder): the startup handshake registered exactly once, so a
    router whose listener restarted (or whose process was replaced —
    it recovers in-flight work from its OWN ledger, never worker disk)
    would simply never drive this worker again. This loop watches for
    SILENCE — no inbound RPC for ``idle_s`` — and re-sends the
    register frame until a listener answers; re-registering at the
    same gen is an idempotent re-attach on the supervisor side. A
    typed protocol rejection stops the worker (the fleet's expected
    shape changed under us — serving on would split streams).

    Backoff is FULL-JITTER exponential (``uniform(0, min(cap, base *
    2^n))``): plain doubling is synchronized across the fleet — every
    worker detects a partition heal on the same idle tick and the
    whole fleet re-registers against the router in one thundering
    herd, exactly when the router is busiest reconciling. The jitter
    decorrelates them; ``rng`` is injectable for deterministic tests
    and seeds from the pid otherwise (each process must draw a
    DIFFERENT schedule — that is the point).

    One SILENCE EPISODE is one logical registration: the idem key on
    the register frame is refreshed when a new episode begins and
    reused across the retries within it, so a listener that executed
    the attach but lost the response answers the retry from its reply
    cache instead of reconciling twice."""
    rng = rng or random.Random(os.getpid())
    attempt = 0
    episode = 0
    in_episode = False
    base_idem = doc.get("idem", f"reg.{doc.get('worker_idx', 0)}"
                                f".{doc.get('gen', 0)}")
    while not worker.stop_event.is_set():
        if time.monotonic() - worker.last_contact < idle_s:
            # healthy traffic: reset the backoff and poll at half the
            # idle threshold so silence is detected promptly
            attempt = 0
            in_episode = False
            await asyncio.sleep(idle_s / 2)
            continue
        if not in_episode:
            in_episode = True
            episode += 1
            doc = {**doc, "idem": f"{base_idem}.re{episode}"}
        try:
            await _register_attempt(router_addr, doc)
            worker.last_contact = time.monotonic()
            attempt = 0
            in_episode = False
            if on_reregister is not None:
                on_reregister()
        except RpcProtocolError as e:
            print(f"re-registration REJECTED (protocol/shape "
                  f"mismatch): {e}; stopping", file=sys.stderr)
            worker.stop_event.set()
            return
        except ConnectionError:
            attempt += 1
        # attempts are spaced by the full-jitter backoff (not the idle
        # poll), so a long outage decays toward uniform draws over
        # [0, cap) — decorrelated across the fleet
        await asyncio.sleep(rng.uniform(
            0.0, min(backoff_cap_s, backoff_s * (2.0 ** attempt))))


def warm_engine(engine: Engine) -> None:
    """One throwaway greedy request through prefill + decode, so
    readiness implies compiled programs (no journal attached yet — a
    warmup request must never appear in a crash journal). With a
    decode window configured the bucketed window programs compiled at
    engine construction (``Engine._warm_windows``); this request is
    long enough to EXERCISE the steady-state path past the admission
    boundary's mixed dispatch (``EngineConfig.warmup_tokens`` — shared
    with the replay warmup)."""
    import numpy as np

    from .requests import SamplingParams
    engine.submit(Request(id="__warmup__",
                          prompt=np.zeros((1,), np.int32),
                          max_new_tokens=engine.ecfg.warmup_tokens(),
                          sampling=SamplingParams(greedy=True)))
    engine.drain()


async def _run_async(worker: WorkerServer, host: str, port: int,
                     router_addr: Optional[str], gen: int,
                     worker_idx: int, shape_hash: str,
                     tier: str = "mixed") -> int:
    # arm the wire-level generation fence: dispatch() rejects calls
    # stamped with any OTHER incarnation's gen (see WorkerServer.gen)
    worker.gen = gen
    server = await asyncio.start_server(
        lambda r, w: serve_connection(r, w, worker.dispatch),
        host, port)
    bound = server.sockets[0].getsockname()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            loop.add_signal_handler(sig, worker.stop_event.set)
        except NotImplementedError:   # non-Unix event loops
            pass
    print(f"worker listening on {bound[0]}:{bound[1]} "
          f"pid={os.getpid()} gen={gen} idx={worker_idx} "
          f"shape={shape_hash} replayed={worker.n_replayed}",
          file=sys.stderr)
    rereg_task = None
    if router_addr:
        # the server is ALREADY live: the supervisor's attach
        # (health/stream_drain/journal_drain RPCs) is served by this
        # same loop while the register coroutine awaits its response
        # "tier" advertises this worker's role in a disaggregated
        # fleet (serve/disagg.py): "prefill" takes prefill_only
        # requests, "decode" takes sessions, "mixed" takes both —
        # the router's placement policy reads it off registration
        # the idem key makes registration safe to blind-retry: a
        # supervisor that executed the attach but lost the response
        # answers the retry from its reply cache (one episode = one
        # logical attach; _reregister_loop refreshes the suffix per
        # silence episode)
        reg_doc = {"port": bound[1], "pid": os.getpid(), "gen": gen,
                   "worker_idx": worker_idx,
                   "replayed": worker.n_replayed,
                   "proto": PROTO_VERSION, "shape_hash": shape_hash,
                   "tier": tier,
                   "page_size": int(worker.engine.pool.page_size),
                   "idem": f"reg.{worker_idx}.{gen}.{os.getpid()}.0"}
        try:
            await _register_with_router(router_addr, reg_doc)
        except RpcProtocolError as e:
            print(f"registration REJECTED (protocol/shape mismatch): "
                  f"{e}", file=sys.stderr)
            server.close()
            await server.wait_closed()
            return 3
        print(f"registered with {router_addr}", file=sys.stderr)
        worker.last_contact = time.monotonic()
        # registration is no longer once-at-startup: the background
        # loop re-announces this worker (bounded backoff) whenever the
        # router goes silent — a RESTARTED router's fresh listener
        # re-attaches us without an operator touching the worker
        rereg_task = asyncio.ensure_future(_reregister_loop(
            worker, router_addr, reg_doc,
            idle_s=getattr(worker, "reregister_idle_s",
                           REREGISTER_IDLE_S),
            on_reregister=lambda: print(
                f"re-registered with {router_addr} (router was "
                f"silent)", file=sys.stderr)))
    await worker.stop_event.wait()
    if rereg_task is not None:
        rereg_task.cancel()
        try:
            await rereg_task
        except asyncio.CancelledError:
            pass
    server.close()
    await server.wait_closed()
    # let an in-flight shutdown response flush before the process exits
    await asyncio.sleep(0.05)
    return 0


def run_worker(args) -> int:
    """The serve-worker subcommand body (see cli.py for the flags)."""
    from ..config import config_from_args
    from ..train.state import create_train_state
    import jax

    cfg = config_from_args(args)
    state = create_train_state(jax.random.PRNGKey(cfg.train.seed),
                               cfg.model, cfg.train)
    if args.checkpoint_dir:
        from ..train.checkpoint import CheckpointManager
        restored = (CheckpointManager(args.checkpoint_dir)
                    .restore_latest(state))
        if restored is None:
            print("no checkpoint found; serving random init",
                  file=sys.stderr)
        else:
            state = restored
    # ONE EngineConfig builder with the router process (cli.py): the
    # multiproc forwarding contract (ENGINE_FORWARD_FLAGS) holds only
    # if both sides parse the same flags into the same config — a
    # worker owning its own --mesh-shape slice included
    from ..cli import engine_config_from_args
    ecfg = engine_config_from_args(args)
    if ecfg.weight_quant != "none":
        # serialized-calibration workflow (quant/weights.py): reuse
        # the scales next to the checkpoint so every worker in the
        # fleet serves the SAME quantized weights bit-for-bit
        from ..quant.weights import prepare_params
        state = state._replace(params=prepare_params(
            state.params, cfg.model, ecfg.weight_quant,
            checkpoint_dir=args.checkpoint_dir,
            log=lambda m: print(m, file=sys.stderr)))
    engine = Engine(state.params, cfg.model, ecfg)
    warm_engine(engine)

    journal = None
    if args.journal:
        journal = RequestJournal(args.journal,
                                 fsync_finish=not args.no_fsync,
                                 lock=True)
        engine.journal = journal
    worker = WorkerServer(engine, journal)
    worker.reregister_idle_s = getattr(args, "reregister_idle_s", 5.0)
    worker.warmed = True
    if args.journal:
        n = worker.replay_journal(args.journal)
        if n:
            print(f"journal replay: {n} unfinished request(s) "
                  f"resubmitted", file=sys.stderr)
    from .rpc import engine_shape_hash
    shape = engine_shape_hash(cfg.model, ecfg)
    try:
        return asyncio.run(_run_async(
            worker, args.host, args.port, args.router_addr, args.gen,
            args.worker_idx, shape,
            tier=getattr(args, "tier", "mixed")))
    finally:
        if journal is not None:
            journal.close()
