"""Worker process: one Engine behind the serve/rpc.py socket protocol.

``python -m replicatinggpt_tpu serve-worker`` is the unit a real
deployment schedules: it owns one engine (its own params, KV pool,
compile caches — a whole interpreter whose death takes nothing else
with it), an exclusively-locked crash journal on shared storage, and a
loopback RPC socket the router drives. The router process
(serve/router.py, :class:`~.router.RemoteReplica`) holds the in-flight
ledger; the supervisor (faults/procsup.py) holds the restart policy;
this process holds the only thing that is actually expensive — the
compiled model — and the journal that makes losing it survivable.

Startup sequence (the order is the crash-recovery contract):

1. build + **warm** the engine (one throwaway greedy token through the
   decode path, un-journaled) — readiness means "the next request pays
   no compile";
2. open the journal with ``lock=True`` (flock: a not-quite-dead
   previous incarnation still holding it fails THIS process loudly
   rather than interleaving two writers) and ``fsync_finish`` on;
3. **replay** the journal: every accepted-but-unfinished request from
   the previous incarnation is resubmitted into the fresh engine — it
   regenerates deterministically from token 0, and the router's
   delivery ledger suppresses the prefix the client already saw
   (exactly-once across ``kill -9``, pinned in
   tests/test_fleet_multiproc.py). Requests the admission queue cannot
   hold yet stay in a pending list retried before every step;
4. bind the RPC server (port 0 = ephemeral) and atomically write the
   **ready file** (`{"port", "pid", "gen", "replayed"}`) the
   supervisor polls — only now is the worker routable.

The worker never steps itself: the router's ``step`` RPC is the one
driver, so fleet scheduling stays single-threaded and deterministic
across the process boundary exactly as it is within one. Finished
results are buffered until the router acks them (serve/rpc.py's
redelivery contract); committed tokens for active slots piggyback on
every step response (the stream-drain the delivery ledger reads).
"""

from __future__ import annotations

import asyncio
import json
import os
import signal
import sys
import time
from typing import Dict, List, Optional

from .engine import Engine
from .journal import RequestJournal
from .requests import FINISH_CANCELLED, Request, RequestResult
from .rpc import (REJECT_REPLICA_DOWN, request_from_wire,
                  result_to_wire, serve_connection)


class WorkerServer:
    """Dispatch table around one engine (single-threaded: runs inside
    the asyncio loop, which is the worker's only thread of control)."""

    def __init__(self, engine: Engine,
                 journal: Optional[RequestJournal],
                 clock=time.monotonic):
        self.engine = engine
        self.journal = journal
        self.clock = clock
        self.draining = False
        self.warmed = False
        self.stop_event = asyncio.Event()
        #: finished results not yet acked by the router — redelivered
        #: in every step response until an ack prunes them (a response
        #: lost to a timeout/reconnect must not lose a finish)
        self._finished: Dict[str, RequestResult] = {}
        #: journal-replayed requests the admission queue could not hold
        #: yet (retried before every step)
        self._replay_pending: List[Request] = []
        self.n_replayed = 0

    # ------------------------------------------------------------ replay

    def replay_journal(self, path: str) -> int:
        """Resubmit the previous incarnation's unfinished requests."""
        pending = RequestJournal.unfinished(path)
        self.n_replayed = len(pending)
        for req in pending:
            rej = self.engine.submit(req)
            if rej is not None:
                self._replay_pending.append(req)
        return self.n_replayed

    def _retry_replays(self) -> None:
        still: List[Request] = []
        for req in self._replay_pending:
            if self.engine.submit(req) is not None:
                still.append(req)
        self._replay_pending = still

    # ---------------------------------------------------------- dispatch

    def dispatch(self, doc: dict) -> dict:
        op = doc.get("op")
        fn = getattr(self, f"op_{op}", None)
        if fn is None:
            raise ValueError(f"unknown op {op!r}")
        return fn(doc)

    def _in_flight_ids(self) -> List[str]:
        return (self.engine.in_flight_ids()
                + [r.id for r in self._replay_pending])

    def _gauges(self) -> dict:
        eng = self.engine
        a = eng.pool.alloc
        return {
            "queue_depth": eng.scheduler.depth,
            "slots_active": int(eng._active.sum()),
            "pages_in_use": a.pages_in_use,
            "prefix_hit_tokens": a.prefix_hit_tokens,
            "prompt_tokens": a.prompt_tokens,
            "n_steps": eng.n_steps,
            "idle": (eng.idle and not self._replay_pending
                     and not self._finished),
            "warmed": self.warmed,
        }

    def _partials(self) -> Dict[str, List[int]]:
        out: Dict[str, List[int]] = {}
        for rid in self.engine.in_flight_ids():
            toks = self.engine.partial_tokens(rid)
            if toks is not None:
                out[rid] = toks
        return out

    def op_submit(self, doc: dict) -> dict:
        if self.draining:
            return {"accepted": False,
                    "rejection": result_to_wire(RequestResult(
                        id=doc["req"]["id"], tokens=[],
                        finish_reason=REJECT_REPLICA_DOWN))}
        req = request_from_wire(doc["req"], self.clock())
        rej = self.engine.submit(req)
        if rej is None:
            return {"accepted": True}
        return {"accepted": False, "rejection": result_to_wire(rej)}

    def op_step(self, doc: dict) -> dict:
        for rid in doc.get("acks", []):
            self._finished.pop(rid, None)
        self._retry_replays()
        for res in self.engine.step():
            self._finished[res.id] = res
        return {
            "finished": [result_to_wire(r)
                         for r in self._finished.values()],
            "partials": self._partials(),
            **self._gauges(),
        }

    def op_stream_drain(self, doc: dict) -> dict:
        return {"partials": self._partials(), **self._gauges()}

    def op_cancel(self, doc: dict) -> dict:
        rid = doc["id"]
        migrated = bool(doc.get("migrated"))
        found = self.engine.cancel(rid, migrated=migrated)
        if not found:
            # a replay-pending id is in flight too (journal says so):
            # cancelling it must journal a finish or a future restart
            # would resurrect it
            for i, req in enumerate(self._replay_pending):
                if req.id == rid:
                    del self._replay_pending[i]
                    if self.journal is not None:
                        self.journal.record_finish(rid, FINISH_CANCELLED)
                    found = True
                    break
        return {"found": found}

    def op_prefix(self, doc: dict) -> dict:
        import numpy as np
        prompt = np.asarray(doc["prompt"],
                            np.int32)
        return {"tokens": int(
            self.engine.pool.cached_prefix_tokens(prompt))}

    def op_health(self, doc: dict) -> dict:
        return {
            "pid": os.getpid(),
            "vocab_size": int(self.engine.cfg.vocab_size),
            "in_flight": self._in_flight_ids(),
            "replayed": self.n_replayed,
            "draining": self.draining,
            "counters": {k: int(v) for k, v in
                         self.engine.metrics.counters.items()},
            **self._gauges(),
        }

    def op_summary(self, doc: dict) -> dict:
        from .engine import engine_summary_block
        return {"block": engine_summary_block(self.engine)}

    def op_drain(self, doc: dict) -> dict:
        """Rolling-restart drain: refuse new submits, cancel everything
        in flight as migrated (the journal records the finishes, so the
        NEXT incarnation's replay resurrects none of it)."""
        self.draining = True
        ids = self._in_flight_ids()
        for rid in list(self.engine.in_flight_ids()):
            self.engine.cancel(rid, migrated=True)
        for req in self._replay_pending:
            if self.journal is not None:
                self.journal.record_finish(req.id, FINISH_CANCELLED)
        self._replay_pending = []
        return {"cancelled": ids}

    def op_shutdown(self, doc: dict) -> dict:
        asyncio.get_running_loop().call_soon(self.stop_event.set)
        return {"stopping": True}


def _write_ready_file(path: str, doc: dict) -> None:
    """Atomic (tmp + rename): the supervisor polling this file must
    never read a torn JSON."""
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def warm_engine(engine: Engine) -> None:
    """One throwaway greedy request through prefill + decode, so
    readiness implies compiled programs (no journal attached yet — a
    warmup request must never appear in a crash journal). With a
    decode window configured the bucketed window programs compiled at
    engine construction (``Engine._warm_windows``); this request is
    long enough to EXERCISE the steady-state path past the admission
    boundary's mixed dispatch (``EngineConfig.warmup_tokens`` — shared
    with the replay warmup)."""
    import numpy as np

    from .requests import SamplingParams
    engine.submit(Request(id="__warmup__",
                          prompt=np.zeros((1,), np.int32),
                          max_new_tokens=engine.ecfg.warmup_tokens(),
                          sampling=SamplingParams(greedy=True)))
    engine.drain()


async def _run_async(worker: WorkerServer, host: str, port: int,
                     ready_file: Optional[str], gen: int) -> int:
    server = await asyncio.start_server(
        lambda r, w: serve_connection(r, w, worker.dispatch),
        host, port)
    bound = server.sockets[0].getsockname()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            loop.add_signal_handler(sig, worker.stop_event.set)
        except NotImplementedError:   # non-Unix event loops
            pass
    print(f"worker listening on {bound[0]}:{bound[1]} "
          f"pid={os.getpid()} gen={gen} "
          f"replayed={worker.n_replayed}", file=sys.stderr)
    if ready_file:
        _write_ready_file(ready_file, {
            "port": bound[1], "pid": os.getpid(), "gen": gen,
            "replayed": worker.n_replayed})
    await worker.stop_event.wait()
    server.close()
    await server.wait_closed()
    # let an in-flight shutdown response flush before the process exits
    await asyncio.sleep(0.05)
    return 0


def run_worker(args) -> int:
    """The serve-worker subcommand body (see cli.py for the flags)."""
    from ..config import config_from_args
    from ..train.state import create_train_state
    import jax

    cfg = config_from_args(args)
    state = create_train_state(jax.random.PRNGKey(cfg.train.seed),
                               cfg.model, cfg.train)
    if args.checkpoint_dir:
        from ..train.checkpoint import CheckpointManager
        restored = (CheckpointManager(args.checkpoint_dir)
                    .restore_latest(state))
        if restored is None:
            print("no checkpoint found; serving random init",
                  file=sys.stderr)
        else:
            state = restored
    # ONE EngineConfig builder with the router process (cli.py): the
    # multiproc forwarding contract (ENGINE_FORWARD_FLAGS) holds only
    # if both sides parse the same flags into the same config — a
    # worker owning its own --mesh-shape slice included
    from ..cli import engine_config_from_args
    ecfg = engine_config_from_args(args)
    engine = Engine(state.params, cfg.model, ecfg)
    warm_engine(engine)

    journal = None
    if args.journal:
        journal = RequestJournal(args.journal,
                                 fsync_finish=not args.no_fsync,
                                 lock=True)
        engine.journal = journal
    worker = WorkerServer(engine, journal)
    worker.warmed = True
    if args.journal:
        n = worker.replay_journal(args.journal)
        if n:
            print(f"journal replay: {n} unfinished request(s) "
                  f"resubmitted", file=sys.stderr)
    try:
        return asyncio.run(_run_async(worker, args.host, args.port,
                                      args.ready_file, args.gen))
    finally:
        if journal is not None:
            journal.close()
