"""Disaggregated prefill/decode: KV pages over the fleet RPC.

Chunked prefill (serve/engine.py) made the prompt phase preemptible
*within* one engine; this module makes it placeable *across* engines.
A **prefill worker** chews the prompt through the ordinary chunked
prefill program (the request carries ``prefill_only=True``, so the
engine finishes it after the first decode token — the token that
rewrites prompt position P-1 and finalizes the last full page — with
``finish_reason="prefilled"``); the finished KV pages then ship to a
**decode worker**, which scatters them into its own page pool through
a construction-warmed install program and registers the chain into its
radix. The next admission on the decode tier claims those pages
through an ordinary prefix claim — the page-table rebase to local
physical indices IS the radix claim, no new admission path — and the
request decodes as if it had prefilled locally.

Why split tiers at all: prefill is compute-bound and bursty (one long
prompt monopolizes the batch budget for several windows), decode is
latency-bound and steady. Colocating them makes every long prompt a
TTFT spike for every short request behind it. Dedicated prefill
workers absorb the bursts; the decode tier's windows stay dense with
decode rows (bench.py ``--disagg`` measures exactly this: short-prompt
TTFT p99 under a mixed long+short trace, disaggregated vs colocated at
equal worker count).

The moving parts, smallest to largest:

- **source / sink adapters** — a common six-step protocol
  (begin/chunk/end on the source, begin/chunk/commit-or-abort on the
  sink) with two implementations each: ``Local*`` call an in-process
  :class:`~.engine.Engine` directly (host numpy blocks, no
  serialization — the in-process fleet's path), ``Rpc*`` speak the
  ``page_transfer`` verb (serve/rpc.py) against a worker process,
  base64 page blocks chunked under the frame bound. The two compose
  freely: a remote prefill worker can feed an in-process decode
  engine and vice versa — the driver never looks inside a block.
- **:func:`transfer_prefix`** — the driver: pin on the source,
  allocate+pin on the sink, stream chunks, commit into the sink's
  radix, unpin both. Every failure path degrades to "prefix not
  cached on the decode tier": the sink aborts (staged pages free, the
  half-landed chain never enters the radix), the source unpins, and
  the caller submits the original request for a full local prefill —
  slower, never wrong.

Wire safety: blocks are raw page bytes per pool entry — int8/fp8/bf16
K/V rows AND the f32 per-row scale arrays of a quantized pool, which
share the page axis and therefore ride the same uniform dict. Shapes
and dtypes never cross the wire; both ends decode against their own
pool's :func:`~.rpc.page_block_template`, and the engine-shape hash
agreed at registration guarantees the templates match.

The router (serve/router.py) owns placement and orchestration policy:
which prompts go to the prefill tier, which decode worker receives the
pages (prefix-affinity), the short-circuit when the decode tier
already holds most of the prompt, and the telemetry/metrics around
each transfer. This module is deliberately policy-free.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

import numpy as np

from .rpc import (PAGE_CHUNK_BYTES, RpcError, page_block_from_wire,
                  page_block_to_wire, page_block_template,
                  page_wire_bytes)

#: transfer seam for fault injection (faults/fleet.py): fired between
#: chunk round-trips with the running chunk index, so chaos tests can
#: kill a tier mid-transfer at a deterministic point. None = no chaos.
TransferFault = Optional[Callable[[int], None]]

#: what a dying endpoint looks like mid-transfer: RPC failures and
#: raw transport errors, the router's ReplicaDownError (a
#: RuntimeError — not imported, no serve.router cycle), a codec
#: shape/length assert, and a missing-key state desync. All degrade
#: to "transfer failed, prefill locally".
XFER_ERRORS = (RpcError, OSError, RuntimeError, KeyError,
               AssertionError)


def _is_wire_block(block: dict) -> bool:
    """Wire blocks carry base64 strings; local blocks carry ndarrays."""
    return isinstance(next(iter(block.values())), str)


# --------------------------------------------------------------- source


class LocalPageSource:
    """Export side against an in-process engine: pin the prompt's
    radix-cached pages, page them out as host numpy blocks."""

    def __init__(self, engine):
        self.engine = engine
        self.template = page_block_template(engine.pool.cache)
        self.page_bytes = page_wire_bytes(self.template)
        self.pages_per_chunk = max(1, PAGE_CHUNK_BYTES // self.page_bytes)
        self._sending: Dict[str, List[int]] = {}

    def begin(self, key: str, prompt: np.ndarray, from_page: int) -> int:
        pinned = self.engine.pool.pin_prefix(key, prompt)
        send = pinned[from_page:]
        if not send:
            self.engine.pool.unpin(key)
            return 0
        self._sending[key] = send
        return len(send)

    def chunk(self, key: str, cursor: int, limit: int = 0):
        send = self._sending[key]
        take = min(self.pages_per_chunk, limit or self.pages_per_chunk)
        batch = send[cursor:cursor + take]
        blocks = self.engine.export_pages(batch)
        nxt = cursor + len(batch)
        return blocks, nxt, nxt >= len(send)

    def end(self, key: str) -> None:
        self._sending.pop(key, None)
        self.engine.pool.unpin(key)      # tolerant of an absent pin


class RpcPageSource:
    """Export side against a worker process: the same three steps as
    :class:`LocalPageSource`, spoken as ``page_transfer`` kinds. The
    worker owns pinning and chunk sizing (it knows its own template);
    blocks arrive as wire docs and stay wire — the sink decodes."""

    def __init__(self, call: Callable[..., dict]):
        #: ``call(op, **kwargs) -> response`` — the router passes its
        #: replica's RpcClient.call (timeouts/reconnects included)
        self.call = call
        self.page_bytes = 0              # learned from export_begin
        self._seq = 0                    # idempotency-key ordinal

    def _idem(self, kind: str, key: str) -> str:
        """One key per LOGICAL page_transfer call: a netchaos duplicate
        or a blind protocol retry of the same call is answered from the
        worker's reply cache, while a fresh transfer attempt for the
        same request id mints new keys and re-executes (GL024)."""
        self._seq += 1
        return f"pt.{key}.{kind}.{self._seq}"

    def begin(self, key: str, prompt: np.ndarray, from_page: int) -> int:
        r = self.call("page_transfer", kind="export_begin", key=key,
                      idem=self._idem("export_begin", key),
                      prompt=[int(t) for t in np.asarray(prompt).reshape(-1)],
                      from_page=int(from_page))
        self.page_bytes = int(r.get("page_bytes", 0))
        return int(r["pages"])

    def chunk(self, key: str, cursor: int, limit: int = 0):
        r = self.call("page_transfer", kind="export_chunk", key=key,
                      idem=self._idem("export_chunk", key),
                      cursor=int(cursor), limit=int(limit))
        return r["blocks"], int(r["cursor"]), bool(r["done"])

    def end(self, key: str) -> None:
        self.call("page_transfer", kind="export_end", key=key,
                  idem=self._idem("export_end", key))


# ----------------------------------------------------------------- sink


class LocalPageSink:
    """Install side against an in-process engine: allocate + pin fresh
    physical pages, scatter arriving blocks through the warmed install
    program, commit the chain into the radix."""

    def __init__(self, engine):
        self.engine = engine
        self.template = page_block_template(engine.pool.cache)
        self._staged: Dict[str, dict] = {}

    def begin(self, key: str, prompt: np.ndarray, from_page: int,
              n_pages: int) -> bool:
        taken = self.engine.pool.install_prefix(
            key, np.asarray(prompt, np.int32).reshape(-1),
            int(from_page), int(n_pages))
        if taken is None:
            return False
        self._staged[key] = {"pages": taken, "cursor": 0}
        return True

    def chunk(self, key: str, blocks: list) -> None:
        st = self._staged[key]
        decoded = [page_block_from_wire(b, self.template)
                   if _is_wire_block(b) else b for b in blocks]
        pages = st["pages"][st["cursor"]:st["cursor"] + len(decoded)]
        assert len(pages) == len(decoded), \
            f"transfer {key!r}: more blocks than staged pages"
        self.engine.install_pages(pages, decoded)
        st["cursor"] += len(decoded)

    def commit(self, key: str) -> int:
        st = self._staged.pop(key)
        if st["cursor"] != len(st["pages"]):
            # short chain: blocks lost between begin and commit — free
            # the staged pages rather than registering garbage
            self.engine.pool.unpin(key)
            return 0
        return self.engine.pool.commit_install(key)

    def abort(self, key: str) -> None:
        self._staged.pop(key, None)
        self.engine.pool.unpin(key)


class RpcPageSink:
    """Install side against a worker process. Blocks already in wire
    form pass through untouched (remote->remote relays once through
    the router, no decode in the middle); local numpy blocks are
    encoded here."""

    def __init__(self, call: Callable[..., dict]):
        self.call = call
        self._seq = 0                    # idempotency-key ordinal

    def _idem(self, kind: str, key: str) -> str:
        """See :meth:`RpcPageSource._idem` — duplicated install calls
        (especially ``install_chunk``, which appends to a staged chain)
        must be reply-cache hits, never double-appends (GL024)."""
        self._seq += 1
        return f"pt.{key}.{kind}.{self._seq}"

    def begin(self, key: str, prompt: np.ndarray, from_page: int,
              n_pages: int) -> bool:
        r = self.call("page_transfer", kind="install_begin", key=key,
                      idem=self._idem("install_begin", key),
                      prompt=[int(t) for t in np.asarray(prompt).reshape(-1)],
                      from_page=int(from_page), n_pages=int(n_pages))
        # "accepted", not "ok": the transport wraps every response in
        # its own ok=true envelope and a nested "ok" would collide
        return bool(r["accepted"])

    def chunk(self, key: str, blocks: list) -> None:
        wire = [b if _is_wire_block(b) else page_block_to_wire(b)
                for b in blocks]
        self.call("page_transfer", kind="install_chunk", key=key,
                  idem=self._idem("install_chunk", key),
                  blocks=wire)

    def commit(self, key: str) -> int:
        r = self.call("page_transfer", kind="install_commit", key=key,
                      idem=self._idem("install_commit", key))
        return int(r["registered"])

    def abort(self, key: str) -> None:
        self.call("page_transfer", kind="install_commit", key=key,
                  idem=self._idem("install_abort", key),
                  abort=True)


# --------------------------------------------------------------- driver


@dataclass
class TransferResult:
    """What one :func:`transfer_prefix` did, for the router's
    telemetry span and Prometheus counters."""

    ok: bool
    pages: int = 0                 # pages landed AND radix-registered
    wire_bytes: int = 0            # raw page bytes moved (pre-base64)
    elapsed_s: float = 0.0
    error: str = ""                # failure class, "" on success


class TransferJob:
    """A resumable transfer: the same begin/chunk/commit protocol as
    :func:`transfer_prefix`, advanced ONE bounded chunk round-trip per
    :meth:`step` call. The router keeps a list of active jobs and
    steps each once per fleet scheduling iteration, so a multi-
    megabyte transfer never stalls the loop that every other request's
    TTFT is riding on — the stall ceiling per fleet step is one chunk
    (``max_chunk_pages`` pages), not one transfer.

    :meth:`step` returns ``None`` while in flight and the final
    :class:`TransferResult` once — cleanup (sink abort on failure,
    source unpin always) happens inside, exactly as the blocking
    driver did it. ``fault`` fires before each chunk with the running
    chunk index (the ``fleet/transfer`` chaos seam); anything it
    raises takes the ordinary failure path."""

    def __init__(self, source, sink, key: str, prompt: np.ndarray,
                 from_page: int, fault: TransferFault = None,
                 clock=time.monotonic, max_chunk_pages: int = 0):
        self.source, self.sink = source, sink
        self.key = key
        self.prompt = np.asarray(prompt, np.int32).reshape(-1)
        self.from_page = int(from_page)
        self.fault = fault
        self.clock = clock
        self.max_chunk_pages = int(max_chunk_pages)
        self.t0 = clock()
        self.result: Optional[TransferResult] = None
        self._state = "begin"
        self._cursor = 0
        self._chunk_idx = 0
        self._sink_begun = False
        self._src_begun = False

    def _finish(self, ok: bool, pages: int = 0,
                error: str = "") -> TransferResult:
        if not ok and self._sink_begun:
            try:
                self.sink.abort(self.key)
            except XFER_ERRORS:
                pass                  # sink gone: pins die with it
        if self._src_begun:
            try:
                self.source.end(self.key)
            except XFER_ERRORS:
                pass                  # source gone: pin died with it
        self._state = "done"
        self.result = TransferResult(
            ok=ok, pages=pages,
            wire_bytes=pages * int(getattr(self.source, "page_bytes",
                                           0)),
            elapsed_s=self.clock() - self.t0, error=error)
        return self.result

    def step(self) -> Optional[TransferResult]:
        if self.result is not None:
            return self.result
        try:
            if self._state == "begin":
                n = self.source.begin(self.key, self.prompt,
                                      self.from_page)
                self._src_begun = n > 0
                if n <= 0:
                    return self._finish(False, error="no_pages")
                if not self.sink.begin(self.key, self.prompt,
                                       self.from_page, n):
                    return self._finish(False, error="sink_refused")
                self._sink_begun = True
                self._state = "stream"
                return None
            # stream: one chunk round-trip, committing right after the
            # last chunk lands (both are sink-side ops — no extra step)
            if self.fault is not None:
                self.fault(self._chunk_idx)
            blocks, self._cursor, done = self.source.chunk(
                self.key, self._cursor, self.max_chunk_pages)
            self.sink.chunk(self.key, blocks)
            self._chunk_idx += 1
            if not done:
                return None
            registered = self.sink.commit(self.key)
            self._sink_begun = False     # commit consumed the staging
            if registered <= 0:
                return self._finish(False, error="commit_raced")
            return self._finish(True, pages=registered)
        except XFER_ERRORS as e:
            return self._finish(False, error=type(e).__name__)


def transfer_prefix(source, sink, key: str, prompt: np.ndarray,
                    from_page: int, fault: TransferFault = None,
                    clock=time.monotonic,
                    max_chunk_pages: int = 0) -> TransferResult:
    """Move prompt pages ``from_page..`` from ``source`` to ``sink``,
    blocking until done — a :class:`TransferJob` driven to completion.

    ``from_page`` is the page count the sink already holds (the
    placement probe's ``cached_prefix_tokens // page_size``) — only the
    uncached tail crosses the wire. Returns a :class:`TransferResult`;
    ``ok=False`` means the decode tier holds nothing new and the caller
    must fall back to a full local prefill (correctness never depends
    on a transfer landing). The source pin is always released, even
    when the sink half fails; a failed sink is aborted best-effort
    (an unreachable sink's pins die with its process)."""
    job = TransferJob(source, sink, key, prompt, from_page,
                      fault=fault, clock=clock,
                      max_chunk_pages=max_chunk_pages)
    while True:
        r = job.step()
        if r is not None:
            return r
