"""Multi-replica router: the fleet tier, in-process or across processes.

One engine is a chip; "millions of users" is a fleet. This module
load-balances requests across N engine replicas and keeps the fleet's
promises when replicas misbehave:

- **Radix-prefix affinity**: a request is routed to the replica whose
  ``RadixIndex`` already owns the longest prefix of its prompt
  (``PagedCachePool.cached_prefix_tokens`` — a pure peek, no LRU
  touch), falling back to least-loaded. Multi-turn sessions therefore
  stick to the replica holding their conversation's KV pages, and the
  fleet's aggregate prefix-hit rate stays close to a single replica's
  (pinned in tests/test_fleet.py).
- **Health probes**: the router times every replica step and reads each
  engine's telemetry counters (queue depth, slots, watchdog stalls —
  the PR-7 Metrics substrate) into per-replica gauges. A replica whose
  steps blow the wedge budget ``wedge_patience`` times in a row is
  *wedged* — quarantined from new routes with its in-flight work
  re-routed (below).
- **Requeue across death**: a killed replica's accepted-but-unfinished
  requests are rebuilt and resubmitted to survivors with bounded retry
  + exponential backoff. For an in-process replica the rebuild reads
  its crash journal (``Replica.journal_state`` — same filesystem by
  construction); for a worker PROCESS the router never opens a worker
  path: the in-memory ledger (mirrored to the router's own crash
  journal, ``RouterConfig.ledger_path``) is the source of truth, so a
  worker HOST can vanish entirely — journal and all, the
  spot-VM/TPU-preemption scenario (``host_loss`` chaos) — and every
  accepted request still finishes. Regeneration is deterministic
  (prompt + sampling + per-request rng_seed), so greedy output is
  token-identical to an uninterrupted run; the router's delivery
  ledger (:meth:`Router.take_new_tokens`) dedupes the stream so a
  client sees every token exactly once across a migration — no drops,
  no duplicates.
- **Hedged re-route on wedge**: a wedged (but not dead) replica's
  in-flight requests are cancelled with ``migrated=True`` (the engine
  releases their slots/pages immediately and tags the telemetry
  envelope as a non-terminal segment) and re-raced onto healthy
  replicas — the fleet never double-decodes an id (the PR-5
  in-flight-id invariant, extended fleet-wide by the router's own
  dedupe at :meth:`submit`).

Two replica backends implement one interface (:class:`ReplicaBase`):

- :class:`Replica` — the in-process engine of PR 8 (one interpreter,
  simulated faults);
- :class:`RemoteReplica` — a **worker process** (serve/worker.py)
  reached over the serve/rpc.py socket protocol, on this machine or
  any other (workers register over the network — faults/procsup.py's
  ``RpcListener`` handshake; the router holds only a host:port). The
  router drives it with the same verbs (submit/step/cancel), reads its
  committed-token streams out of the step response (the stream-drain
  piggyback), and treats transport failures honestly: an RPC *timeout*
  is a slow step the wedge probe sees (SIGSTOP, wedged device), a
  *refused/reset connection* marks the replica down for the process
  supervisor to restart. A restarted worker replays its own journal;
  :meth:`Router.attach_replica` then reconciles the router's in-flight
  ledger against what the worker actually recovered — the worker's
  journal state arrives through the ``journal_drain`` RPC in bounded
  frames (the journal file never leaves the worker's machine):
  surviving requests continue (the delivery ledger suppresses the
  regenerated prefix, so streams stay exactly-once through a real
  ``kill -9``), journaled-finished-but-undelivered ones surface their
  journaled reason, and ghost entries the worker replayed but nobody
  owns are cancelled before they waste a decode.

**The router's own crash journal** (``RouterConfig.ledger_path``)
mirrors the in-memory request ledger to disk: one submit record at
fleet acceptance, one finish record at each terminal result — the same
torn-tail-tolerant ``RequestJournal`` format the workers use. A
restarted router rebuilds its accepted-but-unfinished set from it and
requeues (a finish record torn mid-write replays as unfinished — the
request re-decodes and re-delivers rather than dropping, pinned in
tests/test_fleet_elastic.py). With workers journaling locally AND the
router journaling its own view, no component ever reads another
component's disk — the fleet has no shared-filesystem assumption left
(graftlint GL016 guards the router side against regressions).

Rolling restarts ride the same machinery: :meth:`Router.drain_replica`
marks a replica draining (unroutable, ``/readyz`` excluded), migrates
its in-flight work onto the rest of the fleet, and the supervisor
restarts the emptied worker — repeated replica by replica, the fleet
never drops a request.

Single-threaded by design, like the engine: one loop drives
:meth:`Router.step`. The HTTP front door (serve/http.py) and the fleet
replay driver (serve/loadgen.py) are both such loops.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, replace
from typing import Callable, Dict, List, Optional, Tuple

from ..faults.fleet import (KIND_HOST_LOSS, KIND_PROC_HANG,
                            KIND_PROC_KILL, KIND_REPLICA_KILL,
                            KIND_REPLICA_WEDGE, KIND_TRANSFER_KILL,
                            fleet_step_fault, transfer_fault)
from ..faults.netchaos import FaultyTransport
from ..utils.jsonl import load_jsonl_if_exists
from ..utils.logging import Metrics
from ..utils.telemetry import (ENGINE_TRACK, NULL, REPLICA_TRACK_STRIDE,
                               ROUTER_TRACK, ROUTER_TRACK_NAME)
from .disagg import (LocalPageSink, LocalPageSource, RpcPageSink,
                     RpcPageSource, TransferJob)
from .journal import RequestJournal
from .requests import (FINISH_CANCELLED, FINISH_DEADLINE,
                       FINISH_PREFILLED, REJECT_BAD_REQUEST,
                       REJECT_PROMPT_TOO_LONG, REJECT_QUEUE_FULL,
                       Request, RequestResult)
from .rpc import (REJECT_REPLICA_DOWN, RpcClient, RpcDown, RpcError,
                  RpcProtocolError, RpcTimeout, request_from_wire,
                  request_to_wire, result_from_wire)

#: finish_reason when bounded retry exhausts without a replica
#: accepting the requeued request
REJECT_FLEET_CAPACITY = "rejected_fleet_capacity"

#: rejection verdicts deterministic across replicas (same config, same
#: clock): every replica would say the same thing, so trying another
#: one — or retrying later — is pointless and would inflate the
#: fleet_route_fallbacks capacity-pressure signal
TERMINAL_REJECTS = frozenset({REJECT_BAD_REQUEST,
                              REJECT_PROMPT_TOO_LONG, FINISH_DEADLINE})

#: a submit RPC that TIMED OUT: unlike a refused connection, the hung
#: worker may still execute the buffered submit when it resumes
#: (SIGSTOP). Routing falls through to the next candidate; the
#: maybe-executed copy's eventual finish is swallowed by the
#: replica-aware stale guard in Router._on_finish (ledger entry points
#: at the replica that actually owns the id)
REJECT_REPLICA_TIMEOUT = "rejected_replica_timeout"

#: backpressure-shaped rejections the retry ladder maps to
#: REJECT_FLEET_CAPACITY on exhaustion (try-later verdicts)
RETRYABLE_REJECTS = frozenset({REJECT_QUEUE_FULL, REJECT_REPLICA_DOWN,
                               REJECT_REPLICA_TIMEOUT})


class ReplicaDownError(RuntimeError):
    """A remote replica's transport is gone (refused/reset) — the
    process died or is restarting. The router marks it down and the
    supervisor owns recovery."""


@dataclass(frozen=True)
class RouterConfig:
    """Fleet sizing + routing/recovery knobs (docs/serving.md)."""

    n_replicas: int = 2
    #: IN-PROCESS mode: per-replica crash journals live here
    #: (replica{i}.jsonl); None disables journals — and with them
    #: cross-replica requeue. Worker PROCESSES own their journals
    #: privately (per-worker dirs, any machine) — the router never
    #: reads them; reconciliation rides the journal_drain RPC and the
    #: router's own ledger below.
    journal_dir: Optional[str] = None
    #: the ROUTER's own crash journal: submits at fleet acceptance,
    #: finishes at terminal results. A restarted router requeues its
    #: accepted-but-unfinished set from here — the recovery path that
    #: needs no worker filesystem at all (host_loss survivability).
    #: None disables router-side persistence (in-memory ledger only).
    ledger_path: Optional[str] = None
    #: fsync the ledger's finish records (the torn-tail window narrows
    #: to the submit side, which only ever re-decodes, never drops)
    ledger_fsync: bool = False
    #: route by longest cached prefix (False: pure least-loaded)
    affinity: bool = True
    #: requeue/submit retry ladder: a rejected resubmission retries up
    #: to retry_max times, backing off retry_backoff_steps * 2^attempt
    #: router steps between tries
    retry_max: int = 4
    retry_backoff_steps: int = 1
    #: wedge probe: a replica step slower than wedge_budget_s,
    #: wedge_patience times consecutively, marks the replica wedged
    #: (0 = detection off). The first wedge_skip_steps steps per
    #: replica are exempt (warmup compiles).
    wedge_budget_s: float = 0.0
    wedge_patience: int = 2
    wedge_skip_steps: int = 3
    #: router steps a wedged replica sits out before rejoining rotation
    quarantine_steps: int = 8
    #: RPC budget for one remote step (multi-process mode): past it the
    #: call abandons and the elapsed time feeds the wedge probe. A hung
    #: (SIGSTOPped) worker costs the router this much per step, bounded.
    step_timeout_s: float = 10.0
    #: IN-PROCESS disaggregation (serve/disagg.py): per-replica tier
    #: labels ("prefill" / "decode" / "mixed"), one per replica index.
    #: None = every replica "mixed" (the colocated fleet — placement is
    #: unchanged). Worker processes advertise their tier at
    #: registration instead (serve/worker.py ``--tier``).
    tiers: Optional[Tuple[str, ...]] = None
    #: two-tier placement threshold: a prompt whose UNCACHED tail on
    #: the best decode-tier replica is fewer than this many full pages
    #: short-circuits the prefill tier entirely (the transfer would
    #: cost more than prefilling the tail locally). Prefix-hot traffic
    #: therefore never leaves the decode tier.
    disagg_min_tail: int = 2
    #: page-transfer pacing: each active transfer advances by at most
    #: this many pages per router step (one chunk round-trip). The
    #: scheduling loop's stall ceiling per step is one chunk — a large
    #: transfer spreads across steps instead of freezing the fleet.
    #: 0 = whole frame-bound chunks (rpc.PAGE_CHUNK_BYTES).
    transfer_chunk_pages: int = 8


@dataclass
class _InFlight:
    """Router-side ledger entry for one accepted request."""

    req: Request
    replica: int
    t_submit: float            # fleet submit time (router clock)
    attempts: int = 0


@dataclass
class _Requeue:
    """A request between replicas: awaiting (re)submission."""

    req: Request
    t_submit: float
    attempts: int
    due_step: int
    t_requeued: float = 0.0    # when it left its replica (requeue
    #                            latency = resubmit accept - this)


@dataclass
class _Transfer:
    """An in-flight disaggregated page transfer: the router advances
    ``job`` one chunk per fleet step (:meth:`Router._advance_transfers`)
    and resubmits ``req`` to the decode tier when it lands."""

    job: object                # disagg.TransferJob
    req: Request
    t_submit: float            # the ORIGINAL submit time (TTFT base)
    attempts: int
    src_idx: int
    dst_idx: int
    t0_us: float = 0.0         # telemetry span base


class ReplicaBase:
    """The router-side replica contract: health state every backend
    shares, plus the host-API verbs the router drives. ``Replica``
    (in-process engine) and ``RemoteReplica`` (worker process over
    serve/rpc.py) both speak it — affinity routing, the wedge probe,
    hedged re-route and the delivery ledger are backend-agnostic."""

    is_local = True
    #: page geometry for disaggregated placement (serve/disagg.py) —
    #: 0 = unknown (two-tier placement disabled toward this replica).
    #: Local replicas read their engine's pool; remote ones learn it
    #: from the registration handshake.
    page_size = 0

    def __init__(self, idx: int, journal_path: Optional[str]):
        self.idx = idx
        self.journal_path = journal_path
        self.alive = True
        self.wedged = False
        self.draining = False
        self.suspect_streak = 0
        self.skip_steps = 0
        self.quarantine_until = 0
        self.last_step_s = 0.0
        self.steps = 0
        #: disaggregation role: "prefill" takes only prefill_only
        #: work, "decode" and "mixed" take sessions ("mixed" is the
        #: colocated default — both roles)
        self.tier = "mixed"

    # ------------------------------------------------------ router state

    @property
    def routable(self) -> bool:
        return self.alive and not self.wedged and not self.draining

    @property
    def load(self) -> int:
        return self.queue_depth + self.slots_active

    @property
    def warmed(self) -> bool:
        return True

    def _base_health(self) -> dict:
        return {"replica": self.idx, "alive": self.alive,
                "wedged": self.wedged, "draining": self.draining,
                "last_step_ms": round(self.last_step_s * 1e3, 3)}

    # ----------------------------------------------------- backend verbs

    def submit(self, req: Request) -> Optional[RequestResult]:
        raise NotImplementedError

    def cancel(self, request_id: str, migrated: bool = False) -> bool:
        raise NotImplementedError

    def step_engine(self) -> List[RequestResult]:
        raise NotImplementedError

    def partial_tokens(self, request_id: str) -> Optional[List[int]]:
        raise NotImplementedError

    def cached_prefix_tokens(self, prompt) -> int:
        raise NotImplementedError

    @property
    def queue_depth(self) -> int:
        raise NotImplementedError

    @property
    def slots_active(self) -> int:
        raise NotImplementedError

    @property
    def pages_in_use(self) -> int:
        raise NotImplementedError

    @property
    def engine_idle(self) -> bool:
        raise NotImplementedError

    def hit_tokens(self) -> Tuple[int, int]:
        """(prefix_hit_tokens, prompt_tokens) for the fleet aggregate."""
        raise NotImplementedError

    def journal_state(self, telemetry=None
                      ) -> Tuple[Dict[str, str], List[Request]]:
        """``(finished_reasons, unfinished_requests)`` from this
        replica's crash journal — the reconciliation inputs. The
        BACKEND owns how the journal is reached: the in-process
        replica reads its local file (same filesystem by
        construction), the remote replica pages the ``journal_drain``
        RPC. Router code never opens a replica path (GL016)."""
        return {}, []

    def health(self) -> dict:
        raise NotImplementedError

    def summary_block(self) -> dict:
        """The per-replica block of :meth:`Router.fleet_summary`."""
        raise NotImplementedError

    def close(self) -> None:
        pass


class Replica(ReplicaBase):
    """One in-process engine + its crash journal (the PR-8 backend)."""

    is_local = True

    def __init__(self, idx: int, engine, journal_path: Optional[str],
                 journal: Optional[RequestJournal],
                 skip_steps: int = 0, tier: str = "mixed"):
        super().__init__(idx, journal_path)
        self.engine = engine
        self.journal = journal
        self.skip_steps = skip_steps
        self.tier = tier

    @property
    def page_size(self) -> int:
        return self.engine.pool.page_size

    def submit(self, req: Request) -> Optional[RequestResult]:
        return self.engine.submit(req)

    def cancel(self, request_id: str, migrated: bool = False) -> bool:
        return self.engine.cancel(request_id, migrated=migrated)

    def step_engine(self) -> List[RequestResult]:
        return self.engine.step()

    def partial_tokens(self, request_id: str) -> Optional[List[int]]:
        return self.engine.partial_tokens(request_id)

    def cached_prefix_tokens(self, prompt) -> int:
        return self.engine.pool.cached_prefix_tokens(prompt)

    @property
    def queue_depth(self) -> int:
        return self.engine.scheduler.depth

    @property
    def slots_active(self) -> int:
        return int(self.engine._active.sum())

    @property
    def pages_in_use(self) -> int:
        return self.engine.pool.alloc.pages_in_use

    @property
    def engine_idle(self) -> bool:
        return self.engine.idle

    def hit_tokens(self) -> Tuple[int, int]:
        a = self.engine.pool.alloc
        return a.prefix_hit_tokens, a.prompt_tokens

    def journal_state(self, telemetry=None
                      ) -> Tuple[Dict[str, str], List[Request]]:
        """Local-mode backend: the journal is this process's own file
        (is_local — the one place the fleet may touch a replica path
        directly)."""
        if self.journal_path is None:
            return {}, []
        finished = {r["id"]: r.get("reason", "")
                    for r in load_jsonl_if_exists(self.journal_path)
                    if r.get("ev") == "finish"}
        pending = RequestJournal.unfinished(self.journal_path,
                                            telemetry=telemetry)
        return finished, pending

    def health(self) -> dict:
        """The per-replica health probe: router-side state + the
        engine's own telemetry counters/gauges (PR-7 Metrics)."""
        c = self.engine.metrics.counters
        return {
            **self._base_health(),
            "queue_depth": self.queue_depth,
            "slots_active": self.slots_active,
            "pages_in_use": self.pages_in_use,
            "watchdog_stalls": int(c.get("watchdog_stalls", 0)),
            "shed_requests": int(c.get("shed_requests", 0)),
            "requests_admitted": int(c.get("requests_admitted", 0)),
        }

    def summary_block(self) -> dict:
        from .engine import engine_summary_block
        return {"health": self.health(),
                **engine_summary_block(self.engine)}

    def close(self) -> None:
        if self.journal is not None:
            self.journal.close()


class RemoteReplica(ReplicaBase):
    """A worker process behind serve/rpc.py. The router's view of it is
    built from step responses (gauges, committed-token streams, finished
    results) cached between calls — ``partial_tokens`` and ``health``
    never block the routing loop on a sick worker.

    Finished results are *redelivered* by the worker until acked (a
    step response lost to a timeout or a router restart must not lose a
    finish); ``step_engine`` dedupes redeliveries against the previous
    response and acks on the next call, so the router sees each finish
    exactly once. An id is dropped from the dedupe set when the router
    resubmits it here — a finished-and-popped id is legal to reuse.
    """

    is_local = False

    #: verbs whose handlers MUTATE worker state — every call carries an
    #: idempotency key so a netchaos duplicate or a blind protocol
    #: retry is answered from the worker's reply cache instead of
    #: re-executing (graftlint GL024 audits both sides of this
    #: contract; worker.py:IDEMPOTENT_VERBS is the handler-side pin)
    MUTATING_VERBS = ("submit", "page_transfer", "journal_drain")

    def __init__(self, idx: int, journal_path: Optional[str] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 rpc_timeout_s: float = 10.0,
                 step_timeout_s: float = 10.0):
        # journal_path is NOT read by the router for remote replicas
        # (the worker's disk may be on another machine) — it is kept
        # only as operator-facing metadata in health blocks
        super().__init__(idx, journal_path)
        self.host = host
        self.client: Optional[RpcClient] = None
        self.rpc_timeout_s = rpc_timeout_s
        self.step_timeout_s = step_timeout_s
        self.pid: Optional[int] = None
        self.gen = -1
        self.restarts = 0
        self.rpc_timeouts = 0
        self._warmed = False
        self._idle = True
        self._gauges = {"queue_depth": 0, "slots_active": 0,
                        "pages_in_use": 0, "n_steps": 0,
                        "prefix_hit_tokens": 0, "prompt_tokens": 0}
        self._partials: Dict[str, List[int]] = {}
        self._seen: set = set()        # finish ids delivered, unacked
        self._acks: List[str] = []
        #: wired by Router.__init__ so protocol-hardening counters
        #: (rpc_dup_suppressed & friends) and net_partition/net_heal
        #: instants land in the FLEET's metrics/trace, not a private one
        self.metrics: Optional[Metrics] = None
        self.tel = NULL
        #: monotonic ordinal for auto-minted idempotency keys: each
        #: LOGICAL call attempt gets a fresh key (a resubmission must
        #: re-execute), while wire-level duplicates/retries of the same
        #: attempt reuse it (the reply cache answers those)
        self._idem_seq = 0
        #: half-open detection: last time any RPC round-tripped. A
        #: worker that accepts connects but never answers (one-way
        #: partition) goes silent here; Router.step closes the client
        #: past ``heartbeat_deadline_s`` to force a fresh connect
        #: instead of trusting a dead socket forever.
        self.last_ok_t = time.monotonic()
        self.heartbeat_deadline_s: Optional[float] = None
        if port:
            self.connect(port)

    def _next_idem(self, op: str) -> str:
        self._idem_seq += 1
        return f"r{self.idx}.{op}.{self._idem_seq}"

    # ------------------------------------------------------- connection

    def connect(self, port: int, pid: Optional[int] = None,
                gen: Optional[int] = None,
                host: Optional[str] = None) -> None:
        if self.client is not None:
            self.client.close()
        if host:
            # the registration handshake told us which HOST the worker
            # lives on (its connection's peer address) — a respawned
            # worker may come back on a different machine entirely
            self.host = host
        # FaultyTransport is a strict pass-through while no FaultPlan
        # is installed (one module-global read per call) — wrapping
        # unconditionally keeps chaos runs and clean runs on the SAME
        # code path, so the soak proves the path production uses
        self.client = FaultyTransport(
            RpcClient(self.host, port, timeout_s=self.rpc_timeout_s),
            src="router", dst=f"worker{self.idx}", observer=self)
        if pid is not None:
            self.pid = pid
        if gen is not None:
            self.gen = gen

    def close(self) -> None:
        if self.client is not None:
            self.client.close()

    def _call(self, op: str, timeout_s: Optional[float] = None,
              **kw) -> dict:
        if self.client is None:
            raise ReplicaDownError(f"worker {self.idx}: never attached")
        if op in self.MUTATING_VERBS and "idem" not in kw:
            # safety net for call sites that forgot an explicit key —
            # the named verbs' sites mint their own (GL024)
            kw["idem"] = self._next_idem(op)
        if self.gen >= 0 and "gen" not in kw:
            # stamp the worker incarnation we believe we are talking
            # to: a partitioned-then-restarted worker at a NEWER gen
            # fences this call off instead of executing it (and a
            # stale worker answering a new router gets the mirror
            # rejection from its own fence)
            kw["gen"] = self.gen
        try:
            resp = self.client.call(op, timeout_s=timeout_s, **kw)
        except RpcProtocolError as e:
            resp = self._retry_protocol(op, timeout_s, kw, e)
        except RpcTimeout:
            raise
        except (RpcDown, RpcError) as e:
            # RpcError too: a worker whose dispatch raises is sick — the
            # supervisor's restart path is the recovery for both
            raise ReplicaDownError(f"worker {self.idx}: {e}") from e
        self._note_response(resp)
        return resp

    def _retry_protocol(self, op: str, timeout_s: Optional[float],
                        kw: dict, err: RpcProtocolError) -> dict:
        """Recover from a DATA-PLANE protocol error: the stream is
        poisoned (checksum mismatch, mid-frame EOF), not the call.
        Reconnect and retry ONCE with the SAME kwargs — same idem key,
        so if the first copy actually executed before the stream died,
        the worker's reply cache answers the retry and nothing runs
        twice. A generation-fence rejection is different: the protocol
        is fine, WE are stale — mark the replica down so the attach
        path renegotiates the incarnation."""
        if "stale generation" in str(err):
            if self.metrics is not None:
                self.metrics.inc("rpc_stale_generation_rejects")
            raise ReplicaDownError(f"worker {self.idx}: {err}") from err
        if self.metrics is not None:
            self.metrics.inc("rpc_corrupt_frames")
        self.client.close()
        try:
            return self.client.call(op, timeout_s=timeout_s, **kw)
        except RpcTimeout:
            raise
        except (RpcProtocolError, RpcDown, RpcError) as e2:
            raise ReplicaDownError(f"worker {self.idx}: {e2}") from e2

    def _note_response(self, resp) -> None:
        """Bookkeeping every successful round-trip feeds: the half-open
        heartbeat, and the duplicate-suppression ledger (``idem_hit``
        marks a reply served from the worker's cache — the netchaos
        soak pins rpc_dup_suppressed == injected duplicates)."""
        self.last_ok_t = time.monotonic()
        if (isinstance(resp, dict) and resp.get("idem_hit")
                and self.metrics is not None):
            self.metrics.inc("rpc_dup_suppressed")

    # ------------------------------------------- netchaos observer hooks

    def net_chaos_response(self, resp) -> None:
        """FaultyTransport routes DISCARDED responses here (reorder
        replays, one-way partitions): the call's effects happened on
        the worker even though the caller never saw the reply, so the
        dup-suppression accounting must still count an ``idem_hit``."""
        self._note_response(resp)

    def net_chaos_partition(self, active: bool) -> None:
        if active:
            if self.metrics is not None:
                self.metrics.inc("rpc_partitions_active")
            if self.tel.enabled:
                self.tel.instant("net_partition", ROUTER_TRACK,
                                 replica=self.idx)
        elif self.tel.enabled:
            self.tel.instant("net_heal", ROUTER_TRACK,
                             replica=self.idx)

    # ----------------------------------------------------- backend verbs

    def submit(self, req: Request) -> Optional[RequestResult]:
        try:
            resp = self._call("submit",
                              timeout_s=self.rpc_timeout_s,
                              idem=self._next_idem("submit"),
                              req=request_to_wire(
                                  req, time.monotonic()))
        except RpcTimeout:
            # the worker may still EXECUTE this submit when it resumes
            # — submit has no ack/redeliver protocol like step, so the
            # router must supersede this copy before re-routing the id
            return RequestResult(id=req.id, tokens=[],
                                 finish_reason=REJECT_REPLICA_TIMEOUT)
        except ReplicaDownError:
            return RequestResult(id=req.id, tokens=[],
                                 finish_reason=REJECT_REPLICA_DOWN)
        if resp.get("accepted"):
            # the id may be a legal reuse of a finished-and-popped one:
            # it must not be swallowed by the finish dedupe set
            self._seen.discard(req.id)
            return None
        return result_from_wire(resp["rejection"])

    def cancel(self, request_id: str, migrated: bool = False) -> bool:
        try:
            resp = self._call("cancel", timeout_s=self.rpc_timeout_s,
                              id=request_id, migrated=migrated)
        except (ReplicaDownError, RpcTimeout):
            return False
        return bool(resp.get("found"))

    def step_engine(self) -> List[RequestResult]:
        try:
            resp = self._call("step", acks=self._acks,
                              timeout_s=self.step_timeout_s)
        except RpcTimeout:
            # the worker may be hung (SIGSTOP) — the caller's wall-time
            # measurement feeds the wedge probe; the call itself may
            # still execute when the process resumes, which the
            # ack/redeliver protocol makes safe
            self.rpc_timeouts += 1
            return []
        self._acks = []
        self._absorb(resp)
        delivered = [result_from_wire(d)
                     for d in resp.get("finished", [])]
        fresh = [r for r in delivered if r.id not in self._seen]
        # everything in this response stays buffered worker-side until
        # the next call acks it; everything NOT in it was pruned by a
        # previous ack and can leave the dedupe set
        self._seen = {r.id for r in delivered}
        self._acks = sorted(self._seen)
        return fresh

    def stream_drain(self) -> None:
        """Refresh the committed-token cache without forcing a step
        (reconnect reconciliation)."""
        resp = self._call("stream_drain", timeout_s=self.rpc_timeout_s)
        self._partials.update({rid: list(toks) for rid, toks
                               in resp.get("partials", {}).items()})

    def journal_state(self, telemetry=None,
                      kinds: Tuple[str, ...] = ("finished",)
                      ) -> Tuple[Dict[str, str], List[Request]]:
        """Page the worker's LOCAL journal state through the
        ``journal_drain`` RPC (bounded frames): the file stays on the
        worker's machine, its content crosses the wire. An unreachable
        worker yields an empty view — the caller falls back to the
        router's own ledger (which is precisely the host-loss path).
        ``kinds`` defaults to finish records only: attach
        reconciliation gets the unfinished set from the worker's
        ``in_flight`` (health RPC), so shipping block_size-scale
        prompts it would discard is pure waste — pass
        ``("finished", "unfinished")`` to rebuild from nothing."""
        finished: Dict[str, str] = {}
        unfinished: List[Request] = []
        cursor = 0
        while True:
            try:
                resp = self._call("journal_drain",
                                  timeout_s=self.rpc_timeout_s,
                                  idem=self._next_idem(
                                      "journal_drain"),
                                  cursor=cursor, kinds=list(kinds))
            except (ReplicaDownError, RpcTimeout, RpcError):
                break
            for rec in resp.get("records", []):
                if rec.get("kind") == "finished":
                    finished[rec["id"]] = rec.get("reason", "")
                elif rec.get("kind") == "unfinished":
                    unfinished.append(request_from_wire(
                        rec["req"], time.monotonic()))
            nxt = int(resp.get("cursor", cursor))
            if resp.get("eof", True) or nxt <= cursor:
                break
            cursor = nxt
        if telemetry is not None and telemetry.enabled:
            telemetry.instant("journal_drain", ROUTER_TRACK,
                              replica=self.idx, finished=len(finished),
                              unfinished=len(unfinished))
        return finished, unfinished

    def _absorb(self, resp: dict) -> None:
        for k in self._gauges:
            if k in resp:
                self._gauges[k] = int(resp[k])
        if "idle" in resp:
            self._idle = bool(resp["idle"])
        if "warmed" in resp:
            self._warmed = bool(resp["warmed"])
        self._partials = {rid: list(toks) for rid, toks
                          in resp.get("partials", {}).items()}

    def partial_tokens(self, request_id: str) -> Optional[List[int]]:
        return self._partials.get(request_id)

    #: budget for the hot-routing-path RPCs (prefix peek) — affinity
    #: is an optimization, and a hung-but-not-yet-wedged worker must
    #: not convert every submit into a full rpc_timeout_s stall
    ROUTE_RPC_TIMEOUT_S = 1.0

    def cached_prefix_tokens(self, prompt) -> int:
        import numpy as np
        try:
            resp = self._call("prefix",
                              prompt=np.asarray(prompt).tolist(),
                              timeout_s=min(self.ROUTE_RPC_TIMEOUT_S,
                                            self.rpc_timeout_s))
        except (ReplicaDownError, RpcTimeout):
            return 0
        return int(resp.get("tokens", 0))

    @property
    def queue_depth(self) -> int:
        return self._gauges["queue_depth"]

    @property
    def slots_active(self) -> int:
        return self._gauges["slots_active"]

    @property
    def pages_in_use(self) -> int:
        return self._gauges["pages_in_use"]

    @property
    def engine_idle(self) -> bool:
        return self._idle

    @property
    def warmed(self) -> bool:
        return self._warmed

    def refresh_health(self, timeout_s: Optional[float] = None) -> dict:
        """One live ``health`` RPC (attach reconciliation, the
        front door's one-time vocab lookup); absorbs the gauges it
        carries. Callers on the serving hot path must pass a short
        ``timeout_s`` — the default budget is rpc_timeout_s."""
        resp = self._call("health", timeout_s=timeout_s)
        self._absorb(resp)
        return resp

    def hit_tokens(self) -> Tuple[int, int]:
        return (self._gauges["prefix_hit_tokens"],
                self._gauges["prompt_tokens"])

    def health(self) -> dict:
        """Cached state ONLY — /healthz is the liveness probe and must
        never block the single-threaded loop on a sick worker (the
        class contract). Gauges are absorbed from every step response;
        the supervisor's separate probe and :meth:`refresh_health`
        (attach, vocab lookup) do the live RPCs."""
        h = dict(self._base_health())
        h.update({
            "queue_depth": self.queue_depth,
            "slots_active": self.slots_active,
            "pages_in_use": self.pages_in_use,
            "pid": self.pid, "gen": self.gen,
            "restarts": self.restarts,
            "rpc_timeouts": self.rpc_timeouts,
            "warmed": self.warmed,
        })
        return h

    def summary_block(self) -> dict:
        try:
            resp = self._call("summary", timeout_s=self.rpc_timeout_s)
            block = resp.get("block", {})
        except (ReplicaDownError, RpcTimeout):
            block = {"occupancy_mean": 0.0,
                     "n_steps": self._gauges["n_steps"], "pages": {},
                     "finished": {}, "unreachable": True}
        block["health"] = self.health()
        return block


class Router:
    """N-replica front tier: submit/cancel/step/drain over the fleet.

    Same single-threaded host API shape as :class:`Engine` — ``submit``
    returns None (accepted) or a terminal rejection, ``step`` advances
    every live replica one scheduling iteration and returns the fleet's
    newly finished results, ``drain`` runs to idle. Pass ``backends``
    (a list of :class:`ReplicaBase`, e.g. :class:`RemoteReplica`
    proxies from ``faults.procsup.spawn_fleet``) to run the fleet
    across worker processes instead of in-process engines — ``params``
    and ``cfg`` are unused then (each worker owns its own model)."""

    def __init__(self, params=None, cfg=None,
                 rcfg: RouterConfig = RouterConfig(),
                 ecfg=None,
                 clock: Callable[[], float] = time.monotonic,
                 telemetry=None, resilience=None,
                 drafter_factory: Optional[Callable[[], object]] = None,
                 backends: Optional[List[ReplicaBase]] = None):
        self.rcfg = rcfg
        self.clock = clock
        self.tel = telemetry or NULL
        if self.tel.enabled:
            self.tel.name_track(ROUTER_TRACK, ROUTER_TRACK_NAME)
        self.metrics = Metrics()
        self.remote = backends is not None
        #: the process supervisor (faults/procsup.py), attached by
        #: spawn_fleet — the delegate for proc_kill/proc_hang chaos and
        #: the owner of restart/quarantine decisions
        self.supervisor = None
        self.replicas: List[ReplicaBase] = []
        if backends is not None:
            self.replicas = list(backends)
            for rep in self.replicas:
                rep.skip_steps = rcfg.wedge_skip_steps
                if not rep.is_local:
                    # protocol-hardening telemetry (rpc_* counters,
                    # net_partition instants) lands in the FLEET's
                    # metrics; half-open sockets are declared dead
                    # after several silent step budgets
                    rep.metrics = self.metrics
                    rep.tel = self.tel
                    rep.heartbeat_deadline_s = (
                        rcfg.step_timeout_s * 3.0)
                if self.tel.enabled:
                    self.tel.name_track(self._worker_track(rep.idx),
                                        f"worker{rep.idx}")
        else:
            assert rcfg.n_replicas >= 1, rcfg.n_replicas
            if rcfg.tiers is not None:
                assert len(rcfg.tiers) == rcfg.n_replicas, (
                    f"tiers {rcfg.tiers} vs n_replicas "
                    f"{rcfg.n_replicas}")
            from .engine import Engine, EngineConfig
            ecfg = ecfg or EngineConfig()
            for i in range(rcfg.n_replicas):
                jpath = jr = None
                if rcfg.journal_dir is not None:
                    jpath = os.path.join(rcfg.journal_dir,
                                         f"replica{i}.jsonl")
                    jr = RequestJournal(jpath)
                eng = Engine(params, cfg, ecfg, clock=clock,
                             drafter=(drafter_factory()
                                      if drafter_factory else None),
                             rcfg=resilience, journal=jr,
                             telemetry=self.tel,
                             track_base=i * REPLICA_TRACK_STRIDE,
                             track_label=f"replica{i} ")
                self.replicas.append(Replica(
                    idx=i, engine=eng, journal_path=jpath, journal=jr,
                    skip_steps=rcfg.wedge_skip_steps,
                    tier=(rcfg.tiers[i] if rcfg.tiers else "mixed")))
        self.n_steps = 0
        self._inflight: Dict[str, _InFlight] = {}
        self._requeue: List[_Requeue] = []
        #: in-flight disaggregated page transfers, each advanced one
        #: chunk per step — the request lives HERE between its prefill-
        #: tier finish and its decode-tier resubmission
        self._transfers: List[_Transfer] = []
        #: id -> replica whose engine-surfaced terminal result must be
        #: swallowed (hedged re-route cancelled that copy on that
        #: replica; keyed by replica so the LIVE copy's finish on a
        #: different replica is never mistaken for the dead one's)
        self._superseded: Dict[str, int] = {}
        #: tokens handed to the consumer per id — survives migration,
        #: making delivery exactly-once (take_new_tokens)
        self._delivered: Dict[str, int] = {}
        self._ttft: Dict[str, float] = {}      # fleet TTFT per id
        #: remote mode: request ids with an open telemetry envelope on
        #: a worker track (the router emits worker-process envelopes —
        #: the workers' own recorders live in other processes)
        self._open_env: Dict[str, int] = {}
        #: terminal results produced by the ROUTER (kill without a
        #: journal, journaled-finish on a dead replica, cancel of a
        #: requeued request) — drained into the next step()'s return so
        #: drivers consuming step() output learn about them exactly
        #: like engine-surfaced finishes
        self._router_finished: List[RequestResult] = []
        self.results: Dict[str, RequestResult] = {}
        self.events: List[str] = []
        #: the router's own crash journal (ledger_path): the recovery
        #: source that needs no worker filesystem. Recover FIRST (read
        #: the previous incarnation's tail), then open for append —
        #: lock=True so two routers can never interleave one ledger.
        self.ledger: Optional[RequestJournal] = None
        if rcfg.ledger_path is not None:
            recovered = RequestJournal.unfinished(rcfg.ledger_path,
                                                  telemetry=self.tel)
            self.ledger = RequestJournal(rcfg.ledger_path,
                                         fsync_finish=rcfg.ledger_fsync,
                                         lock=True)
            now = self.clock()
            for req in recovered:
                # deadlines died with the previous router's clock; the
                # request re-decodes deadline-free (docs/robustness.md)
                self._requeue.append(_Requeue(
                    req=req, t_submit=now, attempts=0, due_step=0,
                    t_requeued=now))
            if recovered:
                self.metrics.inc("fleet_ledger_recovered",
                                 len(recovered))
                self._event(f"ledger recovery: {len(recovered)} "
                            f"unfinished request(s) requeued from "
                            f"{rcfg.ledger_path}")
        self._gauges()     # /metrics carries per-replica gauges from step 0

    # ---------------------------------------------------------------- API

    def submit(self, req: Request) -> Optional[RequestResult]:
        """Route and submit one request; None = accepted somewhere.
        Duplicate in-flight ids are rejected fleet-wide (an id keys the
        delivery ledger, the journals, and cancellation — the PR-5
        invariant, now across replicas: a duplicate arriving at a
        *second* replica after a kill is rejected, never
        double-decoded)."""
        self.metrics.inc("fleet_requests_submitted")
        if self.knows(req.id):
            self.metrics.inc("fleet_dedup_rejects")
            return RequestResult(id=req.id, tokens=[],
                                 finish_reason=REJECT_BAD_REQUEST)
        rej = self._submit_routed(req, self.clock(), attempts=0)
        if rej is None and self.ledger is not None:
            # one submit record per id at FLEET acceptance (requeue
            # resubmits never re-record): the router-side half of the
            # every-accepted-request-finishes promise
            self.ledger.record_submit(req)
        return rej

    def cancel(self, request_id: str) -> bool:
        fi = self._inflight.get(request_id)
        if fi is not None:
            return self.replicas[fi.replica].cancel(request_id)
        for i, item in enumerate(self._requeue):
            if item.req.id == request_id:
                del self._requeue[i]
                self._record_result(RequestResult(
                    id=request_id, tokens=[],
                    finish_reason=FINISH_CANCELLED), item.t_submit)
                return True
        return False

    @property
    def idle(self) -> bool:
        # undelivered router-side terminal results keep the fleet
        # non-idle: one more step() must run to surface them. In-flight
        # entries count too — a DOWN remote replica's requests wait for
        # its restart, and the fleet must keep stepping (retry ladder,
        # supervisor ticks ride the driver) until they resolve.
        return (not self._requeue and not self._router_finished
                and not self._inflight and not self._transfers
                and all(r.engine_idle for r in self.replicas if r.alive))

    @property
    def n_alive(self) -> int:
        return sum(r.alive for r in self.replicas)

    def step(self) -> List[RequestResult]:
        """One fleet scheduling iteration: fire fleet faults -> step
        every live replica (timing each step for the wedge probe) ->
        surface finishes -> re-route wedged replicas' work -> drain the
        requeue/retry ladder -> refresh per-replica gauges."""
        step_idx = self.n_steps
        self.n_steps += 1
        t0_us = self.tel.now_us() if self.tel.enabled else 0.0
        wedge_delay: Dict[int, float] = {}

        flt = fleet_step_fault(step_idx)
        if flt is not None:
            if flt.kind == KIND_REPLICA_KILL:
                self._kill(int(flt.arg), step_idx)
            elif flt.kind == KIND_REPLICA_WEDGE:
                wedge_delay[int(flt.arg2)] = float(flt.arg)
            elif flt.kind == KIND_PROC_KILL:
                if self.supervisor is not None:
                    self.supervisor.chaos_kill(int(flt.arg))
                else:
                    self._event(f"step {step_idx}: proc_kill ignored "
                                f"(no supervisor attached)")
            elif flt.kind == KIND_PROC_HANG:
                if self.supervisor is not None:
                    self.supervisor.chaos_hang(int(flt.arg2),
                                               int(flt.arg))
                else:
                    self._event(f"step {step_idx}: proc_hang ignored "
                                f"(no supervisor attached)")
            elif flt.kind == KIND_HOST_LOSS:
                if self.supervisor is not None:
                    self.supervisor.chaos_host_loss(int(flt.arg))
                else:
                    self._event(f"step {step_idx}: host_loss ignored "
                                f"(no supervisor attached)")

        out: List[RequestResult] = []
        if self._router_finished:      # router-side terminals (kill
            out.extend(self._router_finished)   # paths, cancels) surface
            self._router_finished = []          # with this step's batch
        now = self.clock()
        for rep in self.replicas:
            if not rep.alive:
                continue
            t_wall = time.perf_counter()
            delay = wedge_delay.get(rep.idx, 0.0)
            if delay:
                # the injected wedge: the replica's step stalls, inside
                # the router's measurement — indistinguishable from a
                # wedged device or a partition to that replica
                time.sleep(delay)  # graftlint: disable=GL019 — chaos injection: the wedge MUST stall the loop
            try:
                finished = rep.step_engine()
            except ReplicaDownError as e:
                rep.last_step_s = time.perf_counter() - t_wall
                self.mark_down(rep.idx, str(e))
                continue
            rep.last_step_s = time.perf_counter() - t_wall
            rep.steps += 1
            # finishes BEFORE the wedge probe: a request that finished
            # in the very step that trips the probe must leave the
            # ledger first, or _wedge would hedge-requeue it — a second
            # decode (and a second terminal envelope) for a request the
            # client already has in full
            for res in finished:
                done = self._on_finish(res, rep.idx, now)
                if done is not None:
                    out.append(done)
            self._probe(rep, step_idx)
            self._probe_heartbeat(rep, step_idx)

        self._advance_transfers(now)
        self._observe_ttft(now)
        self._drain_requeue(step_idx)
        if self._router_finished:   # terminals recorded DURING this
            out.extend(self._router_finished)   # step (retry exhaustion)
            self._router_finished = []          # surface with its batch
        self._gauges()
        if self.tel.enabled:
            self.tel.complete("router_step", ROUTER_TRACK, t0_us,
                              self.tel.now_us() - t0_us, step=step_idx,
                              n_finished=len(out),
                              n_alive=self.n_alive)
        return out

    def drain(self, max_steps: int = 1_000_000) -> List[RequestResult]:
        out: List[RequestResult] = []
        for _ in range(max_steps):
            if self.idle:
                return out
            out.extend(self.step())
        raise RuntimeError(f"fleet did not drain in {max_steps} steps")

    def take_new_tokens(self, request_id: str) -> List[int]:
        """Consume the tokens newly available for ``request_id`` since
        the last call — the ONE delivery path (SSE streaming and the
        fleet replay both read through here). Exactly-once across
        migration AND across a worker-process restart: a
        requeued/replayed request regenerates deterministically from
        token 0, and this ledger suppresses the prefix already
        delivered, so the concatenated stream equals the uninterrupted
        token list."""
        sent = self._delivered.get(request_id, 0)
        res = self.results.get(request_id)
        if res is not None:
            new = res.tokens[sent:]
        else:
            fi = self._inflight.get(request_id)
            if fi is None:
                return []
            partial = (self.replicas[fi.replica]
                       .partial_tokens(request_id)) or []
            new = partial[sent:]
        if new:
            self._delivered[request_id] = sent + len(new)
        return new

    def result(self, request_id: str) -> Optional[RequestResult]:
        return self.results.get(request_id)

    def knows(self, request_id: str) -> bool:
        """Whether the id is anywhere in the fleet: in flight, between
        replicas awaiting resubmission, or terminal-but-unclaimed."""
        return (request_id in self._inflight
                or request_id in self.results
                or any(q.req.id == request_id for q in self._requeue))

    def pop_result(self, request_id: str) -> Optional[RequestResult]:
        """Take a terminal result out of the router's memory (the HTTP
        layer calls this once a stream fully delivered — a long-lived
        front door must not grow its results map without bound)."""
        self._delivered.pop(request_id, None)
        self._ttft.pop(request_id, None)
        return self.results.pop(request_id, None)

    def close(self) -> None:
        for rep in self.replicas:
            rep.close()
        if self.ledger is not None:
            self.ledger.close()
            self.ledger = None

    # ------------------------------------------------------- supervision

    def mark_down(self, idx: int, reason: str = "") -> None:
        """A remote replica's process is unreachable: stop stepping it,
        keep its in-flight ledger entries — the supervisor decides
        between restart (the worker replays its journal and
        :meth:`attach_replica` reconciles) and abandonment
        (:meth:`abandon_replica` requeues onto survivors)."""
        rep = self.replicas[idx]
        if not rep.alive:
            return
        rep.alive = False
        rep.wedged = False
        self.metrics.inc("fleet_replica_downs")
        self._event(f"step {self.n_steps}: replica {idx} DOWN"
                    + (f" ({reason})" if reason else ""))
        self.tel.instant("worker_down", ROUTER_TRACK, replica=idx)

    def add_replica(self, rep: ReplicaBase) -> int:
        """Grow the fleet at runtime (autoscale scale-up, or an
        unmanaged worker registering from another host): append the
        backend and return its index. The replica joins NOT-alive —
        :meth:`attach_replica` flips it routable once its registration
        handshake completes, so a half-started worker is never
        routed."""
        assert rep.idx == len(self.replicas), (
            f"replica indices are append-only: got {rep.idx}, "
            f"expected {len(self.replicas)}")
        rep.alive = False
        rep.skip_steps = self.rcfg.wedge_skip_steps
        self.replicas.append(rep)
        if self.tel.enabled and not rep.is_local:
            self.tel.name_track(self._worker_track(rep.idx),
                                f"worker{rep.idx}")
        self.metrics.inc("fleet_replicas_added")
        self._event(f"step {self.n_steps}: replica {rep.idx} added "
                    f"(fleet grows to {len(self.replicas)})")
        return rep.idx

    def offered_load(self) -> dict:
        """The autoscaler's input signal, from gauges the router
        already tracks: queued work (admission queues of routable
        replicas + the between-replicas requeue), active decode slots,
        and how many replicas can take traffic. Exported so the
        supervisor never reaches into replica internals."""
        routable = [r for r in self.replicas if r.routable]
        return {
            "queued": (sum(r.queue_depth for r in routable)
                       + len(self._requeue)),
            "active": sum(r.slots_active for r in routable),
            "n_routable": len(routable),
        }

    def attach_replica(self, idx: int, port: int,
                       pid: Optional[int] = None,
                       gen: Optional[int] = None,
                       host: Optional[str] = None,
                       tier: Optional[str] = None,
                       page_size: Optional[int] = None) -> dict:
        """(Re)connect a remote replica and reconcile the router's
        in-flight ledger against what the restarted worker actually
        recovered from its journal (shipped over the ``journal_drain``
        RPC — the worker's filesystem is never touched from here, so
        the worker can live on any machine):

        - ids the worker replayed keep their ledger entries — the
          worker regenerates them from token 0 and the delivery ledger
          suppresses the already-delivered prefix (exactly-once across
          ``kill -9``);
        - ids the journal says *finished* (the result died undelivered
          with the process) surface their journaled reason;
        - ids the worker lost entirely (torn submit record, or a
          vanished HOST whose fresh replacement has an empty journal)
          requeue onto the fleet from the router's own ledger;
        - ids the worker replayed that the router does NOT own (stale
          journal ghosts, previously-migrated work) are cancelled
          before they waste a decode.
        """
        rep = self.replicas[idx]
        assert isinstance(rep, RemoteReplica), "attach is remote-only"
        rep.connect(port, pid=pid, gen=gen, host=host)
        if tier is not None:
            # the worker's advertised disaggregation role + page
            # geometry (registration doc) — a restarted worker may
            # come back with a different role
            rep.tier = tier
        if page_size:
            rep.page_size = int(page_size)
        h = rep.refresh_health()
        rep.stream_drain()
        worker_ids = set(h.get("in_flight", []))
        mine = [rid for rid, fi in self._inflight.items()
                if fi.replica == idx]
        finished_reasons, _ = rep.journal_state(telemetry=self.tel)
        kept = lost = 0
        now = self.clock()
        for rid in mine:
            if rid in worker_ids:
                kept += 1
                continue
            fi = self._inflight.pop(rid)
            if rid in finished_reasons:
                self._env_close(rid, migrated=True)
                self._record_result(RequestResult(
                    id=rid, tokens=[],
                    finish_reason=finished_reasons[rid]), fi.t_submit)
            else:
                self._env_close(rid, migrated=True)
                self._requeue.append(_Requeue(
                    req=fi.req, t_submit=fi.t_submit,
                    attempts=fi.attempts, due_step=self.n_steps,
                    t_requeued=now))
                self.metrics.inc("fleet_requeued_requests")
                lost += 1
        # a replayed id the router does not own at all, OR owns on a
        # DIFFERENT replica (it migrated away while this worker was
        # dead/hung — its live copy is elsewhere), is a ghost here:
        # cancel it before it wastes a decode
        ghosts = [rid for rid in worker_ids
                  if rid not in self._inflight
                  or self._inflight[rid].replica != idx]
        for rid in ghosts:
            rep.cancel(rid, migrated=True)
            self.metrics.inc("fleet_ghost_cancels")
        # superseded entries for finishes this incarnation can never
        # deliver (the pre-restart copy died with the process)
        self._superseded = {rid: i for rid, i
                            in self._superseded.items()
                            if i != idx or rid in worker_ids}
        rep.alive = True
        rep.wedged = False
        rep.draining = False
        rep.suspect_streak = 0
        rep.skip_steps = self.rcfg.wedge_skip_steps
        self.metrics.inc("fleet_replica_attaches")
        self._event(f"step {self.n_steps}: worker {idx} attached "
                    f"(pid {rep.pid}, gen {rep.gen}, kept {kept}, "
                    f"requeued {lost}, ghosts {len(ghosts)})")
        self.tel.instant("worker_attach", ROUTER_TRACK, replica=idx,
                         gen=rep.gen, kept=kept, requeued=lost,
                         ghosts=len(ghosts))
        return {"kept": kept, "requeued": lost, "ghosts": len(ghosts)}

    def abandon_replica(self, idx: int) -> None:
        """Give up on a replica for good (restart budget exhausted →
        quarantine): journal-driven requeue of its in-flight work onto
        the survivors — the same path a fleet-fault ``replica_kill``
        takes."""
        self._kill(idx, self.n_steps)

    def drain_replica(self, idx: int) -> int:
        """Graceful drain for a rolling restart: mark the replica
        draining (unroutable, `/readyz`-excluded), migrate its
        in-flight work onto the rest of the fleet (cancel-with-migrated
        on the replica — its journal records the finishes, so a restart
        never resurrects them), and return how many requests moved.
        The replica keeps stepping while drained (it may still be
        flushing its own cancels); :meth:`attach_replica` (remote) or
        :meth:`undrain_replica` (local) lifts the drain."""
        rep = self.replicas[idx]
        if not rep.alive or rep.draining:
            return 0
        rep.draining = True
        now = self.clock()
        ids = [rid for rid, fi in self._inflight.items()
               if fi.replica == idx]
        n = 0
        for rid in ids:
            fi = self._inflight.pop(rid)
            rep.cancel(rid, migrated=True)
            self._superseded[rid] = idx
            self._env_close(rid, migrated=True)
            self._requeue.append(_Requeue(
                req=fi.req, t_submit=fi.t_submit, attempts=fi.attempts,
                due_step=self.n_steps, t_requeued=now))
            n += 1
        if n:
            self.metrics.inc("fleet_requeued_requests", n)
            self.tel.instant("requeue", ROUTER_TRACK, replica=idx,
                             n=n, cause="drain")
        self.metrics.inc("fleet_drains")
        self._event(f"step {self.n_steps}: replica {idx} draining "
                    f"({n} request(s) migrated)")
        self.tel.instant("replica_drain", ROUTER_TRACK, replica=idx,
                         n=n)
        return n

    def undrain_replica(self, idx: int) -> None:
        self.replicas[idx].draining = False

    # ------------------------------------------------------------ summary

    def fleet_summary(self) -> dict:
        """Fleet-level health/metrics block: router counters, fleet
        TTFT, per-replica occupancy + pages, aggregate prefix-hit rate
        (the affinity claim is about the FLEET's aggregate)."""
        c = self.metrics.counters
        hit_tokens = prompt_tokens = 0
        per_replica = []
        for rep in self.replicas:
            h, p = rep.hit_tokens()
            hit_tokens += h
            prompt_tokens += p
            per_replica.append(rep.summary_block())
        return {
            "n_replicas": len(self.replicas),
            "n_alive": self.n_alive,
            "n_steps": self.n_steps,
            "tiers": {t: sum(1 for r in self.replicas if r.tier == t)
                      for t in sorted({r.tier
                                       for r in self.replicas})},
            "router": {k: int(v) for k, v in sorted(c.items())},
            "fleet_ttft_s": self.metrics.hist_summary("fleet_ttft_s"),
            "transfer_s": self.metrics.hist_summary(
                "fleet_transfer_s"),
            "requeue_latency_s": self.metrics.hist_summary(
                "fleet_requeue_latency_s"),
            "aggregate_prefix_hit_rate": (
                round(hit_tokens / prompt_tokens, 4)
                if prompt_tokens else 0.0),
            "replicas": per_replica,
            "events": list(self.events[-32:]),
        }

    def healthz(self) -> dict:
        """The /healthz body — *liveness*: the router loop is up and
        answering; per-replica detail rides along. Readiness (can the
        fleet take traffic?) is :meth:`readyz` — external supervisors
        gate traffic on that, not on this."""
        return {"ok": True, "live": True,
                "n_alive": self.n_alive,
                "replicas": [r.health() for r in self.replicas]}

    def readyz(self) -> dict:
        """The /readyz body — *readiness*: ok iff at least one replica
        is routable (alive, not wedged, not draining) AND warmed
        (compiled its programs — a worker that would eat the first
        request's compile latency is not ready). 503 during a
        single-survivor rolling-restart drain window, 200 again when a
        restarted worker attaches."""
        ready = [r.idx for r in self.replicas
                 if r.routable and r.warmed]
        return {"ok": bool(ready),
                "ready_replicas": len(ready),
                "n_alive": self.n_alive,
                "draining": [r.idx for r in self.replicas
                             if r.draining]}

    # ----------------------------------------------------------- internals

    def _event(self, msg: str) -> None:
        self.events.append(msg)
        if len(self.events) > 256:
            del self.events[:len(self.events) - 256]

    @staticmethod
    def _worker_track(idx: int) -> int:
        """Remote mode: the router emits each worker's request
        envelopes on one track per worker (the worker's own recorder
        lives in another process). Concurrent envelopes interleave on
        the track; tools/trace_check.py pairs them by request id."""
        return idx * REPLICA_TRACK_STRIDE + ENGINE_TRACK

    def _env_open(self, rid: str, idx: int) -> None:
        if not (self.remote and self.tel.enabled):
            return
        self._open_env[rid] = idx
        self.tel.begin("request", self._worker_track(idx),
                       ts_us=self.tel.ts_us(self.clock()), request=rid)

    def _env_close(self, rid: str, migrated: bool,
                   reason: str = "", n_tokens: int = 0) -> None:
        idx = self._open_env.pop(rid, None)
        if idx is None or not self.tel.enabled:
            return
        args = {"request": rid, "n_tokens": n_tokens}
        if migrated:
            args["migrated"] = True
        if reason:
            args["reason"] = reason
        self.tel.end("request", self._worker_track(idx),
                     ts_us=self.tel.ts_us(self.clock()), **args)

    def _candidates(self, req: Request
                    ) -> List[Tuple[ReplicaBase, int]]:
        """(replica, cached-prefix-tokens) pairs to try, best first:
        longest cached prefix, then least load, then index (stable).
        Dedicated prefill-tier replicas never take sessions — unless
        they are the only thing left alive (a decode tier lost whole
        still beats dropping requests; slower, never wrong)."""
        avail = [r for r in self.replicas
                 if r.routable and r.tier != "prefill"]
        if not avail:
            avail = [r for r in self.replicas if r.routable]
        if not avail:
            # a fully wedged fleet still beats dropping the request on
            # the floor: route to a wedged-but-alive replica (never a
            # draining one — it is being emptied on purpose)
            avail = [r for r in self.replicas
                     if r.alive and not r.draining]
        if not avail:
            return []
        scored = [(rep, (rep.cached_prefix_tokens(req.prompt)
                         if self.rcfg.affinity else 0))
                  for rep in avail]
        scored.sort(key=lambda t: (-t[1], t[0].load, t[0].idx))
        return scored

    def _submit_routed(self, req: Request, t_submit: float,
                       attempts: int) -> Optional[RequestResult]:
        """Try every candidate replica once, in affinity/load order;
        returns None on acceptance or the LAST rejection. With a
        prefill tier present, first-attempt requests whose prompt is
        cold on the decode tier divert through disaggregated prefill
        (:meth:`_submit_prefill`); attempts > 0 — including the
        fallback resubmission after a failed transfer — place directly
        so a sick transfer path can never orbit a request between the
        tiers."""
        if (attempts == 0 and not req.prefill_only
                and self._submit_prefill(req, t_submit)):
            return None
        last: Optional[RequestResult] = None
        for rep, aff in self._candidates(req):
            rej = rep.submit(req)
            if rej is None:
                self._inflight[req.id] = _InFlight(
                    req=req, replica=rep.idx, t_submit=t_submit,
                    attempts=attempts)
                self.metrics.inc("fleet_requests_routed")
                self._env_open(req.id, rep.idx)
                if self.tel.enabled:
                    self.tel.instant(
                        "route", ROUTER_TRACK, request=req.id,
                        replica=rep.idx, attempt=attempts,
                        affinity_tokens=int(aff))
                return None
            last = rej
            # (a REJECT_REPLICA_TIMEOUT copy may execute on the hung
            # worker anyway — if the id is then accepted elsewhere,
            # that copy's eventual finish is swallowed by the
            # replica-aware stale guard in _on_finish, or by the ghost
            # path once the live copy delivered; no extra state needed)
            if rej.finish_reason in TERMINAL_REJECTS:
                # a deterministic verdict (validation, prompt too long,
                # dead-on-arrival deadline) — another replica would say
                # the same thing
                break
            self.metrics.inc("fleet_route_fallbacks")
        if last is None:       # no replicas at all
            last = RequestResult(id=req.id, tokens=[],
                                 finish_reason=REJECT_FLEET_CAPACITY)
        return last

    # ------------------------------------------- disaggregated prefill

    def _page_size(self) -> int:
        for rep in self.replicas:
            if rep.alive and rep.page_size:
                return int(rep.page_size)
        return 0

    def _prefill_tier(self) -> List[ReplicaBase]:
        return [r for r in self.replicas
                if r.routable and r.tier == "prefill"]

    def _decode_target(self, req: Request
                       ) -> Tuple[Optional[ReplicaBase], int]:
        """Best decode-tier home for a session: (replica,
        cached-prefix-tokens), longest prefix then least load — the
        replica whose radix already holds the session's pages."""
        avail = [r for r in self.replicas
                 if r.routable and r.tier != "prefill"]
        if not avail:
            return None, 0
        scored = [(rep, (rep.cached_prefix_tokens(req.prompt)
                         if self.rcfg.affinity else 0))
                  for rep in avail]
        scored.sort(key=lambda t: (-t[1], t[0].load, t[0].idx))
        return scored[0]

    def _submit_prefill(self, req: Request, t_submit: float) -> bool:
        """Two-tier placement: if a prefill tier exists and the best
        decode-tier replica is missing at least ``disagg_min_tail``
        full pages of this prompt, submit a ``prefill_only`` clone to
        the least-loaded prefill worker. The ``prefilled`` finish
        diverts into :meth:`_on_prefilled` (transfer + resubmission).
        Returns False to fall through to ordinary placement — a
        prefix-hot prompt (the short-circuit), no prefill capacity, or
        no page geometry yet."""
        pre = self._prefill_tier()
        psz = self._page_size()
        if not pre or psz <= 0:
            return False
        n_full = len(req.prompt) // psz
        _, cached = self._decode_target(req)
        if n_full - cached // psz < self.rcfg.disagg_min_tail:
            if n_full:
                self.metrics.inc("fleet_disagg_shortcircuits")
            return False
        pre.sort(key=lambda r: (r.load, r.idx))
        rep = pre[0]
        if rep.submit(replace(req, prefill_only=True)) is not None:
            self.metrics.inc("fleet_disagg_fallbacks")
            return False
        self._inflight[req.id] = _InFlight(
            req=req, replica=rep.idx, t_submit=t_submit, attempts=0)
        self.metrics.inc("fleet_requests_routed")
        self.metrics.inc("fleet_disagg_prefills")
        self._env_open(req.id, rep.idx)
        if self.tel.enabled:
            self.tel.instant("route", ROUTER_TRACK, request=req.id,
                             replica=rep.idx, attempt=0,
                             tier="prefill")
        return True

    def _page_source(self, rep: ReplicaBase):
        if rep.is_local:
            return LocalPageSource(rep.engine)
        return RpcPageSource(rep._call)

    def _page_sink(self, rep: ReplicaBase):
        if rep.is_local:
            return LocalPageSink(rep.engine)
        return RpcPageSink(rep._call)

    def _transfer_chaos(self, chunk_idx: int) -> None:
        """Per-chunk fault seam inside a running transfer
        (faults/fleet.py ``transfer_kill``): kill the named replica —
        either tier — and abort the transfer the way a vanished host
        would (the driver falls back to a full decode-tier prefill)."""
        f = transfer_fault(chunk_idx)
        if f is not None and f.kind == KIND_TRANSFER_KILL:
            idx = int(f.arg)
            self._kill(idx, self.n_steps)
            raise OSError(f"replica {idx} lost mid-transfer (chaos)")

    def _on_prefilled(self, res: RequestResult, fi: _InFlight,
                      src_idx: int, now: float) -> None:
        """A prefill-tier worker finished chewing a prompt: start
        shipping its KV pages to the request's decode-tier home. The
        transfer is a :class:`~.disagg.TransferJob` advanced one chunk
        per router step (:meth:`_advance_transfers`) — the scheduling
        loop never blocks on page bytes; the request is resubmitted
        when the transfer resolves. No usable source/target means the
        no-pages fallback immediately: submit without the transfer, a
        full local prefill, token-identical, just slower."""
        req = fi.req
        src = self.replicas[src_idx]
        self._env_close(res.id, migrated=True, reason="prefilled",
                        n_tokens=len(res.tokens))
        dst, cached = self._decode_target(req)
        psz = self._page_size()
        if dst is None or not src.alive or psz <= 0:
            self._resubmit_prefilled(req, fi.t_submit, fi.attempts,
                                     dst, now)
            return
        job = TransferJob(
            self._page_source(src), self._page_sink(dst),
            f"xfer:{req.id}", req.prompt, cached // psz,
            fault=self._transfer_chaos, clock=self.clock,
            max_chunk_pages=self.rcfg.transfer_chunk_pages)
        self._transfers.append(_Transfer(
            job=job, req=req, t_submit=fi.t_submit,
            attempts=fi.attempts, src_idx=src_idx, dst_idx=dst.idx,
            t0_us=(self.tel.ts_us(self.clock())
                   if self.tel.enabled else 0.0)))

    def _advance_transfers(self, now: float) -> None:
        """Advance every in-flight page transfer by ONE chunk
        round-trip; finished jobs record their metrics/span and the
        request resubmits to the decode tier (failed transfers submit
        pageless — full local prefill)."""
        if not self._transfers:
            return
        still: List[_Transfer] = []
        for tr in self._transfers:
            r = tr.job.step()
            if r is None:
                still.append(tr)
                continue
            self.metrics.inc("fleet_transfers")
            self.metrics.observe("fleet_transfer_s", r.elapsed_s)
            if r.ok:
                self.metrics.inc("fleet_transfer_pages", r.pages)
                self.metrics.inc("fleet_transfer_bytes", r.wire_bytes)
            else:
                self.metrics.inc("fleet_transfer_failures")
            if self.tel.enabled:
                self.tel.complete(
                    "page_transfer", ROUTER_TRACK, tr.t0_us,
                    max(self.tel.ts_us(self.clock()) - tr.t0_us, 1.0),
                    request=tr.req.id, src=tr.src_idx, dst=tr.dst_idx,
                    pages=r.pages, bytes=r.wire_bytes, ok=r.ok,
                    **({"error": r.error} if r.error else {}))
            # the chaos seam may have killed dst mid-transfer —
            # re-resolve before resubmitting
            dst = self.replicas[tr.dst_idx]
            if not dst.routable:
                dst, _ = self._decode_target(tr.req)
            self._resubmit_prefilled(tr.req, tr.t_submit, tr.attempts,
                                     dst, now)
        self._transfers = still

    def _resubmit_prefilled(self, req: Request, t_submit: float,
                            attempts: int, dst: Optional[ReplicaBase],
                            now: float) -> None:
        """The decode-tier half of a disaggregated request: submit the
        ORIGINAL request — admission claims whatever prefix the radix
        now holds (the transferred pages, or nothing after a failed
        transfer) and decodes as if it had prefilled locally."""
        if dst is not None and dst.submit(req) is None:
            self._inflight[req.id] = _InFlight(
                req=req, replica=dst.idx, t_submit=t_submit,
                attempts=attempts)
            self.metrics.inc("fleet_requests_routed")
            self._env_open(req.id, dst.idx)
            if self.tel.enabled:
                self.tel.instant("route", ROUTER_TRACK, request=req.id,
                                 replica=dst.idx, attempt=attempts,
                                 tier="decode")
            return
        # no decode capacity right now: the retry ladder owns it, with
        # attempts past 0 so the resubmission places directly
        self._requeue.append(_Requeue(
            req=req, t_submit=t_submit, attempts=attempts + 1,
            due_step=self.n_steps, t_requeued=now))
        self.metrics.inc("fleet_requeued_requests")

    def _on_finish(self, res: RequestResult, replica: int,
                   now: float) -> Optional[RequestResult]:
        if self._superseded.get(res.id) == replica:
            # the hedged re-route cancelled this copy ON THIS replica;
            # the live copy is elsewhere — swallow it (keyed by replica
            # so the live copy's own finish is never mistaken for it)
            del self._superseded[res.id]
            return None
        fi = self._inflight.get(res.id)
        if fi is not None and fi.replica != replica:
            # a stale copy on a replica the ledger does NOT route this
            # id to (a timed-out submit that executed anyway, a
            # pre-migration straggler): the live copy is on fi.replica
            # — swallowing here keeps its entry intact
            self.metrics.inc("fleet_stale_finishes")
            return None
        fi = self._inflight.pop(res.id, None)
        if fi is None:
            # remote-mode ghosts only: a finish for an id the router
            # does not own (a cancelled stale-journal replay, a
            # redelivery that slipped the proxy dedupe). In-process
            # engines cannot produce this — they only ever finish what
            # the router submitted.
            if res.id not in self.results:
                self.metrics.inc("fleet_ghost_finishes")
            return None
        if res.finish_reason == FINISH_PREFILLED:
            # NOT a terminal: the prefill tier's half of a
            # disaggregated request — divert into the page transfer
            # and decode-tier resubmission; no ledger finish, no
            # client-visible result (the decode tier produces it)
            self._on_prefilled(res, fi, replica, now)
            return None
        res.total_s = now - fi.t_submit
        if res.id in self._ttft:
            res.ttft_s = self._ttft[res.id]
        elif res.tokens:
            # finished in the same step its first token committed:
            # _observe_ttft runs after the per-replica loop and only
            # sees ids still in flight, so the FASTEST requests would
            # never enter the fleet_ttft_s histogram (biasing the
            # bench p50/p99 upward) — observe them here
            res.ttft_s = now - fi.t_submit
            self._ttft[res.id] = res.ttft_s
            self.metrics.observe("fleet_ttft_s", res.ttft_s)
        self._env_close(res.id, migrated=False,
                        reason=res.finish_reason,
                        n_tokens=len(res.tokens))
        self.metrics.inc("fleet_requests_finished")
        if self.ledger is not None:
            self.ledger.record_finish(res.id, res.finish_reason)
        self.results[res.id] = res
        return res

    def _record_result(self, res: RequestResult, t_submit: float,
                       envelope: bool = True) -> None:
        """Terminal result produced by the ROUTER (requeue-retry
        exhaustion, cancel-between-replicas, journaled-finish on a dead
        replica) — when no engine closed this request's envelope, the
        router emits the one terminal close itself, as a zero-length
        envelope on the router track: every request id still forms
        exactly one complete span tree (tools/trace_check.py), even
        when its engine segments all ended ``migrated``.
        ``envelope=False`` is the in-process journaled-finish path: the
        engine closed the terminal envelope when it journaled the
        finish (the two happen together in ``_finish_slot``) — a second
        close here would violate the exactly-one-terminal invariant.
        (Remote workers record into their own processes, so the remote
        paths always pass ``envelope=True`` after closing any open
        worker-track segment as migrated.)"""
        now = self.clock()
        res.total_s = now - t_submit
        self._env_close(res.id, migrated=True)   # remote stragglers
        if self.tel.enabled and envelope:
            ts = self.tel.ts_us(now)
            self.tel.begin("request", ROUTER_TRACK, ts_us=ts,
                           request=res.id)
            self.tel.end("request", ROUTER_TRACK, ts_us=ts,
                         request=res.id, reason=res.finish_reason,
                         n_tokens=len(res.tokens))
        self.metrics.inc("fleet_requests_finished")
        if self.ledger is not None:
            self.ledger.record_finish(res.id, res.finish_reason)
        self.results[res.id] = res
        self._router_finished.append(res)

    def _observe_ttft(self, now: float) -> None:
        """Fleet TTFT: first token OBSERVABLE at the router for each
        in-flight id (tokens delivered before a migration count — the
        client had them)."""
        for rid, fi in self._inflight.items():
            if rid in self._ttft or self._delivered.get(rid, 0):
                continue
            partial = self.replicas[fi.replica].partial_tokens(rid)
            if partial:
                self._ttft[rid] = now - fi.t_submit
                self.metrics.observe("fleet_ttft_s", now - fi.t_submit)

    def _probe_heartbeat(self, rep: ReplicaBase,
                         step_idx: int) -> None:
        """Half-open socket detection: a remote replica whose RPCs all
        time out (one-way partition, silently dropped packets) never
        surfaces an error — every call just burns its budget. Once no
        response has round-tripped for ``heartbeat_deadline_s``, close
        the client so the NEXT call re-connects from scratch: a truly
        dead peer then fails fast as ``RpcDown`` (→ mark_down → the
        supervisor), while a healed partition gets a clean socket
        instead of a poisoned half-open one."""
        deadline = getattr(rep, "heartbeat_deadline_s", None)
        if (rep.is_local or deadline is None
                or getattr(rep, "client", None) is None):
            return
        silent_s = time.monotonic() - rep.last_ok_t
        if silent_s <= deadline:
            return
        rep.client.close()
        rep.last_ok_t = time.monotonic()   # one reconnect per deadline
        self._event(f"step {step_idx}: replica {rep.idx} heartbeat "
                    f"deadline blown ({silent_s:.1f}s silent) — "
                    f"forcing reconnect")

    def _probe(self, rep: ReplicaBase, step_idx: int) -> None:
        """Wedge detection over per-step wall time + quarantine expiry."""
        cfg = self.rcfg
        if rep.wedged and step_idx >= rep.quarantine_until:
            rep.wedged = False
            rep.suspect_streak = 0
            self.metrics.inc("fleet_replica_rejoins")
            self._event(f"step {step_idx}: replica {rep.idx} rejoined")
            self.tel.instant("replica_rejoin", ROUTER_TRACK,
                             replica=rep.idx)
            # a remote replica that wedged (e.g. SIGSTOP) may still
            # hold superseded copies the hedge could not cancel while
            # it was unresponsive — clean them up now, best effort
            if not rep.is_local:
                for rid, sidx in list(self._superseded.items()):
                    if sidx == rep.idx:
                        rep.cancel(rid, migrated=True)
        if cfg.wedge_budget_s <= 0 or rep.wedged:
            return
        if rep.skip_steps > 0:        # warmup compiles are not wedges
            rep.skip_steps -= 1
            return
        if rep.last_step_s > cfg.wedge_budget_s:
            rep.suspect_streak += 1
        else:
            rep.suspect_streak = 0
        if rep.suspect_streak >= cfg.wedge_patience:
            self._wedge(rep, step_idx)

    def _wedge(self, rep: ReplicaBase, step_idx: int) -> None:
        """Quarantine a wedged replica and hedge its in-flight work onto
        healthy replicas (cancel-with-migrated on the suspect first, so
        no id is ever live on two replicas — double-decode is
        structurally impossible; a HUNG remote worker cannot be
        cancelled now, so its copy is marked superseded and cancelled
        at rejoin instead — the delivery ledger never reads from it
        either way)."""
        rep.wedged = True
        rep.suspect_streak = 0
        rep.quarantine_until = step_idx + self.rcfg.quarantine_steps
        self.metrics.inc("fleet_replica_wedges")
        self._event(f"step {step_idx}: replica {rep.idx} wedged "
                    f"({rep.last_step_s * 1e3:.1f} ms step over "
                    f"{self.rcfg.wedge_budget_s * 1e3:.1f} ms budget); "
                    f"re-routing its in-flight work")
        self.tel.instant("replica_wedge", ROUTER_TRACK, replica=rep.idx,
                         step_ms=rep.last_step_s * 1e3)
        now = self.clock()
        n = 0
        ids = [rid for rid, fi in self._inflight.items()
               if fi.replica == rep.idx]
        for rid in ids:
            fi = self._inflight.pop(rid)
            rep.cancel(rid, migrated=True)
            self._superseded[rid] = rep.idx
            self._env_close(rid, migrated=True)
            self._requeue.append(_Requeue(
                req=fi.req, t_submit=fi.t_submit,
                attempts=fi.attempts, due_step=step_idx,
                t_requeued=now))
            n += 1
        if n:
            self.metrics.inc("fleet_requeued_requests", n)
            self.tel.instant("requeue", ROUTER_TRACK, replica=rep.idx,
                             n=n, cause="wedge")

    def _kill(self, idx: int, step_idx: int) -> None:
        """Abandon a replica (a process death the supervisor gave up
        on, or the in-process stand-in for one): close its telemetry
        envelopes as migrated segments, replay its crash journal,
        requeue the unfinished."""
        if not (0 <= idx < len(self.replicas)):
            return
        rep = self.replicas[idx]
        if not rep.alive:
            return
        rep.alive = False
        rep.wedged = False
        self.metrics.inc("fleet_replica_kills")
        self._event(f"step {step_idx}: replica {idx} KILLED; replaying "
                    f"its journal")
        self.tel.instant("replica_kill", ROUTER_TRACK, replica=idx)
        now = self.clock()
        # close open request envelopes on the dead replica's tracks:
        # the router observed the death — the segments are non-terminal
        # (migrated), the real tree completes elsewhere
        if self.tel.enabled:
            for rid, fi in self._inflight.items():
                if fi.replica != idx:
                    continue
                if rep.is_local:
                    slot = rep.engine.pool.slot_of(rid)
                    if slot is None:
                        continue
                    partial = rep.engine.partial_tokens(rid) or []
                    self.tel.end("request", rep.engine.slot_track(slot),
                                 ts_us=self.tel.ts_us(now), request=rid,
                                 reason="replica_dead", migrated=True,
                                 n_tokens=len(partial))
                else:
                    self._env_close(rid, migrated=True,
                                    reason="replica_dead")
        rep.close()
        pending: List[Request] = []
        finished_reasons: Dict[str, str] = {}
        if rep.is_local:
            finished_reasons, pending = rep.journal_state(
                telemetry=self.tel)
        else:
            # a dead worker PROCESS — possibly a vanished HOST, journal
            # and all (host_loss chaos, spot-VM preemption): the
            # router's OWN ledger is the source of truth. Every
            # in-flight id on this replica requeues and re-decodes;
            # the delivery ledger suppresses the already-streamed
            # prefix, so a finish that died unacked re-delivers in
            # full instead of surfacing a tokenless journaled reason.
            pending = [fi.req for rid, fi in self._inflight.items()
                       if fi.replica == idx]
        # the router's in-memory ledger is authoritative for THIS run:
        # only replay journal entries for ids the router has in flight
        # ON THE DEAD REPLICA. Anything else is a ghost — a stale
        # record from a previous run sharing this journal dir, or an id
        # that migrated away earlier (its finish landed in the
        # survivor's journal, not here). Resurrecting a ghost whose id
        # collides with a live request would double-decode it.
        live = []
        for p in pending:
            fi = self._inflight.get(p.id)
            if fi is not None and fi.replica == idx:
                live.append(p)
        pending = live
        pending_ids = {r.id for r in pending}
        for p in pending:
            fi = self._inflight.pop(p.id)
            self._requeue.append(_Requeue(
                req=p, t_submit=fi.t_submit, attempts=fi.attempts,
                due_step=step_idx, t_requeued=now))
        if pending:
            self.metrics.inc("fleet_requeued_requests", len(pending))
            self.tel.instant("requeue", ROUTER_TRACK, replica=idx,
                             n=len(pending), cause="kill")
        # in-flight ids the journal says finished but whose terminal
        # result died undelivered with the replica: surface the
        # journaled reason (the tokens are lost with the process — an
        # honest crash semantics, pinned in tests)
        for rid in [r for r, fi in list(self._inflight.items())
                    if fi.replica == idx and r not in pending_ids]:
            fi = self._inflight.pop(rid)
            # a journaled finish means an IN-PROCESS engine already
            # emitted the terminal envelope close (or the
            # request_unstarted instant) — the router must not close it
            # a second time. A remote worker's recorder died with its
            # process: the router always owns the close there.
            self._record_result(RequestResult(
                id=rid, tokens=[],
                finish_reason=finished_reasons.get(rid, "cancelled")),
                fi.t_submit,
                envelope=(not rep.is_local
                          or rid not in finished_reasons))

    def _drain_requeue(self, step_idx: int) -> None:
        """Bounded retry with exponential backoff for requests between
        replicas (requeued after a kill/wedge/drain, or bounced by
        backpressure). Terminal results (retry exhaustion) go through
        :meth:`_record_result` onto the ``_router_finished`` ledger —
        the caller drains it into this step's return."""
        # a fleet with zero routable replicas BECAUSE recovery is in
        # progress (a draining replica mid-rolling-restart, a worker
        # process respawning) holds the requeue without burning retry
        # attempts: router steps race far ahead of wall-clock recovery
        # (thousands of idle steps during one worker restart), and the
        # step-denominated ladder would exhaust in milliseconds and
        # reject requests a one-second wait would have saved. A fleet
        # with nothing coming back (all replicas dead, no supervisor
        # respawn pending) still exhausts honestly.
        if not any(r.routable for r in self.replicas):
            recovering = (
                any(r.alive and r.draining for r in self.replicas)
                or (self.supervisor is not None
                    and self.supervisor.reviving))
            if recovering and self._requeue:
                for item in self._requeue:
                    item.due_step = max(item.due_step, step_idx + 1)
                return
        still: List[_Requeue] = []
        for item in self._requeue:
            if item.due_step > step_idx:
                still.append(item)
                continue
            rej = self._submit_routed(item.req, item.t_submit,
                                      attempts=item.attempts)
            if rej is None:
                self.metrics.inc("fleet_requeue_submits")
                if item.t_requeued:
                    self.metrics.observe(
                        "fleet_requeue_latency_s",
                        max(self.clock() - item.t_requeued, 0.0))
                continue
            item.attempts += 1
            if (item.attempts > self.rcfg.retry_max
                    or rej.finish_reason in TERMINAL_REJECTS):
                reason = (REJECT_FLEET_CAPACITY
                          if rej.finish_reason in RETRYABLE_REJECTS
                          else rej.finish_reason)
                self._record_result(RequestResult(
                    id=item.req.id, tokens=[], finish_reason=reason),
                    item.t_submit)
                self.metrics.inc("fleet_requeue_exhausted")
                continue
            item.due_step = step_idx + (self.rcfg.retry_backoff_steps
                                        * (2 ** (item.attempts - 1)))
            self.metrics.inc("fleet_requeue_retries")
            still.append(item)
        self._requeue = still

    def _gauges(self) -> None:
        for rep in self.replicas:
            i = rep.idx
            self.metrics.gauge(f"replica{i}_alive", int(rep.alive))
            self.metrics.gauge(f"replica{i}_wedged", int(rep.wedged))
            self.metrics.gauge(f"replica{i}_draining",
                               int(rep.draining))
            self.metrics.gauge(f"replica{i}_queue_depth",
                               rep.queue_depth if rep.alive else 0)
            self.metrics.gauge(f"replica{i}_slots_active",
                               rep.slots_active if rep.alive else 0)
            self.metrics.gauge(f"replica{i}_pages_in_use",
                               rep.pages_in_use if rep.alive else 0)
        self.metrics.gauge("fleet_requeue_depth", len(self._requeue))
        self.metrics.gauge("fleet_inflight", len(self._inflight))
        self.metrics.gauge("fleet_replicas", len(self.replicas))
        self.metrics.gauge("fleet_replicas_routable",
                           sum(r.routable for r in self.replicas))
        tiers = {rep.tier for rep in self.replicas}
        if tiers != {"mixed"}:
            # tier occupancy, disaggregated fleets only (a colocated
            # fleet's per-replica gauges above already cover it)
            for tier in sorted(tiers):
                reps = [r for r in self.replicas
                        if r.tier == tier and r.alive]
                self.metrics.gauge(f"tier_{tier}_replicas", len(reps))
                self.metrics.gauge(
                    f"tier_{tier}_slots_active",
                    sum(r.slots_active for r in reps))
                self.metrics.gauge(
                    f"tier_{tier}_queue_depth",
                    sum(r.queue_depth for r in reps))
                self.metrics.gauge(
                    f"tier_{tier}_pages_in_use",
                    sum(r.pages_in_use for r in reps))
