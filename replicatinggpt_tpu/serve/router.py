"""Multi-replica router: the fleet tier over N in-process engines.

One engine is a chip; "millions of users" is a fleet. This module
load-balances requests across N engine replicas and keeps the fleet's
promises when replicas misbehave:

- **Radix-prefix affinity**: a request is routed to the replica whose
  ``RadixIndex`` already owns the longest prefix of its prompt
  (``PagedCachePool.cached_prefix_tokens`` — a pure peek, no LRU
  touch), falling back to least-loaded. Multi-turn sessions therefore
  stick to the replica holding their conversation's KV pages, and the
  fleet's aggregate prefix-hit rate stays close to a single replica's
  (pinned in tests/test_fleet.py).
- **Health probes**: the router times every replica step and reads each
  engine's telemetry counters (queue depth, slots, watchdog stalls —
  the PR-7 Metrics substrate) into per-replica gauges. A replica whose
  steps blow the wedge budget ``wedge_patience`` times in a row is
  *wedged* — quarantined from new routes with its in-flight work
  re-routed (below).
- **Requeue across death**: a killed replica's accepted-but-unfinished
  requests are rebuilt from its crash journal
  (``RequestJournal.unfinished`` over the shared torn-tail-tolerant
  ``utils.jsonl`` reader) and resubmitted to survivors with bounded
  retry + exponential backoff. Regeneration is deterministic (prompt +
  sampling + per-request rng_seed), so greedy output is token-identical
  to an uninterrupted run; the router's delivery ledger
  (:meth:`Router.take_new_tokens`) dedupes the stream so a client sees
  every token exactly once across a migration — no drops, no
  duplicates.
- **Hedged re-route on wedge**: a wedged (but not dead) replica's
  in-flight requests are cancelled with ``migrated=True`` (the engine
  releases their slots/pages immediately and tags the telemetry
  envelope as a non-terminal segment) and re-raced onto healthy
  replicas — the fleet never double-decodes an id (the PR-5
  in-flight-id invariant, extended fleet-wide by the router's own
  dedupe at :meth:`submit`).

Single-threaded by design, like the engine: one loop drives
:meth:`Router.step`. The HTTP front door (serve/http.py) and the fleet
replay driver (serve/loadgen.py) are both such loops.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

from ..config import ModelConfig
from ..faults.fleet import (KIND_REPLICA_KILL, KIND_REPLICA_WEDGE,
                            fleet_step_fault)
from ..utils.jsonl import load_jsonl_if_exists
from ..utils.logging import Metrics
from ..utils.telemetry import (NULL, REPLICA_TRACK_STRIDE, ROUTER_TRACK,
                               ROUTER_TRACK_NAME)
from .engine import Engine, EngineConfig
from .journal import RequestJournal
from .requests import (FINISH_CANCELLED, FINISH_DEADLINE,
                       REJECT_BAD_REQUEST, REJECT_PROMPT_TOO_LONG,
                       REJECT_QUEUE_FULL, Request, RequestResult)

#: finish_reason when bounded retry exhausts without a replica
#: accepting the requeued request
REJECT_FLEET_CAPACITY = "rejected_fleet_capacity"

#: rejection verdicts deterministic across replicas (same config, same
#: clock): every replica would say the same thing, so trying another
#: one — or retrying later — is pointless and would inflate the
#: fleet_route_fallbacks capacity-pressure signal
TERMINAL_REJECTS = frozenset({REJECT_BAD_REQUEST,
                              REJECT_PROMPT_TOO_LONG, FINISH_DEADLINE})


@dataclass(frozen=True)
class RouterConfig:
    """Fleet sizing + routing/recovery knobs (docs/serving.md)."""

    n_replicas: int = 2
    #: per-replica crash journals live here (replica{i}.jsonl); None
    #: disables journals — and with them cross-replica requeue
    journal_dir: Optional[str] = None
    #: route by longest cached prefix (False: pure least-loaded)
    affinity: bool = True
    #: requeue/submit retry ladder: a rejected resubmission retries up
    #: to retry_max times, backing off retry_backoff_steps * 2^attempt
    #: router steps between tries
    retry_max: int = 4
    retry_backoff_steps: int = 1
    #: wedge probe: a replica step slower than wedge_budget_s,
    #: wedge_patience times consecutively, marks the replica wedged
    #: (0 = detection off). The first wedge_skip_steps steps per
    #: replica are exempt (warmup compiles).
    wedge_budget_s: float = 0.0
    wedge_patience: int = 2
    wedge_skip_steps: int = 3
    #: router steps a wedged replica sits out before rejoining rotation
    quarantine_steps: int = 8


@dataclass
class _InFlight:
    """Router-side ledger entry for one accepted request."""

    req: Request
    replica: int
    t_submit: float            # fleet submit time (router clock)
    attempts: int = 0


@dataclass
class _Requeue:
    """A request between replicas: awaiting (re)submission."""

    req: Request
    t_submit: float
    attempts: int
    due_step: int


@dataclass
class Replica:
    """One engine + its crash journal + router-side health state."""

    idx: int
    engine: Engine
    journal_path: Optional[str]
    journal: Optional[RequestJournal]
    alive: bool = True
    wedged: bool = False
    suspect_streak: int = 0
    skip_steps: int = 0
    quarantine_until: int = 0
    last_step_s: float = 0.0
    steps: int = 0

    @property
    def routable(self) -> bool:
        return self.alive and not self.wedged

    @property
    def load(self) -> int:
        e = self.engine
        return e.scheduler.depth + int(e._active.sum())

    def health(self) -> dict:
        """The per-replica health probe: router-side state + the
        engine's own telemetry counters/gauges (PR-7 Metrics)."""
        c = self.engine.metrics.counters
        return {
            "replica": self.idx,
            "alive": self.alive,
            "wedged": self.wedged,
            "queue_depth": self.engine.scheduler.depth,
            "slots_active": int(self.engine._active.sum()),
            "pages_in_use": self.engine.pool.alloc.pages_in_use,
            "watchdog_stalls": int(c.get("watchdog_stalls", 0)),
            "shed_requests": int(c.get("shed_requests", 0)),
            "requests_admitted": int(c.get("requests_admitted", 0)),
            "last_step_ms": round(self.last_step_s * 1e3, 3),
        }


class Router:
    """N-replica front tier: submit/cancel/step/drain over the fleet.

    Same single-threaded host API shape as :class:`Engine` — ``submit``
    returns None (accepted) or a terminal rejection, ``step`` advances
    every live replica one scheduling iteration and returns the fleet's
    newly finished results, ``drain`` runs to idle.
    """

    def __init__(self, params, cfg: ModelConfig,
                 rcfg: RouterConfig = RouterConfig(),
                 ecfg: EngineConfig = EngineConfig(),
                 clock: Callable[[], float] = time.monotonic,
                 telemetry=None, resilience=None,
                 drafter_factory: Optional[Callable[[], object]] = None):
        assert rcfg.n_replicas >= 1, rcfg.n_replicas
        self.rcfg = rcfg
        self.clock = clock
        self.tel = telemetry or NULL
        if self.tel.enabled:
            self.tel.name_track(ROUTER_TRACK, ROUTER_TRACK_NAME)
        self.metrics = Metrics()
        self.replicas: List[Replica] = []
        for i in range(rcfg.n_replicas):
            jpath = jr = None
            if rcfg.journal_dir is not None:
                jpath = os.path.join(rcfg.journal_dir,
                                     f"replica{i}.jsonl")
                jr = RequestJournal(jpath)
            eng = Engine(params, cfg, ecfg, clock=clock,
                         drafter=(drafter_factory() if drafter_factory
                                  else None),
                         rcfg=resilience, journal=jr, telemetry=self.tel,
                         track_base=i * REPLICA_TRACK_STRIDE,
                         track_label=f"replica{i} ")
            self.replicas.append(Replica(
                idx=i, engine=eng, journal_path=jpath, journal=jr,
                skip_steps=rcfg.wedge_skip_steps))
        self.n_steps = 0
        self._inflight: Dict[str, _InFlight] = {}
        self._requeue: List[_Requeue] = []
        #: id -> replica whose engine-surfaced terminal result must be
        #: swallowed (hedged re-route cancelled that copy on that
        #: replica; keyed by replica so the LIVE copy's finish on a
        #: different replica is never mistaken for the dead one's)
        self._superseded: Dict[str, int] = {}
        #: tokens handed to the consumer per id — survives migration,
        #: making delivery exactly-once (take_new_tokens)
        self._delivered: Dict[str, int] = {}
        self._ttft: Dict[str, float] = {}      # fleet TTFT per id
        #: terminal results produced by the ROUTER (kill without a
        #: journal, journaled-finish on a dead replica, cancel of a
        #: requeued request) — drained into the next step()'s return so
        #: drivers consuming step() output learn about them exactly
        #: like engine-surfaced finishes
        self._router_finished: List[RequestResult] = []
        self.results: Dict[str, RequestResult] = {}
        self.events: List[str] = []
        self._gauges()     # /metrics carries per-replica gauges from step 0

    # ---------------------------------------------------------------- API

    def submit(self, req: Request) -> Optional[RequestResult]:
        """Route and submit one request; None = accepted somewhere.
        Duplicate in-flight ids are rejected fleet-wide (an id keys the
        delivery ledger, the journals, and cancellation — the PR-5
        invariant, now across replicas: a duplicate arriving at a
        *second* replica after a kill is rejected, never
        double-decoded)."""
        self.metrics.inc("fleet_requests_submitted")
        if self.knows(req.id):
            self.metrics.inc("fleet_dedup_rejects")
            return RequestResult(id=req.id, tokens=[],
                                 finish_reason=REJECT_BAD_REQUEST)
        return self._submit_routed(req, self.clock(), attempts=0)

    def cancel(self, request_id: str) -> bool:
        fi = self._inflight.get(request_id)
        if fi is not None:
            return self.replicas[fi.replica].engine.cancel(request_id)
        for i, item in enumerate(self._requeue):
            if item.req.id == request_id:
                del self._requeue[i]
                self._record_result(RequestResult(
                    id=request_id, tokens=[],
                    finish_reason=FINISH_CANCELLED), item.t_submit)
                return True
        return False

    @property
    def idle(self) -> bool:
        # undelivered router-side terminal results keep the fleet
        # non-idle: one more step() must run to surface them
        return (not self._requeue and not self._router_finished
                and all(r.engine.idle for r in self.replicas if r.alive))

    @property
    def n_alive(self) -> int:
        return sum(r.alive for r in self.replicas)

    def step(self) -> List[RequestResult]:
        """One fleet scheduling iteration: fire fleet faults -> step
        every live replica (timing each step for the wedge probe) ->
        surface finishes -> re-route wedged replicas' work -> drain the
        requeue/retry ladder -> refresh per-replica gauges."""
        step_idx = self.n_steps
        self.n_steps += 1
        t0_us = self.tel.now_us() if self.tel.enabled else 0.0
        wedge_delay: Dict[int, float] = {}

        flt = fleet_step_fault(step_idx)
        if flt is not None:
            if flt.kind == KIND_REPLICA_KILL:
                self._kill(int(flt.arg), step_idx)
            elif flt.kind == KIND_REPLICA_WEDGE:
                wedge_delay[int(flt.arg2)] = float(flt.arg)

        out: List[RequestResult] = []
        if self._router_finished:      # router-side terminals (kill
            out.extend(self._router_finished)   # paths, cancels) surface
            self._router_finished = []          # with this step's batch
        now = self.clock()
        for rep in self.replicas:
            if not rep.alive:
                continue
            t_wall = time.perf_counter()
            delay = wedge_delay.get(rep.idx, 0.0)
            if delay:
                # the injected wedge: the replica's step stalls, inside
                # the router's measurement — indistinguishable from a
                # wedged device or a partition to that replica
                time.sleep(delay)
            finished = rep.engine.step()
            rep.last_step_s = time.perf_counter() - t_wall
            rep.steps += 1
            self._probe(rep, step_idx)
            for res in finished:
                done = self._on_finish(res, rep.idx, now)
                if done is not None:
                    out.append(done)

        self._observe_ttft(now)
        self._drain_requeue(step_idx)
        if self._router_finished:   # terminals recorded DURING this
            out.extend(self._router_finished)   # step (retry exhaustion)
            self._router_finished = []          # surface with its batch
        self._gauges()
        if self.tel.enabled:
            self.tel.complete("router_step", ROUTER_TRACK, t0_us,
                              self.tel.now_us() - t0_us, step=step_idx,
                              n_finished=len(out),
                              n_alive=self.n_alive)
        return out

    def drain(self, max_steps: int = 1_000_000) -> List[RequestResult]:
        out: List[RequestResult] = []
        for _ in range(max_steps):
            if self.idle:
                return out
            out.extend(self.step())
        raise RuntimeError(f"fleet did not drain in {max_steps} steps")

    def take_new_tokens(self, request_id: str) -> List[int]:
        """Consume the tokens newly available for ``request_id`` since
        the last call — the ONE delivery path (SSE streaming and the
        fleet replay both read through here). Exactly-once across
        migration: a requeued request regenerates deterministically
        from token 0, and this ledger suppresses the prefix already
        delivered, so the concatenated stream equals the uninterrupted
        token list."""
        sent = self._delivered.get(request_id, 0)
        res = self.results.get(request_id)
        if res is not None:
            new = res.tokens[sent:]
        else:
            fi = self._inflight.get(request_id)
            if fi is None:
                return []
            partial = (self.replicas[fi.replica].engine
                       .partial_tokens(request_id)) or []
            new = partial[sent:]
        if new:
            self._delivered[request_id] = sent + len(new)
        return new

    def result(self, request_id: str) -> Optional[RequestResult]:
        return self.results.get(request_id)

    def knows(self, request_id: str) -> bool:
        """Whether the id is anywhere in the fleet: in flight, between
        replicas awaiting resubmission, or terminal-but-unclaimed."""
        return (request_id in self._inflight
                or request_id in self.results
                or any(q.req.id == request_id for q in self._requeue))

    def pop_result(self, request_id: str) -> Optional[RequestResult]:
        """Take a terminal result out of the router's memory (the HTTP
        layer calls this once a stream fully delivered — a long-lived
        front door must not grow its results map without bound)."""
        self._delivered.pop(request_id, None)
        self._ttft.pop(request_id, None)
        return self.results.pop(request_id, None)

    def close(self) -> None:
        for rep in self.replicas:
            if rep.journal is not None:
                rep.journal.close()

    # ------------------------------------------------------------ summary

    def fleet_summary(self) -> dict:
        """Fleet-level health/metrics block: router counters, fleet
        TTFT, per-replica occupancy + pages, aggregate prefix-hit rate
        (the affinity claim is about the FLEET's aggregate)."""
        c = self.metrics.counters
        hit_tokens = prompt_tokens = 0
        per_replica = []
        for rep in self.replicas:
            a = rep.engine.pool.alloc
            hit_tokens += a.prefix_hit_tokens
            prompt_tokens += a.prompt_tokens
            s = rep.engine.metrics_summary()
            per_replica.append({
                "health": rep.health(),
                "occupancy_mean": round(
                    s["histograms"].get("batch_fill_ratio", {})
                    .get("mean", 0.0), 4),
                "n_steps": rep.engine.n_steps,
                "pages": s["pages"],
                "finished": {k: int(v) for k, v in
                             rep.engine.metrics.counters.items()
                             if k.startswith("finished_")},
            })
        return {
            "n_replicas": len(self.replicas),
            "n_alive": self.n_alive,
            "n_steps": self.n_steps,
            "router": {k: int(v) for k, v in sorted(c.items())},
            "fleet_ttft_s": self.metrics.hist_summary("fleet_ttft_s"),
            "aggregate_prefix_hit_rate": (
                round(hit_tokens / prompt_tokens, 4)
                if prompt_tokens else 0.0),
            "replicas": per_replica,
            "events": list(self.events[-32:]),
        }

    def healthz(self) -> dict:
        """The /healthz body: ok iff at least one replica is routable."""
        return {"ok": any(r.routable for r in self.replicas),
                "n_alive": self.n_alive,
                "replicas": [r.health() for r in self.replicas]}

    # ----------------------------------------------------------- internals

    def _event(self, msg: str) -> None:
        self.events.append(msg)
        if len(self.events) > 256:
            del self.events[:len(self.events) - 256]

    def _candidates(self, req: Request) -> List[int]:
        """Replica indices to try, best first: longest cached prefix,
        then least load, then index (stable)."""
        avail = [r for r in self.replicas if r.routable]
        if not avail:
            # a fully wedged fleet still beats dropping the request on
            # the floor: route to a wedged-but-alive replica
            avail = [r for r in self.replicas if r.alive]
        if not avail:
            return []

        def key(rep: Replica):
            aff = (rep.engine.pool.cached_prefix_tokens(req.prompt)
                   if self.rcfg.affinity else 0)
            return (-aff, rep.load, rep.idx)

        return [r.idx for r in sorted(avail, key=key)]

    def _submit_routed(self, req: Request, t_submit: float,
                       attempts: int) -> Optional[RequestResult]:
        """Try every candidate replica once, in affinity/load order;
        returns None on acceptance or the LAST rejection."""
        last: Optional[RequestResult] = None
        for idx in self._candidates(req):
            rep = self.replicas[idx]
            rej = rep.engine.submit(req)
            if rej is None:
                self._inflight[req.id] = _InFlight(
                    req=req, replica=idx, t_submit=t_submit,
                    attempts=attempts)
                self.metrics.inc("fleet_requests_routed")
                if self.tel.enabled:
                    self.tel.instant(
                        "route", ROUTER_TRACK, request=req.id,
                        replica=idx, attempt=attempts,
                        affinity_tokens=int(
                            rep.engine.pool.cached_prefix_tokens(
                                req.prompt)))
                return None
            last = rej
            if rej.finish_reason in TERMINAL_REJECTS:
                # a deterministic verdict (validation, prompt too long,
                # dead-on-arrival deadline) — another replica would say
                # the same thing
                break
            self.metrics.inc("fleet_route_fallbacks")
        if last is None:       # no replicas at all
            last = RequestResult(id=req.id, tokens=[],
                                 finish_reason=REJECT_FLEET_CAPACITY)
        return last

    def _on_finish(self, res: RequestResult, replica: int,
                   now: float) -> Optional[RequestResult]:
        if self._superseded.get(res.id) == replica:
            # the hedged re-route cancelled this copy ON THIS replica;
            # the live copy is elsewhere — swallow it (keyed by replica
            # so the live copy's own finish is never mistaken for it)
            del self._superseded[res.id]
            return None
        fi = self._inflight.pop(res.id, None)
        if fi is not None:
            res.total_s = now - fi.t_submit
            if res.id in self._ttft:
                res.ttft_s = self._ttft[res.id]
            elif res.tokens:
                # finished in the same step its first token committed:
                # _observe_ttft runs after the per-replica loop and only
                # sees ids still in flight, so the FASTEST requests would
                # never enter the fleet_ttft_s histogram (biasing the
                # bench p50/p99 upward) — observe them here
                res.ttft_s = now - fi.t_submit
                self._ttft[res.id] = res.ttft_s
                self.metrics.observe("fleet_ttft_s", res.ttft_s)
        self.metrics.inc("fleet_requests_finished")
        self.results[res.id] = res
        return res

    def _record_result(self, res: RequestResult, t_submit: float,
                       envelope: bool = True) -> None:
        """Terminal result produced by the ROUTER (requeue-retry
        exhaustion, cancel-between-replicas, journaled-finish on a dead
        replica) — when no engine closed this request's envelope, the
        router emits the one terminal close itself, as a zero-length
        envelope on the router track: every request id still forms
        exactly one complete span tree (tools/trace_check.py), even
        when its engine segments all ended ``migrated``.
        ``envelope=False`` is the journaled-finish path: the engine
        closed the terminal envelope when it journaled the finish (the
        two happen together in ``_finish_slot``) — a second close here
        would violate the exactly-one-terminal invariant."""
        now = self.clock()
        res.total_s = now - t_submit
        if self.tel.enabled and envelope:
            ts = self.tel.ts_us(now)
            self.tel.begin("request", ROUTER_TRACK, ts_us=ts,
                           request=res.id)
            self.tel.end("request", ROUTER_TRACK, ts_us=ts,
                         request=res.id, reason=res.finish_reason,
                         n_tokens=len(res.tokens))
        self.metrics.inc("fleet_requests_finished")
        self.results[res.id] = res
        self._router_finished.append(res)

    def _observe_ttft(self, now: float) -> None:
        """Fleet TTFT: first token OBSERVABLE at the router for each
        in-flight id (tokens delivered before a migration count — the
        client had them)."""
        for rid, fi in self._inflight.items():
            if rid in self._ttft or self._delivered.get(rid, 0):
                continue
            partial = (self.replicas[fi.replica].engine
                       .partial_tokens(rid))
            if partial:
                self._ttft[rid] = now - fi.t_submit
                self.metrics.observe("fleet_ttft_s", now - fi.t_submit)

    def _probe(self, rep: Replica, step_idx: int) -> None:
        """Wedge detection over per-step wall time + quarantine expiry."""
        cfg = self.rcfg
        if rep.wedged and step_idx >= rep.quarantine_until:
            rep.wedged = False
            rep.suspect_streak = 0
            self.metrics.inc("fleet_replica_rejoins")
            self._event(f"step {step_idx}: replica {rep.idx} rejoined")
            self.tel.instant("replica_rejoin", ROUTER_TRACK,
                             replica=rep.idx)
        if cfg.wedge_budget_s <= 0 or rep.wedged:
            return
        if rep.skip_steps > 0:        # warmup compiles are not wedges
            rep.skip_steps -= 1
            return
        if rep.last_step_s > cfg.wedge_budget_s:
            rep.suspect_streak += 1
        else:
            rep.suspect_streak = 0
        if rep.suspect_streak >= cfg.wedge_patience:
            self._wedge(rep, step_idx)

    def _wedge(self, rep: Replica, step_idx: int) -> None:
        """Quarantine a wedged replica and hedge its in-flight work onto
        healthy replicas (cancel-with-migrated on the suspect first, so
        no id is ever live on two replicas — double-decode is
        structurally impossible)."""
        rep.wedged = True
        rep.suspect_streak = 0
        rep.quarantine_until = step_idx + self.rcfg.quarantine_steps
        self.metrics.inc("fleet_replica_wedges")
        self._event(f"step {step_idx}: replica {rep.idx} wedged "
                    f"({rep.last_step_s * 1e3:.1f} ms step over "
                    f"{self.rcfg.wedge_budget_s * 1e3:.1f} ms budget); "
                    f"re-routing its in-flight work")
        self.tel.instant("replica_wedge", ROUTER_TRACK, replica=rep.idx,
                         step_ms=rep.last_step_s * 1e3)
        n = 0
        for rid in rep.engine.in_flight_ids():
            fi = self._inflight.pop(rid, None)
            if fi is None:
                continue
            rep.engine.cancel(rid, migrated=True)
            self._superseded[rid] = rep.idx
            self._requeue.append(_Requeue(
                req=fi.req, t_submit=fi.t_submit,
                attempts=fi.attempts, due_step=step_idx))
            n += 1
        if n:
            self.metrics.inc("fleet_requeued_requests", n)
            self.tel.instant("requeue", ROUTER_TRACK, replica=rep.idx,
                             n=n, cause="wedge")

    def _kill(self, idx: int, step_idx: int) -> None:
        """Abandon a replica (the in-process stand-in for a process
        death): close its telemetry envelopes as migrated segments,
        replay its crash journal, requeue the unfinished."""
        if not (0 <= idx < len(self.replicas)):
            return
        rep = self.replicas[idx]
        if not rep.alive:
            return
        rep.alive = False
        rep.wedged = False
        self.metrics.inc("fleet_replica_kills")
        self._event(f"step {step_idx}: replica {idx} KILLED; replaying "
                    f"its journal")
        self.tel.instant("replica_kill", ROUTER_TRACK, replica=idx)
        now = self.clock()
        # close open request envelopes on the dead replica's slot
        # tracks: the router observed the death — the segments are
        # non-terminal (migrated), the real tree completes elsewhere
        if self.tel.enabled:
            for rid, fi in self._inflight.items():
                if fi.replica != idx:
                    continue
                slot = rep.engine.pool.slot_of(rid)
                if slot is None:
                    continue
                partial = rep.engine.partial_tokens(rid) or []
                self.tel.end("request", rep.engine.slot_track(slot),
                             ts_us=self.tel.ts_us(now), request=rid,
                             reason="replica_dead", migrated=True,
                             n_tokens=len(partial))
        if rep.journal is not None:
            rep.journal.close()
        pending: List[Request] = []
        finished_reasons: Dict[str, str] = {}
        if rep.journal_path is not None:
            pending = RequestJournal.unfinished(rep.journal_path,
                                                telemetry=self.tel)
            finished_reasons = {
                r["id"]: r.get("reason", "")
                for r in load_jsonl_if_exists(rep.journal_path)
                if r.get("ev") == "finish"}
        # the router's in-memory ledger is authoritative for THIS run:
        # only replay journal entries for ids the router has in flight
        # ON THE DEAD REPLICA. Anything else is a ghost — a stale
        # record from a previous run sharing this journal dir, or an id
        # that migrated away earlier (its finish landed in the
        # survivor's journal, not here). Resurrecting a ghost whose id
        # collides with a live request would double-decode it.
        live = []
        for p in pending:
            fi = self._inflight.get(p.id)
            if fi is not None and fi.replica == idx:
                live.append(p)
        pending = live
        pending_ids = {r.id for r in pending}
        for p in pending:
            fi = self._inflight.pop(p.id)
            self._requeue.append(_Requeue(
                req=p, t_submit=fi.t_submit, attempts=fi.attempts,
                due_step=step_idx))
        if pending:
            self.metrics.inc("fleet_requeued_requests", len(pending))
            self.tel.instant("requeue", ROUTER_TRACK, replica=idx,
                             n=len(pending), cause="kill")
        # in-flight ids the journal says finished but whose terminal
        # result died undelivered with the replica: surface the
        # journaled reason (the tokens are lost with the process — an
        # honest crash semantics, pinned in tests)
        for rid in [r for r, fi in list(self._inflight.items())
                    if fi.replica == idx and r not in pending_ids]:
            fi = self._inflight.pop(rid)
            # a journaled finish means the engine already emitted the
            # terminal envelope close (or the request_unstarted
            # instant) — the router must not close it a second time
            self._record_result(RequestResult(
                id=rid, tokens=[],
                finish_reason=finished_reasons.get(rid, "cancelled")),
                fi.t_submit, envelope=rid not in finished_reasons)

    def _drain_requeue(self, step_idx: int) -> None:
        """Bounded retry with exponential backoff for requests between
        replicas (requeued after a kill/wedge, or bounced by
        backpressure). Terminal results (retry exhaustion) go through
        :meth:`_record_result` onto the ``_router_finished`` ledger —
        the caller drains it into this step's return."""
        still: List[_Requeue] = []
        for item in self._requeue:
            if item.due_step > step_idx:
                still.append(item)
                continue
            rej = self._submit_routed(item.req, item.t_submit,
                                      attempts=item.attempts)
            if rej is None:
                self.metrics.inc("fleet_requeue_submits")
                continue
            item.attempts += 1
            if (item.attempts > self.rcfg.retry_max
                    or rej.finish_reason in TERMINAL_REJECTS):
                reason = (REJECT_FLEET_CAPACITY
                          if rej.finish_reason == REJECT_QUEUE_FULL
                          else rej.finish_reason)
                self._record_result(RequestResult(
                    id=item.req.id, tokens=[], finish_reason=reason),
                    item.t_submit)
                self.metrics.inc("fleet_requeue_exhausted")
                continue
            item.due_step = step_idx + (self.rcfg.retry_backoff_steps
                                        * (2 ** (item.attempts - 1)))
            self.metrics.inc("fleet_requeue_retries")
            still.append(item)
        self._requeue = still

    def _gauges(self) -> None:
        for rep in self.replicas:
            i = rep.idx
            self.metrics.gauge(f"replica{i}_alive", int(rep.alive))
            self.metrics.gauge(f"replica{i}_wedged", int(rep.wedged))
            self.metrics.gauge(f"replica{i}_queue_depth",
                               rep.engine.scheduler.depth
                               if rep.alive else 0)
            self.metrics.gauge(f"replica{i}_slots_active",
                               int(rep.engine._active.sum())
                               if rep.alive else 0)
            self.metrics.gauge(f"replica{i}_pages_in_use",
                               rep.engine.pool.alloc.pages_in_use
                               if rep.alive else 0)
        self.metrics.gauge("fleet_requeue_depth", len(self._requeue))
        self.metrics.gauge("fleet_inflight", len(self._inflight))
