"""Pooled KV cache: a fixed set of decode slots allocated once at engine
start.

The offline path allocates a fresh KV cache per ``generate`` call; a
serving engine cannot — allocation is a compile-shape change and a
latency spike. Here the pool is ONE stacked cache buffer
(``models.gpt.init_kv_cache`` with batch = n_slots, either layout) whose
batch axis is the slot axis, living on device for the engine's entire
lifetime. Slot assignment/free is host-side bookkeeping: a free-list
(the per-slot position counters live in the engine's step arrays,
which feed the jitted decode directly); the device buffer itself is
never resized or re-zeroed (stale K/V in a freed slot is harmless —
the next occupant's prefill/decode overwrites every position before
attending it, the same invariant ``sample.generate`` relies on for
padded prompts).
"""

from __future__ import annotations

from typing import Dict, List, Optional

import jax.numpy as jnp
import numpy as np

from ..config import ModelConfig
from ..models.gpt import cache_seq_axis, init_kv_cache


def prefill_chunk_size(requested: int, block_size: int) -> int:
    """Effective prefill chunk: the requested (or 0 = auto
    min(64, block_size)) size rounded DOWN to a divisor of block_size.
    Divisibility is a correctness requirement, not a preference: the
    final chunk of a P-token prompt is dispatched at offset
    (ceil(P/c)-1)*c and padded to c, so a non-divisor c could push the
    padded chunk past the cache buffer — and
    jax.lax.dynamic_update_slice silently CLAMPS out-of-bounds starts,
    which would overwrite valid earlier K/V instead of erroring. With
    c | block_size, ceil(P/c)*c <= block_size for every admissible P.
    One definition on purpose: the engine's prefill (EngineConfig.chunk)
    and the model drafter's (serve/speculative.py) must agree on this
    rule or drift apart silently."""
    c = min(requested or min(64, block_size), block_size)
    while block_size % c:
        c -= 1
    return c


def commit_default(x, sharding=None):
    """device_put onto an EXPLICIT placement (the configured default
    device, or ``sharding`` — a NamedSharding over the serving mesh) —
    plain device_put without a device keeps the array *uncommitted*,
    and the engine's jit cache keys on committed-ness: engine-owned
    state must enter the first call exactly as it leaves every step (a
    committed jit output), or warmup compiles one throwaway executable
    per program (observed with checkpoint-restored, i.e. committed,
    params). The sharded engine passes its mesh placement here for the
    same reason: state must enter each window exactly as the previous
    window's constrained outputs left it."""
    import jax
    if sharding is not None:
        return jax.device_put(x, sharding)
    dev = jax.config.jax_default_device or jax.local_devices()[0]
    return jax.device_put(x, dev)


class CachePool:
    """Fixed-size slot pool over one pre-allocated multi-slot KV cache."""

    def __init__(self, cfg: ModelConfig, n_slots: int,
                 max_len: Optional[int] = None, dtype=None):
        assert n_slots >= 1, n_slots
        self.cfg = cfg
        self.n_slots = n_slots
        self.max_len = max_len or cfg.block_size
        # committed up front — see commit_default
        self.cache: Dict[str, jnp.ndarray] = commit_default(init_kv_cache(
            cfg, n_slots, max_len=self.max_len, dtype=dtype))
        self._free: List[int] = list(range(n_slots - 1, -1, -1))
        self._owner: Dict[int, str] = {}        # slot -> request id
        self._slot_by_request: Dict[str, int] = {}  # reverse index: the
        # engine resolves request id -> slot on EVERY finish/cancel, and
        # the old linear scan made that O(n_slots) per call
        # host-side per-slot positions, updated by the engine in place
        # (its step arrays alias this buffer). Living on the pool makes
        # the committed frontier readable by a drafter
        # (serve/speculative.py) without any per-slot device sync — the
        # generated suffix itself is host bookkeeping in the engine.
        self.positions = np.zeros((n_slots,), np.int32)

    @property
    def seq_len(self) -> int:
        return self.cache["k"].shape[cache_seq_axis(self.cfg)]

    @property
    def n_free(self) -> int:
        return len(self._free)

    @property
    def n_used(self) -> int:
        return self.n_slots - len(self._free)

    @property
    def occupancy(self) -> float:
        return self.n_used / self.n_slots

    def acquire(self, request_id: str,
                position: int = 0) -> Optional[int]:
        """Assign a free slot to ``request_id`` starting at ``position``
        (the last prompt index — decode rewrites it first); None when
        the pool is exhausted (the scheduler then leaves the request
        queued)."""
        if not self._free:
            return None
        slot = self._free.pop()
        self._owner[slot] = request_id
        self._slot_by_request[request_id] = slot
        self.positions[slot] = position
        return slot

    def release(self, slot: int) -> None:
        owner = self._owner.pop(slot, None)
        assert owner is not None, f"slot {slot} double-free"
        # conditional: never KeyError another slot's mapping if a caller
        # slipped duplicate request ids past its own validation
        if self._slot_by_request.get(owner) == slot:
            del self._slot_by_request[owner]
        self._free.append(slot)

    def owner(self, slot: int) -> Optional[str]:
        return self._owner.get(slot)

    def slot_of(self, request_id: str) -> Optional[int]:
        return self._slot_by_request.get(request_id)
