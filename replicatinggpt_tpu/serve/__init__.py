"""Continuous-batching serving subsystem.

- ``pages``: paged KV pool + radix prefix cache — refcounted page
  allocator, per-slot page tables (host-mirrored, device-fed), LRU
  eviction of cached prefixes, copy-on-write splits of shared pages;
- ``cache_pool``: the original fixed-slot contiguous pool (kept for
  offline callers; per-slot position counters live here either way);
- ``scheduler``: bounded admission queue with backpressure and deadline
  dropping;
- ``engine``: the per-step loop — admit (chunked prefill into the
  slot's cache region) + ONE jitted multi-slot decode with per-slot
  positions/mask/RNG/sampling params;
- ``speculative``: drafters (host-side n-gram prompt lookup, or a
  second small model with its own pooled cache) + the exact
  point-mass rejection-sampling acceptance behind the engine's jitted
  multi-slot verify step — up to k+1 tokens per slot per full-model
  forward;
- ``journal``: append-only submit/finish request journal — restart
  recovery requeues accepted-but-unfinished requests into a fresh
  engine (docs/robustness.md);
- ``replay``: synthetic Poisson trace driver (`serve-replay` CLI,
  `bench.py --mode serve`);
- ``router``: the fleet tier — N engine replicas behind one
  submit/cancel/step API with radix-prefix affinity routing, health
  probes, crash-journal requeue across replica death, and hedged
  re-route off wedged replicas (docs/serving.md). Replicas are either
  in-process engines (``Replica``) or worker PROCESSES
  (``RemoteReplica`` over the ``rpc`` protocol);
- ``rpc``: length-prefixed, CRC32-checksummed JSON RPC over sockets —
  the wire between the router and worker processes (register/submit/
  step/stream-drain/journal-drain/cancel/drain/health verbs, ack-based
  finish redelivery, per-call idempotency keys on mutating verbs
  answered from a bounded reply cache, generation fencing, protocol-
  version + engine-shape-hash handshake with typed
  ``RpcProtocolError`` rejection, and the poll-driven ``RpcListener``
  registration endpoint; chaos coverage in ``faults/netchaos.py``);
- ``disagg``: disaggregated prefill/decode tiers — page
  sources/sinks (in-process and RPC), the chunked ``TransferJob``
  that ships a prefilled request's KV pages (storage-dtype bytes +
  quant scales, no dequant) from a prefill worker to a decode
  worker's pool via a warmed jitted install, and the router policy
  that diverts long-tail prompts to the prefill tier
  (docs/serving.md#disaggregation);
- ``worker``: the worker process (`serve-worker` CLI) — one engine +
  an exclusively-locked PRIVATE crash journal, replayed at startup
  and streamed to the router over RPC, so a ``kill -9`` mid-decode
  costs nothing the journal + the router's delivery ledger cannot
  reconstruct — and a lost HOST (journal gone too) costs nothing the
  router's own ledger cannot (faults/procsup.py supervises restarts
  and autoscaling);
- ``loadgen``: multi-turn session load generator + fleet replay driver
  (`bench.py --mode fleet`, the fleet chaos soak);
- ``http``: the asyncio HTTP/SSE front door (`serve` CLI) —
  submit/stream/cancel/healthz/metrics over the router.

Self-healing (step watchdog, speculative auto-disable, load shedding)
is opt-in via ``faults.watchdog.ResilienceConfig`` on the Engine;
fleet-level faults (replica kill/wedge, hot-key skew) live behind
``faults.fleet``.
"""

from .cache_pool import CachePool
from .disagg import (LocalPageSink, LocalPageSource, RpcPageSink,
                     RpcPageSource, TransferJob, TransferResult,
                     transfer_prefix)
from .engine import Engine, EngineConfig, compile_counts
from .journal import JournalBusyError, RequestJournal
from .loadgen import (SessionLoadConfig, StepClock, make_sessions,
                      run_fleet_replay, session_request)
from .pages import PageAllocator, PagedCachePool, RadixIndex
from .replay import ReplayConfig, format_summary, make_trace, run_replay
from .requests import Request, RequestResult, SamplingParams
from .router import (REJECT_FLEET_CAPACITY, RemoteReplica, Replica,
                     ReplicaBase, Router, RouterConfig)
from .rpc import REJECT_REPLICA_DOWN, RpcClient, RpcDown, RpcTimeout
from .scheduler import Scheduler
from .speculative import (Drafter, ModelDrafter, NGramDrafter,
                          draft_config_from_preset, make_drafter)

__all__ = ["CachePool", "Engine", "EngineConfig", "compile_counts",
           "PageAllocator", "PagedCachePool", "RadixIndex",
           "JournalBusyError", "RequestJournal",
           "ReplayConfig", "format_summary", "make_trace", "run_replay",
           "Request", "RequestResult", "SamplingParams", "Scheduler",
           "Drafter", "ModelDrafter", "NGramDrafter",
           "draft_config_from_preset", "make_drafter",
           "REJECT_FLEET_CAPACITY", "REJECT_REPLICA_DOWN",
           "RemoteReplica", "Replica", "ReplicaBase", "Router",
           "RouterConfig", "RpcClient", "RpcDown", "RpcTimeout",
           "SessionLoadConfig", "StepClock", "make_sessions",
           "run_fleet_replay", "session_request",
           "LocalPageSink", "LocalPageSource", "RpcPageSink",
           "RpcPageSource", "TransferJob", "TransferResult",
           "transfer_prefix"]
