"""Continuous-batching serving subsystem.

- ``cache_pool``: fixed slot pool over one pre-allocated multi-slot KV
  cache (slot assignment/free + per-slot position counters);
- ``scheduler``: bounded admission queue with backpressure and deadline
  dropping;
- ``engine``: the per-step loop — admit (chunked prefill into the
  slot's cache region) + ONE jitted multi-slot decode with per-slot
  positions/mask/RNG/sampling params;
- ``replay``: synthetic Poisson trace driver (`serve-replay` CLI,
  `bench.py --mode serve`).
"""

from .cache_pool import CachePool
from .engine import Engine, EngineConfig, compile_counts
from .replay import ReplayConfig, format_summary, make_trace, run_replay
from .requests import Request, RequestResult, SamplingParams
from .scheduler import Scheduler

__all__ = ["CachePool", "Engine", "EngineConfig", "compile_counts",
           "ReplayConfig", "format_summary", "make_trace", "run_replay",
           "Request", "RequestResult", "SamplingParams", "Scheduler"]
