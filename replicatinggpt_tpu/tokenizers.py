"""Tokenizers: char-level, self-contained byte-level BPE, optional tiktoken.

Capability parity with the reference's tokenizer mux (GPT1.py:25-70):

- ``'base'`` char branch (GPT1.py:54-66)  -> :class:`CharTokenizer`
- ``'tiktoken'`` branch (GPT1.py:29-36)   -> :class:`TiktokenTokenizer`
  (optional: tiktoken fetches its BPE ranks over the network on first use,
  which is unavailable in air-gapped environments — so the framework also
  ships its own trainable byte-level BPE, :class:`ByteBPETokenizer`, giving
  the BPE capability with zero downloads)
- the broken ``'nltk'`` branch (GPT1.py:38-52, SURVEY.md §8-B2) is dropped
  deliberately.

All tokenizers expose the same interface the reference's encode/decode
closures had (GPT1.py:63-64): ``encode(str) -> list[int]``,
``decode(ids) -> str``, plus ``vocab_size`` and JSON save/load.
"""

from __future__ import annotations

import json
import os
from collections import Counter
from typing import Dict, List, Optional, Sequence, Tuple

# GPT-2-style pre-tokenization pattern (public regex from the GPT-2 release;
# splits into contractions / letter runs / digit runs / symbol runs / spaces).
_PRETOKEN_PAT = (
    r"""'s|'t|'re|'ve|'m|'ll|'d| ?\p{L}+| ?\p{N}+| ?[^\s\p{L}\p{N}]+|\s+(?!\S)|\s+"""
)


def _bytes_to_unicode() -> Dict[int, str]:
    """Reversible byte <-> printable-unicode map (GPT-2's byte-level trick).

    Maps every possible byte to a unicode character that is printable and
    never a space, so BPE merges can be stored as plain strings.
    """
    bs = (list(range(ord("!"), ord("~") + 1))
          + list(range(ord("¡"), ord("¬") + 1))
          + list(range(ord("®"), ord("ÿ") + 1)))
    cs = bs[:]
    n = 0
    for b in range(256):
        if b not in bs:
            bs.append(b)
            cs.append(256 + n)
            n += 1
    return dict(zip(bs, [chr(c) for c in cs]))


_BYTE_ENCODER = _bytes_to_unicode()
_BYTE_DECODER = {v: k for k, v in _BYTE_ENCODER.items()}


class CharTokenizer:
    """Character-level tokenizer (GPT1.py:54-66 'base' branch).

    Vocabulary is the sorted set of characters of the corpus (65 for Tiny
    Shakespeare, verified in SURVEY.md §2.0).
    """

    kind = "char"

    def __init__(self, chars: Sequence[str]):
        self.chars = list(chars)
        self.stoi = {c: i for i, c in enumerate(self.chars)}
        self.itos = {i: c for i, c in enumerate(self.chars)}
        # byte->id LUT for the native fastpath; valid only for pure-ASCII
        # vocabularies (one utf-8 byte per char)
        self._lut = None
        if all(len(c) == 1 and ord(c) < 128 for c in self.chars):
            import numpy as np
            self._lut = np.full(256, -1, np.int32)
            for c, i in self.stoi.items():
                self._lut[ord(c)] = i

    @classmethod
    def from_text(cls, text: str) -> "CharTokenizer":
        return cls(sorted(set(text)))

    @property
    def vocab_size(self) -> int:
        return len(self.chars)

    def encode(self, s: str) -> List[int]:
        return [self.stoi[c] for c in s]

    def encode_np(self, s: str):
        """Corpus-scale encode via the native LUT kernel (identical ids)."""
        import numpy as np
        if self._lut is not None and len(s) > 4096:
            try:
                from .native import encode_lut
                return encode_lut(s.encode("utf-8"), self._lut)
            except ValueError:
                pass  # bytes outside alphabet: fall through for the KeyError
        return np.asarray(self.encode(s), np.int32)

    def decode(self, ids: Sequence[int]) -> str:
        return "".join(self.itos[int(i)] for i in ids)

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump({"kind": self.kind, "chars": self.chars}, f)

    @classmethod
    def load(cls, path: str) -> "CharTokenizer":
        with open(path) as f:
            d = json.load(f)
        return cls(d["chars"])


class ByteBPETokenizer:
    """Self-contained byte-level BPE: trainable, saveable, download-free.

    Gives the framework the BPE capability of the reference's tiktoken branch
    (GPT1.py:29-36, GPT-2.py:192-196) without network access. Standard GPT-2
    construction: GPT-2 pre-tokenizer regex, byte-to-unicode base alphabet of
    256 symbols, then learned merges ranked by training order.
    """

    kind = "bpe"

    def __init__(self, merges: List[Tuple[str, str]],
                 vocab: Optional[List[str]] = None):
        import regex
        self._pat = regex.compile(_PRETOKEN_PAT)
        self.merges = [tuple(m) for m in merges]
        self.ranks = {m: i for i, m in enumerate(self.merges)}
        if vocab is None:
            base = [(_BYTE_ENCODER[b]) for b in range(256)]
            vocab = base + ["".join(m) for m in self.merges]
        self.vocab = vocab
        self.token_to_id = {t: i for i, t in enumerate(vocab)}
        self.id_to_token = {i: t for i, t in enumerate(vocab)}
        self._cache: Dict[str, List[int]] = {}
        self._ntable = False  # built lazily; None = native unusable

    # --- training ----------------------------------------------------------

    @classmethod
    def train(cls, text: str, vocab_size: int = 1024) -> "ByteBPETokenizer":
        """Learn merges on ``text`` until the vocab reaches ``vocab_size``.

        Counting is done on deduplicated pre-token "words" weighted by
        frequency, so training on megabyte-scale corpora is fast in pure
        Python.
        """
        import regex
        assert vocab_size > 256, "byte alphabet alone is 256 symbols"
        pat = regex.compile(_PRETOKEN_PAT)
        words = Counter()
        for w in pat.findall(text):
            units = tuple(_BYTE_ENCODER[b] for b in w.encode("utf-8"))
            words[units] += 1

        merges: List[Tuple[str, str]] = []
        words = dict(words)
        while 256 + len(merges) < vocab_size:
            pairs: Counter = Counter()
            for units, freq in words.items():
                for a, b in zip(units, units[1:]):
                    pairs[(a, b)] += freq
            if not pairs:
                break
            best = max(pairs, key=lambda p: (pairs[p], p))
            merges.append(best)
            merged = best[0] + best[1]
            new_words = {}
            for units, freq in words.items():
                out = []
                i = 0
                while i < len(units):
                    if (i + 1 < len(units)
                            and units[i] == best[0] and units[i + 1] == best[1]):
                        out.append(merged)
                        i += 2
                    else:
                        out.append(units[i])
                        i += 1
                new_words[tuple(out)] = new_words.get(tuple(out), 0) + freq
            words = new_words
        return cls(merges)

    # --- encode/decode -----------------------------------------------------

    def _bpe_word(self, word: str) -> List[int]:
        if word in self._cache:
            return self._cache[word]
        units = [_BYTE_ENCODER[b] for b in word.encode("utf-8")]
        while len(units) > 1:
            pairs = list(zip(units, units[1:]))
            ranked = [(self.ranks.get(p, 1 << 30), i) for i, p in enumerate(pairs)]
            rank, i = min(ranked)
            if rank >= (1 << 30):
                break
            units = units[:i] + [units[i] + units[i + 1]] + units[i + 2:]
        ids = [self.token_to_id[u] for u in units]
        self._cache[word] = ids
        return ids

    @property
    def vocab_size(self) -> int:
        return len(self.vocab)

    def encode(self, s: str) -> List[int]:
        out: List[int] = []
        for w in self._pat.findall(s):
            out.extend(self._bpe_word(w))
        return out

    def _native_merge_table(self):
        """Merge rules re-keyed into token-id space for the C++ kernel.

        Sound because id<->string is bijective over the ids the encoder can
        produce (token_to_id keeps the *last* id for duplicate merged
        strings — same dict semantics as ranks, tokenizers.py:111,116 — and
        base byte ids equal the raw byte value since base symbols are the
        only single-char vocab entries)."""
        if self._ntable is False:
            import numpy as np

            from .native import BpeMergeTable, available
            # the id-space kernel feeds raw utf-8 bytes as base token ids,
            # which is only sound when vocab slot b holds byte-symbol b for
            # all 256 base slots; a reordered/custom vocab (e.g. an edited
            # bpe_*.json) must fall back to the string-keyed Python path
            base_ok = all(
                self.token_to_id.get(_BYTE_ENCODER[b]) == b
                for b in range(256))
            if not available() or not base_ok:
                self._ntable = None
            else:
                pairs, rks, nids = [], [], []
                for (a, b), r in self.ranks.items():
                    merged = self.token_to_id.get(a + b)
                    ia, ib = self.token_to_id.get(a), self.token_to_id.get(b)
                    if merged is None or ia is None or ib is None:
                        continue  # unreachable rule (not in this vocab)
                    pairs.append((ia, ib))
                    rks.append(r)
                    nids.append(merged)
                self._ntable = BpeMergeTable(
                    np.asarray(pairs, np.int32).reshape(-1, 2),
                    np.asarray(rks, np.int32), np.asarray(nids, np.int32))
        return self._ntable

    def encode_np(self, s: str):
        """Corpus-scale encode via the native BPE kernel (identical ids)."""
        import numpy as np
        table = self._native_merge_table() if len(s) > 4096 else None
        if table is not None:
            from .native import bpe_encode_words
            bufs = [w.encode("utf-8") for w in self._pat.findall(s)]
            units = np.frombuffer(b"".join(bufs), np.uint8).astype(np.int32)
            off = np.zeros(len(bufs) + 1, np.int64)
            np.cumsum([len(b) for b in bufs], out=off[1:])
            out = bpe_encode_words(units, off, table)
            if out is not None:
                return out
        return np.asarray(self.encode(s), np.int32)

    def decode(self, ids: Sequence[int]) -> str:
        text = "".join(self.id_to_token[int(i)] for i in ids)
        data = bytes(_BYTE_DECODER[ch] for ch in text)
        return data.decode("utf-8", errors="replace")

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump({"kind": self.kind, "merges": self.merges,
                       "vocab": self.vocab}, f)

    @classmethod
    def load(cls, path: str) -> "ByteBPETokenizer":
        with open(path) as f:
            d = json.load(f)
        return cls([tuple(m) for m in d["merges"]], d["vocab"])


class TiktokenTokenizer:
    """Wrapper over tiktoken encodings (GPT1.py:29-36 used o200k_base;
    GPT-2.py:192 used gpt2). Requires tiktoken's BPE ranks to be cached
    locally or downloadable; raises a clear error otherwise."""

    kind = "tiktoken"

    def __init__(self, encoding_name: str = "gpt2"):
        import tiktoken
        try:
            self.enc = tiktoken.get_encoding(encoding_name)
        except Exception as e:  # network failure in air-gapped envs
            raise RuntimeError(
                f"tiktoken encoding {encoding_name!r} unavailable (needs "
                f"cached BPE ranks or network). Use tokenizer='bpe' for the "
                f"self-contained byte-level BPE instead. Original: {e}"
            ) from e
        self.encoding_name = encoding_name

    @property
    def vocab_size(self) -> int:
        # Correct per-encoding vocab (fixes SURVEY.md §8-B1, where the
        # reference hard-coded 50257 for o200k_base).
        return self.enc.n_vocab

    def encode(self, s: str) -> List[int]:
        return self.enc.encode(s)

    def decode(self, ids: Sequence[int]) -> str:
        return self.enc.decode(list(int(i) for i in ids))


def get_tokenizer(spec: str, corpus_text: Optional[str] = None,
                  cache_dir: str = "datasets"):
    """Resolve a tokenizer spec string.

    - ``'char'``            : char vocab built from ``corpus_text``
    - ``'bpe'``             : byte-level BPE trained on ``corpus_text``
                              (cached to ``cache_dir/bpe_<vocab>.json``)
    - ``'bpe:<path>'``      : load a saved ByteBPETokenizer
    - ``'tiktoken:<name>'`` : tiktoken encoding (gpt2, o200k_base, ...)
    """
    if spec == "char":
        assert corpus_text is not None, "char tokenizer needs corpus text"
        return CharTokenizer.from_text(corpus_text)
    if spec == "bpe" or spec.startswith("bpe:"):
        if ":" in spec:
            return ByteBPETokenizer.load(spec.split(":", 1)[1])
        assert corpus_text is not None, "training BPE needs corpus text"
        cache = os.path.join(cache_dir, "bpe_1024.json")
        if os.path.exists(cache):
            return ByteBPETokenizer.load(cache)
        tok = ByteBPETokenizer.train(corpus_text, vocab_size=1024)
        try:
            os.makedirs(cache_dir, exist_ok=True)
            tok.save(cache)
        except OSError:
            pass
        return tok
    if spec.startswith("tiktoken"):
        name = spec.split(":", 1)[1] if ":" in spec else "gpt2"
        return TiktokenTokenizer(name)
    raise ValueError(f"unknown tokenizer spec {spec!r}")
