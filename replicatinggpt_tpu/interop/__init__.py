from .hf import (GPT2_SIZES, import_hf_state_dict, model_config_from_hf,
                 config_for_model_type, from_pretrained)

__all__ = ["GPT2_SIZES", "import_hf_state_dict", "model_config_from_hf",
           "config_for_model_type", "from_pretrained"]
