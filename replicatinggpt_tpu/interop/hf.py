"""HuggingFace GPT-2 checkpoint import.

Capability parity with ``GPT.from_pretrained`` (GPT-2.py:132-177): the size
ladder gpt2/124M → gpt2-xl/1.5B (GPT-2.py:140-145), buffer filtering
(``.attn.bias``/``.attn.masked_bias``, GPT-2.py:153,159-160), and the Conv1D
weight handling (GPT-2.py:161-170).

Layout note: HF's Conv1D stores weights as (in_features, out_features); the
reference must transpose them into torch Linear's (out, in) layout. This
framework's kernels are (in, out) by convention (``x @ W``), so HF Conv1D
weights copy through **without** transposition — the reference's transpose
list is resolved by layout choice rather than per-tensor surgery. Per-layer
tensors are stacked along a leading (n_layer,) axis to match the lax.scan
parameter layout, and can be device_put with TP/FSDP shardings at load time.
"""

from __future__ import annotations

from typing import Any, Dict, Mapping, Tuple

import numpy as np

from ..config import ModelConfig

# model_type -> (n_layer, n_head, n_embd); GPT-2.py:140-145
GPT2_SIZES = {
    "gpt2":        (12, 12, 768),    # 124M
    "gpt2-medium": (24, 16, 1024),   # 350M
    "gpt2-large":  (36, 20, 1280),   # 774M
    "gpt2-xl":     (48, 25, 1600),   # 1558M
}


def config_for_model_type(model_type: str) -> ModelConfig:
    L, H, C = GPT2_SIZES[model_type]
    # vocab 50257, context 1024 forced for all sizes (GPT-2.py:146-147)
    return ModelConfig(vocab_size=50257, block_size=1024, n_layer=L,
                       n_head=H, n_embd=C, dropout=0.0, attn_dropout=0.0,
                       tied_head=True, activation="gelu")


def model_config_from_hf(hf_config: Any) -> ModelConfig:
    return ModelConfig(
        vocab_size=hf_config.vocab_size,
        block_size=hf_config.n_positions,
        n_layer=hf_config.n_layer, n_head=hf_config.n_head,
        n_embd=hf_config.n_embd, dropout=0.0, attn_dropout=0.0,
        tied_head=True, activation="gelu",
        layernorm_eps=float(getattr(hf_config, "layer_norm_epsilon", 1e-5)))


def import_hf_state_dict(sd: Mapping[str, Any], mcfg: ModelConfig,
                         dtype=np.float32) -> Dict[str, Any]:
    """Map a GPT2LMHeadModel state_dict onto this framework's param pytree.

    Accepts torch tensors or numpy arrays. Ignores the causal-mask buffers
    the reference filters (GPT-2.py:153,159-160) implicitly — only named
    weights are read.
    """
    def g(key: str) -> np.ndarray:
        t = sd[key]
        if hasattr(t, "detach"):
            t = t.detach().cpu().numpy()
        return np.asarray(t, dtype=dtype)

    L, C = mcfg.n_layer, mcfg.n_embd

    def stack(fmt: str) -> np.ndarray:
        return np.stack([g(fmt.format(i)) for i in range(L)])

    wte = g("transformer.wte.weight")
    assert wte.shape == (mcfg.vocab_size, C), (wte.shape, mcfg)
    wpe = g("transformer.wpe.weight")
    assert wpe.shape == (mcfg.block_size, C)

    blocks = {
        "ln1_scale": stack("transformer.h.{}.ln_1.weight"),
        "ln1_bias": stack("transformer.h.{}.ln_1.bias"),
        # Conv1D (in, out) == our kernel layout: no transpose
        "qkv_kernel": stack("transformer.h.{}.attn.c_attn.weight"),
        "qkv_bias": stack("transformer.h.{}.attn.c_attn.bias"),
        "attn_out_kernel": stack("transformer.h.{}.attn.c_proj.weight"),
        "attn_out_bias": stack("transformer.h.{}.attn.c_proj.bias"),
        "ln2_scale": stack("transformer.h.{}.ln_2.weight"),
        "ln2_bias": stack("transformer.h.{}.ln_2.bias"),
        "mlp_up_kernel": stack("transformer.h.{}.mlp.c_fc.weight"),
        "mlp_up_bias": stack("transformer.h.{}.mlp.c_fc.bias"),
        "mlp_down_kernel": stack("transformer.h.{}.mlp.c_proj.weight"),
        "mlp_down_bias": stack("transformer.h.{}.mlp.c_proj.bias"),
    }
    assert blocks["qkv_kernel"].shape == (L, C, 3 * C)
    assert blocks["mlp_up_kernel"].shape == (L, C, 4 * C)

    params: Dict[str, Any] = {
        "wte": wte, "wpe": wpe, "blocks": blocks,
        "ln_f_scale": g("transformer.ln_f.weight"),
        "ln_f_bias": g("transformer.ln_f.bias"),
    }
    if not mcfg.tied_head:
        # HF ties lm_head to wte; untied configs get an explicit copy
        params["lm_head"] = wte.T.copy()
    return params


def from_pretrained(model_type: str, mesh=None, mesh_cfg=None
                    ) -> Tuple[Dict[str, Any], ModelConfig]:
    """Download (or read from local HF cache) a pretrained GPT-2 and import
    it. With ``mesh``/``mesh_cfg``, arrays are device_put directly into
    their TP/FSDP shardings (no full replica per device)."""
    from transformers import GPT2LMHeadModel

    mcfg = config_for_model_type(model_type)
    hf = GPT2LMHeadModel.from_pretrained(model_type)
    params = import_hf_state_dict(hf.state_dict(), mcfg)
    if mesh is not None:
        import jax
        from jax.sharding import NamedSharding
        from ..parallel.mesh import state_pspecs
        specs = state_pspecs(params, mesh_cfg)
        params = jax.tree_util.tree_map(
            lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
            params, specs)
    return params, mcfg
