"""Committed lint baseline: pre-existing findings that must not block CI.

A new static analyzer over an existing ~8.6k-line package always finds
things; blocking every PR on a full cleanup guarantees the tool gets
turned off. Instead the accepted findings are frozen into
``graftlint_baseline.json`` and ``lint --baseline`` fails only on NEW
findings. Fixing a baselined finding then requires refreshing the file
(``lint --write-baseline``) — the tier-1 test asserts the committed
baseline matches a fresh whole-package run exactly, so it can go stale
in neither direction.

Baseline entries key on ``(path, rule, stripped source line)`` with
multiplicity — line numbers are recorded for humans but ignored for
matching, so findings survive unrelated edits that shift lines.
"""

from __future__ import annotations

import json
from collections import Counter
from dataclasses import dataclass
from pathlib import Path
from typing import Dict, List, Sequence, Tuple

from .linter import REPO_ROOT
from .rules import Finding

DEFAULT_BASELINE = REPO_ROOT / "graftlint_baseline.json"

Key = Tuple[str, str, str]


def finding_key(f: Finding) -> Key:
    return (f.path, f.rule, f.text)


def write_baseline(findings: Sequence[Finding], path: Path) -> None:
    entries = [{"path": f.path, "rule": f.rule, "line": f.line,
                "text": f.text}
               for f in sorted(findings,
                               key=lambda f: (f.path, f.line, f.rule))]
    Path(path).write_text(json.dumps(
        {"version": 1, "tool": "graftlint", "findings": entries},
        indent=1) + "\n")


def load_baseline(path: Path) -> Counter:
    data = json.loads(Path(path).read_text())
    return Counter((e["path"], e["rule"], e["text"])
                   for e in data.get("findings", []))


@dataclass
class BaselineDiff:
    new: List[Finding]        # findings not covered by the baseline
    matched: int              # findings absorbed by the baseline
    stale: List[Key]          # baseline entries with no current finding

    @property
    def clean(self) -> bool:
        return not self.new

    @property
    def exact(self) -> bool:
        """True when current findings == baseline exactly (the tier-1
        staleness assertion, stronger than `clean`)."""
        return not self.new and not self.stale


def diff_against_baseline(findings: Sequence[Finding],
                          baseline: Counter) -> BaselineDiff:
    budget: Dict[Key, int] = dict(baseline)
    new: List[Finding] = []
    matched = 0
    for f in findings:
        k = finding_key(f)
        if budget.get(k, 0) > 0:
            budget[k] -= 1
            matched += 1
        else:
            new.append(f)
    stale = sorted(k for k, n in budget.items() for _ in range(n))
    return BaselineDiff(new=new, matched=matched, stale=stale)
