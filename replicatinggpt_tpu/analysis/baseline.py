"""Committed lint baseline: pre-existing findings that must not block CI.

A new static analyzer over an existing ~10k-line package always finds
things; blocking every PR on a full cleanup guarantees the tool gets
turned off. Instead the accepted findings are frozen into
``graftlint_baseline.json`` and ``lint --baseline`` fails only on NEW
findings. Fixing a baselined finding then requires refreshing the file
(``lint --write-baseline``) — the tier-1 test asserts the committed
baseline matches a fresh whole-project run exactly, so it can go stale
in neither direction.

v2 semantics:

- Entries key on ``(path, rule, stripped source line)`` — line numbers
  are recorded for humans but ignored for matching, so findings survive
  unrelated edits that shift lines. Keys are a SET, not a multiset: one
  entry absorbs every finding with that key (two findings on one line
  produce one reviewable entry, the duplicate-entry bug the v1 writer
  had), and the writer dedupes + stably sorts so baseline diffs read as
  plain add/remove lines.
- The baseline is a **ratchet**: :func:`check_ratchet` refuses a
  refresh whose key set is not a subset of the committed one, so the
  suppressed-findings count can only go down. Growing the baseline is a
  reviewed, explicit act (``--allow-growth``), never a side effect of
  re-running the writer.
- Only error-severity findings participate; warning-tier directories
  (tests/) never enter the file.
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from pathlib import Path
from typing import Dict, List, Sequence, Set, Tuple

from .linter import REPO_ROOT
from .rules import Finding

DEFAULT_BASELINE = REPO_ROOT / "graftlint_baseline.json"

Key = Tuple[str, str, str]


def finding_key(f: Finding) -> Key:
    return (f.path, f.rule, f.text)


def write_baseline(findings: Sequence[Finding], path: Path) -> int:
    """Write the deduped, stably-sorted baseline; returns the entry
    count (== distinct keys, not raw findings)."""
    by_key: Dict[Key, Finding] = {}
    for f in findings:
        k = finding_key(f)
        if k not in by_key or f.line < by_key[k].line:
            by_key[k] = f
    entries = [{"path": f.path, "rule": f.rule, "line": f.line,
                "text": f.text}
               for f in sorted(by_key.values(),
                               key=lambda f: (f.path, f.line, f.rule,
                                              f.text))]
    Path(path).write_text(json.dumps(
        {"version": 2, "tool": "graftlint", "findings": entries},
        indent=1) + "\n")
    return len(entries)


def load_baseline(path: Path) -> Set[Key]:
    data = json.loads(Path(path).read_text())
    return {(e["path"], e["rule"], e["text"])
            for e in data.get("findings", [])}


@dataclass
class BaselineDiff:
    new: List[Finding]        # findings not covered by the baseline
    matched: int              # findings absorbed by the baseline
    stale: List[Key]          # baseline entries with no current finding

    @property
    def clean(self) -> bool:
        return not self.new

    @property
    def exact(self) -> bool:
        """True when current findings == baseline exactly (the tier-1
        staleness assertion, stronger than `clean`)."""
        return not self.new and not self.stale


def diff_against_baseline(findings: Sequence[Finding],
                          baseline: Set[Key]) -> BaselineDiff:
    new: List[Finding] = []
    matched = 0
    seen: Set[Key] = set()
    for f in findings:
        k = finding_key(f)
        if k in baseline:
            matched += 1
            seen.add(k)
        else:
            new.append(f)
    stale = sorted(baseline - seen)
    return BaselineDiff(new=new, matched=matched, stale=stale)


@dataclass
class RatchetViolation:
    """Keys a proposed refresh would ADD relative to the committed
    baseline — the thing ``--write-baseline`` refuses to do."""

    grown: List[Key]

    def format(self) -> str:
        lines = [f"  + {p}: {r}: {t}" for p, r, t in self.grown]
        return ("baseline ratchet: refusing to grow the baseline by "
                f"{len(self.grown)} entr"
                f"{'y' if len(self.grown) == 1 else 'ies'}:\n"
                + "\n".join(lines)
                + "\nfix the finding(s), suppress with a reviewed pragma, "
                  "or pass --allow-growth for an explicitly reviewed "
                  "baseline expansion")


def check_ratchet(findings: Sequence[Finding],
                  committed_path: Path) -> List[Key]:
    """Keys the findings would add vs the committed baseline (empty ==
    the refresh only shrinks or holds). A missing committed file is a
    bootstrap, not growth."""
    if not Path(committed_path).exists():
        return []
    committed = load_baseline(committed_path)
    proposed = {finding_key(f) for f in findings}
    return sorted(proposed - committed)
