"""The ``lint`` subcommand (wired into replicatinggpt_tpu.cli).

Fast and CPU-only by construction — the analysis package never imports
jax — so it runs as a tier-1 gate. Default invocation lints the whole
project (package + bench.py + tools/ + tests/) against the committed
baseline (exit 1 on any NEW error finding; tests/ findings are
warnings and never gate); ``--write-baseline`` refreshes the committed
file through the ratchet (it refuses to grow the baseline);
``--changed <git-ref>`` restricts *reporting* to files that differ
from the ref while still indexing the whole project, so
interprocedural findings in changed files keep their cross-file
context; ``--format sarif`` emits SARIF 2.1.0 for CI annotation;
``--docs`` regenerates the rule reference.
"""

from __future__ import annotations

import argparse
import json
import subprocess
import sys
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Set

from .baseline import (DEFAULT_BASELINE, RatchetViolation, check_ratchet,
                       diff_against_baseline, load_baseline, write_baseline)
from .docgen import render_rule_docs
from .linter import DEFAULT_SEVERITY, REPO_ROOT, lint_paths, rel_label
from .rules import RULES, Finding

SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
                "master/Schemata/sarif-schema-2.1.0.json")


def add_lint_flags(p: argparse.ArgumentParser) -> None:
    p.add_argument("paths", nargs="*", default=[],
                   help="files/dirs to lint (default: the package plus "
                        "bench.py, tools/ and tests/)")
    p.add_argument("--baseline", nargs="?", const=str(DEFAULT_BASELINE),
                   default=None, metavar="PATH",
                   help="compare against a committed baseline; fail only "
                        "on NEW findings (default path: "
                        "graftlint_baseline.json; auto-applied for a "
                        "bare project lint when the file exists)")
    p.add_argument("--no-baseline", action="store_true",
                   help="report every finding even when the committed "
                        "baseline exists")
    p.add_argument("--write-baseline", action="store_true",
                   help="write the current findings as the new baseline "
                        "(deduped, sorted, and RATCHETED: refuses to add "
                        "entries the committed baseline doesn't have)")
    p.add_argument("--allow-growth", action="store_true",
                   help="override the ratchet for an explicitly reviewed "
                        "baseline expansion")
    p.add_argument("--changed", metavar="GIT_REF", default=None,
                   help="diff-aware mode: report only findings in files "
                        "that differ from GIT_REF (plus untracked files); "
                        "the whole project is still indexed so cross-file "
                        "dataflow stays sound")
    p.add_argument("--severity", action="append", default=None,
                   metavar="DIR=LEVEL",
                   help="per-directory severity tier, e.g. "
                        "'tests/=warning' (repeatable; default: "
                        "tests/=warning). LEVEL is error|warning; "
                        "warnings are reported but never fail the gate "
                        "or enter the baseline")
    p.add_argument("--rules", default=None,
                   help="comma-separated rule ids to run (default: all)")
    p.add_argument("--format", choices=("text", "json", "sarif", "github"),
                   default="text",
                   help="output format; 'github' prints workflow-command "
                        "annotations (::error file=...,line=...) that "
                        "GitHub Actions renders inline on the PR diff")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule table and exit")
    p.add_argument("--docs", action="store_true",
                   help="print the generated rule reference (markdown) "
                        "and exit")


def _print_findings(findings: List[Finding], stream=None) -> None:
    stream = stream or sys.stdout
    for f in findings:
        print(f.format(), file=stream)


def _print_github(findings: Sequence[Finding],
                  warnings: Sequence[Finding], stream=None) -> None:
    """GitHub Actions workflow-command annotations: one ``::error`` /
    ``::warning`` line per finding, which the Actions runner turns into
    inline PR-diff annotations. Message text is %-escaped per the
    workflow-command spec (%, CR, LF)."""
    stream = stream or sys.stdout
    for f in (*findings, *warnings):
        kind = "error" if f.severity == "error" else "warning"
        msg = (f"{f.rule} {f.message}".replace("%", "%25")
               .replace("\r", "%0D").replace("\n", "%0A"))
        print(f"::{kind} file={f.path},line={max(f.line, 1)},"
              f"col={f.col + 1},title=graftlint {f.rule}::{msg}",
              file=stream)


def _parse_severity(args) -> Optional[Dict[str, str]]:
    if not args.severity:
        return None                      # the linter default (tests/=warning)
    out = dict(DEFAULT_SEVERITY)
    for spec in args.severity:
        if "=" not in spec:
            raise SystemExit(f"bad --severity {spec!r} (want DIR=LEVEL)")
        prefix, level = spec.split("=", 1)
        if level not in ("error", "warning"):
            raise SystemExit(f"bad --severity level {level!r}")
        out[prefix] = level
    return out


def _paths_from_name_status(text: str) -> Set[str]:
    """Current-tree paths from ``git diff --name-status`` output.

    Plain statuses (M/A/...) are ``<status>\\t<path>``; renames and
    copies (R<score>/C<score>) are ``<status>\\t<old>\\t<new>`` — only
    the NEW path exists in the working tree, so that is the lintable
    one (the old path would silently drop the file from the scope,
    hiding every finding a rename carried along)."""
    out: Set[str] = set()
    for line in text.splitlines():
        parts = line.rstrip("\n").split("\t")
        if len(parts) < 2:
            continue
        status = parts[0]
        path = parts[2] if status[:1] in ("R", "C") and len(parts) >= 3 \
            else parts[1]
        if path.endswith(".py"):
            out.add(path)
    return out


def changed_files(ref: str) -> Set[str]:
    """Repo-relative labels of .py files differing from ``ref`` in the
    working tree (rename/copy-aware: R/C entries contribute their NEW
    path), plus untracked ones."""
    cmd = ["git", "diff", "--name-status", "-M", "-C",
           "--diff-filter=d", ref]
    proc = subprocess.run(cmd, cwd=REPO_ROOT, capture_output=True,
                          text=True, timeout=60)
    if proc.returncode != 0:
        raise SystemExit(f"--changed: `{' '.join(cmd)}` failed: "
                         f"{proc.stderr.strip()}")
    out = _paths_from_name_status(proc.stdout)
    cmd = ["git", "ls-files", "--others", "--exclude-standard"]
    proc = subprocess.run(cmd, cwd=REPO_ROOT, capture_output=True,
                          text=True, timeout=60)
    if proc.returncode != 0:
        raise SystemExit(f"--changed: `{' '.join(cmd)}` failed: "
                         f"{proc.stderr.strip()}")
    out |= {line.strip() for line in proc.stdout.splitlines()
            if line.strip().endswith(".py")}
    return out


def render_sarif(findings: Sequence[Finding],
                 warnings: Sequence[Finding]) -> dict:
    """SARIF 2.1.0 payload: one run, the full rule table on the driver,
    one result per finding with severity mapped to SARIF level."""
    rule_ids = sorted(RULES)
    rule_index = {rid: i for i, rid in enumerate(rule_ids)}
    results = []
    for f in (*findings, *warnings):
        results.append({
            "ruleId": f.rule,
            "ruleIndex": rule_index.get(f.rule, -1),
            "level": "error" if f.severity == "error" else "warning",
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f.path,
                                         "uriBaseId": "SRCROOT"},
                    "region": {"startLine": max(f.line, 1),
                               "startColumn": f.col + 1},
                },
            }],
        })
    return {
        "$schema": SARIF_SCHEMA,
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "graftlint",
                "informationUri":
                    "docs/graftlint_rules.md",
                "rules": [{
                    "id": rid,
                    "name": RULES[rid].name,
                    "shortDescription": {"text": RULES[rid].name},
                    "fullDescription": {"text": RULES[rid].rationale},
                } for rid in rule_ids],
            }},
            "originalUriBaseIds": {"SRCROOT": {"uri": REPO_ROOT.as_uri()
                                               + "/"}},
            "results": results,
        }],
    }


def run_lint(args) -> int:
    if args.docs:
        print(render_rule_docs(), end="")
        return 0
    if args.list_rules:
        for rid in sorted(RULES):
            print(f"{rid}  {RULES[rid].name}")
        return 0
    rule_ids = ([r.strip().upper() for r in args.rules.split(",")]
                if args.rules else ())
    for r in rule_ids:
        if r not in RULES:
            print(f"unknown rule {r!r} (see --list-rules)", file=sys.stderr)
            return 2
    if args.write_baseline:
        # the committed baseline is a whole-project contract: writing it
        # from a diff-filtered or path-restricted view would silently
        # DROP every entry outside the view (and the ratchet would
        # pass, because the key set only shrank)
        if args.changed is not None:
            print("--write-baseline needs the full project view; "
                  "drop --changed", file=sys.stderr)
            return 2
        target = Path(args.baseline or DEFAULT_BASELINE).resolve()
        if args.paths and target == DEFAULT_BASELINE.resolve():
            print("--write-baseline of the committed baseline needs the "
                  "full project view; drop the path arguments (an "
                  "explicit --baseline PATH elsewhere may scope freely)",
                  file=sys.stderr)
            return 2
    res = lint_paths(args.paths, rule_ids, severity=_parse_severity(args))

    findings, warnings = res.findings, res.warnings
    if args.changed is not None:
        scope = changed_files(args.changed)
        findings = [f for f in findings if f.path in scope]
        warnings = [f for f in warnings if f.path in scope]

    baseline_path = args.baseline
    if (baseline_path is None and not args.no_baseline and not args.paths
            and not args.write_baseline and DEFAULT_BASELINE.exists()):
        # bare `lint` over the project: the committed baseline is the
        # contract (the acceptance criterion's "runs clean" mode)
        baseline_path = str(DEFAULT_BASELINE)
    if args.no_baseline:
        baseline_path = None

    if args.write_baseline:
        out = Path(args.baseline or DEFAULT_BASELINE)
        if not args.allow_growth:
            grown = check_ratchet(findings, out)
            if grown:
                print(RatchetViolation(grown).format(), file=sys.stderr)
                return 2
        n = write_baseline(findings, out)
        print(f"wrote {n} entr{'y' if n == 1 else 'ies'} "
              f"({len(findings)} finding(s)) to {out}")
        return 0

    if baseline_path is None:
        if args.format == "json":
            print(json.dumps({
                "files": res.files,
                "findings": [vars(f) for f in findings],
                "warnings": [vars(f) for f in warnings],
                "suppressed": [vars(f) for f in res.suppressed],
            }))
        elif args.format == "sarif":
            print(json.dumps(render_sarif(findings, warnings)))
        elif args.format == "github":
            _print_github(findings, warnings)
        else:
            _print_findings(findings)
            _print_findings(warnings)
            print(f"graftlint: {len(findings)} finding(s), "
                  f"{len(warnings)} warning(s), "
                  f"{len(res.suppressed)} suppressed, {res.files} file(s)",
                  file=sys.stderr)
        return 1 if findings else 0

    diff = diff_against_baseline(findings, load_baseline(baseline_path))
    stale = [] if args.changed is not None else diff.stale
    if args.format == "json":
        # the diffed view IS the result under a baseline: `findings`
        # holds only NEW hazards (matching the exit code); baselined
        # ones are a count, stale entries listed for refresh tooling
        print(json.dumps({
            "files": res.files,
            "findings": [vars(f) for f in diff.new],
            "warnings": [vars(f) for f in warnings],
            "baselined": diff.matched,
            "stale": [list(k) for k in stale],
            "suppressed": [vars(f) for f in res.suppressed],
        }))
    elif args.format == "sarif":
        print(json.dumps(render_sarif(diff.new, warnings)))
    elif args.format == "github":
        _print_github(diff.new, warnings)
    else:
        _print_findings(diff.new)
        for key in stale:
            print(f"stale baseline entry (finding fixed? refresh with "
                  f"--write-baseline): {key[0]}: {key[1]}: {key[2]}",
                  file=sys.stderr)
        print(f"graftlint: {len(diff.new)} new finding(s), "
              f"{diff.matched} baselined, {len(stale)} stale, "
              f"{len(warnings)} warning(s), "
              f"{len(res.suppressed)} suppressed, {res.files} file(s)",
              file=sys.stderr)
    return 1 if diff.new else 0
