"""The ``lint`` subcommand (wired into replicatinggpt_tpu.cli).

Fast and CPU-only by construction — the analysis package never imports
jax — so it runs as a tier-1 gate. Default invocation lints the
package against the committed baseline (exit 1 on any NEW finding);
``--write-baseline`` refreshes the committed file after a reviewed
change; ``--docs`` regenerates the rule reference.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path
from typing import List

from .baseline import (DEFAULT_BASELINE, diff_against_baseline,
                       load_baseline, write_baseline)
from .docgen import render_rule_docs
from .linter import lint_paths
from .rules import RULES, Finding


def add_lint_flags(p: argparse.ArgumentParser) -> None:
    p.add_argument("paths", nargs="*", default=[],
                   help="files/dirs to lint (default: the "
                        "replicatinggpt_tpu package)")
    p.add_argument("--baseline", nargs="?", const=str(DEFAULT_BASELINE),
                   default=None, metavar="PATH",
                   help="compare against a committed baseline; fail only "
                        "on NEW findings (default path: "
                        "graftlint_baseline.json; auto-applied for a "
                        "bare package lint when the file exists)")
    p.add_argument("--no-baseline", action="store_true",
                   help="report every finding even when the committed "
                        "baseline exists")
    p.add_argument("--write-baseline", action="store_true",
                   help="write the current findings as the new baseline")
    p.add_argument("--rules", default=None,
                   help="comma-separated rule ids to run (default: all)")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule table and exit")
    p.add_argument("--docs", action="store_true",
                   help="print the generated rule reference (markdown) "
                        "and exit")


def _print_findings(findings: List[Finding], stream=None) -> None:
    stream = stream or sys.stdout
    for f in findings:
        print(f.format(), file=stream)


def run_lint(args) -> int:
    if args.docs:
        print(render_rule_docs(), end="")
        return 0
    if args.list_rules:
        for rid in sorted(RULES):
            print(f"{rid}  {RULES[rid].name}")
        return 0
    rule_ids = ([r.strip().upper() for r in args.rules.split(",")]
                if args.rules else ())
    for r in rule_ids:
        if r not in RULES:
            print(f"unknown rule {r!r} (see --list-rules)", file=sys.stderr)
            return 2
    res = lint_paths(args.paths, rule_ids)

    baseline_path = args.baseline
    if (baseline_path is None and not args.no_baseline and not args.paths
            and not args.write_baseline and DEFAULT_BASELINE.exists()):
        # bare `lint` over the package: the committed baseline is the
        # contract (the acceptance criterion's "runs clean" mode)
        baseline_path = str(DEFAULT_BASELINE)
    if args.no_baseline:
        baseline_path = None

    if args.write_baseline:
        out = Path(args.baseline or DEFAULT_BASELINE)
        write_baseline(res.findings, out)
        print(f"wrote {len(res.findings)} finding(s) to {out}")
        return 0

    if baseline_path is None:
        if args.format == "json":
            print(json.dumps({
                "files": res.files,
                "findings": [vars(f) for f in res.findings],
                "suppressed": [vars(f) for f in res.suppressed],
            }))
        else:
            _print_findings(res.findings)
            print(f"graftlint: {len(res.findings)} finding(s), "
                  f"{len(res.suppressed)} suppressed, {res.files} file(s)",
                  file=sys.stderr)
        return 1 if res.findings else 0

    diff = diff_against_baseline(res.findings, load_baseline(baseline_path))
    if args.format == "json":
        # the diffed view IS the result under a baseline: `findings`
        # holds only NEW hazards (matching the exit code); baselined
        # ones are a count, stale entries listed for refresh tooling
        print(json.dumps({
            "files": res.files,
            "findings": [vars(f) for f in diff.new],
            "baselined": diff.matched,
            "stale": [list(k) for k in diff.stale],
            "suppressed": [vars(f) for f in res.suppressed],
        }))
    else:
        _print_findings(diff.new)
        for key in diff.stale:
            print(f"stale baseline entry (finding fixed? refresh with "
                  f"--write-baseline): {key[0]}: {key[1]}: {key[2]}",
                  file=sys.stderr)
        print(f"graftlint: {len(diff.new)} new finding(s), "
              f"{diff.matched} baselined, {len(diff.stale)} stale, "
              f"{len(res.suppressed)} suppressed, {res.files} file(s)",
              file=sys.stderr)
    return 1 if diff.new else 0
