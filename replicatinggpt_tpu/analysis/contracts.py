"""Project contract registry: statically extracted wire/config/metrics
contracts (graftlint v3).

Every fleet PR since PR 8 shipped post-review fixes for the same drift
classes: a wire-codec key written on one side and never read on the
other, a new :class:`EngineConfig` knob the ``--multiproc`` forwarding
whitelist silently drops, a counter incremented in code but missing
from the pinned Prometheus exposition, a telemetry span the trace
validator expects but nothing emits. None of these need execution to
detect — both sides of each contract are literal structure in the AST.
This module extracts the contracts and checks them:

- **RPC verbs** (GL018): ``op_<verb>`` handler methods on classes that
  also define ``dispatch`` (serve/worker.py), vs every literal
  ``.call("verb", ...)`` / ``._call("verb", ...)`` site
  (serve/router.py, serve/disagg.py, serve/procsup.py). Per verb the
  handler's required (top-level ``doc["k"]``) and optional
  (``doc.get("k")``, or any read under a branch) request keys, and the
  union of its literal response-dict keys, checked against the keys
  each call site sends and the keys callers read off the response.
  Plus the ``<stem>_to_wire`` / ``<stem>_from_wire`` codec pairs:
  a key one direction writes and the other never reads is drift.
- **Forwarded flags** (GL022): ``ENGINE_FORWARD_FLAGS`` /
  ``ENGINE_FORWARD_SWITCHES`` / ``MODEL_OVERRIDE_FLAGS`` whitelists vs
  the ``args.<dest>`` reads of the ``EngineConfig(...)`` builder and
  the field sets of the config classes themselves.
- **Counter schema** (GL021): literal ``Metrics.inc`` names in the
  pinned counter families vs the ``PROM_PINNED_COUNTERS`` exposition
  schema (utils/telemetry.py).
- **Telemetry spans** (GL023): names ``tools/trace_check.py`` pins in
  ``TRACE_VALIDATED_NAMES`` vs the span/instant/meta names the code
  actually emits.

Conservatism contract (same as callgraph.py / dataflow.py): checks fire
on *resolved literal* facts only. A ``**spread`` into a response dict,
a dynamically computed counter name, or a verb behind a variable makes
that side of the contract open — the check skips rather than guesses.
Each rule also skips entirely when its registry anchor (a dispatch
class, a whitelist assignment, the pins tuple) is absent from the
project, so one-file lints of unrelated modules stay quiet.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .callgraph import ModuleInfo, ProjectIndex, dotted
from .rules import Finding

#: kwargs a call site may pass that are transport envelope, not payload
#: ("idem" / "gen" are consumed by the dispatch layer — the idempotency
#: reply cache and the generation fence — never by op_ handlers)
_TRANSPORT_KEYS = {"timeout_s", "idem", "gen"}
_RPC_CALL_ATTRS = {"call", "_call"}


def _line_of(node: ast.AST, lines: Sequence[str]) -> str:
    i = getattr(node, "lineno", 1) - 1
    return lines[i].strip() if 0 <= i < len(lines) else ""


def _finding(rule_id: str, node: ast.AST, message: str, mod: ModuleInfo,
             ) -> Finding:
    return Finding(path=mod.label, rule=rule_id,
                   line=getattr(node, "lineno", 1),
                   col=getattr(node, "col_offset", 0), message=message,
                   text=_line_of(node, mod.lines))


def _const_str(node: Optional[ast.expr]) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _resolve_str(mod: ModuleInfo, idx: ProjectIndex,
                 node: ast.expr, depth: int = 0) -> Optional[str]:
    """A literal string, or a Name that resolves (through module
    globals and one import hop) to one."""
    s = _const_str(node)
    if s is not None:
        return s
    if not isinstance(node, ast.Name) or depth > 2:
        return None
    g = mod.globals.get(node.id)
    if g is not None:
        return _resolve_str(mod, idx, g, depth + 1)
    b = mod.imports.get(node.id)
    if b is not None and b.symbol is not None:
        other = idx.module_for(b.module)
        if other is not None and b.symbol in other.globals:
            return _const_str(other.globals[b.symbol])
    return None


def _fmt(keys: Set[str]) -> str:
    return ", ".join(repr(k) for k in sorted(keys))


# --------------------------------------------------------------------------
# GL018 — RPC verb / wire-key contracts
# --------------------------------------------------------------------------


@dataclass
class VerbContract:
    """One ``op_<verb>`` handler's statically visible wire shape."""

    verb: str
    mod: ModuleInfo = None
    node: ast.AST = None          # the handler FunctionDef
    required: Set[str] = field(default_factory=set)
    optional: Set[str] = field(default_factory=set)
    response: Set[str] = field(default_factory=set)
    response_open: bool = False   # **spread / non-literal return seen


@dataclass
class CallSiteInfo:
    """One literal ``.call("verb", ...)`` site."""

    verb: str
    mod: ModuleInfo = None
    node: ast.Call = None
    sent: Set[str] = field(default_factory=set)
    #: every kwarg at the site INCLUDING transport-envelope keys —
    #: GL024 audits the envelope ("idem" present on mutating verbs)
    #: that GL018's payload view deliberately excludes
    sent_all: Set[str] = field(default_factory=set)
    sent_open: bool = False       # **spread at the call
    #: name the response is bound to (``resp = self._call(...)``), when
    #: the site is the sole value of a simple assignment
    bound_name: Optional[str] = None
    #: enclosing function AST, for the response-read scan
    fn_node: ast.AST = None


def _scan_handler(fn: ast.FunctionDef, doc_param: str) -> VerbContract:
    c = VerbContract(verb="")

    def scan(node: ast.AST, branch_depth: int) -> None:
        for child in ast.iter_child_nodes(node):
            depth = branch_depth
            if isinstance(child, (ast.If, ast.For, ast.While, ast.Try,
                                  ast.IfExp)):
                depth += 1
            if isinstance(child, ast.Subscript) \
                    and isinstance(child.value, ast.Name) \
                    and child.value.id == doc_param:
                key = _const_str(child.slice)
                if key is not None:
                    (c.optional if depth else c.required).add(key)
            elif isinstance(child, ast.Call) \
                    and isinstance(child.func, ast.Attribute) \
                    and child.func.attr == "get" \
                    and isinstance(child.func.value, ast.Name) \
                    and child.func.value.id == doc_param and child.args:
                key = _const_str(child.args[0])
                if key is not None:
                    c.optional.add(key)
            if isinstance(child, ast.Return) and child.value is not None:
                if isinstance(child.value, ast.Dict):
                    for k in child.value.keys:
                        if k is None:          # ** spread
                            c.response_open = True
                        else:
                            key = _const_str(k)
                            if key is None:
                                c.response_open = True
                            else:
                                c.response.add(key)
                else:
                    c.response_open = True
            scan(child, depth)

    scan(fn, 0)
    c.optional -= c.required
    return c


def _harvest_handlers(idx: ProjectIndex) -> Dict[str, VerbContract]:
    handlers: Dict[str, VerbContract] = {}
    for mod in idx.modules.values():
        for info in mod.classes.values():
            if "dispatch" not in info.methods or info.node is None:
                continue
            for sub in info.node.body:
                if not isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                    continue
                if not sub.name.startswith("op_"):
                    continue
                params = [a.arg for a in sub.args.args]
                doc_param = params[1] if len(params) > 1 else ""
                c = _scan_handler(sub, doc_param)
                c.verb = sub.name[len("op_"):]
                c.mod, c.node = mod, sub
                handlers[c.verb] = c
    return handlers


def _harvest_call_sites(idx: ProjectIndex) -> List[CallSiteInfo]:
    sites: List[CallSiteInfo] = []
    for mod in idx.modules.values():
        for fn in (*mod.functions.values(), mod.toplevel):
            if fn is None or fn.node is None:
                continue
            bound: Dict[int, str] = {}       # id(call node) -> var name
            for sub in ast.walk(fn.node):
                if isinstance(sub, ast.Assign) and len(sub.targets) == 1 \
                        and isinstance(sub.targets[0], ast.Name) \
                        and isinstance(sub.value, ast.Call):
                    bound[id(sub.value)] = sub.targets[0].id
            for sub in ast.walk(fn.node):
                if not (isinstance(sub, ast.Call)
                        and isinstance(sub.func, ast.Attribute)
                        and sub.func.attr in _RPC_CALL_ATTRS and sub.args):
                    continue
                verb = _const_str(sub.args[0])
                if verb is None:
                    continue
                s = CallSiteInfo(verb=verb, mod=mod, node=sub,
                                 bound_name=bound.get(id(sub)))
                for kw in sub.keywords:
                    if kw.arg is None:
                        s.sent_open = True
                    else:
                        s.sent_all.add(kw.arg)
                        if kw.arg not in _TRANSPORT_KEYS:
                            s.sent.add(kw.arg)
                s.fn_node = fn.node          # for response-read scan
                sites.append(s)
    return sites


def _response_reads(fn_node: ast.AST, var: str) -> Set[str]:
    """Literal keys read off ``var`` anywhere in the function:
    ``var["k"]``, ``var.get("k")``, ``"k" in var``."""
    reads: Set[str] = set()
    for sub in ast.walk(fn_node):
        if isinstance(sub, ast.Subscript) \
                and isinstance(sub.value, ast.Name) \
                and sub.value.id == var:
            k = _const_str(sub.slice)
            if k is not None:
                reads.add(k)
        elif isinstance(sub, ast.Call) \
                and isinstance(sub.func, ast.Attribute) \
                and sub.func.attr == "get" \
                and isinstance(sub.func.value, ast.Name) \
                and sub.func.value.id == var and sub.args:
            k = _const_str(sub.args[0])
            if k is not None:
                reads.add(k)
        elif isinstance(sub, ast.Compare) and len(sub.ops) == 1 \
                and isinstance(sub.ops[0], ast.In) \
                and isinstance(sub.comparators[0], ast.Name) \
                and sub.comparators[0].id == var:
            k = _const_str(sub.left)
            if k is not None:
                reads.add(k)
    return reads


def _dict_literal_keys(fn: ast.FunctionDef) -> Tuple[Set[str], bool]:
    """Union of literal dict keys returned by ``fn`` (wire writers
    return one dict literal; comprehensions / spreads open the set)."""
    keys: Set[str] = set()
    open_ = False
    for sub in ast.walk(fn):
        if not isinstance(sub, ast.Return) or sub.value is None:
            continue
        if isinstance(sub.value, ast.Dict):
            for k in sub.value.keys:
                s = _const_str(k) if k is not None else None
                if s is None:
                    open_ = True
                else:
                    keys.add(s)
        else:
            open_ = True
    return keys, open_


def check_rpc_verb_contract(idx: ProjectIndex) -> List[Finding]:
    findings: List[Finding] = []
    handlers = _harvest_handlers(idx)
    sites = _harvest_call_sites(idx)

    if handlers and sites:
        called_verbs = {s.verb for s in sites}
        for verb, h in sorted(handlers.items()):
            if verb not in called_verbs:
                findings.append(_finding(
                    "GL018", h.node,
                    f"RPC handler `op_{verb}` has no literal "
                    f".call({verb!r}, ...) site anywhere in the project — "
                    f"either the client codec was never wired or the verb "
                    f"is dead; every dispatched verb needs a caller",
                    h.mod))
    if handlers:
        for s in sites:
            h = handlers.get(s.verb)
            if h is None:
                findings.append(_finding(
                    "GL018", s.node,
                    f".call({s.verb!r}, ...) has no `op_{s.verb}` handler "
                    f"on any dispatch class — the worker will raise "
                    f"`unknown op` at runtime",
                    s.mod))
                continue
            missing = h.required - s.sent
            if missing and not s.sent_open:
                findings.append(_finding(
                    "GL018", s.node,
                    f".call({s.verb!r}, ...) omits key(s) "
                    f"{_fmt(missing)} that `op_{s.verb}` reads "
                    f"unconditionally — a guaranteed KeyError on the "
                    f"worker", s.mod))
            unknown = s.sent - h.required - h.optional
            if unknown:
                findings.append(_finding(
                    "GL018", s.node,
                    f".call({s.verb!r}, ...) sends key(s) "
                    f"{_fmt(unknown)} that `op_{s.verb}` never reads — "
                    f"dead wire weight, or a key rename that only "
                    f"landed on one side", s.mod))
            if s.bound_name and not h.response_open:
                reads = _response_reads(s.fn_node, s.bound_name)
                ghost = reads - h.response
                if ghost:
                    findings.append(_finding(
                        "GL018", s.node,
                        f"caller reads key(s) {_fmt(ghost)} off the "
                        f"{s.verb!r} response, but `op_{s.verb}` never "
                        f"returns them", s.mod))

    # ---- <stem>_to_wire / <stem>_from_wire codec pairs ------------------
    for mod in idx.modules.values():
        for name, fn in sorted(mod.functions.items()):
            if not name.endswith("_to_wire") or "." in name:
                continue
            stem = name[: -len("_to_wire")]
            reader = mod.functions.get(f"{stem}_from_wire")
            if reader is None or reader.node is None or fn.node is None:
                continue
            writes, w_open = _dict_literal_keys(fn.node)
            if not reader.params:
                continue
            rc = _scan_handler(reader.node, reader.params[0])
            reads = rc.required | rc.optional
            if not w_open:
                for k in sorted(reads - writes):
                    findings.append(_finding(
                        "GL018", reader.node,
                        f"`{stem}_from_wire` reads {k!r} but "
                        f"`{stem}_to_wire` never writes it — the decoded "
                        f"object silently gets the fallback default on "
                        f"every wire crossing", mod))
                for k in sorted(writes - reads):
                    findings.append(_finding(
                        "GL018", fn.node,
                        f"`{stem}_to_wire` writes {k!r} but "
                        f"`{stem}_from_wire` never reads it — dead wire "
                        f"weight, or a reader-side key that drifted",
                        mod))
    return findings


# --------------------------------------------------------------------------
# GL024 — mutating RPC verbs must be idempotent
# --------------------------------------------------------------------------

#: The fleet's MUTATING verbs: their handlers change worker/supervisor
#: state, and every retry ladder in the fleet (router retry-once on
#:  protocol errors, blind re-registration, netchaos duplicates) can
#: deliver them twice. Each one must (a) be declared in a module-global
#: ``*IDEMPOTENT*`` tuple next to its dispatch class, (b) have its
#: dispatch/handler consult an idem-keyed reply cache (an attribute
#: whose name mentions ``replies``), and (c) carry an explicit ``idem``
#: kwarg at every literal call site. Read-only verbs (step, health,
#: prefix, ...) are exempt — re-executing them is harmless.
RPC_MUTATING_VERBS = ("submit", "page_transfer", "journal_drain",
                      "register")


def _reads_key_literal(node: ast.AST, key: str) -> bool:
    """Whether the subtree reads the literal string ``key`` off any
    mapping (``x["key"]`` / ``x.get("key")`` / ``"key" in x``)."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Subscript) \
                and _const_str(sub.slice) == key:
            return True
        if isinstance(sub, ast.Call) \
                and isinstance(sub.func, ast.Attribute) \
                and sub.func.attr == "get" and sub.args \
                and _const_str(sub.args[0]) == key:
            return True
        if isinstance(sub, ast.Compare) and len(sub.ops) == 1 \
                and isinstance(sub.ops[0], ast.In) \
                and _const_str(sub.left) == key:
            return True
    return False


def _consults_reply_cache(node: ast.AST) -> bool:
    """Whether the subtree touches a reply-cache attribute or name
    (``self._replies`` / ``self._reg_replies`` / ...)."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and "replies" in sub.attr:
            return True
        if isinstance(sub, ast.Name) and "replies" in sub.id:
            return True
    return False


def _idempotent_declared(mod: ModuleInfo, idx: ProjectIndex,
                         ) -> Optional[Set[str]]:
    """The union of verbs declared idempotent by the module's
    ``*IDEMPOTENT*`` tuple globals; None when no such global exists."""
    out: Optional[Set[str]] = None
    for name, val in mod.globals.items():
        if "IDEMPOTENT" not in name.upper():
            continue
        if not isinstance(val, (ast.Tuple, ast.List)):
            continue
        out = out or set()
        out |= {s for s in (_resolve_str(mod, idx, e) for e in val.elts)
                if s is not None}
    return out


def check_idempotent_verb_contract(idx: ProjectIndex) -> List[Finding]:
    findings: List[Finding] = []

    # ---- dispatch classes with op_<mutating-verb> handlers -------------
    handled_verbs: Set[str] = set()
    for mod in idx.modules.values():
        for info in mod.classes.values():
            if "dispatch" not in info.methods or info.node is None:
                continue
            dispatch_fn = None
            mutating = []
            for sub in info.node.body:
                if not isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                    continue
                if sub.name == "dispatch":
                    dispatch_fn = sub
                elif sub.name.startswith("op_") \
                        and sub.name[len("op_"):] in RPC_MUTATING_VERBS:
                    mutating.append(sub)
            if not mutating:
                continue
            handled_verbs |= {m.name[len("op_"):] for m in mutating}
            declared = _idempotent_declared(mod, idx)
            if declared is None:
                findings.append(_finding(
                    "GL024", info.node,
                    f"dispatch class `{info.name}` handles mutating RPC "
                    f"verb(s) "
                    f"{_fmt({m.name[len('op_'):] for m in mutating})} "
                    f"but its module declares no *IDEMPOTENT* verbs "
                    f"tuple — duplicated or blindly-retried calls will "
                    f"re-execute", mod))
            else:
                for m in mutating:
                    verb = m.name[len("op_"):]
                    if verb not in declared:
                        findings.append(_finding(
                            "GL024", m,
                            f"mutating RPC verb {verb!r} is not in the "
                            f"module's *IDEMPOTENT* verbs tuple — its "
                            f"replies are never cached, so a netchaos "
                            f"duplicate or a protocol-error retry "
                            f"re-executes it", mod))
            if dispatch_fn is not None and not (
                    _reads_key_literal(dispatch_fn, "idem")
                    and _consults_reply_cache(dispatch_fn)):
                findings.append(_finding(
                    "GL024", dispatch_fn,
                    f"`{info.name}.dispatch` handles mutating verb(s) "
                    f"but never consults an idem-keyed reply cache "
                    f"(read doc's 'idem' + a `*replies*` attribute) — "
                    f"idempotency keys sent by callers are ignored",
                    mod))

    # ---- registration-style handlers (no op_ method) -------------------
    for verb in RPC_MUTATING_VERBS:
        if verb in handled_verbs:
            continue
        for mod in idx.modules.values():
            for name, fn in sorted(mod.functions.items()):
                short = name.split(".")[-1]
                if short not in (f"_handle_{verb}", f"handle_{verb}"):
                    continue
                if fn.node is None:
                    continue
                handled_verbs.add(verb)
                if not (_reads_key_literal(fn.node, "idem")
                        and _consults_reply_cache(fn.node)):
                    findings.append(_finding(
                        "GL024", fn.node,
                        f"`{short}` executes the mutating {verb!r} "
                        f"handshake but never consults an idem-keyed "
                        f"reply cache — a worker whose registration "
                        f"response was lost will blind-retry and "
                        f"reconcile twice", mod))

    # ---- call sites: mutating verbs must carry an explicit idem key ----
    if handled_verbs:
        for s in _harvest_call_sites(idx):
            if s.verb not in RPC_MUTATING_VERBS \
                    or s.verb not in handled_verbs:
                continue
            if "idem" not in s.sent_all and not s.sent_open:
                findings.append(_finding(
                    "GL024", s.node,
                    f".call({s.verb!r}, ...) sends no 'idem' key — the "
                    f"handler caches replies by idempotency key, so an "
                    f"unkeyed duplicate of this mutating call "
                    f"re-executes instead of hitting the cache",
                    s.mod))
    return findings


# --------------------------------------------------------------------------
# GL021 — counter vs pinned Prometheus schema
# --------------------------------------------------------------------------

_PINS_NAME = "PROM_PINNED_COUNTERS"


def _pinned_counters(idx: ProjectIndex,
                     ) -> Optional[Tuple[ModuleInfo, ast.expr, List[str]]]:
    for mod in idx.modules.values():
        g = mod.globals.get(_PINS_NAME)
        if g is not None and isinstance(g, (ast.Tuple, ast.List)):
            pins = [s for s in (_resolve_str(mod, idx, e) for e in g.elts)
                    if s is not None]
            return mod, g, pins
    return None


def _inc_name(mod: ModuleInfo, idx: ProjectIndex,
              arg: ast.expr) -> Tuple[Optional[str], Optional[str]]:
    """(literal, prefix) of a counter-name argument; (None, None) means
    fully dynamic (a wildcard that can inc anything)."""
    s = _resolve_str(mod, idx, arg)
    if s is not None:
        return s, None
    if isinstance(arg, ast.BinOp) and isinstance(arg.op, ast.Add):
        left = _resolve_str(mod, idx, arg.left)
        if left is not None:
            return None, left
    if isinstance(arg, ast.JoinedStr) and arg.values:
        head = arg.values[0]
        if isinstance(head, ast.Constant) and isinstance(head.value, str):
            return None, head.value
    return None, None


def check_counter_schema_drift(idx: ProjectIndex) -> List[Finding]:
    pinned = _pinned_counters(idx)
    if pinned is None:
        return []
    pins_mod, pins_node, pins = pinned
    families = {p.split("_", 1)[0] + "_" for p in pins if "_" in p}

    findings: List[Finding] = []
    literals: List[Tuple[ModuleInfo, ast.Call, str]] = []
    prefixes: Set[str] = set()
    saw_wildcard = False
    for mod in idx.modules.values():
        for sub in ast.walk(mod.tree):
            if not (isinstance(sub, ast.Call)
                    and isinstance(sub.func, ast.Attribute)
                    and sub.func.attr == "inc" and sub.args):
                continue
            lit, pre = _inc_name(mod, idx, sub.args[0])
            if lit is not None:
                literals.append((mod, sub, lit))
            elif pre is not None:
                prefixes.add(pre)
            else:
                saw_wildcard = True

    for mod, node, lit in literals:
        if any(lit.startswith(f) for f in families) and lit not in pins:
            findings.append(_finding(
                "GL021", node,
                f"counter {lit!r} is incremented here but absent from "
                f"{_PINS_NAME} ({pins_mod.label}) — it will not appear "
                f"in the pinned Prometheus exposition until first "
                f"increment, so dashboards and alerts on it silently "
                f"read 'no data' instead of 0", mod))

    # The never-incremented direction needs the incrementing side in
    # scope to judge liveness: a one-file lint of the pins module alone
    # (zero inc sites anywhere) proves nothing, so stay silent there.
    lit_names = {lit for _, _, lit in literals}
    any_inc_site = bool(literals or prefixes or saw_wildcard)
    if any_inc_site and not saw_wildcard:
        for p in pins:
            if p in lit_names:
                continue
            if any(p.startswith(pre) for pre in prefixes):
                continue
            findings.append(_finding(
                "GL021", pins_node,
                f"pinned counter {p!r} is never incremented anywhere — "
                f"the exposition advertises a metric no code path can "
                f"move; delete the pin or wire the increment",
                pins_mod))
    return findings


# --------------------------------------------------------------------------
# GL022 — forwarded-flag whitelists vs config fields
# --------------------------------------------------------------------------

_ENGINE_LISTS = ("ENGINE_FORWARD_FLAGS", "ENGINE_FORWARD_SWITCHES")
_MODEL_LIST = "MODEL_OVERRIDE_FLAGS"


def _dest_pairs(expr: ast.expr) -> List[str]:
    """dests of a ((dest, flag), ...) whitelist literal."""
    out: List[str] = []
    if isinstance(expr, (ast.Tuple, ast.List)):
        for e in expr.elts:
            if isinstance(e, (ast.Tuple, ast.List)) and e.elts:
                d = _const_str(e.elts[0])
                if d is not None:
                    out.append(d)
    return out


def _arg_attr_reads(node: ast.AST, ns_names: Set[str]) -> Set[str]:
    """Attributes read off any of the namespace names inside ``node``."""
    out: Set[str] = set()
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) \
                and isinstance(sub.value, ast.Name) \
                and sub.value.id in ns_names:
            out.add(sub.attr)
    return out


def _class_fields(idx: ProjectIndex, cls_name: str) -> Optional[Set[str]]:
    infos = idx.class_infos(cls_name)
    if not infos:
        return None
    fields: Set[str] = set()
    for _, info in infos:
        if info.node is None:
            continue
        for sub in info.node.body:
            if isinstance(sub, ast.AnnAssign) \
                    and isinstance(sub.target, ast.Name):
                fields.add(sub.target.id)
    return fields or None


def check_forwarded_flag_drift(idx: ProjectIndex) -> List[Finding]:
    findings: List[Finding] = []

    # ---- engine side: whitelists vs the EngineConfig(...) builder -------
    # The contract is deliberately local: ENGINE_FORWARD_FLAGS lives next
    # to the builder that consumes it (cli.py), so only builders in a
    # whitelist-defining module are held to the whitelist.  Ad-hoc
    # EngineConfig(...) constructions elsewhere (bench harnesses, tests)
    # are not part of the multiproc respawn surface.
    lists_mods: List[ModuleInfo] = []
    for mod in idx.modules.values():
        if any(mod.globals.get(l) is not None for l in _ENGINE_LISTS):
            lists_mods.append(mod)

    for lists_mod in lists_mods:
        engine_dests: Set[str] = set()
        list_nodes: List[Tuple[ModuleInfo, str, ast.expr]] = []
        for lname in _ENGINE_LISTS:
            g = lists_mod.globals.get(lname)
            if g is not None:
                engine_dests |= set(_dest_pairs(g))
                list_nodes.append((lists_mod, lname, g))
        for mod in (lists_mod,):
            for fname, fn in sorted(mod.functions.items()):
                if fn.node is None:
                    continue
                ns = {p for p in fn.params}
                for sub in ast.walk(fn.node):
                    if not (isinstance(sub, ast.Call) and sub.keywords):
                        continue
                    d = dotted(sub.func)
                    if d is None or d.split(".")[-1] != "EngineConfig":
                        continue
                    kw_dests: Dict[str, Set[str]] = {}
                    local_reads = _local_name_arg_reads(fn.node, ns)
                    any_arg_read = False
                    for kw in sub.keywords:
                        if kw.arg is None:
                            continue
                        dests = _arg_attr_reads(kw.value, ns)
                        for n in {x.id for x in ast.walk(kw.value)
                                  if isinstance(x, ast.Name)}:
                            dests |= local_reads.get(n, set())
                        if dests:
                            any_arg_read = True
                        kw_dests[kw.arg] = dests
                    if not any_arg_read:
                        continue          # a literal construction, not
                                          # the CLI builder
                    for kw_name, dests in sorted(kw_dests.items()):
                        stray = dests - engine_dests
                        if stray:
                            findings.append(_finding(
                                "GL022", sub,
                                f"EngineConfig field `{kw_name}` is built "
                                f"from args.{'/args.'.join(sorted(stray))} "
                                f"but no ENGINE_FORWARD_FLAGS/_SWITCHES "
                                f"entry carries it — `serve --multiproc` "
                                f"workers respawn WITHOUT this knob and "
                                f"silently serve a different engine shape",
                                mod))
                    fields = _class_fields(idx, "EngineConfig")
                    if fields:
                        for missing in sorted(fields - set(kw_dests)):
                            findings.append(_finding(
                                "GL022", sub,
                                f"EngineConfig field `{missing}` is never "
                                f"passed by this builder — the flag "
                                f"surface cannot express it, so every "
                                f"deployment silently runs the default",
                                mod))
                    used = _arg_attr_reads(fn.node, ns)
                    for mod2, lname, g in list_nodes:
                        for dest in _dest_pairs(g):
                            if dest not in used:
                                findings.append(_finding(
                                    "GL022", g,
                                    f"{lname} entry `{dest}` is not read "
                                    f"by the EngineConfig builder — a "
                                    f"stale whitelist row forwards a flag "
                                    f"the engine no longer consumes",
                                    mod2))

    # ---- model side: MODEL_OVERRIDE_FLAGS dests must be ModelConfig ----
    for mod in idx.modules.values():
        g = mod.globals.get(_MODEL_LIST)
        if g is None:
            continue
        fields = _class_fields(idx, "ModelConfig")
        if not fields:
            continue
        for dest in _dest_pairs(g):
            if dest not in fields:
                findings.append(_finding(
                    "GL022", g,
                    f"{_MODEL_LIST} entry `{dest}` is not a ModelConfig "
                    f"field — the override either crashes replace() or "
                    f"silently does nothing", mod))
    return findings


def _local_name_arg_reads(fn: ast.AST, ns: Set[str]) -> Dict[str, Set[str]]:
    """For each local name, the args-attributes its assignments read —
    one level: ``d, m = parse_mesh_shape(args.mesh_shape)`` makes both
    ``d`` and ``m`` carry ``mesh_shape``."""
    out: Dict[str, Set[str]] = {}
    for sub in ast.walk(fn):
        if not isinstance(sub, ast.Assign):
            continue
        reads = _arg_attr_reads(sub.value, ns)
        if not reads:
            continue
        for t in sub.targets:
            targets = t.elts if isinstance(t, (ast.Tuple, ast.List)) \
                else [t]
            for x in targets:
                if isinstance(x, ast.Name):
                    out.setdefault(x.id, set()).update(reads)
    return out


# --------------------------------------------------------------------------
# GL023 — telemetry span names vs the trace validator's pins
# --------------------------------------------------------------------------

_TRACE_PINS_NAME = "TRACE_VALIDATED_NAMES"
_EMIT_ATTRS = {"begin", "end", "instant", "complete", "span", "name_track"}


def _emitted_names(idx: ProjectIndex) -> Set[str]:
    names: Set[str] = set()
    for mod in idx.modules.values():
        for sub in ast.walk(mod.tree):
            if isinstance(sub, ast.Call) \
                    and isinstance(sub.func, ast.Attribute) \
                    and sub.func.attr in _EMIT_ATTRS:
                for a in sub.args:
                    s = _resolve_str(mod, idx, a)
                    if s is not None:
                        names.add(s)
            elif isinstance(sub, ast.Dict) and sub.keys:
                keys = {_const_str(k) for k in sub.keys if k is not None}
                if "ph" in keys and "name" in keys:
                    for k, v in zip(sub.keys, sub.values):
                        if _const_str(k) == "name":
                            s = _resolve_str(mod, idx, v)
                            if s is not None:
                                names.add(s)
    return names


def check_telemetry_span_contract(idx: ProjectIndex) -> List[Finding]:
    pins_mod = pins_node = None
    pins: List[str] = []
    for mod in idx.modules.values():
        g = mod.globals.get(_TRACE_PINS_NAME)
        if g is not None and isinstance(g, (ast.Tuple, ast.List)):
            pins_mod, pins_node = mod, g
            pins = [s for s in (_resolve_str(mod, idx, e) for e in g.elts)
                    if s is not None]
            break
    if pins_mod is None:
        return []
    emitted = _emitted_names(idx)
    if not emitted:
        # no emission site in scope at all (e.g. a one-file lint of the
        # validator itself) — absence proves nothing, stay silent
        return []
    findings: List[Finding] = []
    for p in pins:
        if p not in emitted:
            findings.append(_finding(
                "GL023", pins_node,
                f"the trace validator pins event name {p!r} "
                f"({_TRACE_PINS_NAME}) but no telemetry call in the "
                f"project emits it — check_trace would reject every "
                f"trace, or the validation is dead", pins_mod))
    return findings
