"""Rule reference generated from the registry (docs/graftlint_rules.md).

One source of truth: a rule's ID, rationale, and examples live on its
``Rule`` entry in rules.py; this renderer turns the registry into the
committed markdown reference, and tests/test_lint.py asserts the
committed file matches a fresh render — docs cannot drift from code.
Regenerate with ``python -m replicatinggpt_tpu lint --docs >
docs/graftlint_rules.md``.
"""

from __future__ import annotations

from .rules import RULES

_HEADER = """\
# graftlint rule reference

<!-- GENERATED from replicatinggpt_tpu/analysis/rules.py — do not edit
     by hand. Regenerate:
     python -m replicatinggpt_tpu lint --docs > docs/graftlint_rules.md -->

`graftlint` is this package's JAX-hazard static analyzer: pure-AST
checks for the failure modes that cost TPU time or corrupt results
without crashing — silent recompiles, host stalls in hot loops, RNG
reuse, `dynamic_update_slice` clamp corruption, sharding specs that
disagree with their mesh. Since v2 the analyzer is **interprocedural**:
one pass builds a project-wide call graph with per-function summaries
(callgraph.py), and the rules consult it through dataflow.py — GL004
fires when the `.item()` hides two helper calls below the step loop,
GL002 when the import-time device work sits behind a re-exported
wrapper, GL005 when a donated buffer is read back through an alias.
Run it with:

```
python -m replicatinggpt_tpu lint                  # whole project vs baseline
python -m replicatinggpt_tpu lint path/to/file.py  # specific files
python -m replicatinggpt_tpu lint --changed origin/main  # diff-aware
python -m replicatinggpt_tpu lint --write-baseline # refresh (ratcheted)
python -m replicatinggpt_tpu lint --format json    # machine-readable
python -m replicatinggpt_tpu lint --format sarif   # SARIF 2.1.0 for CI
```

Discovery covers the package plus `bench.py`, `tools/` and `tests/`;
findings under `tests/` are *warnings* (reported, never gating — a test
that syncs to assert on a value is the norm), tunable per directory
with `--severity DIR=LEVEL`.

Suppression, in precedence order:

1. fix the hazard (preferred);
2. `# graftlint: disable=GL004` on the flagged line (or
   `disable=GL004,GL006`, or `disable=all`) for a reviewed,
   intentional exception — leave a comment saying why. A pragma at a
   sync site also stops interprocedural propagation from that site;
3. `# graftlint: disable-file=GL002` anywhere in a file;
4. the committed `graftlint_baseline.json` absorbs pre-existing
   findings; `lint --baseline` (the tier-1 gate) fails only on NEW
   ones. The tier-1 test also asserts the baseline exactly matches a
   fresh run, so fixing a baselined finding requires
   `--write-baseline` — which is a **ratchet**: it refuses to add
   entries the committed baseline doesn't already have (override for a
   reviewed expansion with `--allow-growth`), so the baseline can only
   shrink over time.

`GL000` (not listed below) reports files that fail to parse.

"""


def render_rule_docs() -> str:
    parts = [_HEADER]
    for rid in sorted(RULES):
        r = RULES[rid]
        parts.append(f"## {r.id} — `{r.name}`\n\n"
                     f"{r.rationale}\n\n"
                     f"**Flagged:**\n\n```python\n{r.bad}```\n\n"
                     f"**Clean:**\n\n```python\n{r.good}```\n\n"
                     f"Suppress with `# graftlint: disable={r.id}`.\n")
    return "\n".join(parts)
