"""Project-wide call graph with per-function summaries (graftlint v2).

One pass parses every lint target, resolves imports between them, and
builds a :class:`FunctionSummary` per module-level function / method:
does it host-sync, does it device-call, which params flow into
shape/static positions, which params are donated, which returns alias
parameters, which names it captures from enclosing scope. dataflow.py
then re-runs the rule set with these summaries available, which is what
turns the per-file syntactic rules interprocedural — GL004 fires when
the ``.item()`` is two helper calls below the step loop, GL002 when the
device call hides behind a re-exported wrapper.

Everything here is still pure host Python over ``ast`` — no jax import,
no tracing — so the project pass stays a sub-second tier-1 check.

Resolution is deliberately conservative (this is a heuristic analysis
of a dynamic language): a call resolves only when its target is
unambiguous — a module-level function of the same module (not shadowed
by a local binding), a name imported from another linted module
(re-export chains followed), a ``module.attr`` access through an
imported module, or ``self.method`` within the defining class.
Unresolved calls simply don't propagate; we prefer a silent miss over
an interprocedural false positive.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from pathlib import PurePosixPath
from typing import Dict, List, Optional, Sequence, Set, Tuple

# same pragma grammar as linter.py (kept here so callgraph stays
# import-free of the driver): summaries must not propagate a sync the
# author explicitly reviewed and suppressed at its site.
PRAGMA = re.compile(r"#\s*graftlint:\s*(disable(?:-file)?)\s*=\s*"
                    r"([A-Za-z0-9_,\s]+)")


def parse_pragmas(lines: Sequence[str],
                  all_rule_ids: Sequence[str]) -> Tuple[Dict[int, Set[str]],
                                                        Set[str]]:
    """(line -> disabled rule ids, file-wide disabled ids)."""
    per_line: Dict[int, Set[str]] = {}
    per_file: Set[str] = set()
    for i, line in enumerate(lines, start=1):
        m = PRAGMA.search(line)
        if not m:
            continue
        ids = {tok.strip().upper() for tok in m.group(2).split(",")
               if tok.strip()}
        if "ALL" in ids:
            ids = set(all_rule_ids) | {"ALL"}
        if m.group(1) == "disable-file":
            per_file |= ids
        else:
            per_line.setdefault(i, set()).update(ids)
    return per_line, per_file


def dotted(node: ast.AST) -> Optional[str]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


# --------------------------------------------------------------------------
# site classifiers shared with the syntactic rules' vocabulary
# --------------------------------------------------------------------------

_SYNC_FUNCS = {"np.asarray": "np.asarray", "numpy.asarray": "np.asarray",
               "np.array": "np.array", "numpy.array": "np.array",
               "jax.device_get": "jax.device_get"}

#: kinds that PROPAGATE through the call graph. ``np.asarray``/``np.array``
#: deliberately don't: outside a loop they are overwhelmingly host-side
#: dtype coercion (e.g. utils.sanitize.check_in_bounds normalizing an
#: index that is already a Python int), and propagating them
#: interprocedurally drowns real chains in guard-helper noise. Inside a
#: loop the per-file GL004 still flags them directly.
PROPAGATING_SYNCS = {".item()", "float(...)", "jax.device_get"}

_DEVICE_PREFIXES = ("jnp.", "jax.numpy.", "jax.random.")
_DEVICE_EXACT = {"jax.device_put"}

_JIT_WRAPPERS = {"jax.jit", "jit", "pjit", "jax.pmap", "pmap",
                 "jax.experimental.pjit.pjit"}
_PARTIAL = {"functools.partial", "partial"}

_SHAPE_BUILDERS = {"jnp.zeros", "jnp.ones", "jnp.full", "jnp.empty",
                   "jnp.arange", "jnp.eye", "jnp.tri", "jnp.linspace",
                   "jax.numpy.zeros", "jax.numpy.ones", "jax.numpy.full",
                   "jax.numpy.empty", "jax.numpy.arange",
                   "np.zeros", "np.ones", "np.full", "np.empty",
                   "np.arange"}
_SHAPE_METHODS = {"reshape", "broadcast_to"}


def sync_call_kind(node: ast.Call) -> Optional[str]:
    """'np.asarray' / '.item()' / 'float(...)' when this call forces a
    device->host sync (GL004's vocabulary), else None."""
    f = dotted(node.func)
    if (isinstance(node.func, ast.Attribute) and node.func.attr == "item"
            and not node.args):
        return ".item()"
    if f in _SYNC_FUNCS:
        return _SYNC_FUNCS[f]
    if (isinstance(node.func, ast.Name) and node.func.id == "float"
            and len(node.args) == 1
            and not isinstance(node.args[0], ast.Constant)):
        return "float(...)"
    return None


def device_call_kind(node: ast.Call) -> Optional[str]:
    """Dotted name when this call allocates/computes on device (GL002's
    vocabulary), else None."""
    f = dotted(node.func)
    if f is None:
        return None
    if f in _DEVICE_EXACT or any(f.startswith(p) for p in _DEVICE_PREFIXES):
        return f
    return None


#: event-loop blockers (GL019's vocabulary). Exact dotted calls that
#: park the host thread, plus socket-receive methods (the RPC client's
#: frame reads) and subprocess waits. ``asyncio.sleep`` never appears
#: here — it is awaited, and awaited calls are excluded at scan time.
_BLOCKING_EXACT = {
    "time.sleep": "time.sleep",
    "os.fsync": "os.fsync",
    "subprocess.run": "subprocess.run",
    "subprocess.call": "subprocess.call",
    "subprocess.check_call": "subprocess.check_call",
    "subprocess.check_output": "subprocess.check_output",
}
_BLOCKING_RECV_ATTRS = {"recv", "recvfrom", "recv_into"}
#: the project's synchronous RPC spelling: ``client.call("verb", ...)``
#: / ``replica._call("verb", ...)``. Only a call WITHOUT an explicit
#: budget is classified — ``timeout_s=...`` (or a positional timeout)
#: is the reviewed bound that makes a blocking RPC acceptable.
_RPC_CALL_ATTRS = {"call", "_call"}


def blocking_call_kind(node: ast.Call) -> Optional[str]:
    """A human-readable kind string when this call blocks the host
    thread without a budget (GL019's vocabulary), else None."""
    f = dotted(node.func)
    if f in _BLOCKING_EXACT:
        return _BLOCKING_EXACT[f]
    if not isinstance(node.func, ast.Attribute):
        return None
    if node.func.attr in _BLOCKING_RECV_ATTRS:
        return f"socket .{node.func.attr}()"
    if node.func.attr in _RPC_CALL_ATTRS and node.args \
            and isinstance(node.args[0], ast.Constant) \
            and isinstance(node.args[0].value, str):
        timed = (len(node.args) >= 2
                 or any(kw.arg == "timeout_s" for kw in node.keywords))
        if not timed:
            return f"untimed rpc .{node.func.attr}({node.args[0].value!r})"
    return None


def jit_wrap_call(node: ast.AST) -> Optional[ast.Call]:
    if isinstance(node, ast.Call):
        f = dotted(node.func)
        if f in _JIT_WRAPPERS:
            return node
        if f in _PARTIAL and node.args and dotted(node.args[0]) in _JIT_WRAPPERS:
            return node
    return None


def is_jit_wrapper(node: ast.AST) -> bool:
    return (dotted(node) in _JIT_WRAPPERS) or jit_wrap_call(node) is not None


def jit_kwargs(node: ast.AST) -> Dict[str, ast.expr]:
    call = jit_wrap_call(node)
    if call is None:
        return {}
    return {kw.arg: kw.value for kw in call.keywords if kw.arg}


def const_str_items(node: Optional[ast.expr]) -> List[str]:
    if node is None:
        return []
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        return [e.value for e in node.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, str)]
    return []


def const_int_items(node: Optional[ast.expr]) -> List[int]:
    if node is None:
        return []
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        return [e.value for e in node.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, int)]
    return []


def param_names(fn: ast.FunctionDef) -> List[str]:
    a = fn.args
    return [p.arg for p in (*a.posonlyargs, *a.args, *a.kwonlyargs)]


def annotation_type_names(ann: Optional[ast.expr]) -> Set[str]:
    """Every identifier a type annotation mentions: ``Dict[str,
    RemoteReplica]`` -> {'Dict', 'str', 'RemoteReplica'}. Callers
    validate against the project class registry, which drops the typing
    vocabulary. String annotations are parsed ("Router" works under
    ``from __future__ import annotations``)."""
    if ann is None:
        return set()
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        try:
            ann = ast.parse(ann.value, mode="eval").body
        except SyntaxError:
            return set()
    names: Set[str] = set()
    for n in ast.walk(ann):
        if isinstance(n, ast.Name):
            names.add(n.id)
        elif isinstance(n, ast.Attribute):
            names.add(n.attr)
        elif isinstance(n, ast.Constant) and isinstance(n.value, str):
            names.add(n.value)               # nested string annotation
    return names


def _annotated_params(fn: ast.FunctionDef) -> Dict[str, Set[str]]:
    a = fn.args
    return {p.arg: annotation_type_names(p.annotation)
            for p in (*a.posonlyargs, *a.args, *a.kwonlyargs)
            if p.annotation is not None}


# --------------------------------------------------------------------------
# summaries
# --------------------------------------------------------------------------


@dataclass
class CallSite:
    """One call expression inside a function body (or at module scope)."""

    node: ast.Call
    func_expr: ast.expr           # the callee expression
    loop_depth: int               # enclosing loops within this function
    guarded: bool                 # under an `if` inside the innermost loop
    loop_vars: Set[str]           # for-targets of enclosing loops


@dataclass
class FunctionSummary:
    label: str                    # file label the function lives in
    name: str                     # local qualname: "f" or "Class.f"
    node: ast.FunctionDef = None
    params: List[str] = field(default_factory=list)
    is_async: bool = False        # declared ``async def``
    jitted: bool = False
    static_params: Set[str] = field(default_factory=set)
    donated_params: Set[str] = field(default_factory=set)
    shard_annotated: bool = False    # jitted with in_/out_shardings
    #: params that flow into shape-building / static positions (the
    #: recompile-per-value surface of GL013)
    shape_params: Set[str] = field(default_factory=set)
    #: direct host-sync sites in the body (pragma-suppressed ones are
    #: already dropped): (node, kind)
    sync_sites: List[Tuple[ast.AST, str]] = field(default_factory=list)
    #: direct device-call sites (GL002 vocabulary), pragma-filtered
    device_sites: List[Tuple[ast.AST, str]] = field(default_factory=list)
    #: direct event-loop blockers (GL019 vocabulary): (node, kind).
    #: Awaited calls are excluded at scan time, and a GL019 pragma at
    #: the site stops interprocedural propagation.
    blocking_sites: List[Tuple[ast.AST, str]] = field(default_factory=list)
    calls: List[CallSite] = field(default_factory=list)
    #: names read but never bound locally (captured from enclosing scope)
    free_reads: Set[str] = field(default_factory=set)
    #: params returned as-is (possibly through a trivial local alias)
    returns_params: Set[str] = field(default_factory=set)
    local_names: Set[str] = field(default_factory=set)

    @property
    def qname(self) -> str:
        return f"{self.label}::{self.name}"


@dataclass
class ImportBinding:
    """What a local name means: a module, or a symbol of a module."""

    module: str                   # python dotted module name
    symbol: Optional[str] = None  # None => the name IS the module


@dataclass
class ClassInfo:
    """One class's structure, as far as a heuristic needs it: bases (for
    override resolution through abstract seams like ReplicaBase), the
    method-name set, and candidate attribute types harvested from
    annotations (``self.x: Optional[RpcClient]``), annotated-parameter
    assignments (``self.router = router`` with ``router: Router``), and
    constructor calls (``self.x = RpcClient(...)``). Type *names* only —
    validated against the project's class registry at query time, which
    naturally drops typing containers (List, Optional, ...)."""

    name: str
    label: str
    node: ast.ClassDef = None
    bases: Set[str] = field(default_factory=set)
    methods: Set[str] = field(default_factory=set)
    attr_types: Dict[str, Set[str]] = field(default_factory=dict)


@dataclass
class ModuleInfo:
    label: str
    tree: ast.Module
    lines: Sequence[str]
    functions: Dict[str, FunctionSummary] = field(default_factory=dict)
    classes: Dict[str, ClassInfo] = field(default_factory=dict)
    imports: Dict[str, ImportBinding] = field(default_factory=dict)
    #: module-scope simple assignments: name -> value expression
    globals: Dict[str, ast.expr] = field(default_factory=dict)
    #: names whose module-scope value is a raw device/host array build
    #: with no sharding attached (GL011's candidates)
    unsharded_array_globals: Set[str] = field(default_factory=set)
    #: summary of module top-level code (import-time execution)
    toplevel: FunctionSummary = None


def _python_module_name(label: str) -> Optional[str]:
    """'replicatinggpt_tpu/serve/engine.py' -> 'replicatinggpt_tpu.serve.engine'."""
    p = PurePosixPath(label)
    if p.suffix != ".py":
        return None
    parts = list(p.with_suffix("").parts)
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts) if parts else None


_ARRAYISH_PREFIXES = ("jnp.", "jax.numpy.", "np.", "numpy.", "jax.random.")
_SHARD_BLESSED = {"jax.device_put", "device_put"}


def _is_unsharded_array_build(value: ast.expr) -> bool:
    """Module-scope value that builds an array with no sharding attached
    (a ``device_put`` with an explicit sharding argument is blessed)."""
    if not isinstance(value, ast.Call):
        return False
    f = dotted(value.func)
    if f is None:
        return False
    if f in _SHARD_BLESSED:
        return len(value.args) + len(value.keywords) < 2
    return any(f.startswith(p) for p in _ARRAYISH_PREFIXES)


class _FnScanner(ast.NodeVisitor):
    """Single linear walk of one function body building its summary.
    Nested function defs are skipped (they get no summary; a captured
    closure is opaque to resolution anyway)."""

    def __init__(self, summary: FunctionSummary,
                 suppressed=lambda line, rule: False):
        self.s = summary
        self.suppressed = suppressed
        self.loop_depth = 0
        self.if_depth_in_loop = 0
        self.cond_depth = 0            # `if` nesting anywhere in the body
        self.loop_vars: List[Set[str]] = []
        #: id()s of Call nodes under an ``await`` — an awaited call
        #: yields to the event loop instead of blocking it
        self._awaited: Set[int] = set()

    def _collect_store_names(self, target: ast.AST) -> Set[str]:
        return {n.id for n in ast.walk(target)
                if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store)}

    # -- structure ---------------------------------------------------------

    def visit_FunctionDef(self, node):      # nested def: opaque
        self.s.local_names.add(node.name)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        pass

    def _visit_loop(self, children, targets: Set[str]):
        self.loop_depth += 1
        saved_if = self.if_depth_in_loop
        self.if_depth_in_loop = 0
        self.loop_vars.append(targets)
        for child in children:
            self.visit(child)
        self.loop_vars.pop()
        self.if_depth_in_loop = saved_if
        self.loop_depth -= 1

    def visit_For(self, node):
        # the iterator expression evaluates ONCE, before the loop — it
        # is visited at the enclosing depth, not as loop-body work
        self.visit(node.iter)
        tgt = self._collect_store_names(node.target)
        self.s.local_names |= tgt
        self._visit_loop((node.target, *node.body, *node.orelse), tgt)

    visit_AsyncFor = visit_For

    def visit_While(self, node):
        # the test IS re-evaluated per iteration: it belongs to the loop
        self._visit_loop((node.test, *node.body, *node.orelse), set())

    def visit_If(self, node):
        self.visit(node.test)
        self.cond_depth += 1
        if self.loop_depth > 0:
            self.if_depth_in_loop += 1
        for child in (*node.body, *node.orelse):
            self.visit(child)
        if self.loop_depth > 0:
            self.if_depth_in_loop -= 1
        self.cond_depth -= 1

    # -- bindings ----------------------------------------------------------

    def visit_Assign(self, node):
        self.visit(node.value)
        for t in node.targets:
            self.s.local_names |= self._collect_store_names(t)

    def visit_AnnAssign(self, node):
        if node.value is not None:
            self.visit(node.value)
        self.s.local_names |= self._collect_store_names(node.target)

    def visit_AugAssign(self, node):
        self.visit(node.value)
        self.s.local_names |= self._collect_store_names(node.target)

    def visit_NamedExpr(self, node):
        self.visit(node.value)
        self.s.local_names |= self._collect_store_names(node.target)

    def visit_Import(self, node):
        for a in node.names:
            self.s.local_names.add((a.asname or a.name).split(".")[0])

    visit_ImportFrom = visit_Import

    def visit_comprehension(self, node):
        self.s.local_names |= self._collect_store_names(node.target)
        self.generic_visit(node)

    def visit_Return(self, node):
        if isinstance(node.value, ast.Name):
            self.s.returns_params.add(node.value.id)
        elif isinstance(node.value, ast.Tuple):
            for e in node.value.elts:
                if isinstance(e, ast.Name):
                    self.s.returns_params.add(e.id)
        if node.value is not None:
            self.visit(node.value)

    # -- reads & calls -----------------------------------------------------

    def visit_Await(self, node):
        if isinstance(node.value, ast.Call):
            self._awaited.add(id(node.value))
        self.generic_visit(node)

    def visit_Name(self, node):
        if isinstance(node.ctx, ast.Load):
            self.s.free_reads.add(node.id)    # filtered against locals later

    def visit_Call(self, node):
        line = getattr(node, "lineno", 0)
        kind = sync_call_kind(node)
        # a sync under a conditional is treated as intentional (cadence,
        # rank-0, debug) — the same exemption the loop-side guard check
        # applies — so it must not propagate through the call graph either
        if kind in PROPAGATING_SYNCS and self.cond_depth == 0 \
                and not self.suppressed(line, "GL004"):
            self.s.sync_sites.append((node, kind))
        dev = device_call_kind(node)
        if dev is not None and not self.suppressed(line, "GL002"):
            self.s.device_sites.append((node, dev))
        if id(node) not in self._awaited:
            blk = blocking_call_kind(node)
            if blk is not None and not self.suppressed(line, "GL019"):
                self.s.blocking_sites.append((node, blk))
        enclosing = set().union(*self.loop_vars) if self.loop_vars else set()
        self.s.calls.append(CallSite(
            node=node, func_expr=node.func, loop_depth=self.loop_depth,
            guarded=self.if_depth_in_loop > 0, loop_vars=enclosing))
        # shape-building positions: names feeding them
        self._note_shape_args(node)
        self.generic_visit(node)

    def _note_shape_args(self, node: ast.Call):
        f = dotted(node.func)
        shape_exprs: List[ast.expr] = []
        if f in _SHAPE_BUILDERS:
            if node.args:
                shape_exprs.append(node.args[0])
            for kw in node.keywords:
                if kw.arg in ("shape", "num", "N", "M"):
                    shape_exprs.append(kw.value)
        elif (isinstance(node.func, ast.Attribute)
              and node.func.attr in _SHAPE_METHODS):
            shape_exprs.extend(node.args)
        elif f in ("jnp.broadcast_to", "jax.numpy.broadcast_to") \
                and len(node.args) >= 2:
            shape_exprs.append(node.args[1])
        for e in shape_exprs:
            for n in ast.walk(e):
                if isinstance(n, ast.Name):
                    self.s.shape_params.add(n.id)  # intersected with params


def _summarize_function(label: str, qual: str, fn: ast.FunctionDef,
                        suppressed) -> FunctionSummary:
    s = FunctionSummary(label=label, name=qual, node=fn,
                        is_async=isinstance(fn, ast.AsyncFunctionDef))
    s.params = param_names(fn)
    s.local_names |= set(s.params)
    dec = None
    for d in fn.decorator_list:
        if is_jit_wrapper(d):
            dec = d
            break
    if dec is not None:
        s.jitted = True
        kw = jit_kwargs(dec)
        _apply_jit_kwargs(s, kw)
    sc = _FnScanner(s, suppressed)
    for d in fn.decorator_list:
        sc.visit(d)
    for stmt in fn.body:
        sc.visit(stmt)
    s.free_reads -= s.local_names
    s.shape_params = (s.shape_params & set(s.params)) | s.static_params
    s.returns_params &= set(s.params)
    return s


def _apply_jit_kwargs(s: FunctionSummary, kw: Dict[str, ast.expr]) -> None:
    s.static_params |= set(const_str_items(kw.get("static_argnames")))
    for i in const_int_items(kw.get("static_argnums")):
        if 0 <= i < len(s.params):
            s.static_params.add(s.params[i])
    s.donated_params |= set(const_str_items(kw.get("donate_argnames")))
    for i in const_int_items(kw.get("donate_argnums")):
        if 0 <= i < len(s.params):
            s.donated_params.add(s.params[i])
    if "in_shardings" in kw or "out_shardings" in kw:
        s.shard_annotated = True


def _harvest_attr_types(info: ClassInfo, fn: ast.FunctionDef) -> None:
    """Collect candidate type names for ``self.<attr>`` from one method:
    ``self.x: T = ...`` annotations, ``self.x = <annotated param>``, and
    ``self.x = ClassName(...)`` constructor calls. Every method is
    harvested (``connect``-style late binding is as real as __init__)."""
    pmap = _annotated_params(fn)

    def is_self_attr(t: ast.AST) -> Optional[str]:
        if (isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name)
                and t.value.id == "self"):
            return t.attr
        return None

    for node in ast.walk(fn):
        if isinstance(node, ast.AnnAssign):
            attr = is_self_attr(node.target)
            if attr is not None:
                info.attr_types.setdefault(attr, set()).update(
                    annotation_type_names(node.annotation))
        elif isinstance(node, ast.Assign):
            names: Set[str] = set()
            if isinstance(node.value, ast.Name) \
                    and node.value.id in pmap:
                names = pmap[node.value.id]
            elif isinstance(node.value, ast.Call):
                d = dotted(node.value.func)
                if d:
                    names = {d.split(".")[-1]}
            if not names:
                continue
            for t in node.targets:
                attr = is_self_attr(t)
                if attr is not None:
                    info.attr_types.setdefault(attr, set()).update(names)


def _is_main_guard(stmt: ast.stmt) -> bool:
    """``if __name__ == "__main__":`` — runs as a script, not at import."""
    if not isinstance(stmt, ast.If) or not isinstance(stmt.test, ast.Compare):
        return False
    names = {n.id for n in ast.walk(stmt.test) if isinstance(n, ast.Name)}
    return "__name__" in names


def _summarize_toplevel(label: str, tree: ast.Module,
                        suppressed) -> FunctionSummary:
    """Module top-level code as a pseudo-function (import-time loops and
    calls; function/class bodies excluded, their decorators/defaults
    included — mirroring GL002's import-time evaluation model)."""
    s = FunctionSummary(label=label, name="<module>")
    sc = _FnScanner(s, suppressed)
    for stmt in tree.body:
        if _is_main_guard(stmt):
            continue                      # script entry, not import time
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for d in stmt.decorator_list:
                sc.visit(d)
            for default in (*stmt.args.defaults,
                            *[d for d in stmt.args.kw_defaults if d]):
                sc.visit(default)
        elif isinstance(stmt, ast.ClassDef):
            for d in stmt.decorator_list:
                sc.visit(d)
        else:
            sc.visit(stmt)
    s.free_reads -= s.local_names
    return s


# --------------------------------------------------------------------------
# the index
# --------------------------------------------------------------------------


class ProjectIndex:
    """Everything dataflow.py needs: modules by label, functions by
    qname, call resolution, and memoized transitive reachability."""

    def __init__(self):
        self.modules: Dict[str, ModuleInfo] = {}
        self._by_pyname: Dict[str, str] = {}      # python module -> label
        self._sync_memo: Dict[str, Optional[List[str]]] = {}
        self._dev_memo: Dict[str, Optional[List[str]]] = {}
        self._blk_memo: Dict[str, Optional[List[str]]] = {}
        #: class name -> [(label, ClassInfo)] across every module
        self._class_registry: Dict[str, List[Tuple[str, ClassInfo]]] = {}
        self._subclass_memo: Dict[str, Set[str]] = {}

    # -- construction ------------------------------------------------------

    @classmethod
    def build(cls, files: Sequence[Tuple[str, ast.Module, Sequence[str]]],
              all_rule_ids: Sequence[str] = ()) -> "ProjectIndex":
        """``files`` is (label, parsed tree, source lines) triples."""
        idx = cls()
        for label, tree, lines in files:
            per_line, per_file = parse_pragmas(lines, all_rule_ids)

            def suppressed(line, rule, _pl=per_line, _pf=per_file):
                return rule in _pf or rule in _pl.get(line, set())

            mod = ModuleInfo(label=label, tree=tree, lines=lines)
            pyname = _python_module_name(label)
            if pyname:
                idx._by_pyname[pyname] = label
            for stmt in tree.body:
                idx._index_stmt(mod, stmt, suppressed)
            mod.toplevel = _summarize_toplevel(label, tree, suppressed)
            idx.modules[label] = mod
        return idx

    def _index_stmt(self, mod: ModuleInfo, stmt: ast.stmt, suppressed):
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            mod.functions[stmt.name] = _summarize_function(
                mod.label, stmt.name, stmt, suppressed)
        elif isinstance(stmt, ast.ClassDef):
            info = ClassInfo(name=stmt.name, label=mod.label, node=stmt)
            for b in stmt.bases:
                d = dotted(b)
                if d:
                    info.bases.add(d.split(".")[-1])
            for sub in stmt.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    qual = f"{stmt.name}.{sub.name}"
                    mod.functions[qual] = _summarize_function(
                        mod.label, qual, sub, suppressed)
                    info.methods.add(sub.name)
                    _harvest_attr_types(info, sub)
                elif isinstance(sub, ast.AnnAssign) \
                        and isinstance(sub.target, ast.Name):
                    info.attr_types.setdefault(sub.target.id, set()) \
                        .update(annotation_type_names(sub.annotation))
            mod.classes[stmt.name] = info
            self._class_registry.setdefault(stmt.name, []).append(
                (mod.label, info))
        elif isinstance(stmt, ast.Import):
            for a in stmt.names:
                mod.imports[a.asname or a.name.split(".")[0]] = \
                    ImportBinding(module=a.name if a.asname
                                  else a.name.split(".")[0])
        elif isinstance(stmt, ast.ImportFrom):
            base = self._from_base(mod.label, stmt)
            if base is None:
                return
            for a in stmt.names:
                if a.name == "*":
                    continue
                mod.imports[a.asname or a.name] = ImportBinding(
                    module=base, symbol=a.name)
        elif isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name):
            name = stmt.targets[0].id
            mod.globals[name] = stmt.value
            if _is_unsharded_array_build(stmt.value):
                mod.unsharded_array_globals.add(name)

    @staticmethod
    def _from_base(label: str, stmt: ast.ImportFrom) -> Optional[str]:
        """Python module name an ImportFrom pulls from, resolving
        relative imports against the importing file's package path."""
        if stmt.level == 0:
            return stmt.module
        parts = list(PurePosixPath(label).parts[:-1])  # package dir
        up = stmt.level - 1
        if up > len(parts):
            return None
        base_parts = parts[:len(parts) - up] if up else parts
        if stmt.module:
            base_parts = base_parts + stmt.module.split(".")
        return ".".join(base_parts) if base_parts else None

    # -- lookup ------------------------------------------------------------

    def module_for(self, pyname: str) -> Optional[ModuleInfo]:
        label = self._by_pyname.get(pyname)
        return self.modules.get(label) if label else None

    def _lookup_symbol(self, pyname: str, symbol: str,
                       depth: int = 0) -> Optional[FunctionSummary]:
        """Find ``symbol`` in module ``pyname``, following re-export
        chains (``from .engine import step`` in an ``__init__``)."""
        if depth > 4:
            return None
        mod = self.module_for(pyname)
        if mod is None:
            return None
        if symbol in mod.functions:
            return mod.functions[symbol]
        b = mod.imports.get(symbol)
        if b is not None:
            if b.symbol is None:
                return None                   # a module, not a function
            return self._lookup_symbol(b.module, b.symbol, depth + 1)
        return None

    def resolve_call(self, mod: ModuleInfo,
                     caller: Optional[FunctionSummary],
                     func_expr: ast.expr) -> Optional[FunctionSummary]:
        """Resolve a callee expression to a summarized project function,
        or None when the target is ambiguous/external."""
        # plain name: local module function or imported symbol, unless
        # the caller rebinds the name locally
        if isinstance(func_expr, ast.Name):
            name = func_expr.id
            # module top-level "locals" ARE the module's defs/imports —
            # only an actual module-scope assignment shadows there;
            # inside a function any local binding (param, assign, local
            # import) makes the name opaque
            if (caller is not None and caller.name != "<module>"
                    and name in caller.local_names):
                return None
            if name in mod.globals:           # rebound at module scope
                return None
            if name in mod.functions:
                return mod.functions[name]
            b = mod.imports.get(name)
            if b is not None and b.symbol is not None:
                return self._lookup_symbol(b.module, b.symbol)
            return None
        if not isinstance(func_expr, ast.Attribute):
            return None
        # self.method() inside a class
        if (isinstance(func_expr.value, ast.Name)
                and func_expr.value.id in ("self", "cls")
                and caller is not None and "." in caller.name):
            cls_name = caller.name.split(".", 1)[0]
            return mod.functions.get(f"{cls_name}.{func_expr.attr}")
        # module_alias.func() through an imported module
        d = dotted(func_expr.value)
        if d is None:
            return None
        head = d.split(".")[0]
        if caller is not None and head in caller.local_names:
            return None
        b = mod.imports.get(head)
        if b is None or b.symbol is not None:
            # unknown object, or attribute access on an imported symbol
            # (a method on an instance we can't type) — don't guess
            return None
        tail = d.split(".")[1:]
        pyname = ".".join([b.module] + tail) if tail else b.module
        return self._lookup_symbol(pyname, func_expr.attr)

    # -- transitive properties --------------------------------------------

    def _transitive(self, s: FunctionSummary, direct_attr: str,
                    memo: Dict[str, Optional[List[str]]],
                    depth: int, stack: Set[str],
                    ) -> Tuple[Optional[List[str]], bool]:
        """(chain of qnames from ``s`` to a function with a direct site
        of the given kind, search-was-exhaustive). Depth-limited; cycles
        break via the visiting stack. A negative result is only
        MEMOIZED when the search was exhaustive — a None produced by
        depth/cycle truncation must not poison later, shallower queries
        (results would depend on query order)."""
        if s.qname in memo:
            return memo[s.qname], True
        direct = getattr(s, direct_attr)
        if direct:
            memo[s.qname] = [s.qname]
            return memo[s.qname], True
        if depth >= 4 or s.qname in stack:
            return None, False
        stack = stack | {s.qname}
        mod = self.modules.get(s.label)
        if mod is None:
            memo[s.qname] = None
            return None, True
        complete = True
        for site in s.calls:
            callee = self.resolve_call(mod, s, site.func_expr)
            if callee is None:
                continue
            if direct_attr == "sync_sites" and callee.jitted:
                continue                      # a jitted body can't host-sync
            sub, sub_complete = self._transitive(callee, direct_attr, memo,
                                                 depth + 1, stack)
            if sub is not None:
                memo[s.qname] = [s.qname] + sub
                return memo[s.qname], True
            complete = complete and sub_complete
        if complete:
            memo[s.qname] = None
        return None, complete

    def sync_chain(self, s: FunctionSummary) -> Optional[List[str]]:
        """qname chain to a host-sync site reachable from ``s``'s body
        (s itself first), or None. A pragma at the sync site stops the
        chain at the source."""
        return self._transitive(s, "sync_sites", self._sync_memo,
                                0, set())[0]

    def device_chain(self, s: FunctionSummary) -> Optional[List[str]]:
        return self._transitive(s, "device_sites", self._dev_memo,
                                0, set())[0]

    def sync_site_of(self, qname: str) -> Optional[Tuple[str, int, str]]:
        """(label, line, kind) of the first direct sync site of a
        summarized function, for chain-naming messages."""
        label, name = qname.split("::", 1)
        mod = self.modules.get(label)
        fn = (mod.functions.get(name) if mod and name != "<module>"
              else (mod.toplevel if mod else None))
        if fn and fn.sync_sites:
            node, kind = fn.sync_sites[0]
            return (label, getattr(node, "lineno", 0), kind)
        return None

    # -- class registry / receiver typing ----------------------------------

    def class_infos(self, name: str) -> List[Tuple[str, "ClassInfo"]]:
        return self._class_registry.get(name, [])

    def subclasses_of(self, name: str) -> Set[str]:
        """All registered class names reachable downward from ``name``
        (including ``name`` itself) — override resolution through
        abstract seams like ReplicaBase."""
        if name in self._subclass_memo:
            return self._subclass_memo[name]
        out = {name}
        changed = True
        while changed:
            changed = False
            for cls_name, infos in self._class_registry.items():
                if cls_name in out:
                    continue
                for _, info in infos:
                    if info.bases & out:
                        out.add(cls_name)
                        changed = True
                        break
        self._subclass_memo[name] = out
        return out

    def _attr_types(self, type_name: str, attr: str,
                    depth: int = 0) -> Set[str]:
        """Candidate type names of ``<type_name> instance>.<attr>``,
        searching the class and its transitive bases."""
        out: Set[str] = set()
        if depth > 4:
            return out
        for _, info in self._class_registry.get(type_name, []):
            out |= info.attr_types.get(attr, set())
            for b in info.bases:
                out |= self._attr_types(b, attr, depth + 1)
        return {t for t in out if t in self._class_registry}

    def expr_type_names(self, mod: ModuleInfo,
                        caller: Optional[FunctionSummary],
                        expr: ast.expr, depth: int = 0) -> Set[str]:
        """Best-effort set of *registered class* names an expression may
        evaluate to. Flow-insensitive and deliberately shallow: params
        and locals via annotations, ``x = ClassName(...)``, attribute
        chains through harvested attr types, element passthrough for
        subscripts / for-targets / ``.values()``."""
        if depth > 5:
            return set()
        reg = self._class_registry
        if isinstance(expr, ast.Name):
            name = expr.id
            if name in ("self", "cls") and caller is not None \
                    and "." in caller.name:
                cls_name = caller.name.split(".", 1)[0]
                return {cls_name} if cls_name in reg else set()
            if caller is None or caller.node is None:
                return set()
            out: Set[str] = set()
            ann = _annotated_params(caller.node).get(name)
            if ann:
                out |= {t for t in ann if t in reg}
            for sub in ast.walk(caller.node):
                if isinstance(sub, ast.AnnAssign) \
                        and isinstance(sub.target, ast.Name) \
                        and sub.target.id == name:
                    out |= {t for t in annotation_type_names(sub.annotation)
                            if t in reg}
                elif isinstance(sub, ast.Assign) and sub.value is not None:
                    for t in sub.targets:
                        if isinstance(t, ast.Name) and t.id == name:
                            out |= self.expr_type_names(
                                mod, caller, sub.value, depth + 1)
                elif isinstance(sub, ast.For) \
                        and isinstance(sub.target, ast.Name) \
                        and sub.target.id == name:
                    out |= self.expr_type_names(
                        mod, caller, sub.iter, depth + 1)
            return out
        if isinstance(expr, ast.Attribute):
            base_types = self.expr_type_names(mod, caller, expr.value,
                                              depth + 1)
            out = set()
            for t in base_types:
                out |= self._attr_types(t, expr.attr)
            return out
        if isinstance(expr, ast.Subscript):
            # element-of-container passthrough: List[T]/Dict[_, T]
            # annotations already contribute T to the container's types
            return self.expr_type_names(mod, caller, expr.value, depth + 1)
        if isinstance(expr, (ast.ListComp, ast.GeneratorExp, ast.SetComp)) \
                and len(expr.generators) == 1 \
                and isinstance(expr.elt, ast.Name) \
                and isinstance(expr.generators[0].target, ast.Name) \
                and expr.elt.id == expr.generators[0].target.id:
            return self.expr_type_names(mod, caller,
                                        expr.generators[0].iter, depth + 1)
        if isinstance(expr, ast.Call):
            if isinstance(expr.func, ast.Attribute) \
                    and expr.func.attr == "values":
                return self.expr_type_names(mod, caller, expr.func.value,
                                            depth + 1)
            d = dotted(expr.func)
            if d:
                tail = d.split(".")[-1]
                if tail in reg:
                    return {tail}
            return set()
        return set()

    def resolve_method_candidates(self, mod: ModuleInfo,
                                  caller: Optional[FunctionSummary],
                                  func_expr: ast.expr,
                                  ) -> List[FunctionSummary]:
        """Like resolve_call, but when the direct resolution fails on an
        attribute call, type the receiver and return every matching
        method across the receiver's class and its subclasses (capped).
        Used by the blocking-chain search so ``rep.submit(...)`` through
        an abstract base reaches the concrete overrides."""
        direct = self.resolve_call(mod, caller, func_expr)
        if direct is not None:
            return [direct]
        if not isinstance(func_expr, ast.Attribute):
            return []
        recv_types = self.expr_type_names(mod, caller, func_expr.value)
        out: List[FunctionSummary] = []
        seen: Set[str] = set()
        for t in sorted(recv_types):
            for cand in sorted(self.subclasses_of(t)):
                for label, info in self._class_registry.get(cand, []):
                    if func_expr.attr not in info.methods:
                        continue
                    owner = self.modules.get(label)
                    summ = (owner.functions.get(f"{cand}.{func_expr.attr}")
                            if owner else None)
                    if summ is not None and summ.qname not in seen:
                        seen.add(summ.qname)
                        out.append(summ)
                        if len(out) >= 8:
                            return out
        return out

    # -- blocking reachability (GL019) ------------------------------------

    def _blocking_search(self, s: FunctionSummary, depth: int,
                         stack: Set[str],
                         ) -> Tuple[Optional[List[str]], bool]:
        """Like _transitive over ``blocking_sites``, but resolves calls
        through receiver types (so abstract replica seams are crossed)
        and skips async callees: calling an ``async def`` without
        awaiting it just builds a coroutine — it cannot block here, and
        awaited paths are the *callee's* GL019 problem."""
        if s.qname in self._blk_memo:
            return self._blk_memo[s.qname], True
        if s.blocking_sites:
            self._blk_memo[s.qname] = [s.qname]
            return self._blk_memo[s.qname], True
        if depth >= 8 or s.qname in stack:
            return None, False
        stack = stack | {s.qname}
        mod = self.modules.get(s.label)
        if mod is None:
            self._blk_memo[s.qname] = None
            return None, True
        complete = True
        for site in s.calls:
            for callee in self.resolve_method_candidates(
                    mod, s, site.func_expr):
                if callee.jitted or callee.is_async:
                    continue
                sub, sub_complete = self._blocking_search(callee, depth + 1,
                                                          stack)
                if sub is not None:
                    self._blk_memo[s.qname] = [s.qname] + sub
                    return self._blk_memo[s.qname], True
                complete = complete and sub_complete
        if complete:
            self._blk_memo[s.qname] = None
        return None, complete

    def blocking_chain(self, s: FunctionSummary) -> Optional[List[str]]:
        """qname chain from ``s`` to a function with a direct
        event-loop-blocking site, or None. A GL019 pragma at the
        blocking site stops the chain at the source."""
        return self._blocking_search(s, 0, set())[0]

    def blocking_site_of(self, qname: str) -> Optional[Tuple[str, int, str]]:
        """(label, line, kind) of the first direct blocking site of a
        summarized function, for chain-naming messages."""
        label, name = qname.split("::", 1)
        mod = self.modules.get(label)
        fn = (mod.functions.get(name) if mod and name != "<module>"
              else (mod.toplevel if mod else None))
        if fn and fn.blocking_sites:
            node, kind = fn.blocking_sites[0]
            return (label, getattr(node, "lineno", 0), kind)
        return None
