"""Interprocedural rule passes over the project call graph (graftlint v2).

Two rule families live here, both consuming :class:`callgraph.ProjectIndex`:

1. **Interprocedural upgrades** of the per-file syntactic rules —
   GL004 fires when the host sync hides in a helper called (possibly
   through two more helpers, possibly in another file) from inside a
   loop; GL002 when a module-scope call reaches a device computation
   through a re-exported wrapper; GL005 when a donated buffer is read
   after the jitted call, including through a local alias.

2. **The mesh/sharding family GL010–GL014** — PartitionSpec axes vs the
   constructing mesh, unsharded module-array capture under annotated
   programs, ``in_shardings``/``in_specs`` arity vs the wrapped
   function, per-iteration Python scalars flowing into shape/static
   positions of jitted calls, and donation of a buffer the jitted body
   also captures as a closure constant.

Conservatism contract (same as callgraph.py): every check here only
fires on *resolved* facts — an unresolvable callee, a mesh with
non-constant axis names, or a spec behind an opaque variable simply
doesn't participate. Calls under ANY conditional inside the loop are
exempt from the interprocedural GL004: a conditioned sync is almost
always intentional (eval cadence ``if step % k == 0:``, rank-0 logging,
debug dumps), and distinguishing those from a data-dependent
per-iteration stall is beyond a syntactic guard test — the rule trades
that recall for zero false positives on the standard logging patterns.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .callgraph import (CallSite, FunctionSummary, ModuleInfo, ProjectIndex,
                        const_int_items, const_str_items, dotted,
                        jit_kwargs, jit_wrap_call)
from .rules import Finding


def _line_of(node: ast.AST, lines: Sequence[str]) -> str:
    i = getattr(node, "lineno", 1) - 1
    return lines[i].strip() if 0 <= i < len(lines) else ""


def _finding(rule_id: str, node: ast.AST, message: str, mod: ModuleInfo,
             ) -> Finding:
    return Finding(path=mod.label, rule=rule_id,
                   line=getattr(node, "lineno", 1),
                   col=getattr(node, "col_offset", 0), message=message,
                   text=_line_of(node, mod.lines))


def _display(qname: str) -> str:
    label, name = qname.split("::", 1)
    return name if name != "<module>" else label


def _map_args(call: ast.Call, callee: FunctionSummary) -> Dict[str, ast.expr]:
    """param name -> argument expression for a plain-function call
    (methods and *args/**kwargs splats give up on the splatted part)."""
    out: Dict[str, ast.expr] = {}
    params = callee.params
    if "." in callee.name and params and params[0] in ("self", "cls"):
        params = params[1:]
    for i, a in enumerate(call.args):
        if isinstance(a, ast.Starred):
            break
        if i < len(params):
            out[params[i]] = a
    for kw in call.keywords:
        if kw.arg:
            out[kw.arg] = kw.value
    return out


# --------------------------------------------------------------------------
# GL004 — host sync reached through helpers called from a loop
# --------------------------------------------------------------------------


def check_sync_through_helpers(idx: ProjectIndex) -> List[Finding]:
    findings: List[Finding] = []
    for mod in idx.modules.values():
        for fn in (*mod.functions.values(), mod.toplevel):
            for site in fn.calls:
                if site.loop_depth <= 0 or site.guarded:
                    continue
                callee = idx.resolve_call(mod, fn, site.func_expr)
                if callee is None or callee.jitted:
                    continue
                chain = idx.sync_chain(callee)
                if chain is None:
                    continue
                src = idx.sync_site_of(chain[-1])
                where = (f"`{src[2]}` at {src[0]}:{src[1]}" if src
                         else "a device->host sync")
                via = " -> ".join(_display(q) for q in chain)
                findings.append(_finding(
                    "GL004", site.node,
                    f"call to `{_display(chain[0])}` inside a loop reaches "
                    f"{where} (via {via}) — one device->host stall per "
                    f"iteration, just hidden behind the call; accumulate "
                    f"on device and sync once after the loop",
                    mod))
    return findings


# --------------------------------------------------------------------------
# GL002 — import-time device work through re-exported wrappers
# --------------------------------------------------------------------------


def check_device_call_at_import(idx: ProjectIndex) -> List[Finding]:
    findings: List[Finding] = []
    for mod in idx.modules.values():
        fn = mod.toplevel
        for site in fn.calls:
            callee = idx.resolve_call(mod, fn, site.func_expr)
            if callee is None or callee.jitted:
                continue
            chain = idx.device_chain(callee)
            if chain is None:
                continue
            via = " -> ".join(_display(q) for q in chain)
            findings.append(_finding(
                "GL002", site.node,
                f"module-scope call to `{_display(chain[0])}` runs device "
                f"computation at import time (via {via}) — same hazard as "
                f"a bare module-scope jnp call, one wrapper deep; build "
                f"lazily or inside the jitted fn",
                mod))
    return findings


# --------------------------------------------------------------------------
# GL005 — donated buffer read after the jitted call (alias-aware)
# --------------------------------------------------------------------------


class _DonationScanner:
    """Linear source-order walk of one function: track names donated
    into jitted calls (plus trivial ``alias = name`` aliases) and flag
    loads of them in later statements. Rebinding clears. `if`/`else`
    branches walk from the same pre-branch state (mutually exclusive)."""

    def __init__(self, idx: ProjectIndex, mod: ModuleInfo,
                 fn: FunctionSummary):
        self.idx, self.mod, self.fn = idx, mod, fn
        self.aliases: Dict[str, str] = {}       # alias -> root name
        #: root name -> (call, callee display, param, callee-returns-it)
        self.donated: Dict[str, Tuple[ast.Call, str, str, bool]] = {}
        self.findings: List[Finding] = []
        self.flagged: Set[Tuple[int, str]] = set()

    def run(self) -> List[Finding]:
        if self.fn.node is None:
            return []
        for stmt in self.fn.node.body:
            self._stmt(stmt)
        return self.findings

    def _root(self, name: str) -> str:
        seen = set()
        while name in self.aliases and name not in seen:
            seen.add(name)
            name = self.aliases[name]
        return name

    def _check_loads(self, stmt: ast.stmt, skip: Set[int]) -> None:
        if not self.donated:
            return
        for node in ast.walk(stmt):
            if id(node) in skip:
                continue
            if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
                root = self._root(node.id)
                rec = self.donated.get(root)
                if rec is None:
                    continue
                call, callee, param, returned = rec
                key = (node.lineno, node.id)
                if key in self.flagged:
                    continue
                self.flagged.add(key)
                hint = (f"`{callee}` returns `{param}`'s successor — "
                        f"read the value the call returned"
                        if returned else "use the returned value instead")
                self.findings.append(_finding(
                    "GL005", node,
                    f"`{node.id}` was donated to jitted `{callee}` (param "
                    f"`{param}`, line {call.lineno}) and is read again "
                    f"here — donated buffers are deallocated/aliased by "
                    f"XLA, so this read sees freed or overwritten memory; "
                    f"{hint}",
                    self.mod))

    def _register_donations(self, stmt: ast.stmt) -> None:
        """Record donations made by calls inside ``stmt`` (loads in the
        same statement were already checked, with donating-call
        arguments excluded, by _check_loads_excluding_call_args)."""
        for node in ast.walk(stmt):
            if not isinstance(node, ast.Call):
                continue
            callee = self.idx.resolve_call(self.mod, self.fn, node.func)
            if callee is None or not callee.donated_params:
                continue
            for param, arg in _map_args(node, callee).items():
                if param in callee.donated_params \
                        and isinstance(arg, ast.Name):
                    self.donated[self._root(arg.id)] = (
                        node, _display(callee.qname), param,
                        param in callee.returns_params)

    def _rebind(self, target: ast.AST) -> None:
        for n in ast.walk(target):
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store):
                self.donated.pop(n.id, None)
                self.aliases.pop(n.id, None)

    def _stmt(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return
        if isinstance(stmt, ast.If):
            saved_d, saved_a = dict(self.donated), dict(self.aliases)
            self._expr_stmtlike(stmt.test)
            for s in stmt.body:
                self._stmt(s)
            after_body = self.donated
            self.donated = dict(saved_d)
            self.aliases = dict(saved_a)
            for s in stmt.orelse:
                self._stmt(s)
            # a branch that cannot fall through contributes nothing to
            # the statements after the If — in either direction
            terminal = (ast.Return, ast.Raise, ast.Continue, ast.Break)
            body_term = stmt.body and isinstance(stmt.body[-1], terminal)
            else_term = stmt.orelse and isinstance(stmt.orelse[-1],
                                                   terminal)
            if else_term:
                self.donated = after_body
            elif not body_term:
                self.donated.update(after_body)
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
            if isinstance(stmt, (ast.For, ast.AsyncFor)):
                self._expr_stmtlike(stmt.iter)
                self._rebind(stmt.target)
            else:
                self._expr_stmtlike(stmt.test)
            for s in (*stmt.body, *stmt.orelse):
                self._stmt(s)
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._expr_stmtlike(item.context_expr)
            for s in stmt.body:
                self._stmt(s)
            return
        if isinstance(stmt, ast.Try):
            for s in (*stmt.body, *stmt.orelse, *stmt.finalbody):
                self._stmt(s)
            for h in stmt.handlers:
                for s in h.body:
                    self._stmt(s)
            return
        # leaf statement: loads first (against donations from EARLIER
        # statements), then new donations, then rebinds/aliases
        self._check_loads_excluding_call_args(stmt)
        if isinstance(stmt, ast.AugAssign) \
                and isinstance(stmt.target, ast.Name):
            # `state += 1` READS state before rebinding it, but the
            # target carries Store ctx so the load walk misses it
            loadlike = ast.copy_location(
                ast.Name(id=stmt.target.id, ctx=ast.Load()), stmt.target)
            self._check_loads(ast.copy_location(ast.Expr(value=loadlike),
                                                stmt), set())
        self._register_donations(stmt)
        if isinstance(stmt, ast.Assign):
            if (len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)
                    and isinstance(stmt.value, ast.Name)):
                # alias AFTER rebind bookkeeping: `a = state`
                self._rebind(stmt.targets[0])
                self.aliases[stmt.targets[0].id] = self._root(stmt.value.id)
            else:
                for t in stmt.targets:
                    self._rebind(t)
        elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign, ast.NamedExpr)):
            self._rebind(stmt.target)

    def _check_loads_excluding_call_args(self, stmt: ast.stmt) -> None:
        """Loads in this statement, excluding names that only appear as
        arguments of donating calls registered this statement (the
        donation itself isn't a use-after-donate)."""
        donating_arg_ids: Set[int] = set()
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call):
                callee = self.idx.resolve_call(self.mod, self.fn, node.func)
                if callee is not None and callee.donated_params:
                    for a in (*node.args,
                              *(kw.value for kw in node.keywords)):
                        for x in ast.walk(a):
                            donating_arg_ids.add(id(x))
        self._check_loads(stmt, donating_arg_ids)

    def _expr_stmtlike(self, expr: ast.expr) -> None:
        wrapper = ast.Expr(value=expr)
        ast.copy_location(wrapper, expr)
        self._check_loads_excluding_call_args(wrapper)
        self._register_donations(wrapper)


def check_use_after_donate(idx: ProjectIndex) -> List[Finding]:
    findings: List[Finding] = []
    for mod in idx.modules.values():
        for fn in mod.functions.values():
            findings.extend(_DonationScanner(idx, mod, fn).run())
    return findings


# --------------------------------------------------------------------------
# GL010 — PartitionSpec axis names vs the constructing mesh
# --------------------------------------------------------------------------

_MESH_CTORS = {"Mesh", "jax.sharding.Mesh", "sharding.Mesh",
               "jax.make_mesh", "make_mesh"}
_SPEC_CTORS = {"P", "PartitionSpec", "jax.sharding.PartitionSpec",
               "sharding.PartitionSpec"}
_NAMED_SHARDING = {"NamedSharding", "jax.sharding.NamedSharding",
                   "sharding.NamedSharding"}
_SHARD_MAP = {"shard_map", "jax.experimental.shard_map.shard_map",
              "shard_map.shard_map"}


def _mesh_axes(call: ast.Call) -> Optional[List[str]]:
    """Constant axis names of a Mesh/make_mesh construction, or None
    when they aren't statically known."""
    axis_expr = None
    if len(call.args) >= 2:
        axis_expr = call.args[1]
    for kw in call.keywords:
        if kw.arg == "axis_names":
            axis_expr = kw.value
    if axis_expr is None:
        return None
    axes = const_str_items(axis_expr)
    if isinstance(axis_expr, ast.Constant) and isinstance(axis_expr.value,
                                                          str):
        return [axis_expr.value]
    if isinstance(axis_expr, (ast.Tuple, ast.List)) \
            and len(axes) == len(axis_expr.elts):
        return axes
    return None


def _spec_axes(expr: ast.expr,
               local_assigns: Dict[str, List[ast.expr]],
               ) -> List[Tuple[str, ast.AST]]:
    """(axis name, spec node) pairs for every PartitionSpec constant
    axis inside ``expr``. Names bound one level away resolve through
    ``local_assigns`` — only when bound exactly once (flow-insensitive:
    a rebound spec name is ambiguous and yields nothing)."""
    if isinstance(expr, ast.Name) and len(local_assigns.get(expr.id,
                                                            ())) == 1:
        expr = local_assigns[expr.id][0]
    out: List[Tuple[str, ast.AST]] = []
    for node in ast.walk(expr):
        if not (isinstance(node, ast.Call)
                and dotted(node.func) in _SPEC_CTORS):
            continue
        for a in node.args:
            if isinstance(a, ast.Constant) and isinstance(a.value, str):
                out.append((a.value, node))
            elif isinstance(a, (ast.Tuple, ast.List)):
                for e in a.elts:
                    if isinstance(e, ast.Constant) \
                            and isinstance(e.value, str):
                        out.append((e.value, node))
    return out


def _walk_scope(body: Sequence[ast.stmt]):
    """ast.walk over statements, PRUNING nested function/lambda bodies
    (ast.walk has no pruning, so a bare `continue` on a FunctionDef
    still yields its whole subtree — inner scopes would leak out)."""
    stack = list(body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue                       # own scope — roots included
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _scoped_assigns(body: Sequence[ast.stmt]) -> Dict[str,
                                                      List[ast.expr]]:
    """name -> EVERY value it is simple-assigned in this scope. The
    analysis is flow-insensitive, so consumers must treat a multiply-
    assigned name as known only when all its values agree."""
    out: Dict[str, List[ast.expr]] = {}
    for sub in _walk_scope(body):
        if (isinstance(sub, ast.Assign) and len(sub.targets) == 1
                and isinstance(sub.targets[0], ast.Name)):
            out.setdefault(sub.targets[0].id, []).append(sub.value)
    return out


def _agreed_meshes(assigns: Dict[str, List[ast.expr]],
                   ) -> Dict[str, List[str]]:
    """Flow-insensitive mesh map: a name is a known mesh only when
    EVERY assignment to it is a Mesh construction and they all agree on
    axes — a rebound mesh with different axes is unknown, not whichever
    assignment happened to be collected first."""
    out: Dict[str, List[str]] = {}
    for name, values in assigns.items():
        axes_seen = [(_mesh_axes(v) if isinstance(v, ast.Call)
                      and dotted(v.func) in _MESH_CTORS else None)
                     for v in values]
        if axes_seen and axes_seen[0] is not None \
                and all(a == axes_seen[0] for a in axes_seen):
            out[name] = axes_seen[0]
    return out


def _check_mesh_axes_in_scope(body: Sequence[ast.stmt], mod: ModuleInfo,
                              inherited: Dict[str, List[str]],
                              local_bound: Set[str],
                              findings: List[Finding],
                              assigns: Optional[Dict[str,
                                                     List[ast.expr]]] = None,
                              ) -> None:
    if assigns is None:
        assigns = _scoped_assigns(body)
    meshes: Dict[str, List[str]] = dict(inherited)
    # ANY local binding of an inherited mesh name (parameter, unpacking,
    # non-Mesh rebind) makes it a different, unknown mesh in this scope
    for name in (local_bound | set(assigns)):
        meshes.pop(name, None)
    meshes.update(_agreed_meshes(assigns))

    def check_spec_against(mesh_expr: ast.expr, spec_exprs):
        if not isinstance(mesh_expr, ast.Name):
            return
        axes = meshes.get(mesh_expr.id)
        if axes is None:
            return
        for spec_expr in spec_exprs:
            for axis, node in _spec_axes(spec_expr, assigns):
                if axis not in axes:
                    findings.append(_finding(
                        "GL010", node,
                        f"PartitionSpec axis '{axis}' is not an axis of "
                        f"mesh `{mesh_expr.id}` (axes: "
                        f"{', '.join(repr(a) for a in axes)}) — GSPMD "
                        f"treats unknown axes as replicated or raises at "
                        f"lowering, silently dropping the intended "
                        f"sharding",
                        mod))

    for node in _walk_scope(body):
        if not isinstance(node, ast.Call):
            continue
        f = dotted(node.func)
        if f in _NAMED_SHARDING and node.args:
            check_spec_against(node.args[0], node.args[1:])
        elif f in _SHARD_MAP:
            mesh_expr = node.args[1] if len(node.args) >= 2 else None
            spec_exprs = list(node.args[2:])
            for kw in node.keywords:
                if kw.arg == "mesh":
                    mesh_expr = kw.value
                elif kw.arg in ("in_specs", "out_specs"):
                    spec_exprs.append(kw.value)
            if mesh_expr is not None:
                check_spec_against(mesh_expr, spec_exprs)


def check_spec_mesh_mismatch(idx: ProjectIndex) -> List[Finding]:
    findings: List[Finding] = []
    for mod in idx.modules.values():
        toplevel = [s for s in mod.tree.body
                    if not isinstance(s, (ast.FunctionDef,
                                          ast.AsyncFunctionDef,
                                          ast.ClassDef))]
        # module meshes inherit into functions under the same all-
        # assignments-agree rule the scoped check applies
        module_assigns = _scoped_assigns(toplevel)
        module_meshes = _agreed_meshes(module_assigns)
        _check_mesh_axes_in_scope(toplevel, mod, {}, set(), findings,
                                  assigns=module_assigns)
        for fn in mod.functions.values():
            if fn.node is None:
                continue
            bound = set(fn.params)
            for n in _walk_scope(fn.node.body):
                if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store):
                    bound.add(n.id)
            _check_mesh_axes_in_scope(fn.node.body, mod, module_meshes,
                                      bound, findings)
    return findings


# --------------------------------------------------------------------------
# GL011 — annotated programs capturing unsharded module arrays
# --------------------------------------------------------------------------


def _annotated_functions(idx: ProjectIndex,
                         mod: ModuleInfo) -> List[FunctionSummary]:
    """Functions whose program carries sharding annotations: jitted with
    in_/out_shardings, or handed to shard_map/pjit by name."""
    out = {fn.name: fn for fn in mod.functions.values()
           if fn.shard_annotated}
    spmdish = _SHARD_MAP | {"pjit", "jax.experimental.pjit.pjit"}
    for node in ast.walk(mod.tree):
        if (isinstance(node, ast.Call) and dotted(node.func) in spmdish
                and node.args and isinstance(node.args[0], ast.Name)):
            fn = mod.functions.get(node.args[0].id)
            if fn is not None:
                out[fn.name] = fn
    return list(out.values())


def check_unsharded_global_capture(idx: ProjectIndex) -> List[Finding]:
    findings: List[Finding] = []
    for mod in idx.modules.values():
        if not mod.unsharded_array_globals:
            continue
        for fn in _annotated_functions(idx, mod):
            hits = sorted(fn.free_reads & mod.unsharded_array_globals)
            if not hits or fn.node is None:
                continue
            for name in hits:
                node = next((n for n in ast.walk(fn.node)
                             if isinstance(n, ast.Name) and n.id == name
                             and isinstance(n.ctx, ast.Load)), fn.node)
                findings.append(_finding(
                    "GL011", node,
                    f"sharding-annotated `{fn.name}` captures module "
                    f"array `{name}` which has no sharding of its own — "
                    f"the constant is baked in fully replicated on every "
                    f"device, outside the program's sharding contract; "
                    f"pass it as an argument with an explicit spec or "
                    f"device_put it with a NamedSharding",
                    mod))
    return findings


# --------------------------------------------------------------------------
# GL012 — in_shardings / in_specs arity vs the wrapped function
# --------------------------------------------------------------------------


def _tuple_len(expr: Optional[ast.expr]) -> Optional[int]:
    if isinstance(expr, (ast.Tuple, ast.List)):
        return len(expr.elts)
    return None


def _statics_of(fn: ast.FunctionDef,
                kwargs: Dict[str, ast.expr]) -> Set[str]:
    """Params declared static at this jit site — excluded from the
    in_shardings zip (JAX strips static args from the pytree match)."""
    static = set(const_str_items(kwargs.get("static_argnames")))
    params = [p.arg for p in (*fn.args.posonlyargs, *fn.args.args,
                              *fn.args.kwonlyargs)]
    for i in const_int_items(kwargs.get("static_argnums")):
        if 0 <= i < len(params):
            static.add(params[i])
    return static


def _positional_arity(fn: ast.FunctionDef,
                      static: Set[str]) -> Optional[Tuple[int, int]]:
    """(required, total) DYNAMIC positional params; None when *args
    makes any arity legal."""
    a = fn.args
    if a.vararg is not None:
        return None
    params = [p.arg for p in (*a.posonlyargs, *a.args)]
    if params and params[0] in ("self", "cls"):
        params = params[1:]
    has_default = ([False] * (len(params) - len(a.defaults))
                   + [True] * len(a.defaults))
    dyn = [(p, d) for p, d in zip(params, has_default) if p not in static]
    total = len(dyn)
    required = sum(1 for _, d in dyn if not d)
    return required, total


def _return_tuple_arity(fn: ast.FunctionDef) -> Optional[int]:
    """Common length of all literal-tuple returns, else None. Nested
    defs are pruned — their returns are not this function's."""
    lens: Set[int] = set()
    for node in _walk_scope(fn.body):
        if isinstance(node, ast.Return) and node.value is not None:
            if isinstance(node.value, ast.Tuple):
                lens.add(len(node.value.elts))
            else:
                return None
    return lens.pop() if len(lens) == 1 else None


def _check_arity(fn_node: ast.FunctionDef, site: ast.AST,
                 kwargs: Dict[str, ast.expr], kind_in: str, kind_out: str,
                 mod: ModuleInfo, findings: List[Finding]) -> None:
    n_in = _tuple_len(kwargs.get(kind_in))
    static = _statics_of(fn_node, kwargs) if kind_in == "in_shardings" \
        else set()
    arity = _positional_arity(fn_node, static)
    if n_in is not None and arity is not None:
        required, total = arity
        if n_in > total or n_in < required:
            findings.append(_finding(
                "GL012", site,
                f"{kind_in} has {n_in} entr{'y' if n_in == 1 else 'ies'} "
                f"but `{fn_node.name}` takes "
                f"{total if required == total else f'{required}-{total}'} "
                f"dynamic positional argument(s) — the spec-to-argument "
                f"zip is "
                f"positional, so every spec after the mismatch silently "
                f"lands on the wrong argument (or raises at call time)",
                mod))
    n_out = _tuple_len(kwargs.get(kind_out))
    ret = _return_tuple_arity(fn_node)
    if n_out is not None and ret is not None and n_out != ret:
        findings.append(_finding(
            "GL012", site,
            f"{kind_out} has {n_out} entr{'y' if n_out == 1 else 'ies'} "
            f"but `{fn_node.name}` returns a {ret}-tuple — output specs "
            f"zip positionally against the returned pytree",
            mod))


def check_shardings_arity(idx: ProjectIndex) -> List[Finding]:
    findings: List[Finding] = []
    for mod in idx.modules.values():
        for fn in mod.functions.values():
            if fn.node is None:
                continue
            for dec in fn.node.decorator_list:
                kw = jit_kwargs(dec)
                if kw:
                    _check_arity(fn.node, dec, kw, "in_shardings",
                                 "out_shardings", mod, findings)
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            f = dotted(node.func)
            # jax.jit(f, in_shardings=...) / pjit(f, ...)
            call = jit_wrap_call(node)
            if call is not None and call.args:
                first = call.args[0]
                if dotted(first) in ("jax.jit", "jit", "pjit"):
                    first = call.args[1] if len(call.args) > 1 else None
                if isinstance(first, ast.Name) \
                        and first.id in mod.functions:
                    target = mod.functions[first.id]
                    if target.node is not None:
                        _check_arity(target.node, node,
                                     {k.arg: k.value for k in node.keywords
                                      if k.arg},
                                     "in_shardings", "out_shardings", mod,
                                     findings)
            elif f in _SHARD_MAP and node.args \
                    and isinstance(node.args[0], ast.Name):
                target = mod.functions.get(node.args[0].id)
                if target is not None and target.node is not None:
                    kw = {k.arg: k.value for k in node.keywords if k.arg}
                    if len(node.args) >= 3:
                        kw.setdefault("in_specs", node.args[2])
                    if len(node.args) >= 4:
                        kw.setdefault("out_specs", node.args[3])
                    _check_arity(target.node, node, kw, "in_specs",
                                 "out_specs", mod, findings)
    return findings


# --------------------------------------------------------------------------
# GL013 — per-iteration Python scalars into shape/static positions
# --------------------------------------------------------------------------


_MUTATORS = {"pop", "append", "extend", "insert", "remove", "clear",
             "popitem", "update", "add", "discard"}


def _mutated_names(fn: FunctionSummary) -> Set[str]:
    """Names rebound or mutated in place INSIDE a loop of this function
    — the set over which a ``len(...)`` can change per iteration. A
    name bound once before the loop is loop-invariant and exempt."""
    if fn.node is None:
        return set()
    out: Set[str] = set()
    for loop in _walk_scope(fn.node.body):
        if not isinstance(loop, (ast.For, ast.AsyncFor, ast.While)):
            continue
        for n in _walk_scope(loop.body):
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store):
                out.add(n.id)
            elif (isinstance(n, ast.Call)
                  and isinstance(n.func, ast.Attribute)
                  and n.func.attr in _MUTATORS
                  and isinstance(n.func.value, ast.Name)):
                out.add(n.func.value.id)
    return out


def _varying_reason(arg: ast.expr, site: CallSite,
                    mutated: Set[str]) -> Optional[str]:
    """Why this argument takes a new Python value every iteration —
    None when it is loop-invariant (e.g. len() of a never-mutated
    container compiles exactly one program)."""
    for n in ast.walk(arg):
        if isinstance(n, ast.Name) and n.id in site.loop_vars:
            return f"loop variable `{n.id}`"
        if (isinstance(n, ast.Call) and isinstance(n.func, ast.Name)
                and n.func.id == "len" and n.args):
            operand = {x.id for x in ast.walk(n.args[0])
                       if isinstance(x, ast.Name)}
            if operand & (site.loop_vars | mutated):
                return "`len(...)` of a mutated container, recomputed " \
                       "per iteration"
    return None


def check_varying_shape_args(idx: ProjectIndex) -> List[Finding]:
    findings: List[Finding] = []
    for mod in idx.modules.values():
        for fn in (*mod.functions.values(), mod.toplevel):
            mutated = _mutated_names(fn)
            for site in fn.calls:
                if site.loop_depth <= 0:
                    continue
                callee = idx.resolve_call(mod, fn, site.func_expr)
                if callee is None or not callee.jitted \
                        or not callee.shape_params:
                    continue
                for param, arg in _map_args(site.node, callee).items():
                    if param not in callee.shape_params:
                        continue
                    reason = _varying_reason(arg, site, mutated)
                    if reason is None:
                        continue
                    findings.append(_finding(
                        "GL013", site.node,
                        f"{reason} flows into `{param}`, a shape/static "
                        f"position of jitted `{callee.name}` — every "
                        f"distinct value compiles a fresh program (the "
                        f"classic recompile-per-length death spiral); pad "
                        f"to a fixed bucket or make the size a traced "
                        f"array dimension",
                        mod))
    return findings


# --------------------------------------------------------------------------
# GL014 — donating a buffer the jitted body captures as a constant
# --------------------------------------------------------------------------


def check_donated_closure_capture(idx: ProjectIndex) -> List[Finding]:
    findings: List[Finding] = []
    for mod in idx.modules.values():
        for fn in (*mod.functions.values(), mod.toplevel):
            for site in fn.calls:
                callee = idx.resolve_call(mod, fn, site.func_expr)
                if callee is None or not callee.jitted \
                        or not callee.donated_params:
                    continue
                callee_mod = idx.modules.get(callee.label)
                if callee_mod is None:
                    continue
                for param, arg in _map_args(site.node, callee).items():
                    if param not in callee.donated_params:
                        continue
                    if not isinstance(arg, ast.Name):
                        continue
                    # the argument must BE the captured module global,
                    # not a caller local/param that merely shares its
                    # name (different binding, different buffer). Module
                    # top-level "locals" ARE the module globals, so the
                    # shadowing guard only applies inside functions.
                    if fn.name != "<module>" and arg.id in fn.local_names:
                        continue
                    if mod.label == callee.label:
                        global_name = arg.id
                    else:
                        b = mod.imports.get(arg.id)
                        if b is None or b.symbol is None \
                                or idx.module_for(b.module) is not callee_mod:
                            continue
                        global_name = b.symbol
                    if global_name in callee.free_reads \
                            and global_name in callee_mod.globals:
                        findings.append(_finding(
                            "GL014", site.node,
                            f"`{arg.id}` is donated to jitted "
                            f"`{callee.name}` (param `{param}`) but the "
                            f"jitted body ALSO captures `{arg.id}` as a "
                            f"closure constant — donation frees the very "
                            f"buffer the compiled program holds baked in; "
                            f"the next call reads freed memory or "
                            f"silently stale values",
                            mod))
    return findings


# --------------------------------------------------------------------------
# GL019 — event-loop blocker reachable from an async def
# --------------------------------------------------------------------------


def check_async_blocking_call(idx: ProjectIndex) -> List[Finding]:
    """A blocking operation (socket recv, fsync, ``time.sleep``,
    subprocess, an RPC call with no explicit ``timeout_s``) directly in,
    or transitively reachable from, an ``async def`` body. The serving
    front door is a single-threaded asyncio loop: one blocked coroutine
    stalls every request, every /healthz probe, and the SSE heartbeats
    at once (the PR 9 hang class). Call resolution crosses receiver
    types and abstract bases (``rep.submit(...)`` through ReplicaBase
    reaches the RemoteReplica override), awaited calls never count, and
    a GL019 pragma at the blocking site stops the chain at the source —
    use it for sites whose blocking is budgeted by construction."""
    findings: List[Finding] = []
    for mod in idx.modules.values():
        for fn in mod.functions.values():
            if not fn.is_async:
                continue
            for node, kind in fn.blocking_sites:
                findings.append(_finding(
                    "GL019", node,
                    f"`{kind}` directly inside async "
                    f"`{_display(fn.qname)}` blocks the event loop — "
                    f"every other coroutine (requests, health probes, "
                    f"SSE streams) stalls behind it; offload to an "
                    f"executor or give the call an explicit timeout_s "
                    f"budget", mod))
            for site in fn.calls:
                for callee in idx.resolve_method_candidates(
                        mod, fn, site.func_expr):
                    if callee.jitted or callee.is_async:
                        continue
                    chain = idx.blocking_chain(callee)
                    if chain is None:
                        continue
                    src = idx.blocking_site_of(chain[-1])
                    where = (f"`{src[2]}` at {src[0]}:{src[1]}" if src
                             else "a blocking call")
                    via = " -> ".join(_display(q) for q in chain)
                    findings.append(_finding(
                        "GL019", site.node,
                        f"async `{_display(fn.qname)}` reaches {where} "
                        f"(via {via}) — the single-threaded event loop "
                        f"blocks for the full duration; offload the "
                        f"chain to an executor or bound it with an "
                        f"explicit timeout_s budget", mod))
                    break          # one finding per call site
    return findings


# --------------------------------------------------------------------------
# GL020 — terminal result recorded without the delivery ledger
# --------------------------------------------------------------------------


def check_unledgered_finish(idx: ProjectIndex) -> List[Finding]:
    """In a class that owns a crash ledger/journal, any method that
    stores a terminal result (``self.results[...] = ...``) must also
    route through ``record_finish`` in the same method — the
    exactly-once dedupe seam. A finish path that skips the ledger
    resurrects the request on the next crash recovery (the journal
    replays what it never saw finish) and double-delivers its stream."""
    findings: List[Finding] = []
    for mod in idx.modules.values():
        for info in mod.classes.values():
            if info.node is None:
                continue
            has_ledger = False
            for sub in ast.walk(info.node):
                target = None
                if isinstance(sub, ast.Assign) and len(sub.targets) == 1:
                    target = sub.targets[0]
                elif isinstance(sub, ast.AnnAssign):
                    target = sub.target
                if isinstance(target, ast.Attribute) \
                        and isinstance(target.value, ast.Name) \
                        and target.value.id == "self" \
                        and target.attr in ("ledger", "journal"):
                    has_ledger = True
                    break
            if not has_ledger:
                continue
            for m in info.node.body:
                if not isinstance(m, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    continue
                ledgered = any(
                    isinstance(sub, ast.Call)
                    and isinstance(sub.func, ast.Attribute)
                    and sub.func.attr == "record_finish"
                    for sub in ast.walk(m))
                if ledgered:
                    continue
                for sub in ast.walk(m):
                    if isinstance(sub, ast.Assign) \
                            and len(sub.targets) == 1 \
                            and isinstance(sub.targets[0], ast.Subscript):
                        t = sub.targets[0].value
                        if isinstance(t, ast.Attribute) \
                                and isinstance(t.value, ast.Name) \
                                and t.value.id == "self" \
                                and t.attr == "results":
                            findings.append(_finding(
                                "GL020", sub,
                                f"`{info.name}.{m.name}` stores a "
                                f"terminal result without calling "
                                f"record_finish — this finish bypasses "
                                f"the delivery ledger, so a crash "
                                f"recovery will resurrect the request "
                                f"and double-deliver its stream", mod))
    return findings
