"""graftlint rule registry: JAX hazards as pure-AST passes.

Every expensive JAX failure mode this package has hit by hand — silent
recompiles from tracer-dependent Python control flow, retained donated
buffers, RNG key reuse, per-step host round-trips, the
``dynamic_update_slice`` clamp corruption PR 1 debugged in the serving
prefill — leaves a recognizable syntactic footprint. These rules match
those footprints with ``ast`` only: no jax import, no tracing, no
device, so ``python -m replicatinggpt_tpu lint`` is a sub-second
CPU-only tier-1 check.

Each rule is registered with an ID, a rationale, and a bad/good example
pair; ``docgen.render_rule_docs`` turns the registry into
``docs/graftlint_rules.md`` and ``tests/test_lint.py`` parametrizes
over it, so a rule cannot exist without docs and fixture coverage.

Suppression: ``# graftlint: disable=GL004`` on the flagged line, or
``# graftlint: disable-file=GL004`` anywhere in the file (see
linter.py); pre-existing findings live in the committed baseline
(baseline.py) so the lint gate only fails on NEW hazards.

Static analysis over a dynamic language is heuristic by construction:
the rules are tuned to the idioms of this codebase (decorator-jitted
functions, ``partial(jax.jit, ...)``, module-level jits) and prefer
missing an exotic spelling over drowning real findings in noise.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Tuple

# ---------------------------------------------------------------------------
# data model
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Finding:
    """One lint hit. ``text`` is the stripped source line — the baseline
    matches on (path, rule, text) rather than line numbers, so findings
    survive unrelated edits that shift lines. ``severity`` is assigned
    by the driver from the per-directory tier map (tests/ findings are
    warnings); only errors gate CI or enter the baseline."""

    path: str
    rule: str
    line: int
    col: int
    message: str
    text: str
    severity: str = "error"

    def format(self) -> str:
        tag = "" if self.severity == "error" else f" {self.severity}:"
        return (f"{self.path}:{self.line}:{self.col}:{tag} "
                f"{self.rule} {self.message}")


@dataclass(frozen=True)
class Rule:
    """``checker`` is the per-file syntactic pass; ``project_checker``
    (v2) runs once per lint invocation over the whole-project
    :class:`~.callgraph.ProjectIndex` and is how a rule sees across
    function and file boundaries. A rule may have either or both — the
    driver runs both and merges the findings under one rule id."""

    id: str
    name: str
    rationale: str
    bad: str
    good: str
    checker: Optional[Callable[[ast.Module, Sequence[str], str],
                               List[Finding]]] = None
    project_checker: Optional[Callable[..., List[Finding]]] = None


RULES: Dict[str, Rule] = {}


def _register(rule: Rule) -> Rule:
    assert rule.id not in RULES, f"duplicate rule id {rule.id}"
    assert rule.checker or rule.project_checker, rule.id
    RULES[rule.id] = rule
    return rule


def _project(check_name: str):
    """Lazy dispatch into dataflow.py / contracts.py (rules.py is
    imported by both, so the project checkers bind at call time, not
    import time). dataflow owns the callgraph-walking families;
    contracts owns the wire/config/metrics contract registry (v3)."""
    def run(index):
        from . import contracts, dataflow
        target = getattr(dataflow, check_name, None)
        if target is None:
            target = getattr(contracts, check_name)
        return target(index)
    run.__name__ = check_name
    return run


# ---------------------------------------------------------------------------
# shared AST helpers
# ---------------------------------------------------------------------------

def dotted(node: ast.AST) -> Optional[str]:
    """'jax.lax.dynamic_update_slice' for a Name/Attribute chain, else
    None (calls, subscripts etc. in the chain give up)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


_JIT_WRAPPERS = {"jax.jit", "jit", "pjit", "jax.pmap", "pmap",
                 "jax.experimental.pjit.pjit"}
_PARTIAL = {"functools.partial", "partial"}


def _line_of(node: ast.AST, lines: Sequence[str]) -> str:
    i = getattr(node, "lineno", 1) - 1
    return lines[i].strip() if 0 <= i < len(lines) else ""


def _finding(rule_id: str, node: ast.AST, message: str, path: str,
             lines: Sequence[str]) -> Finding:
    return Finding(path=path, rule=rule_id, line=node.lineno,
                   col=node.col_offset, message=message,
                   text=_line_of(node, lines))


def _jit_wrap_call(node: ast.AST) -> Optional[ast.Call]:
    """The jax.jit(...) Call under ``node`` when node is a jit wrapper
    expression: ``jax.jit``, ``jax.jit(...)``, or
    ``partial(jax.jit, ...)``. None otherwise."""
    if isinstance(node, ast.Call):
        f = dotted(node.func)
        if f in _JIT_WRAPPERS:
            return node
        if f in _PARTIAL and node.args and dotted(node.args[0]) in _JIT_WRAPPERS:
            return node
    return None


def _is_jit_wrapper(node: ast.AST) -> bool:
    return (dotted(node) in _JIT_WRAPPERS) or _jit_wrap_call(node) is not None


def _jit_kwargs(node: ast.AST) -> Dict[str, ast.expr]:
    call = _jit_wrap_call(node)
    if call is None:
        return {}
    return {kw.arg: kw.value for kw in call.keywords if kw.arg}


def _const_str_items(node: Optional[ast.expr]) -> List[str]:
    """String elements of a tuple/list/str constant expression."""
    if node is None:
        return []
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        return [e.value for e in node.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, str)]
    return []


def _const_int_items(node: Optional[ast.expr]) -> List[int]:
    if node is None:
        return []
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        return [e.value for e in node.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, int)]
    return []


def _param_names(fn: ast.FunctionDef) -> List[str]:
    a = fn.args
    return [p.arg for p in (*a.posonlyargs, *a.args, *a.kwonlyargs)]


def _static_param_names(fn: ast.FunctionDef,
                        kwargs: Dict[str, ast.expr]) -> set:
    static = set(_const_str_items(kwargs.get("static_argnames")))
    params = _param_names(fn)
    for i in _const_int_items(kwargs.get("static_argnums")):
        if 0 <= i < len(params):
            static.add(params[i])
    return static


def _jit_decorator(fn: ast.FunctionDef) -> Optional[ast.AST]:
    for dec in fn.decorator_list:
        if _is_jit_wrapper(dec):
            return dec
    return None


def _top_level_functions(tree: ast.Module) -> List[ast.FunctionDef]:
    """Module-level and method-level defs (nested defs analyzed as part
    of their parent, not separately — guards in the outer scope bless
    the whole lexical function)."""
    out: List[ast.FunctionDef] = []
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.append(node)
        elif isinstance(node, ast.ClassDef):
            out.extend(n for n in node.body
                       if isinstance(n, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)))
    return out


#: keyed on id(tree): every per-file rule asks for the same function
#: list, and re-walking a large module once per rule dominates the
#: per-file pass. The strong tree reference makes id() aliasing
#: impossible while an entry lives; the linter clears the cache at the
#: start of each run so trees don't accumulate across runs.
_ALL_FUNCTIONS_CACHE: Dict[int, Tuple[ast.Module, List[ast.FunctionDef]]] = {}


def _all_functions(tree: ast.Module) -> List[ast.FunctionDef]:
    hit = _ALL_FUNCTIONS_CACHE.get(id(tree))
    if hit is not None and hit[0] is tree:
        return hit[1]
    fns = [n for n in ast.walk(tree)
           if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
    _ALL_FUNCTIONS_CACHE[id(tree)] = (tree, fns)
    return fns


# ---------------------------------------------------------------------------
# GL001 — tracer-dependent Python control flow in jitted functions
# ---------------------------------------------------------------------------

def _check_tracer_branch(tree, lines, path):
    findings = []
    for fn in _all_functions(tree):
        dec = _jit_decorator(fn)
        if dec is None:
            continue
        static = _static_param_names(fn, _jit_kwargs(dec))
        traced = {n for n in _param_names(fn) if n not in static} - {"self"}
        for node in ast.walk(fn):
            if not isinstance(node, (ast.If, ast.While)):
                continue
            test = node.test
            # `x is None` / `x is not None` on a traced name is a static
            # Python identity check, not a tracer branch
            if (isinstance(test, ast.Compare)
                    and all(isinstance(op, (ast.Is, ast.IsNot))
                            for op in test.ops)):
                continue
            used = {n.id for n in ast.walk(test) if isinstance(n, ast.Name)}
            hit = sorted(used & traced)
            if hit:
                kw = "while" if isinstance(node, ast.While) else "if"
                findings.append(_finding(
                    "GL001", node,
                    f"Python `{kw}` on traced argument(s) {', '.join(hit)} "
                    f"inside jitted `{fn.name}` — branches on tracers raise "
                    f"ConcretizationTypeError or silently retrace per value; "
                    f"use jnp.where/lax.cond or mark the arg static",
                    path, lines))
    return findings


_register(Rule(
    id="GL001", name="tracer-branch",
    rationale=(
        "Python `if`/`while` on a traced value inside a jitted function "
        "either crashes (ConcretizationTypeError) or — when the value is "
        "accidentally concrete, e.g. a host scalar passed per step — "
        "recompiles the program for every distinct value. Recompiles are "
        "the top TPU-time sink in the pjit scaling postmortems this repo "
        "is built on."),
    bad="""\
@jax.jit
def step(x, n):
    if n > 0:            # n is traced: retrace/crash
        x = x * n
    return x
""",
    good="""\
@partial(jax.jit, static_argnames=("n",))
def step(x, n):
    if n > 0:            # n is a static (hashable) Python value
        x = x * n
    return x
# ...or keep n traced and branch on device: jnp.where(n > 0, x * n, x)
""",
    checker=_check_tracer_branch))


# ---------------------------------------------------------------------------
# GL002 — device computation at module import time
# ---------------------------------------------------------------------------

_GL002_PREFIXES = ("jnp.", "jax.numpy.", "jax.random.")
_GL002_EXACT = {"jax.device_put"}


def _gl002_call_hit(call: ast.Call) -> bool:
    f = dotted(call.func)
    if f is None:
        return False
    return f in _GL002_EXACT or any(f.startswith(p) for p in _GL002_PREFIXES)


def _check_module_scope_jnp(tree, lines, path):
    findings = []

    def scan(node):
        """Walk expressions evaluated at import time, skipping function
        and lambda BODIES (their defaults/decorators DO evaluate at
        import and are scanned)."""
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for d in node.decorator_list:
                scan(d)
            for default in (*node.args.defaults, *node.args.kw_defaults):
                if default is not None:
                    scan(default)
            return
        if isinstance(node, ast.Lambda):
            return
        if isinstance(node, ast.Call) and _gl002_call_hit(node):
            findings.append(_finding(
                "GL002", node,
                f"`{dotted(node.func)}(...)` runs at module import: it "
                f"allocates device memory / compiles before any jit, on "
                f"whatever backend import-time default is, and once per "
                f"process — build arrays inside the jitted fn or lazily",
                path, lines))
        for child in ast.iter_child_nodes(node):
            scan(child)

    for stmt in tree.body:
        scan(stmt)
    return findings


_register(Rule(
    id="GL002", name="module-scope-device-call",
    rationale=(
        "A `jnp.*` / `jax.random.*` call at module scope executes during "
        "import: it initializes the backend early (breaking later "
        "platform/flag configuration), allocates device memory that "
        "lives for the process, and runs eagerly un-jitted. Constants "
        "built this way also become committed arrays whose placement "
        "can split jit cache keys."),
    bad="""\
import jax.numpy as jnp
MASK = jnp.tril(jnp.ones((1024, 1024)))   # device alloc at import
""",
    good="""\
import numpy as np
MASK = np.tril(np.ones((1024, 1024)))     # host constant; or build
                                          # inside the jitted function
""",
    checker=_check_module_scope_jnp,
    project_checker=_project("check_device_call_at_import")))


# ---------------------------------------------------------------------------
# GL003 — PRNG key reuse (>= 2 consumers without split)
# ---------------------------------------------------------------------------

_KEY_SOURCES = {"jax.random.PRNGKey", "jax.random.key", "jax.random.split",
                "jax.random.fold_in", "random.PRNGKey", "random.split",
                "random.fold_in"}
_KEY_DERIVERS = {"jax.random.split", "jax.random.fold_in", "random.split",
                 "random.fold_in", "jax.random.clone"}


def _is_key_source(node: ast.expr) -> bool:
    if isinstance(node, ast.Call) and dotted(node.func) in _KEY_SOURCES:
        return True
    if isinstance(node, ast.Subscript):   # keys[0] of a split
        return _is_key_source(node.value)
    return False


class _KeyReuseScanner:
    """Linear, source-order walk of one function body. Tracks names
    bound to PRNG keys; any call consuming a key name (except
    split/fold_in derivation) counts one use — two uses without an
    intervening rebind is reuse. A consumption inside a loop deeper
    than the key's binding counts twice (the classic per-iteration
    reuse)."""

    def __init__(self, fn, lines, path):
        self.fn, self.lines, self.path = fn, lines, path
        self.keys: Dict[str, dict] = {}      # name -> {depth, uses}
        self.findings: List[Finding] = []
        self.depth = 0

    def run(self):
        for stmt in self.fn.body:
            self._stmt(stmt)
        return self.findings

    def _bind(self, name: str, value: Optional[ast.expr]):
        if value is not None and _is_key_source(value):
            self.keys[name] = {"depth": self.depth, "uses": 0,
                               "flagged": False}
        else:
            self.keys.pop(name, None)

    def _targets(self, target: ast.expr, value: Optional[ast.expr]):
        if isinstance(target, ast.Name):
            self._bind(target.id, value)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                if isinstance(e, ast.Name):
                    # tuple-unpack of a split: every element is a key
                    self._bind(e.id, value)

    def _consume(self, call: ast.Call):
        f = dotted(call.func)
        derive = f in _KEY_DERIVERS
        for arg in (*call.args, *(kw.value for kw in call.keywords)):
            if isinstance(arg, ast.Name) and arg.id in self.keys:
                rec = self.keys[arg.id]
                if derive:
                    continue
                rec["uses"] += 2 if self.depth > rec["depth"] else 1
                if rec["uses"] >= 2 and not rec["flagged"]:
                    rec["flagged"] = True
                    self.findings.append(_finding(
                        "GL003", call,
                        f"PRNG key `{arg.id}` consumed more than once "
                        f"without jax.random.split — every consumer sees "
                        f"the SAME randomness (correlated samples); split "
                        f"or fold_in a fresh key per consumer",
                        self.path, self.lines))

    def _expr(self, node: ast.AST):
        for call in ast.walk(node):
            if isinstance(call, ast.Call):
                self._consume(call)

    def _stmt(self, stmt: ast.stmt):
        if isinstance(stmt, ast.Assign):
            self._expr(stmt.value)
            for t in stmt.targets:
                self._targets(t, stmt.value)
        elif isinstance(stmt, ast.AnnAssign):
            if stmt.value is not None:
                self._expr(stmt.value)
            if isinstance(stmt.target, ast.Name):
                self._bind(stmt.target.id, stmt.value)
        elif isinstance(stmt, ast.AugAssign):
            self._expr(stmt.value)
            if isinstance(stmt.target, ast.Name):
                self.keys.pop(stmt.target.id, None)
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._expr(stmt.iter)
            self.depth += 1
            self._targets(stmt.target, stmt.iter)
            for s in (*stmt.body, *stmt.orelse):
                self._stmt(s)
            self.depth -= 1
        elif isinstance(stmt, ast.While):
            self._expr(stmt.test)
            self.depth += 1
            for s in (*stmt.body, *stmt.orelse):
                self._stmt(s)
            self.depth -= 1
        elif isinstance(stmt, ast.If):
            self._expr(stmt.test)
            # branches are mutually exclusive: walk each from the same
            # pre-branch state and keep the worst-case use count per key
            # (a consumer in `if` plus one in `else` is NOT reuse)
            snap = {n: dict(rec) for n, rec in self.keys.items()}
            for s in stmt.body:
                self._stmt(s)
            after_body = self.keys
            self.keys = snap
            for s in stmt.orelse:
                self._stmt(s)
            # a body that cannot fall through (return/raise) contributes
            # nothing to the statements after the If — the fall-through
            # path IS the implicit else
            terminal = (ast.Return, ast.Raise, ast.Continue, ast.Break)
            if stmt.body and isinstance(stmt.body[-1], terminal):
                return
            for n, rec in after_body.items():
                if n in self.keys:
                    cur = self.keys[n]
                    cur["uses"] = max(cur["uses"], rec["uses"])
                    cur["flagged"] = cur["flagged"] or rec["flagged"]
                else:
                    self.keys[n] = rec
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._expr(item.context_expr)
            for s in stmt.body:
                self._stmt(s)
        elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            pass                    # nested defs get their own scan
        elif isinstance(stmt, (ast.Return, ast.Expr)):
            if stmt.value is not None:
                self._expr(stmt.value)
        elif isinstance(stmt, ast.Try):
            for s in (*stmt.body, *stmt.orelse, *stmt.finalbody):
                self._stmt(s)
            for h in stmt.handlers:
                for s in h.body:
                    self._stmt(s)
        else:
            self._expr(stmt)


def _check_key_reuse(tree, lines, path):
    findings = []
    for fn in _all_functions(tree):
        findings.extend(_KeyReuseScanner(fn, lines, path).run())
    return findings


_register(Rule(
    id="GL003", name="rng-key-reuse",
    rationale=(
        "jax.random is splittable, not stateful: passing one key to two "
        "consumers gives both the SAME stream. Correlated dropout masks "
        "or init tensors are silent statistical corruption — the run "
        "trains, the loss curve just quietly lies. A consumer inside a "
        "loop over the key's binding reuses it every iteration."),
    bad="""\
key = jax.random.PRNGKey(0)
a = jax.random.normal(key, (8,))
b = jax.random.normal(key, (8,))      # identical to `a`
""",
    good="""\
key = jax.random.PRNGKey(0)
ka, kb = jax.random.split(key)
a = jax.random.normal(ka, (8,))
b = jax.random.normal(kb, (8,))
""",
    checker=_check_key_reuse))


# ---------------------------------------------------------------------------
# GL004 — host-device sync inside step loops
# ---------------------------------------------------------------------------

_GL004_FUNCS = {"np.asarray": "np.asarray", "numpy.asarray": "np.asarray",
                "np.array": "np.array", "numpy.array": "np.array",
                "jax.device_get": "jax.device_get"}


def _check_host_sync_in_loop(tree, lines, path):
    findings = []

    def scan(node, loop_depth):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            body = node.body if not isinstance(node, ast.Lambda) else []
            for child in body:
                scan(child, 0)       # fresh function: loop depth resets
            return
        if isinstance(node, (ast.For, ast.AsyncFor, ast.While)):
            loop_depth += 1
        if loop_depth > 0 and isinstance(node, ast.Call):
            what = None
            f = dotted(node.func)
            if (isinstance(node.func, ast.Attribute)
                    and node.func.attr == "item" and not node.args):
                what = ".item()"
            elif f in _GL004_FUNCS:
                what = _GL004_FUNCS[f]
            elif (isinstance(node.func, ast.Name)
                  and node.func.id == "float" and len(node.args) == 1
                  and not isinstance(node.args[0], ast.Constant)):
                what = "float(...)"
            if what:
                findings.append(_finding(
                    "GL004", node,
                    f"`{what}` inside a loop forces a device->host sync "
                    f"every iteration (stalls the dispatch pipeline); "
                    f"accumulate on device and fetch once after the loop",
                    path, lines))
        for child in ast.iter_child_nodes(node):
            scan(child, loop_depth)

    for stmt in tree.body:
        scan(stmt, 0)
    return findings


_register(Rule(
    id="GL004", name="host-sync-in-loop",
    rationale=(
        "`float()` / `.item()` / `np.asarray()` on a device value blocks "
        "until the device finishes — inside a step loop that's one full "
        "pipeline stall per iteration (the TPUv4 pjit postmortem "
        "attributes most lost time to exactly these host stalls, not "
        "FLOPs). This package's eval loop paid one round-trip per eval "
        "batch until the PR that introduced this linter fixed it."),
    bad="""\
total = 0.0
for _ in range(k):
    total += float(eval_step(params, batch))   # sync per batch
""",
    good="""\
total = None
for _ in range(k):
    loss = eval_step(params, batch)            # stays on device
    total = loss if total is None else total + loss
mean = float(total) / k                        # ONE sync per split
""",
    checker=_check_host_sync_in_loop,
    project_checker=_project("check_sync_through_helpers")))


# ---------------------------------------------------------------------------
# GL005 — jit over state/cache pytrees without donation
# ---------------------------------------------------------------------------

_DONATABLE = {"state", "opt_state", "cache", "kv_cache", "caches",
              "train_state", "carry"}


def _check_missing_donation(tree, lines, path):
    findings = []
    module_fns = {fn.name: fn for fn in _all_functions(tree)}

    def check(fn: ast.FunctionDef, site: ast.AST, kwargs):
        if "donate_argnums" in kwargs or "donate_argnames" in kwargs:
            return
        hit = sorted(set(_param_names(fn)) & _DONATABLE)
        if hit:
            findings.append(_finding(
                "GL005", site,
                f"jit of `{fn.name}` takes {', '.join(hit)} but donates "
                f"nothing — without donate_argnums/donate_argnames the "
                f"old buffers stay live across the call, doubling HBM "
                f"for update-in-place state (OOM at exactly the model "
                f"size that otherwise fits)",
                path, lines))

    for fn in _all_functions(tree):
        dec = _jit_decorator(fn)
        if dec is not None:
            check(fn, dec, _jit_kwargs(dec))
    for node in ast.walk(tree):
        call = _jit_wrap_call(node)
        if call is None or not call.args:
            continue
        # jax.jit(f, ...) / partial(jax.jit, f, ...) with f a plain
        # function defined in this module
        first = call.args[0]
        if dotted(first) in _JIT_WRAPPERS:        # the partial spelling
            if len(call.args) < 2:
                continue
            first = call.args[1]
        if isinstance(first, ast.Name) and first.id in module_fns:
            check(module_fns[first.id], call, _jit_kwargs(node))
    return findings


_register(Rule(
    id="GL005", name="missing-donation",
    rationale=(
        "A jitted update step that takes a large pytree (train state, KV "
        "cache) and returns its successor keeps BOTH alive unless the "
        "input is donated — the peak-HBM doubling that decides whether "
        "a model fits. Donation also lets XLA alias the update in "
        "place. Heuristic: parameters named state/cache/opt_state/... "
        "are update-in-place pytrees."),
    bad="""\
@jax.jit
def update(state, batch):        # old state buffers stay live
    return state.apply(batch)
""",
    good="""\
@partial(jax.jit, donate_argnames=("state",))
def update(state, batch):        # old buffers reused for the new state
    return state.apply(batch)
""",
    checker=_check_missing_donation,
    project_checker=_project("check_use_after_donate")))


# ---------------------------------------------------------------------------
# GL006 — dynamic_update_slice without an in-bounds guard
# ---------------------------------------------------------------------------

_DUS = {"jax.lax.dynamic_update_slice", "lax.dynamic_update_slice",
        "jax.lax.dynamic_update_slice_in_dim",
        "lax.dynamic_update_slice_in_dim"}
_BOUNDS_GUARDS = ("check_in_bounds", "assert_in_bounds", "checkify.check")


def _const_like(node: ast.expr, const_names: set) -> bool:
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, ast.Name):
        return node.id in const_names
    if isinstance(node, ast.Call):
        f = dotted(node.func)
        if f in ("jnp.int32", "jnp.uint32", "int") and node.args:
            return _const_like(node.args[0], const_names)
    if isinstance(node, ast.UnaryOp):
        return _const_like(node.operand, const_names)
    return False


def _check_unguarded_dus(tree, lines, path):
    findings = []
    for fn in _top_level_functions(tree):
        # one-level local constant/tuple resolution
        assigns: Dict[str, ast.expr] = {}
        for node in ast.walk(fn):
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)):
                assigns[node.targets[0].id] = node.value
        const_names = {n for n, v in assigns.items()
                       if _const_like(v, set())}
        # clamped names: bound from jnp.minimum / jnp.clip / `%`
        clamped = {n for n, v in assigns.items()
                   if (isinstance(v, ast.Call)
                       and dotted(v.func) in ("jnp.minimum", "jnp.clip",
                                              "jax.numpy.minimum",
                                              "jax.numpy.clip"))
                   or (isinstance(v, ast.BinOp)
                       and isinstance(v.op, ast.Mod))}
        # blessing: a sanctioned guard call anywhere in the function, or
        # an `assert` naming one of the start indices
        guard_called = any(
            isinstance(n, ast.Call)
            and dotted(n.func) is not None
            and (dotted(n.func) in _BOUNDS_GUARDS
                 or dotted(n.func).split(".")[-1] in _BOUNDS_GUARDS)
            for n in ast.walk(fn))
        assert_names: set = set()
        for n in ast.walk(fn):
            if isinstance(n, ast.Assert):
                assert_names |= {x.id for x in ast.walk(n.test)
                                 if isinstance(x, ast.Name)}

        for call in ast.walk(fn):
            if not (isinstance(call, ast.Call)
                    and dotted(call.func) in _DUS):
                continue
            if guard_called:
                continue
            start_args = call.args[2:]
            names: set = set()
            for a in start_args:
                if isinstance(a, ast.Name) and a.id in assigns:
                    a = assigns[a.id]
                for x in ast.walk(a):
                    if isinstance(x, ast.Name):
                        names.add(x.id)
            nonconst = {n for n in names if n not in const_names}
            if not nonconst:
                continue
            if nonconst & clamped or nonconst & assert_names:
                continue
            findings.append(_finding(
                "GL006", call,
                f"dynamic_update_slice start index ({', '.join(sorted(nonconst))}) "
                f"has no in-bounds guard in `{fn.name}` — out-of-bounds "
                f"starts silently CLAMP and overwrite valid earlier data "
                f"(the serving prefill corruption bug); add "
                f"check_in_bounds(...) (utils.sanitize) or an assert on "
                f"the index",
                path, lines))
    return findings


_register(Rule(
    id="GL006", name="unguarded-dynamic-update-slice",
    rationale=(
        "`jax.lax.dynamic_update_slice` does not raise on out-of-bounds "
        "start indices: it CLAMPS them, silently overwriting valid "
        "earlier data. PR 1's chunked-prefill bug corrupted KV-cache "
        "entries exactly this way. The sanctioned pattern is a "
        "`check_in_bounds(start, length, size)` call "
        "(utils.sanitize) — or an `assert` naming the index — in the "
        "same function."),
    bad="""\
def write(buf, row, pos):
    return jax.lax.dynamic_update_slice(buf, row, (pos, 0))
""",
    good="""\
from replicatinggpt_tpu.utils.sanitize import check_in_bounds

def write(buf, row, pos):
    check_in_bounds(pos, row.shape[0], buf.shape[0])  # asserts when
    return jax.lax.dynamic_update_slice(buf, row, (pos, 0))  # concrete
""",
    checker=_check_unguarded_dus))


# ---------------------------------------------------------------------------
# GL007 — non-hashable values for static jit parameters
# ---------------------------------------------------------------------------

_MUTABLE_LITERALS = (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp,
                     ast.SetComp)


def _check_unhashable_static(tree, lines, path):
    findings = []
    # jitted defs and their static param names
    static_of: Dict[str, set] = {}
    for fn in _all_functions(tree):
        dec = _jit_decorator(fn)
        if dec is None:
            continue
        static = _static_param_names(fn, _jit_kwargs(dec))
        if static:
            static_of[fn.name] = static
        # (a) static param whose DEFAULT is a mutable literal
        a = fn.args
        params = [p.arg for p in (*a.posonlyargs, *a.args)]
        for p, d in zip(params[len(params) - len(a.defaults):], a.defaults):
            if p in static and isinstance(d, _MUTABLE_LITERALS):
                findings.append(_finding(
                    "GL007", d,
                    f"static arg `{p}` of jitted `{fn.name}` defaults to a "
                    f"non-hashable {type(d).__name__.lower()} — jit "
                    f"statics are dict keys; this raises "
                    f"`unhashable type` at the first call (use a tuple / "
                    f"frozen dataclass)",
                    path, lines))
        for p, d in zip([p.arg for p in a.kwonlyargs], a.kw_defaults):
            if d is not None and p in static and isinstance(d, _MUTABLE_LITERALS):
                findings.append(_finding(
                    "GL007", d,
                    f"static arg `{p}` of jitted `{fn.name}` defaults to a "
                    f"non-hashable {type(d).__name__.lower()}",
                    path, lines))
    # assigned wrappers: g = jax.jit(f, static_argnames=(...))
    for node in ast.walk(tree):
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            call = _jit_wrap_call(node.value)
            if call is not None:
                statics = set(_const_str_items(
                    _jit_kwargs(node.value).get("static_argnames")))
                if statics:
                    static_of[node.targets[0].id] = statics
    # (b) callsites passing a mutable literal to a known static kwarg
    for call in ast.walk(tree):
        if not isinstance(call, ast.Call):
            continue
        name = dotted(call.func)
        statics = static_of.get(name or "", set())
        if not statics:
            continue
        for kw in call.keywords:
            if kw.arg in statics and isinstance(kw.value, _MUTABLE_LITERALS):
                findings.append(_finding(
                    "GL007", kw.value,
                    f"call passes a non-hashable "
                    f"{type(kw.value).__name__.lower()} as static arg "
                    f"`{kw.arg}` of jitted `{name}` — raises `unhashable "
                    f"type: ...` (pass a tuple / frozen value)",
                    path, lines))
    return findings


_register(Rule(
    id="GL007", name="unhashable-static-arg",
    rationale=(
        "jit's static arguments become cache-dictionary keys: a list / "
        "dict / set value raises `TypeError: unhashable type` at call "
        "time — and a mutable-but-hashable value is worse, silently "
        "splitting the cache per identity. Statics should be tuples, "
        "strings, numbers, or frozen dataclasses (like this package's "
        "ModelConfig)."),
    bad="""\
@partial(jax.jit, static_argnames=("dims",))
def pool(x, dims=[1, 2]):        # unhashable at first call
    return x.sum(tuple(dims))
""",
    good="""\
@partial(jax.jit, static_argnames=("dims",))
def pool(x, dims=(1, 2)):        # hashable static
    return x.sum(dims)
""",
    checker=_check_unhashable_static))


# ---------------------------------------------------------------------------
# GL008 — pmap/shard_map bodies capturing module globals
# ---------------------------------------------------------------------------

_SPMD_WRAPPERS = {"jax.pmap", "pmap", "shard_map",
                  "jax.experimental.shard_map.shard_map"}


def _spmd_decorator(fn: ast.FunctionDef) -> bool:
    for dec in fn.decorator_list:
        if dotted(dec) in _SPMD_WRAPPERS:
            return True
        if isinstance(dec, ast.Call):
            f = dotted(dec.func)
            if f in _SPMD_WRAPPERS:
                return True
            if f in _PARTIAL and dec.args and dotted(dec.args[0]) in _SPMD_WRAPPERS:
                return True
    return False


def _check_spmd_global_capture(tree, lines, path):
    # module-scope mutable-looking globals: lowercase simple assignments
    globals_: set = set()
    for stmt in tree.body:
        if isinstance(stmt, ast.Assign):
            for t in stmt.targets:
                if (isinstance(t, ast.Name) and not t.id.startswith("__")
                        and not t.id.isupper()
                        and not isinstance(stmt.value,
                                           (ast.Lambda, ast.Constant))):
                    globals_.add(t.id)
    if not globals_:
        return []
    # functions handed to pmap/shard_map by name
    spmd_fns = {fn.name for fn in _all_functions(tree) if _spmd_decorator(fn)}
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call) and dotted(node.func) in _SPMD_WRAPPERS
                and node.args and isinstance(node.args[0], ast.Name)):
            spmd_fns.add(node.args[0].id)
    findings = []
    for fn in _all_functions(tree):
        if fn.name not in spmd_fns:
            continue
        local = set(_param_names(fn))
        for n in ast.walk(fn):
            if isinstance(n, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                tgts = n.targets if isinstance(n, ast.Assign) else [n.target]
                for t in tgts:
                    for x in ast.walk(t):
                        if isinstance(x, ast.Name):
                            local.add(x.id)
        seen = set()
        for n in ast.walk(fn):
            if (isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)
                    and n.id in globals_ and n.id not in local
                    and n.id not in seen):
                seen.add(n.id)
                findings.append(_finding(
                    "GL008", n,
                    f"`{fn.name}` runs under pmap/shard_map but captures "
                    f"module global `{n.id}` — captured arrays are "
                    f"broadcast into every program (replicated HBM copy, "
                    f"silent retrace when rebound); pass it as an "
                    f"argument with an explicit spec",
                    path, lines))
    return findings


_register(Rule(
    id="GL008", name="spmd-global-capture",
    rationale=(
        "A function run under pmap/shard_map that closes over a module "
        "global embeds that value into the compiled program: arrays get "
        "broadcast to every device (a full replicated copy in HBM, "
        "outside any sharding spec), and rebinding the global later "
        "does nothing — or forces a retrace. Per-device data must "
        "arrive as arguments with explicit specs."),
    bad="""\
table = jnp.zeros((50_000, 512))     # module global

def embed(ids):
    return table[ids]                # broadcast into every program

embed_p = jax.pmap(embed)
""",
    good="""\
def embed(table, ids):               # explicit argument
    return table[ids]

embed_p = jax.pmap(embed, in_axes=(None, 0))
""",
    checker=_check_spmd_global_capture))


# ---------------------------------------------------------------------------
# GL009 — broad except swallowing checkpoint / device I/O failures
# ---------------------------------------------------------------------------

# call footprints that mean "this try block does checkpoint or device
# I/O": last dotted segment (methods on managers, jax transfer calls)
# or a bare name (builtins). Tuned to this codebase's idioms — orbax
# manager methods, jax device transfer, raw file handles.
_GL009_IO_ATTRS = {"save", "restore", "restore_latest", "item_metadata",
                   "wait_until_finished", "device_get", "device_put",
                   "block_until_ready", "read_bytes", "write_bytes",
                   "read_text", "write_text"}
_GL009_IO_NAMES = {"open"}
_GL009_IO_PREFIXES = ("ocp.", "orbax.", "jax.device_", "os.")

_GL009_LOG_NAMES = {"print", "log", "warn", "warning", "error", "exception",
                    "debug", "info", "log_step", "log_eval"}

_BROAD_EXC = {"Exception", "BaseException"}


def _gl009_is_broad_handler(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:                                  # bare `except:`
        return True
    if isinstance(t, (ast.Name, ast.Attribute)):
        d = dotted(t)
        return d is not None and d.split(".")[-1] in _BROAD_EXC
    if isinstance(t, ast.Tuple):
        return any(dotted(e) is not None
                   and dotted(e).split(".")[-1] in _BROAD_EXC
                   for e in t.elts)
    return False


def _gl009_io_call(call: ast.Call) -> Optional[str]:
    f = dotted(call.func)
    if f is None:
        if isinstance(call.func, ast.Attribute) \
                and call.func.attr in _GL009_IO_ATTRS:
            return call.func.attr            # method on a computed object
        return None
    last = f.split(".")[-1]
    if last in _GL009_IO_ATTRS or f in _GL009_IO_NAMES:
        return f
    if any(f.startswith(p) for p in _GL009_IO_PREFIXES):
        return f
    return None


def _gl009_handler_swallows(handler: ast.ExceptHandler) -> bool:
    """True when the handler neither re-raises nor logs — the failure
    leaves no trace at all."""
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return False
        if isinstance(node, ast.Call):
            f = dotted(node.func)
            name = (f.split(".")[-1] if f
                    else getattr(node.func, "attr", ""))
            if name in _GL009_LOG_NAMES:
                return False
    return True


def _check_swallowed_io_except(tree, lines, path):
    findings = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Try):
            continue
        io_call = None
        for sub in node.body:
            for c in ast.walk(sub):
                if isinstance(c, ast.Call):
                    io_call = io_call or _gl009_io_call(c)
        if io_call is None:
            continue
        for handler in node.handlers:
            if not _gl009_is_broad_handler(handler):
                continue
            if not _gl009_handler_swallows(handler):
                continue
            findings.append(_finding(
                "GL009", handler,
                f"broad `except` swallows failures of `{io_call}(...)` "
                f"with no re-raise and no log — a corrupt/partial "
                f"checkpoint or failed device transfer disappears here "
                f"and resurfaces later as an unrelated cryptic error; "
                f"catch the narrow exception, or log/re-raise with the "
                f"step and path named",
                path, lines))
    return findings


_register(Rule(
    id="GL009", name="swallowed-io-except",
    rationale=(
        "`except Exception:` (or bare `except:`) around checkpoint or "
        "device I/O that neither re-raises nor logs erases the only "
        "evidence of a half-written checkpoint, a failed device "
        "transfer, or transient storage trouble. The failure then "
        "resurfaces steps later as a cryptic unrelated error — this "
        "package's restore path did exactly that, silently skipping "
        "its RNG-impl check on corrupt checkpoints until the "
        "robustness PR made corruption a named, typed error. Narrow "
        "the exception (OSError for transient I/O, KeyError for "
        "missing metadata) or convert it into a typed error naming "
        "the step."),
    bad="""\
def latest_rng_shape(mngr, step):
    try:
        return mngr.item_metadata(step)["state"]["rng"].shape
    except Exception:        # corrupt step vanishes here
        return None
""",
    good="""\
def latest_rng_shape(mngr, step):
    try:
        return mngr.item_metadata(step)["state"]["rng"].shape
    except (KeyError, TypeError, OSError) as e:
        raise CorruptCheckpointError(
            f"checkpoint step {step} is corrupt: {e}") from e
""",
    checker=_check_swallowed_io_except))


# ---------------------------------------------------------------------------
# GL010–GL014 — mesh/sharding hazard family (project-index passes; the
# implementations live in dataflow.py, next to the call-graph plumbing
# they share with the interprocedural upgrades above)
# ---------------------------------------------------------------------------

_register(Rule(
    id="GL010", name="spec-axis-not-in-mesh",
    rationale=(
        "A PartitionSpec naming an axis the mesh doesn't have is the "
        "silent version of a wrong layout: depending on context GSPMD "
        "either raises at lowering or treats the unknown axis as "
        "replicated — the array LOOKS sharded in the code and is not, "
        "so the program runs, just with a full copy per device and "
        "collectives that don't match the mental model. The pjit/TPUv4 "
        "scaling story is sharding-annotation consistency; this rule "
        "checks the half of it that is statically checkable (meshes "
        "whose axis names are literal)."),
    bad="""\
mesh = Mesh(devices, ("data", "model"))
s = NamedSharding(mesh, P("data", "seq"))   # 'seq' is not a mesh axis
""",
    good="""\
mesh = Mesh(devices, ("data", "seq", "model"))
s = NamedSharding(mesh, P("data", "seq"))   # every axis exists
""",
    project_checker=_project("check_spec_mesh_mismatch")))


_register(Rule(
    id="GL011", name="unsharded-global-in-annotated-program",
    rationale=(
        "A function whose program carries sharding annotations "
        "(in_shardings/out_shardings, shard_map, pjit) that closes over "
        "a module-level array built with plain jnp/np calls embeds that "
        "array OUTSIDE the sharding contract: it is baked into the "
        "program fully replicated on every device. For a lookup table "
        "or mask at model scale that's a full per-device HBM copy no "
        "spec accounts for — the exact waste the annotations were "
        "supposed to rule out."),
    bad="""\
table = jnp.zeros((50_000, 512))              # module scope, no sharding

@partial(jax.jit, in_shardings=(x_sharding,))
def embed(ids):
    return table[ids]                         # replicated capture
""",
    good="""\
@partial(jax.jit, in_shardings=(x_sharding, table_sharding))
def embed(ids, table):                        # explicit, spec'd argument
    return table[ids]
""",
    project_checker=_project("check_unsharded_global_capture")))


_register(Rule(
    id="GL012", name="shardings-arity-mismatch",
    rationale=(
        "in_shardings / in_specs zip positionally against the wrapped "
        "function's arguments (and out_shardings / out_specs against "
        "its returns). A literal tuple of the wrong length either "
        "raises at the first call — or worse, with optional trailing "
        "arguments, quietly shifts every spec onto the wrong parameter "
        "so the batch gets the weights' sharding and vice versa. The "
        "arity is statically checkable whenever the spec tuple is a "
        "literal; this rule checks exactly that and nothing more."),
    bad="""\
@partial(jax.jit, in_shardings=(x_shard, w_shard))
def apply(x, w, b):                  # 3 args, 2 specs: b inherits w's?
    return x @ w + b
""",
    good="""\
@partial(jax.jit, in_shardings=(x_shard, w_shard, b_shard))
def apply(x, w, b):                  # one spec per argument
    return x @ w + b
""",
    project_checker=_project("check_shardings_arity")))


_register(Rule(
    id="GL013", name="varying-scalar-into-shape-arg",
    rationale=(
        "A Python scalar that changes per loop iteration (the loop "
        "variable, a len() of a growing list) flowing into a parameter "
        "a jitted function uses in a shape — or declared static — "
        "compiles a fresh program per distinct value. This is the "
        "recompile-per-length death spiral: the run works at toy sizes "
        "and spends 90% of wall-clock in XLA at real ones. Pad to "
        "fixed buckets (what the serving engine's static slot/window "
        "shapes do) or keep the size a traced array dimension."),
    bad="""\
@partial(jax.jit, static_argnames=("n",))
def window(x, n):
    return x[:n] * jnp.ones((n,))

for i in range(steps):
    out = window(x, i)        # one fresh XLA program per i
""",
    good="""\
@partial(jax.jit, static_argnames=("n",))
def window(x, n):
    return x[:n] * jnp.ones((n,))

BUCKET = 128                  # pad sizes to a fixed bucket: one program
for i in range(steps):
    out = window(x, BUCKET)
""",
    project_checker=_project("check_varying_shape_args")))


_register(Rule(
    id="GL014", name="donated-closure-constant",
    rationale=(
        "Donating a buffer that the jitted body ALSO captures as a "
        "closure constant frees the very memory the compiled program "
        "holds a baked-in reference to: XLA reuses the donated pages "
        "for the output while the constant still points at them. The "
        "first call may even work; later calls read whatever the "
        "output overwrote — silent corruption, not a crash. If the "
        "buffer must be updated in place, pass it as the donated "
        "argument everywhere and drop the capture."),
    bad="""\
state = jnp.zeros((1024,))

@partial(jax.jit, donate_argnames=("s",))
def step(s):
    return s + state              # captures `state` as a constant

out = step(state)                 # ...and donates the same buffer
""",
    good="""\
@partial(jax.jit, donate_argnames=("s",))
def step(s, delta):
    return s + delta              # everything arrives as an argument

state = step(state, delta)
""",
    project_checker=_project("check_donated_closure_capture")))


# ---------------------------------------------------------------------------
# GL015 — host-blocking calls inside the windowed dispatch path
# ---------------------------------------------------------------------------

#: function-name prefixes marking the LAUNCH side of a double-buffered
#: dispatch path (the serving engine's `_launch*` family): code here runs
#: BETWEEN dispatching window N and fetching window N-1, so any blocking
#: fetch forfeits the overlap the whole async design exists to buy
_GL015_LAUNCH_PREFIXES = ("_launch",)
#: calls that force a host<->device sync (or drain the in-flight window)
_GL015_BLOCKING_NAMES = {"np.asarray", "numpy.asarray", "jax.device_get",
                         "jax.block_until_ready"}
_GL015_BLOCKING_ATTRS = {"_drain_pending", "_drain_window",
                         "block_until_ready", "item"}


def _check_windowed_host_block(tree: ast.Module, lines: Sequence[str],
                               path: str) -> List[Finding]:
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if not any(node.name.startswith(p)
                   for p in _GL015_LAUNCH_PREFIXES):
            continue
        for call in ast.walk(node):
            if not isinstance(call, ast.Call):
                continue
            f = dotted(call.func)
            hit = None
            if f in _GL015_BLOCKING_NAMES:
                hit = f
            elif (isinstance(call.func, ast.Attribute)
                  and call.func.attr in _GL015_BLOCKING_ATTRS):
                hit = call.func.attr
            if hit is not None:
                findings.append(_finding(
                    "GL015", call,
                    f"`{hit}(...)` inside `{node.name}` — the launch "
                    f"side of a windowed dispatch path must not block "
                    f"on (or drain) the in-flight window: a "
                    f"synchronous fetch here serializes host and "
                    f"device, silently re-creating the blocked "
                    f"step-per-dispatch loop the window path exists "
                    f"to amortize; fetch in the drain-side function "
                    f"(`_drain_window`) after the next window has "
                    f"launched",
                    path, lines))
    return findings


_register(Rule(
    id="GL015", name="windowed-path-host-block",
    rationale=(
        "The async serving engine's launch path (`_launch*`) runs "
        "between dispatching window N and fetching window N-1 — the "
        "host-runs-ahead overlap that amortizes the per-dispatch host "
        "tax (BENCH_r03's 4-5x). A blocking fetch (np.asarray of a "
        "device array, jax.device_get, .block_until_ready(), .item()) "
        "or a `_drain_pending()`/`_drain_window()` call introduced "
        "there serializes host against device on EVERY window and "
        "silently reverts the engine to blocked step-per-dispatch "
        "behavior — no error, no recompile, just the dispatch-split "
        "line quietly collapsing. Continuous windows made admissions, "
        "deadlines and cancels ride the dispatch as masks exactly so "
        "nothing needs to block at launch; keep every sync in the "
        "drain-side function, after the next window is in flight."),
    bad="""\
class Engine:
    def _launch(self, k):
        toks = np.asarray(self._inflight.toks)   # blocks mid-launch
        self._drain_pending()                    # breaks the window
        return self._dispatch(k)
""",
    good="""\
class Engine:
    def _launch(self, k):
        out = self._dispatch(k)      # enqueue only; no device wait
        out.copy_to_host_async()     # overlap the transfer
        return out

    def _drain_window(self, w):
        return np.asarray(w.toks)    # the ONE sync, at the boundary
""",
    checker=_check_windowed_host_block))


# ---------------------------------------------------------------------------
# GL016 — shared-filesystem assumptions on the router side of the fleet
# ---------------------------------------------------------------------------

#: reader calls that imply the caller can see the target file
_GL016_READERS = {"open", "load_jsonl_if_exists",
                  "RequestJournal.unfinished"}
#: attribute/name spellings of PER-WORKER artifact paths: a router
#: holding one of these and reading through it assumes the worker's
#: disk is mounted here
_GL016_PATH_NAMES = {"journal_path", "ready_file"}
#: string literals shaped like per-replica artifacts: flat
#: replica{i}.jsonl / worker{i}.jsonl names, the per-worker-dir
#: layout worker{i}/journal.jsonl, and ready files
_GL016_PATH_LITERAL = re.compile(
    r"(?:replica|worker)\d*[^/]*\.jsonl$"
    r"|(?:^|/)worker\d*/journal\.jsonl$"
    r"|\.ready(?:\.json)?$")


def _gl016_class_is_local(node: ast.ClassDef) -> bool:
    """A class declaring ``is_local = True`` at class level is the
    local-mode backend: its replica shares the router's filesystem by
    construction, so reading its own journal path is legitimate."""
    for stmt in node.body:
        if isinstance(stmt, ast.Assign):
            for t in stmt.targets:
                if (isinstance(t, ast.Name) and t.id == "is_local"
                        and isinstance(stmt.value, ast.Constant)
                        and stmt.value.value is True):
                    return True
    return False


def _gl016_worker_path_arg(call: ast.Call) -> Optional[str]:
    for arg in list(call.args) + [kw.value for kw in call.keywords]:
        for n in ast.walk(arg):
            if (isinstance(n, ast.Attribute)
                    and n.attr in _GL016_PATH_NAMES):
                return n.attr
            if isinstance(n, ast.Name) and n.id in _GL016_PATH_NAMES:
                return n.id
            if (isinstance(n, ast.Constant)
                    and isinstance(n.value, str)
                    and _GL016_PATH_LITERAL.search(n.value)):
                return repr(n.value)
    return None


def _check_fleet_shared_fs(tree: ast.Module, lines: Sequence[str],
                           path: str) -> List[Finding]:
    findings: List[Finding] = []

    def visit(node: ast.AST, exempt: bool) -> None:
        if isinstance(node, ast.ClassDef):
            exempt = exempt or _gl016_class_is_local(node)
        if isinstance(node, ast.Call) and not exempt:
            f = dotted(node.func)
            is_reader = (f in _GL016_READERS
                         or (isinstance(node.func, ast.Attribute)
                             and node.func.attr == "unfinished"))
            if is_reader:
                hit = _gl016_worker_path_arg(node)
                if hit is not None:
                    findings.append(_finding(
                        "GL016", node,
                        f"`{f or node.func.attr}(...)` reads a "
                        f"per-worker artifact ({hit}) on the router "
                        f"side of the fleet — a shared-filesystem "
                        f"assumption: the worker's disk may be on "
                        f"another machine (or gone entirely, the "
                        f"host-loss case). Reconcile through the "
                        f"backend's `journal_state()` (journal_drain "
                        f"RPC for remote replicas) or the router's "
                        f"own ledger; only the local-mode backend "
                        f"(`is_local = True`) may touch a replica "
                        f"path directly",
                        path, lines))
        for child in ast.iter_child_nodes(node):
            visit(child, exempt)

    visit(tree, False)
    return findings


_register(Rule(
    id="GL016", name="fleet-shared-filesystem",
    rationale=(
        "The multi-host fleet's contract is that NO component reads "
        "another component's disk: workers journal locally, the "
        "router journals its own ledger, and reconciliation state "
        "crosses the RPC channel (register handshake, journal_drain "
        "frames). Router-side code that opens a worker's journal or "
        "a ready file works perfectly on one machine and silently "
        "pins the whole fleet to one filesystem — the moment a worker "
        "lands on another host (or its host vanishes, taking the "
        "journal with it), recovery reads an empty/missing file and "
        "requests are dropped or double-decoded. The in-process "
        "backend (`is_local = True`) is exempt: its replica shares "
        "the router's filesystem by construction."),
    bad="""\
class Router:
    def reconcile(self, rep):
        # the worker's journal may live on ANOTHER MACHINE
        return RequestJournal.unfinished(rep.journal_path)

    def await_worker(self, spec):
        with open(spec.ready_file) as f:   # ready-file handshake
            return json.load(f)
""",
    good="""\
class Replica:
    is_local = True                        # in-process: same disk

    def journal_state(self):
        return RequestJournal.unfinished(self.journal_path)

class Router:
    def reconcile(self, rep):
        # the BACKEND owns journal access: local file or
        # journal_drain RPC — the router never sees a path
        return rep.journal_state()
""",
    checker=_check_fleet_shared_fs))


# ---------------------------------------------------------------------------
# GL017 — dtype drift: implicit upcasts in kernel bodies, uncast pool writes
# ---------------------------------------------------------------------------

#: a function whose parameter list carries this many ``*_ref`` names is
#: treated as a Pallas kernel body (the convention every kernel in
#: ops/ follows)
_GL017_MIN_REF_PARAMS = 2
#: root names of KV-pool-shaped arrays a scatter/dynamic_update_slice
#: may write into: the paged pool arrays (ck/cv), their quantization
#: scale arrays (cks/cvs), and anything called cache/pool
_GL017_POOL_NAME = re.compile(r"^(c[kv]s?|cc|cache|.*pool.*)$")


def _gl017_is_kernel_body(fn) -> bool:
    args = fn.args
    names = [a.arg for a in (args.posonlyargs + args.args
                             + args.kwonlyargs)]
    if args.vararg is not None:
        names.append(args.vararg.arg)
    return sum(n.endswith("_ref") for n in names) >= _GL017_MIN_REF_PARAMS


def _gl017_ref_load(node) -> Optional[str]:
    """The ``name_ref[...]`` spelling of a raw ref load, or None."""
    if (isinstance(node, ast.Subscript)
            and isinstance(node.value, ast.Name)
            and node.value.id.endswith("_ref")):
        return node.value.id
    return None


def _gl017_is_astype_call(node) -> bool:
    return (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "astype")


def _gl017_pool_root(node) -> Optional[str]:
    """Root NAME of a pool-shaped write target: ``ck``, ``cache["k"]``
    (root ``cache``), ... — None when the base is not a plain name or
    does not look pool-shaped."""
    base = node
    while isinstance(base, ast.Subscript):
        base = base.value
    if isinstance(base, ast.Name) and _GL017_POOL_NAME.match(base.id):
        return base.id
    return None


def _gl017_value_casts_to_target_dtype(value: ast.AST) -> bool:
    """True when the written value contains an ``.astype(<x>.dtype)``
    call — the explicit store-dtype cast every pool write must carry."""
    for n in ast.walk(value):
        if _gl017_is_astype_call(n) and n.args:
            for a in ast.walk(n.args[0]):
                if isinstance(a, ast.Attribute) and a.attr == "dtype":
                    return True
    return False


def _check_dtype_drift(tree: ast.Module, lines: Sequence[str],
                       path: str) -> List[Finding]:
    findings: List[Finding] = []
    # half 1: implicit upcasts in Pallas kernel bodies — a raw
    # ``x_ref[...]`` load mixed with an explicitly-cast operand in one
    # arithmetic expression promotes by the REF's (implicit) dtype
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if not _gl017_is_kernel_body(node):
            continue
        for op in ast.walk(node):
            if not isinstance(op, ast.BinOp):
                continue
            sides = (op.left, op.right)
            for raw, cast in (sides, sides[::-1]):
                ref = _gl017_ref_load(raw)
                if ref is not None and _gl017_is_astype_call(cast):
                    findings.append(_finding(
                        "GL017", op,
                        f"raw `{ref}[...]` load mixed with an "
                        f"explicitly-cast operand in one expression "
                        f"inside kernel body `{node.name}` — the "
                        f"result dtype silently follows the ref's "
                        f"storage dtype (an int8/bf16 pool block "
                        f"upcasts or truncates here without a trace); "
                        f"bind the load to a name with an explicit "
                        f"`.astype(...)` first so the compute "
                        f"precision is visible at the use site",
                        path, lines))
                    break
    # half 2: mixed-dtype scatter / dynamic_update_slice writes into
    # pool-shaped arrays — quantized pools made the store dtype (int8/
    # fp8 rows, f32 scales) diverge from the compute dtype, so an
    # uncast write either promotes the whole pool buffer or silently
    # rounds through the wrong dtype
    for call in ast.walk(tree):
        if not isinstance(call, ast.Call):
            continue
        target = value = None
        f = dotted(call.func)
        if (isinstance(call.func, ast.Attribute)
                and call.func.attr in ("set", "add")
                and isinstance(call.func.value, ast.Subscript)
                and isinstance(call.func.value.value, ast.Attribute)
                and call.func.value.value.attr == "at"):
            # <target>.at[...].set(value)
            target = _gl017_pool_root(call.func.value.value.value)
            value = call.args[0] if call.args else None
        elif f in ("jax.lax.dynamic_update_slice",
                   "lax.dynamic_update_slice",
                   "dynamic_update_slice") and len(call.args) >= 2:
            target = _gl017_pool_root(call.args[0])
            value = call.args[1]
            # ONE exemption, for this spelling only: a bare-name value
            # into dynamic_update_slice is the COW page-copy idiom
            # (re-writing a slice OF the same pool — the dtype is
            # carried by construction). Scatter writes get no such
            # pass: `.at[...].set(k_m)` is the uncast fresh-row write
            # the rule exists to flag.
            if isinstance(value, ast.Name):
                continue
        if target is None or value is None:
            continue
        if not _gl017_value_casts_to_target_dtype(value):
            findings.append(_finding(
                "GL017", call,
                f"write into pool-shaped array `{target}` without an "
                f"explicit `.astype({target}.dtype)` on the value — "
                f"with quantized pools the store dtype (int8/fp8 rows, "
                f"f32 scales) differs from the compute dtype, and an "
                f"uncast scatter either type-promotes the whole pool "
                f"buffer (silent 2-4x HBM regression) or rounds "
                f"through the wrong dtype; cast the value to the "
                f"target's dtype at the write site",
                path, lines))
    return findings


_register(Rule(
    id="GL017", name="dtype-drift",
    rationale=(
        "Quantized KV pools (quant/) store int8/fp8 rows next to f32 "
        "scale arrays while compute runs in bf16/f32 — the one place "
        "in the codebase where three dtypes meet in a single "
        "expression. Two silent failure shapes: (1) inside a Pallas "
        "kernel body, a raw `x_ref[...]` load mixed into an "
        "expression whose other operand is explicitly `.astype(...)`-"
        "cast promotes by the ref's STORAGE dtype — an int8 page "
        "block scores attention in int arithmetic, or a bf16 block "
        "silently upcasts per element instead of once; (2) a scatter "
        "or dynamic_update_slice into a pool-shaped array whose value "
        "lacks `.astype(<target>.dtype)` relies on implicit casting — "
        "under type promotion the WRITE can promote the whole pool "
        "buffer (a silent 2-4x HBM regression), and with a quantized "
        "pool it rounds through the wrong dtype without an error. "
        "Both are one explicit cast away from unambiguous."),
    bad="""\
def _my_kernel(q_ref, kp_ref, out_ref, *, scale):
    # raw int8 ref load mixed with a cast operand: implicit upcast
    s = kp_ref[...] * q_ref[...].astype(jnp.float32)
    out_ref[...] = s

def write(ck, k_m, layer, phys, woff):
    # uncast scatter into the pool: promotes or mis-rounds the buffer
    return ck.at[layer, phys, woff, :].set(k_m, mode="drop")
""",
    good="""\
def _my_kernel(q_ref, kp_ref, out_ref, *, scale):
    kc = kp_ref[...].astype(jnp.float32)     # precision visible here
    s = kc * q_ref[...].astype(jnp.float32)
    out_ref[...] = s.astype(out_ref.dtype)

def write(ck, k_m, layer, phys, woff):
    return ck.at[layer, phys, woff, :].set(
        k_m.astype(ck.dtype), mode="drop")   # store dtype explicit
""",
    checker=_check_dtype_drift))


# ---------------------------------------------------------------------------
# GL018–GL023 — distributed-protocol & async-concurrency family (v3).
# All six are project_checker-only: the contracts they check (wire
# codecs, forwarding whitelists, metric schemas, trace pins) span files
# by construction. dataflow.py hosts the callgraph-walking pair
# (GL019/GL020); contracts.py hosts the contract-registry four.
# ---------------------------------------------------------------------------


_register(Rule(
    id="GL018", name="rpc-verb-contract",
    rationale=(
        "The fleet RPC wire protocol is JSON dicts over a framed "
        "socket: nothing type-checks the verb names or the per-verb "
        "request/response keys, so a key renamed on one side of the "
        "router/worker boundary fails at RUNTIME on the other — as a "
        "worker-side KeyError that downs the replica, or worse, a "
        "``.get()`` default silently zeroing a field every wire "
        "crossing (the drift class every fleet PR since PR 13 fixed "
        "by hand at review). Both sides are literal AST structure: "
        "``op_<verb>`` handlers on dispatch classes read "
        "``doc[\"k\"]`` (required) / ``doc.get(\"k\")`` or "
        "branch-guarded keys (optional) and return literal dicts; "
        "call sites name the verb and keys literally. The rule "
        "cross-checks verb existence in both directions, sent-vs-read "
        "request keys, caller reads vs returned response keys, and "
        "``<stem>_to_wire``/``<stem>_from_wire`` codec pairs. A "
        "``**spread`` on either side opens that set (no guessing); "
        "the checks engage only when a dispatch class or codec pair "
        "exists in the linted project."),
    bad="""\
class Worker:
    def dispatch(self, doc):
        return getattr(self, "op_" + doc.get("op"))(doc)
    def op_submit(self, doc):
        req = doc["req"]                     # required key
        return {"accepted": True}
    def op_drain(self, doc):                 # no caller anywhere: dead verb
        return {}

class Client:
    def __init__(self, call):
        self.call = call
    def submit(self, req):
        resp = self.call("submit", payload=req)   # sends 'payload',
        return resp["rejection"]                  # reads a key never returned
""",
    good="""\
class Worker:
    def dispatch(self, doc):
        return getattr(self, "op_" + doc.get("op"))(doc)
    def op_submit(self, doc):
        req = doc["req"]
        if not req:
            return {"accepted": False, "rejection": "empty"}
        return {"accepted": True}

class Client:
    def __init__(self, call):
        self.call = call
    def submit(self, req):
        resp = self.call("submit", req=req, timeout_s=1.0)
        if not resp["accepted"]:
            return resp["rejection"]
        return None
""",
    project_checker=_project("check_rpc_verb_contract")))


_register(Rule(
    id="GL019", name="async-blocking-call",
    rationale=(
        "The serving front door and the worker host are "
        "single-threaded asyncio loops: ONE blocking call inside any "
        "coroutine stalls every concurrent request, every /healthz "
        "probe, and every SSE heartbeat simultaneously (the PR 9 "
        "``/healthz`` hang was exactly this — a liveness probe stuck "
        "behind a sick worker's socket). Blocking hides behind "
        "helpers, so the check is interprocedural: socket "
        "``.recv()``, ``os.fsync``, ``time.sleep``, subprocess "
        "calls, and RPC ``.call(\"verb\", ...)`` sites with no "
        "explicit ``timeout_s`` budget are blocking sites, and any "
        "``async def`` that reaches one through sync calls — "
        "including through receiver types and abstract bases like "
        "``rep.submit(...)`` via ReplicaBase — is flagged at its "
        "call site with the full chain. Awaited calls never count "
        "(they yield), and a reviewed ``# graftlint: disable=GL019`` "
        "at the blocking site blesses every caller: use it for sites "
        "whose blocking is budgeted by construction (a socket under "
        "``settimeout``, deliberate chaos injection)."),
    bad="""\
import time

class Poller:
    def _backoff(self):
        time.sleep(0.5)                  # blocks the event loop

    async def tick(self, client):
        self._backoff()                  # reached from async def
        return client.call("health")     # untimed RPC: unbounded stall
""",
    good="""\
import asyncio

class Poller:
    async def tick(self, client, loop):
        await asyncio.sleep(0.5)         # yields instead of blocking
        return await loop.run_in_executor(
            None, lambda: client.call("health", timeout_s=1.0))
""",
    project_checker=_project("check_async_blocking_call")))


_register(Rule(
    id="GL020", name="unledgered-finish",
    rationale=(
        "Exactly-once delivery across crashes hangs on ONE seam: "
        "every terminal result must route through the crash ledger's "
        "``record_finish`` before (or with) its delivery-map store. "
        "A finish path that stores ``self.results[...]`` without the "
        "ledger write works perfectly until the next crash recovery, "
        "when the journal replays the request it never saw finish — "
        "double-delivering its stream to the client (the PR 13 "
        "ledger exists precisely to prevent this). The rule arms on "
        "classes that own a ``self.ledger``/``self.journal`` and "
        "flags any method storing into ``self.results`` without a "
        "``record_finish`` call in the same method."),
    bad="""\
class MiniRouter:
    def __init__(self, journal):
        self.journal = journal
        self.results = {}

    def on_finish(self, res):
        self.results[res.id] = res       # crash-recovery will resurrect it
""",
    good="""\
class MiniRouter:
    def __init__(self, journal):
        self.journal = journal
        self.results = {}

    def on_finish(self, res):
        if self.journal is not None:
            self.journal.record_finish(res.id, res.finish_reason)
        self.results[res.id] = res       # ledger first, then delivery
""",
    project_checker=_project("check_unledgered_finish")))


_register(Rule(
    id="GL021", name="counter-schema-drift",
    rationale=(
        "Dashboards and alerts index Prometheus counters BY NAME, and "
        "``Metrics.inc`` creates counters on first increment — so a "
        "counter absent from the pinned exposition schema "
        "(``PROM_PINNED_COUNTERS`` in utils/telemetry.py) reads as "
        "'no data' instead of 0 until its first event, which for "
        "failure counters is exactly when you needed the alert to "
        "have been armed. Drift goes both ways: an increment outside "
        "the pinned schema (a new fleet_* counter nobody pinned), "
        "and a pinned name no code path increments (a rename that "
        "left the schema behind — the exposition advertises a metric "
        "that can never move). Literal and resolvable-constant "
        "increment names check exactly; ``\"prefix_\" + reason`` "
        "increments match pins by prefix; a fully dynamic "
        "``inc(k)`` anywhere disables the never-incremented "
        "direction (it could increment anything). Skipped entirely "
        "when the linted project has no pins tuple."),
    bad="""\
PROM_PINNED_COUNTERS = (
    "fleet_requests_routed",
    "fleet_requeue_retries",             # nothing increments this
)

def step(metrics):
    metrics.inc("fleet_requests_routed")
    metrics.inc("fleet_replica_downs")   # incremented but not pinned
""",
    good="""\
PROM_PINNED_COUNTERS = (
    "fleet_requests_routed",
    "fleet_replica_downs",
)

def step(metrics):
    metrics.inc("fleet_requests_routed")
    metrics.inc("fleet_replica_downs")
    metrics.inc("engine_steps")          # outside the pinned families: fine
""",
    project_checker=_project("check_counter_schema_drift")))


_register(Rule(
    id="GL022", name="forwarded-flag-drift",
    rationale=(
        "``serve --multiproc`` respawns workers by RECONSTRUCTING the "
        "command line from the ``ENGINE_FORWARD_FLAGS`` / "
        "``ENGINE_FORWARD_SWITCHES`` whitelists — an ``EngineConfig`` "
        "knob the whitelist doesn't carry means a fleet of workers "
        "silently serving a DIFFERENT engine shape (pool, pages, "
        "decode window, mesh slice) than the operator asked for: the "
        "exact bug class PR 9's review caught by hand. Three drift "
        "directions, all literal AST: a builder keyword whose "
        "``args.<dest>`` read no whitelist entry carries; an "
        "``EngineConfig`` field the builder never passes (the flag "
        "surface cannot express it at all); and a stale whitelist "
        "row whose dest the builder no longer reads. The "
        "``MODEL_OVERRIDE_FLAGS`` dests are checked against "
        "``ModelConfig``'s fields the same way. Skipped when the "
        "linted project has no whitelist assignment."),
    bad="""\
ENGINE_FORWARD_FLAGS = (
    ("pool_size", "--pool-size"),
    ("stale_knob", "--stale-knob"),      # builder never reads it
)

class EngineConfig:
    pool_size: int = 8
    max_queue: int = 64
    page_size: int = 0                   # never passed: inexpressible

def engine_config_from_args(args):
    return EngineConfig(pool_size=args.pool_size,
                        max_queue=args.max_queue)   # not whitelisted
""",
    good="""\
ENGINE_FORWARD_FLAGS = (
    ("pool_size", "--pool-size"),
    ("max_queue", "--max-queue"),
    ("page_size", "--page-size"),
)

class EngineConfig:
    pool_size: int = 8
    max_queue: int = 64
    page_size: int = 0

def engine_config_from_args(args):
    return EngineConfig(pool_size=args.pool_size,
                        max_queue=args.max_queue,
                        page_size=args.page_size)
""",
    project_checker=_project("check_forwarded_flag_drift")))


_register(Rule(
    id="GL023", name="telemetry-span-contract",
    rationale=(
        "``tools/trace_check.py`` validates exported Chrome traces "
        "against named event envelopes (``TRACE_VALIDATED_NAMES``): "
        "request begin/end pairing, page_transfer spans, token "
        "instants, thread_name metadata. The validator and the "
        "emitters drift independently — a span renamed at the "
        "emission site leaves the validator pinning a name nothing "
        "emits, so ``check_trace`` either rejects every healthy "
        "trace or (worse) the validation goes dead and the soak "
        "gate stops checking anything. The rule collects every "
        "literal or constant-resolvable name passed to "
        "``begin/end/instant/complete/span/name_track`` and every "
        "``{\"ph\": ..., \"name\": ...}`` event literal, and flags "
        "pinned names with no emission site. Skipped when the "
        "linted project has no pins tuple."),
    bad="""\
TRACE_VALIDATED_NAMES = ("request", "token", "page_transfer")

def emit(t, track, rid):
    t.begin("request", track, id=rid)
    t.instant("token", track, index=0)   # 'page_transfer' never emitted
""",
    good="""\
TRACE_VALIDATED_NAMES = ("request", "token")

def emit(t, track, rid):
    t.begin("request", track, id=rid)
    t.instant("token", track, index=0)
    t.end("request", track)
""",
    project_checker=_project("check_telemetry_span_contract")))


_register(Rule(
    id="GL024", name="idempotent-mutating-verbs",
    rationale=(
        "Every retry ladder in the fleet is a duplicate-delivery "
        "generator: the router re-sends after a protocol error, a "
        "worker blind-retries registration when the response is "
        "lost, and netchaos (faults/netchaos.py) duplicates frames "
        "outright. A MUTATING verb (``RPC_MUTATING_VERBS`` in "
        "analysis/contracts.py: submit, page_transfer, "
        "journal_drain, register) that re-executes under any of "
        "these double-decodes a request, double-appends staged KV "
        "pages, or reconciles an attach twice — the exactly-once "
        "promise dies at the wire. The contract has three legs, "
        "all literal AST: the verb is declared in a module-global "
        "``*IDEMPOTENT*`` tuple next to its dispatch class; the "
        "dispatch/handler consults an idem-keyed reply cache (reads "
        "``'idem'`` and touches a ``*replies*`` attribute) so a "
        "duplicated call returns the cached reply; and every "
        "literal call site sends an explicit ``idem`` key. Skipped "
        "when the linted files contain no handler for a mutating "
        "verb."),
    bad="""\
class WorkerStub:
    def dispatch(self, doc):
        op = doc.get("op")
        fn = getattr(self, "op_" + op, None)
        if fn is None:
            raise ValueError(op)
        return fn(doc)          # no reply cache, no idem read

    def op_submit(self, doc):   # mutating: enqueues a request
        req = doc["req"]
        return {"accepted": bool(req)}

class ClientStub:
    def __init__(self, call):
        self.call = call

    def submit(self, req):
        # no idem key: a duplicated frame re-enqueues the request
        resp = self.call("submit", req=req, timeout_s=1.0)
        return resp["accepted"]
""",
    good="""\
IDEMPOTENT_VERBS = ("submit",)

class WorkerStub:
    def __init__(self):
        self._replies = {}

    def dispatch(self, doc):
        op = doc.get("op")
        fn = getattr(self, "op_" + op, None)
        if fn is None:
            raise ValueError(op)
        idem = doc.get("idem")
        if op in IDEMPOTENT_VERBS and idem is not None:
            cached = self._replies.get(idem)
            if cached is not None:
                return {**cached, "idem_hit": True}
        resp = fn(doc)
        if op in IDEMPOTENT_VERBS and idem is not None:
            self._replies[idem] = resp
        return resp

    def op_submit(self, doc):
        req = doc["req"]
        return {"accepted": bool(req)}

class ClientStub:
    def __init__(self, call):
        self.call = call
        self._seq = 0

    def submit(self, req):
        self._seq += 1
        resp = self.call("submit", req=req, timeout_s=1.0,
                         idem="sub.%d" % self._seq)
        return resp["accepted"]
""",
    project_checker=_project("check_idempotent_verb_contract")))


def all_rule_ids() -> List[str]:
    return sorted(RULES)
