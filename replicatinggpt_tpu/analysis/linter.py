"""graftlint driver: discovery, project indexing, dispatch, pragmas.

v2 pipeline — parse every target file ONCE, build the project-wide
call-graph index (callgraph.py), then run two checker kinds per rule:
the per-file syntactic pass and the project pass (dataflow.py) that
sees across functions and files. Findings from both merge under one
rule id and flow through the same pragma/severity/baseline machinery.

Discovery (no paths given) covers the package **plus** ``bench.py``,
``tools/*.py`` and ``tests/`` — nothing that executes JAX escapes the
hazard rules anymore. Findings are tiered by directory: ``tests/``
findings are *warnings* (reported, never fail the gate, never
baselined) because a test deliberately syncing to assert on a value is
the norm, not a hazard; everything else is an *error*. Fixture trees
named ``fixtures`` are skipped during directory expansion (they are
intentional bad code) but lint normally when named explicitly.

Pragmas (unchanged from v1, shared with callgraph summaries so a
suppressed sync site also stops interprocedural propagation):

- line-level: ``x = risky()  # graftlint: disable=GL004`` (or
  ``disable=GL004,GL006`` / ``disable=all``);
- file-level: ``# graftlint: disable-file=GL002`` anywhere in the file.

Suppressed findings are counted, not discarded silently — ``lint
--format json`` reports them so a pragma audit stays possible.
"""

from __future__ import annotations

import ast
import dataclasses
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, Iterable, List, Mapping, Optional, Sequence, Tuple

from .callgraph import ProjectIndex, parse_pragmas
from . import rules as rules_mod
from .rules import RULES, Finding

#: repo root when running from a checkout (analysis/ -> package -> root)
REPO_ROOT = Path(__file__).resolve().parents[2]
DEFAULT_TARGET = Path(__file__).resolve().parents[1]

#: default discovery set beyond the package (repo-root relative; only
#: entries that exist are linted, so an installed package degrades to
#: package-only linting)
EXTRA_TARGETS = ("bench.py", "tools", "tests")

#: per-directory severity: longest matching label prefix wins; paths
#: with no match are errors. The CLI exposes this as --severity.
DEFAULT_SEVERITY: Mapping[str, str] = {"tests/": "warning"}

#: directory names pruned during directory expansion
_PRUNE_DIRS = {"__pycache__", "fixtures"}


@dataclass
class LintResult:
    findings: List[Finding] = field(default_factory=list)   # errors
    warnings: List[Finding] = field(default_factory=list)
    suppressed: List[Finding] = field(default_factory=list)
    files: int = 0

    def extend(self, other: "LintResult") -> None:
        self.findings.extend(other.findings)
        self.warnings.extend(other.warnings)
        self.suppressed.extend(other.suppressed)
        self.files += other.files


@dataclass
class _FileCtx:
    label: str
    lines: Sequence[str]
    tree: Optional[ast.Module]          # None on syntax error
    error: Optional[Finding] = None


def iter_python_files(paths: Iterable[Path],
                      prune: bool = False) -> List[Path]:
    skip = _PRUNE_DIRS if prune else {"__pycache__"}
    out: List[Path] = []
    for p in paths:
        p = Path(p)
        if p.is_dir():
            out.extend(sorted(q for q in p.rglob("*.py")
                              if not set(q.parts) & skip))
        elif p.suffix == ".py":
            out.append(p)
    return out


def rel_label(path: Path) -> str:
    """Repo-relative, forward-slash label for a file (falls back to the
    absolute path outside the checkout) — the identity baselines key on."""
    p = Path(path).resolve()
    try:
        return p.relative_to(REPO_ROOT).as_posix()
    except ValueError:
        return p.as_posix()


def default_targets() -> List[Path]:
    targets: List[Path] = [DEFAULT_TARGET]
    for extra in EXTRA_TARGETS:
        p = REPO_ROOT / extra
        if p.exists():
            targets.append(p)
    return targets


def severity_for(label: str, severity: Mapping[str, str]) -> str:
    best = ""
    level = "error"
    for prefix, lvl in severity.items():
        if label.startswith(prefix) and len(prefix) > len(best):
            best, level = prefix, lvl
    return level


def _parse_file(source: str, label: str) -> _FileCtx:
    lines = source.splitlines()
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        return _FileCtx(label=label, lines=lines, tree=None, error=Finding(
            path=label, rule="GL000", line=e.lineno or 1, col=e.offset or 0,
            message=f"syntax error: {e.msg}", text=(e.text or "").strip()))
    return _FileCtx(label=label, lines=lines, tree=tree)


def _lint_files(ctxs: Sequence[_FileCtx],
                rule_ids: Sequence[str] = (),
                severity: Optional[Mapping[str, str]] = None) -> LintResult:
    """The v2 core: per-file syntactic passes + one project pass, then
    pragma filtering and severity tiering."""
    severity = DEFAULT_SEVERITY if severity is None else severity
    active = [RULES[r] for r in (rule_ids or sorted(RULES))]
    rules_mod._ALL_FUNCTIONS_CACHE.clear()
    res = LintResult(files=len(ctxs))

    parsed = [c for c in ctxs if c.tree is not None]
    raw: Dict[str, List[Finding]] = {c.label: [] for c in ctxs}
    for c in ctxs:
        if c.error is not None:
            raw[c.label].append(c.error)
    for rule in active:
        if rule.checker is not None:
            for c in parsed:
                for f in rule.checker(c.tree, c.lines, c.label):
                    raw.setdefault(f.path, []).append(f)
    if any(rule.project_checker is not None for rule in active):
        index = ProjectIndex.build(
            [(c.label, c.tree, c.lines) for c in parsed], sorted(RULES))
        for rule in active:
            if rule.project_checker is not None:
                for f in rule.project_checker(index):
                    raw.setdefault(f.path, []).append(f)

    pragmas = {c.label: parse_pragmas(c.lines, sorted(RULES)) for c in ctxs}
    for c in ctxs:
        per_line, per_file = pragmas[c.label]
        for f in sorted(raw.get(c.label, ()),
                        key=lambda f: (f.line, f.col, f.rule)):
            if f.rule in per_file or f.rule in per_line.get(f.line, set()):
                res.suppressed.append(f)
                continue
            lvl = severity_for(f.path, severity)
            if lvl != f.severity:
                f = dataclasses.replace(f, severity=lvl)
            (res.findings if lvl == "error" else res.warnings).append(f)
    return res


def lint_source(source: str, path: str,
                rule_ids: Sequence[str] = (),
                severity: Optional[Mapping[str, str]] = None) -> LintResult:
    """Lint one file's source text. ``path`` is the label findings carry
    (callers pass repo-relative paths so baselines are portable). The
    file is its own one-module project, so self-contained
    interprocedural findings still fire."""
    return _lint_files([_parse_file(source, path)], rule_ids, severity)


def lint_paths(paths: Sequence = (),
               rule_ids: Sequence[str] = (),
               severity: Optional[Mapping[str, str]] = None) -> LintResult:
    """Lint files/directories (default: the replicatinggpt_tpu package
    plus bench.py, tools/ and tests/). All targets are indexed together,
    so cross-file dataflow sees the whole target set."""
    explicit = [Path(p) for p in paths]
    files = (iter_python_files(explicit) if explicit
             else iter_python_files(default_targets(), prune=True))
    # overlapping targets (`lint pkg pkg/file.py`, a file listed twice)
    # must lint once: dedupe on the label identity findings carry
    ctxs, seen = [], set()
    for f in files:
        label = rel_label(f)
        if label not in seen:
            seen.add(label)
            ctxs.append(_parse_file(f.read_text(), label))
    return _lint_files(ctxs, rule_ids, severity)
