"""graftlint driver: file discovery, pragma handling, rule dispatch.

Pure host Python (no jax import): parse each file once, run every
registered rule over the tree, then drop findings suppressed by
pragmas. Two pragma forms:

- line-level: ``x = risky()  # graftlint: disable=GL004`` (or
  ``disable=GL004,GL006`` / ``disable=all``) — suppresses findings
  REPORTED on that line (for a multi-line statement, the line where it
  starts);
- file-level: ``# graftlint: disable-file=GL002`` anywhere in the file.

Suppressed findings are counted, not discarded silently — ``lint
--format json`` reports them so a pragma audit stays possible.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, Iterable, List, Sequence, Set, Tuple

from .rules import RULES, Finding

#: repo root when running from a checkout (analysis/ -> package -> root)
REPO_ROOT = Path(__file__).resolve().parents[2]
DEFAULT_TARGET = Path(__file__).resolve().parents[1]

_PRAGMA = re.compile(r"#\s*graftlint:\s*(disable(?:-file)?)\s*=\s*"
                     r"([A-Za-z0-9_,\s]+)")


@dataclass
class LintResult:
    findings: List[Finding] = field(default_factory=list)
    suppressed: List[Finding] = field(default_factory=list)
    files: int = 0

    def extend(self, other: "LintResult") -> None:
        self.findings.extend(other.findings)
        self.suppressed.extend(other.suppressed)
        self.files += other.files


def _parse_pragmas(lines: Sequence[str]) -> Tuple[Dict[int, Set[str]],
                                                  Set[str]]:
    """(line -> disabled rule ids, file-wide disabled ids). 'all' means
    every rule."""
    per_line: Dict[int, Set[str]] = {}
    per_file: Set[str] = set()
    for i, line in enumerate(lines, start=1):
        m = _PRAGMA.search(line)
        if not m:
            continue
        ids = {tok.strip().upper() for tok in m.group(2).split(",")
               if tok.strip()}
        if "ALL" in ids:
            ids = set(RULES) | {"ALL"}
        if m.group(1) == "disable-file":
            per_file |= ids
        else:
            per_line.setdefault(i, set()).update(ids)
    return per_line, per_file


def lint_source(source: str, path: str,
                rule_ids: Sequence[str] = ()) -> LintResult:
    """Lint one file's source text. ``path`` is the label findings carry
    (callers pass repo-relative paths so baselines are portable)."""
    res = LintResult(files=1)
    lines = source.splitlines()
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        res.findings.append(Finding(
            path=path, rule="GL000", line=e.lineno or 1, col=e.offset or 0,
            message=f"syntax error: {e.msg}",
            text=(e.text or "").strip()))
        return res
    per_line, per_file = _parse_pragmas(lines)
    active = [RULES[r] for r in (rule_ids or sorted(RULES))]
    found: List[Finding] = []
    for rule in active:
        found.extend(rule.checker(tree, lines, path))
    for f in sorted(found, key=lambda f: (f.line, f.col, f.rule)):
        if f.rule in per_file or f.rule in per_line.get(f.line, set()):
            res.suppressed.append(f)
        else:
            res.findings.append(f)
    return res


def iter_python_files(paths: Iterable[Path]) -> List[Path]:
    out: List[Path] = []
    for p in paths:
        p = Path(p)
        if p.is_dir():
            out.extend(sorted(q for q in p.rglob("*.py")
                              if "__pycache__" not in q.parts))
        elif p.suffix == ".py":
            out.append(p)
    return out


def rel_label(path: Path) -> str:
    """Repo-relative, forward-slash label for a file (falls back to the
    absolute path outside the checkout) — the identity baselines key on."""
    p = Path(path).resolve()
    try:
        return p.relative_to(REPO_ROOT).as_posix()
    except ValueError:
        return p.as_posix()


def lint_paths(paths: Sequence = (),
               rule_ids: Sequence[str] = ()) -> LintResult:
    """Lint files/directories (default: the replicatinggpt_tpu package)."""
    targets = [Path(p) for p in paths] or [DEFAULT_TARGET]
    res = LintResult()
    for f in iter_python_files(targets):
        res.extend(lint_source(f.read_text(), rel_label(f), rule_ids))
    return res
