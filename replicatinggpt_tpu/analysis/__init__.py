"""graftlint: JAX-hazard static analysis (pure AST — no jax import).

``python -m replicatinggpt_tpu lint`` is the entry point; see
docs/graftlint_rules.md for the rule reference and
utils/sanitize.py for the runtime half (CompileGuard, donation checks,
GRAFT_SANITIZE mode).
"""

from .baseline import (DEFAULT_BASELINE, check_ratchet,
                       diff_against_baseline, finding_key, load_baseline,
                       write_baseline)
from .callgraph import ProjectIndex
from .docgen import render_rule_docs
from .linter import (DEFAULT_SEVERITY, LintResult, lint_paths, lint_source,
                     severity_for)
from .rules import RULES, Finding, Rule, all_rule_ids

__all__ = ["DEFAULT_BASELINE", "DEFAULT_SEVERITY", "Finding", "LintResult",
           "ProjectIndex", "RULES", "Rule", "all_rule_ids", "check_ratchet",
           "diff_against_baseline", "finding_key", "lint_paths",
           "lint_source", "load_baseline", "render_rule_docs",
           "severity_for", "write_baseline"]
