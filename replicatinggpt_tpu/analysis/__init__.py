"""graftlint: JAX-hazard static analysis (pure AST — no jax import).

``python -m replicatinggpt_tpu lint`` is the entry point; see
docs/graftlint_rules.md for the rule reference and
utils/sanitize.py for the runtime half (CompileGuard, donation checks,
GRAFT_SANITIZE mode).
"""

from .baseline import (DEFAULT_BASELINE, diff_against_baseline,
                       finding_key, load_baseline, write_baseline)
from .docgen import render_rule_docs
from .linter import LintResult, lint_paths, lint_source
from .rules import RULES, Finding, Rule, all_rule_ids

__all__ = ["DEFAULT_BASELINE", "Finding", "LintResult", "RULES", "Rule",
           "all_rule_ids", "diff_against_baseline", "finding_key",
           "lint_paths", "lint_source", "load_baseline",
           "render_rule_docs", "write_baseline"]
