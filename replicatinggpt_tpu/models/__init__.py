from .gpt import init_params, forward, param_count, init_kv_cache, decode_step

__all__ = ["init_params", "forward", "param_count", "init_kv_cache",
           "decode_step"]
