"""GPT as pure functions over a parameter pytree.

Re-expresses the reference's module stacks (GPT1.py:100-212 and
GPT-2.py:22-128) as ``init_params(rng, cfg) -> params`` and
``forward(params, idx, cfg, ...) -> (logits, loss)``:

- fused QKV projection (the GPT-2.py:28 formulation; GPT1's per-head Python
  loop, GPT1.py:130-136, is strictly worse on any hardware),
- pre-LN residual blocks (GPT1.py:162-165 / GPT-2.py:76-79),
- learned positional embeddings (GPT1.py:170-171 / GPT-2.py:97),
- optional weight tying (GPT-2.py:104) / untied head (GPT1.py:174) via
  ``cfg.tied_head``,
- GELU or ReLU MLP via ``cfg.activation``,
- GPT-2-paper init (std 0.02, residual projections scaled by
  1/sqrt(2*n_layer)) — the reference *tags* this intent
  (NANOGPT_SCALE_INIT, GPT-2.py:31,59) but never applies it (SURVEY.md
  §8-Q4); here it is real.

Layer parameters are stacked along a leading (n_layer,) axis and the block
stack runs under ``lax.scan`` — one compiled block body regardless of depth,
which keeps compile time flat and maps cleanly onto pipeline/FSDP sharding.
A KV-cache decode path shares the same block body (one position per step)
for the lax.scan generation loop.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ..config import ModelConfig
from ..ops.attention import (cached_attention, full_causal_attention,
                             uint8_inverted_dropout,
                             windowed_cached_attention)
from ..utils.sanitize import check_in_bounds

Params = Dict[str, Any]


def _dtype(name: str):
    return {"float32": jnp.float32, "bfloat16": jnp.bfloat16,
            "float16": jnp.float16}[name]


# ---------------------------------------------------------------------------
# init
# ---------------------------------------------------------------------------

def init_params(rng: jax.Array, cfg: ModelConfig) -> Params:
    """Initialize the parameter pytree. Shapes (C = n_embd, L = n_layer):

    wte (V, C) · wpe (block, C) · per-layer stacked tensors with leading L ·
    final layernorm · optional untied lm_head (C, V).
    """
    cfg.validate()
    C, L, V = cfg.n_embd, cfg.n_layer, cfg.vocab_size
    pd = _dtype(cfg.param_dtype)
    std = cfg.init_std
    resid_std = std * (2 * L) ** -0.5
    keys = jax.random.split(rng, 8)

    def norm(key, shape, s):
        return (jax.random.normal(key, shape, jnp.float32) * s).astype(pd)

    blocks = {
        "ln1_scale": jnp.ones((L, C), pd),
        "ln1_bias": jnp.zeros((L, C), pd),
        "qkv_kernel": norm(keys[2], (L, C, 3 * C), std),
        "qkv_bias": jnp.zeros((L, 3 * C), pd),
        "attn_out_kernel": norm(keys[3], (L, C, C), resid_std),
        "attn_out_bias": jnp.zeros((L, C), pd),
        "ln2_scale": jnp.ones((L, C), pd),
        "ln2_bias": jnp.zeros((L, C), pd),
        "mlp_up_kernel": norm(keys[4], (L, C, 4 * C), std),
        "mlp_up_bias": jnp.zeros((L, 4 * C), pd),
        "mlp_down_kernel": norm(keys[5], (L, 4 * C, C), resid_std),
        "mlp_down_bias": jnp.zeros((L, C), pd),
    }
    params: Params = {
        "wte": norm(keys[0], (V, C), std),
        "wpe": norm(keys[1], (cfg.block_size, C), std),
        "blocks": blocks,
        "ln_f_scale": jnp.ones((C,), pd),
        "ln_f_bias": jnp.zeros((C,), pd),
    }
    if not cfg.tied_head:
        params["lm_head"] = norm(keys[6], (C, V), std)
    return params


def param_count(params: Params) -> int:
    return sum(x.size for x in jax.tree_util.tree_leaves(params))


# ---------------------------------------------------------------------------
# building blocks
# ---------------------------------------------------------------------------

def _layer_norm(x: jnp.ndarray, scale: jnp.ndarray, bias: jnp.ndarray,
                eps: float) -> jnp.ndarray:
    # LN statistics in float32 for bf16 stability; result back in x.dtype.
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(xf - mu), axis=-1, keepdims=True)
    y = (xf - mu) * jax.lax.rsqrt(var + eps)
    return (y * scale.astype(jnp.float32)
            + bias.astype(jnp.float32)).astype(x.dtype)


def _dropout(x: jnp.ndarray, rate: float, rng: Optional[jax.Array],
             train: bool) -> jnp.ndarray:
    # Residual/MLP dropout (GPT1.py:147). uint8-bits inverted dropout,
    # 1/256-quantized rate shared with every other dropout site — see
    # ops.attention.quantize_dropout_rate.
    if not train or rate <= 0.0 or rng is None:
        return x
    return uint8_inverted_dropout(x, rate, rng)


def _activation(x: jnp.ndarray, kind: str) -> jnp.ndarray:
    return jax.nn.gelu(x) if kind == "gelu" else jax.nn.relu(x)


def _wmm(h: jnp.ndarray, lp: Dict[str, jnp.ndarray], name: str,
         cd, aq: bool = False) -> jnp.ndarray:
    """``h @ lp[name]`` with weight-quantization dequant fused into the
    matmul: quantized params (quant/weights.py) store the kernel in
    int8/fp8 plus a per-OUTPUT-channel f32 ``<name>_scale`` vector, and
    per-output-channel scales commute through the contraction — so the
    dequant is one multiply on the output row, never a rematerialized
    full-precision weight. Unquantized params take the identical
    ``h @ W.astype(cd)`` path (the scale key is simply absent, a static
    pytree property — no recompile churn, one program per params
    structure).

    ``aq`` (W8A8): when the kernel is already int8, quantize the
    ACTIVATION rows too — per-row symmetric int8 (same ``max(amax/127,
    eps)`` scale law as quant/kv.py) into an int8 x int8 -> int32
    ``dot_general``, dequanted by the separable rank-1 scale product
    ``s_act (rows) x s_w (output channels)``. Rows-within-int8-range is
    exact in int32, so W8A8 divergence comes only from the activation
    rounding (bounded like the KV int8 budget). Falls through to the
    weight-only path when the kernel is not int8 (fp8 kernels keep
    f32-accumulated matmuls)."""
    s = lp.get(name + "_scale")
    if aq and s is not None and lp[name].dtype == jnp.int8:
        f = h.astype(jnp.float32)
        s_act = jnp.maximum(
            jnp.max(jnp.abs(f), axis=-1, keepdims=True) / 127.0, 1e-8)
        hq = jnp.clip(jnp.round(f / s_act), -127.0,
                      127.0).astype(jnp.int8)
        y = jax.lax.dot_general(
            hq, lp[name], (((hq.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32)
        return (y.astype(jnp.float32) * s_act
                * s.astype(jnp.float32)).astype(cd)
    y = h @ lp[name].astype(cd)
    if s is not None:
        y = y * s.astype(cd)
    return y


def _split_heads(x: jnp.ndarray, n_head: int) -> jnp.ndarray:
    B, T, C = x.shape
    return x.reshape(B, T, n_head, C // n_head).transpose(0, 2, 1, 3)


def _merge_heads(x: jnp.ndarray) -> jnp.ndarray:
    B, H, T, D = x.shape
    return x.transpose(0, 2, 1, 3).reshape(B, T, H * D)


def _block(x: jnp.ndarray, lp: Dict[str, jnp.ndarray], cfg: ModelConfig, *,
           rng: Optional[jax.Array], train: bool,
           attention_fn=None) -> jnp.ndarray:
    """One pre-LN transformer block over a full (B, T, C) sequence.

    ``attention_fn`` overrides the attention core (used by the ring-attention
    sequence-parallel path); default picks einsum/flash per cfg.
    """
    cd = x.dtype
    r_attn, r_drop1, r_drop2 = (jax.random.split(rng, 3)
                                if rng is not None else (None, None, None))
    h = _layer_norm(x, lp["ln1_scale"], lp["ln1_bias"], cfg.layernorm_eps)
    qkv = _wmm(h, lp, "qkv_kernel", cd) + lp["qkv_bias"].astype(cd)
    attn = None
    impl = cfg.attention_impl
    if attention_fn is not None:
        # mesh wrappers without head/seq sharding expose a packed-qkv
        # hook (parallel/sharded_flash.py) so sharded runs also skip the
        # head-layout round trip; None -> ordinary split-heads path
        packed_hook = getattr(attention_fn, "packed_qkv", None)
        if packed_hook is not None:
            attn = packed_hook(qkv, cfg.n_head, rng=r_attn, train=train)
    if attention_fn is None and impl in ("auto", "ring", "ulysses",
                                         "flash"):
        if impl != "flash":
            # seq-parallel impls ('ring'/'ulysses') only exist as sharded
            # wrappers (parallel/ring_attention.py, parallel/ulysses.py)
            # passed in via attention_fn; locally they degrade to the
            # dense/flash choice. FLASH_MIN_T is the measured v5e
            # crossover (19.2 vs 19.7 ms/step on the char-GPT workload at
            # T=256; 2.3x kernel speedup at 512x512 auto tiles made the
            # old T>=1024 threshold stale). Kernel-envelope and dropout
            # fallbacks belong to full_causal_attention/_pallas_supported
            # (one source of truth — attention-weight dropout runs
            # in-kernel on the Pallas path, and degrades to dense einsum
            # elsewhere).
            from ..ops.flash_attention import FLASH_MIN_T
            impl = "flash" if qkv.shape[1] >= FLASH_MIN_T else "einsum"
        if impl == "flash":
            # packed-heads kernel consumes the fused projection output
            # directly — no (B,T,H,D)<->(B,H,T,D) round trip on either
            # pass; None off the envelope -> split-heads path below
            from ..ops.flash_attention import packed_qkv_attention
            attn = packed_qkv_attention(qkv, cfg.n_head,
                                        dropout_rate=cfg.attn_dropout,
                                        rng=r_attn, train=train)
    if attn is None:
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q, k, v = (_split_heads(t, cfg.n_head) for t in (q, k, v))
        if attention_fn is not None:
            # seq-parallel cores (ring/Ulysses) apply attention-weight
            # dropout themselves from the per-block rng (per-device
            # streams derived inside their shard_map regions)
            attn = attention_fn(q, k, v, rng=r_attn, train=train)
        else:
            attn = full_causal_attention(
                q, k, v, dropout_rate=cfg.attn_dropout, rng=r_attn,
                train=train, impl=impl)
        attn = _merge_heads(attn)
    attn = _wmm(attn, lp, "attn_out_kernel", cd) + lp["attn_out_bias"].astype(cd)
    # Projection dropout: declared-but-unapplied in the reference
    # (GPT1.py:132,136, SURVEY.md §8-Q2); correct-by-default here.
    x = x + _dropout(attn, cfg.dropout, r_drop1, train)
    h = _layer_norm(x, lp["ln2_scale"], lp["ln2_bias"], cfg.layernorm_eps)
    h = _activation(_wmm(h, lp, "mlp_up_kernel", cd)
                    + lp["mlp_up_bias"].astype(cd), cfg.activation)
    h = _wmm(h, lp, "mlp_down_kernel", cd) + lp["mlp_down_bias"].astype(cd)
    return x + _dropout(h, cfg.dropout, r_drop2, train)


def _remat_policy(name: str):
    """Resolve cfg.remat_policy to a jax.checkpoint policy (None = save
    nothing, recompute the whole block — the 'full' default)."""
    if name == "full":
        return None
    if name == "dots":
        return jax.checkpoint_policies.dots_saveable
    if name == "dots_no_batch":
        return jax.checkpoint_policies.dots_with_no_batch_dims_saveable
    raise ValueError(f"remat_policy must be 'full', 'dots' or "
                     f"'dots_no_batch', got {name!r}")


def _run_blocks(x: jnp.ndarray, blocks: Dict[str, jnp.ndarray],
                cfg: ModelConfig, *, rng: Optional[jax.Array], train: bool,
                attention_fn=None) -> jnp.ndarray:
    L = cfg.n_layer

    def body(carry, inputs):
        lp, layer_idx = inputs
        r = (jax.random.fold_in(rng, layer_idx)
             if rng is not None else None)
        if cfg.remat:
            fn = jax.checkpoint(
                lambda c, p: _block(c, p, cfg, rng=r, train=train,
                                    attention_fn=attention_fn),
                policy=_remat_policy(cfg.remat_policy))
            return fn(carry, lp), None
        return _block(carry, lp, cfg, rng=r, train=train,
                      attention_fn=attention_fn), None

    layer_ids = jnp.arange(L)
    if cfg.use_layer_scan:
        x, _ = jax.lax.scan(body, x, (blocks, layer_ids))
        return x
    for i in range(L):
        lp = jax.tree_util.tree_map(lambda a: a[i], blocks)
        x, _ = body(x, (lp, layer_ids[i]))
    return x


# ---------------------------------------------------------------------------
# forward (training / full-sequence)
# ---------------------------------------------------------------------------

def forward(params: Params, idx: jnp.ndarray, cfg: ModelConfig, *,
            targets: Optional[jnp.ndarray] = None,
            rng: Optional[jax.Array] = None, train: bool = False,
            attention_fn=None, blocks_fn=None
            ) -> Tuple[jnp.ndarray, Optional[jnp.ndarray]]:
    """Full-sequence forward. idx: (B, T) int32.

    Always returns ``(logits, loss)``; loss is None without targets — the
    reference's asymmetric return (GPT-2.py:124-128) is normalized away.
    Cross-entropy is computed in float32 over flattened (B*T) positions
    (GPT1.py:186-192 semantics). Exception: with ``cfg.loss_chunk`` set
    and targets given, the chunked CE head returns ``(None, loss)`` —
    the full logits array is exactly what that mode avoids building. ``blocks_fn`` replaces the whole block
    stack (the pipeline-parallel schedule plugs in here); ``attention_fn``
    replaces just the attention core inside the default stack.
    """
    B, T = idx.shape
    cd = _dtype(cfg.dtype)
    # Out-of-range ids would silently clamp on TPU gathers; the reference
    # instead crashed (SURVEY.md §8-B1/B5). Config and tokenizer are
    # validated host-side in the pipeline instead.
    x = params["wte"].astype(cd)[idx] + params["wpe"].astype(cd)[:T]
    if blocks_fn is not None:
        x = blocks_fn(x, params["blocks"], cfg, rng=rng, train=train)
    else:
        x = _run_blocks(x, params["blocks"], cfg, rng=rng, train=train,
                        attention_fn=attention_fn)
    x = _layer_norm(x, params["ln_f_scale"], params["ln_f_bias"],
                    cfg.layernorm_eps)
    head = (params["wte"].astype(cd).T if cfg.tied_head
            else params["lm_head"].astype(cd))
    if targets is not None and cfg.loss_chunk:
        if (B * T) % cfg.loss_chunk != 0:
            # a silent fallback here would let an A/B arm measure the
            # one-shot head while claiming the chunked one (and forfeit
            # the HBM saving a config was chosen for) — fail loudly
            raise ValueError(
                f"loss_chunk={cfg.loss_chunk} must divide B*T="
                f"{B * T}; pick a divisor or set loss_chunk=0")
        return None, _chunked_ce_loss(x, head, targets, cfg.loss_chunk)
    logits = (x @ head).astype(jnp.float32)
    if targets is None:
        return logits, None
    import optax
    loss = optax.softmax_cross_entropy_with_integer_labels(
        logits.reshape(B * T, -1), targets.reshape(B * T)).mean()
    return logits, loss


def _chunked_ce_loss(x, head, targets, chunk: int) -> jnp.ndarray:
    """Cross-entropy without materializing the full (B*T, V) f32 logits:
    a lax.scan over ``chunk``-row slices computes each chunk's logits +
    per-row CE and accumulates the sum; the chunk body is jax.checkpoint
    so the backward recomputes chunk logits instead of storing them as
    scan residuals (full-logits storage is exactly what this avoids).
    Per-row math is identical to the unchunked head — rows are
    independent under softmax-CE — so only the final mean's reduction
    order differs (f32 sum). At GPT-2 vocab (V=50304, B=32, T=1024) the
    unchunked head round-trips a ~6.6 GB f32 logits array through HBM
    for loss + backward; chunked, the working set is chunk*V bytes.
    Trades one extra head matmul in the backward (~+10% model FLOPs at
    124M) for that traffic — measure before defaulting
    (cfg.loss_chunk=0 keeps the unchunked head)."""
    import optax
    N = x.shape[0] * x.shape[1]
    C = x.shape[-1]
    xf = x.reshape(N // chunk, chunk, C)
    tf = targets.reshape(N // chunk, chunk)

    @jax.checkpoint
    def body(acc, xs):
        xc, tc = xs
        lg = (xc @ head).astype(jnp.float32)
        return acc + optax.softmax_cross_entropy_with_integer_labels(
            lg, tc).sum(), None

    acc, _ = jax.lax.scan(body, jnp.zeros((), jnp.float32), (xf, tf))
    return acc / N


# ---------------------------------------------------------------------------
# KV-cache decode path (shared weights, single-position block body)
# ---------------------------------------------------------------------------

def _cached_qkv_merged(h_in, lp, cfg: ModelConfig, cd):
    """ln1 + fused QKV projection, heads still merged — the cache-path
    front half of a block as (B, T, C) q/k/v rows (one source of truth
    for the math that must produce identical K/V on decode and
    prefill). The packed cache layout writes these rows untouched."""
    aq = getattr(cfg, "act_quant", "none") == "int8"
    h = _layer_norm(h_in, lp["ln1_scale"], lp["ln1_bias"],
                    cfg.layernorm_eps)
    qkv = _wmm(h, lp, "qkv_kernel", cd, aq=aq) + lp["qkv_bias"].astype(cd)
    return jnp.split(qkv, 3, axis=-1)


def _cached_qkv(h_in, lp, cfg: ModelConfig, cd):
    """`_cached_qkv_merged` + head split — the (B, H, T, D) form the
    einsum attention cores consume."""
    q, k, v = _cached_qkv_merged(h_in, lp, cfg, cd)
    return tuple(_split_heads(t, cfg.n_head) for t in (q, k, v))


def _cached_block_tail(h_in, attn_merged, lp, cfg: ModelConfig, cd):
    """Output projection + residual + ln2 + MLP + residual — the
    cache-path back half of a block, shared by decode_step and prefill
    (no dropout: decode paths never train)."""
    aq = getattr(cfg, "act_quant", "none") == "int8"
    attn = (_wmm(attn_merged, lp, "attn_out_kernel", cd, aq=aq)
            + lp["attn_out_bias"].astype(cd))
    h_mid = h_in + attn
    h = _layer_norm(h_mid, lp["ln2_scale"], lp["ln2_bias"],
                    cfg.layernorm_eps)
    h = _activation(_wmm(h, lp, "mlp_up_kernel", cd, aq=aq)
                    + lp["mlp_up_bias"].astype(cd), cfg.activation)
    h = (_wmm(h, lp, "mlp_down_kernel", cd, aq=aq)
         + lp["mlp_down_bias"].astype(cd))
    return h_mid + h


def cache_seq_axis(cfg: ModelConfig) -> int:
    """Axis of the sequence dimension in the stacked KV cache — layout-
    dependent (callers that grow/measure the cache buffer must not
    hard-code it)."""
    return 2 if cfg.decode_cache_layout == "packed" else 3


def init_kv_cache(cfg: ModelConfig, batch: int, max_len: Optional[int] = None,
                  dtype=None) -> Dict[str, jnp.ndarray]:
    """Cache layout, stacked over layers for lax.scan:
    (L, B, H, S, D) for ``decode_cache_layout='heads'``, or the fully
    lane-packed (L, B, S, C) for ``'packed'`` (see the config field)."""
    S = max_len or cfg.block_size
    dt = dtype or _dtype(cfg.dtype)
    if cfg.decode_cache_layout == "packed":
        shape = (cfg.n_layer, batch, S, cfg.n_embd)
    else:
        shape = (cfg.n_layer, batch, cfg.n_head, S, cfg.head_dim)
    return {"k": jnp.zeros(shape, dt), "v": jnp.zeros(shape, dt)}


def _fused_decode_backend_ok() -> bool:
    """Pallas lowering gate for the fused decode kernel (tests
    monkeypatch this to exercise the interpret-mode kernel on CPU)."""
    return jax.default_backend() == "tpu"


def _all_single_device(tree) -> bool:
    """True when every array leaf lives on one device (no NamedSharding
    over a mesh) — the GSPMD-safety answer the decode kernels' gate
    needs: a bare pallas_call cannot be partitioned, so the kernels are
    only safe when the program cannot be mesh-sharded. Only meaningful
    on CONCRETE arrays (tracers carry no committed sharding)."""
    from jax.sharding import SingleDeviceSharding
    for leaf in jax.tree_util.tree_leaves(tree):
        s = getattr(leaf, "sharding", None)
        if s is not None and not isinstance(s, SingleDeviceSharding):
            return False
    return True


_PALLAS_GATE_LOGGED = False


def _default_allow_pallas(*inputs) -> bool:
    """Default kernel gate for direct decode_step callers.

    When the inputs are concrete arrays, the answer is precise: inspect
    their actual shardings (exactly what generate() does eagerly via
    ``_all_single_device``), so single-device inputs on a multi-device
    host keep the fused kernels. Inside a trace the shardings are
    unknowable and the gate falls back to the conservative
    process-topology guess (device_count()==1); callers that KNOW their
    traced inputs are single-device pass allow_pallas=True. Logs once
    per process when the gate turns the kernels off on a backend that
    would otherwise run them (a silent perf cliff is worse than one
    stderr line)."""
    leaves = jax.tree_util.tree_leaves(inputs)
    if any(isinstance(leaf, jax.core.Tracer) for leaf in leaves):
        ok = jax.device_count() == 1
    else:
        ok = _all_single_device(inputs)
    if not ok and _fused_decode_backend_ok():
        global _PALLAS_GATE_LOGGED
        if not _PALLAS_GATE_LOGGED:
            _PALLAS_GATE_LOGGED = True
            import sys
            print("note: fused decode kernels gated off (multi-device "
                  "inputs or traced call on a multi-device process); "
                  "pass allow_pallas=True to decode_step if the inputs "
                  "are known single-device", file=sys.stderr)
    return ok


def decode_step(params: Params, idx_t: jnp.ndarray, pos: jnp.ndarray,
                cache: Dict[str, jnp.ndarray], cfg: ModelConfig, *,
                allow_pallas: Optional[bool] = None
                ) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """One autoregressive step. idx_t: (B,) int32 current tokens; pos: scalar
    int32 position. Returns (logits (B, V) float32, updated cache).

    Replaces the reference's full re-forward per generated token
    (GPT1.py:200-202) with O(T) work per token. Single-stream (B=1)
    steps on TPU route the whole layer loop through the fused Pallas
    decode kernel (ops/decode_pallas.py) when the per-layer weights fit
    its VMEM envelope — one launch instead of ~125 op dispatches.

    The cache may be shorter than cfg.block_size (``init_kv_cache``'s
    max_len): every step streams the whole buffer, so callers that know
    ``pos`` stays small keep the buffer small — sample.generate grows it
    chunk-by-chunk instead of paying the full static bucket from token 1
    (a static prefix *slice* here instead was measured 10x WORSE at
    124M B=8: slicing the scan-carried buffer defeats XLA's in-place
    aliasing of the dynamic_update_slice writes and copies the cache
    every step).
    """
    cd = _dtype(cfg.dtype)
    B = idx_t.shape[0]
    x = params["wte"].astype(cd)[idx_t] + params["wpe"].astype(cd)[pos]
    x = x[:, None, :]  # (B, 1, C)

    if allow_pallas is None:
        allow_pallas = _default_allow_pallas(params, idx_t, cache)
    S_actual = cache["k"].shape[cache_seq_axis(cfg)]
    # a past-the-end pos would CLAMP in the cache write below and
    # overwrite the last valid K/V (lint GL006); concrete (eager) calls
    # assert here, traced callers bound pos host-side (generate's
    # window refresh, the serve engine's admission room check)
    check_in_bounds(pos, 1, S_actual, what="decode_step cache write")
    from ..ops.decode_pallas import fused_decode_layers, fused_decode_supported
    # the envelope gates on the CACHE actually handed in (its length and
    # dtype may differ from cfg.block_size / the compute dtype via
    # init_kv_cache's max_len/dtype overrides)
    # the fused all-layers kernel handles BOTH cache layouts (heads
    # blocks or packed lane-sliced rows), so B=1 keeps its one-launch
    # path if the packed layout becomes the default
    use_fused = (allow_pallas
                 and _fused_decode_backend_ok()
                 and cache["k"].dtype == cd
                 # quantized params carry per-channel scales the fused
                 # kernel's weight stream does not consume — the XLA
                 # path below applies them via _wmm
                 and "qkv_kernel_scale" not in params["blocks"]
                 and fused_decode_supported(
                     cfg, B, jnp.dtype(cd).itemsize, seq_len=S_actual))
    if use_fused:
        x_row, cache = fused_decode_layers(x[:, 0, :], params["blocks"],
                                           pos, cache, cfg)
        return _decode_head(x_row[:, None, :], params, cfg, cd), cache

    if cfg.decode_cache_layout == "packed":
        return _decode_step_packed(params, x, pos, cache, cfg, cd,
                                   allow_pallas)

    def body(carry, inputs):
        # Caches ride the carry as the full stacked (L, B, H, S, D)
        # arrays, updated by dynamic_update_slice at (layer, pos) — XLA
        # keeps ONE buffer in place across layers and across the outer
        # decode scan. The previous formulation emitted per-layer caches
        # as scan ys, which allocates and copies the entire cache every
        # generated token (measured: decode step time scaled with cache
        # bytes, 0.44 ms at B=8 -> 1.54 ms at B=32 for a model whose
        # per-token math is microseconds).
        h_in, ck, cv = carry
        lp, layer_idx = inputs
        q, k, v = _cached_qkv(h_in, lp, cfg, cd)  # (B, H, 1, D)
        zero = jnp.int32(0)
        start = (layer_idx, zero, zero, pos, zero)
        ck = jax.lax.dynamic_update_slice(ck, k.astype(ck.dtype)[None],
                                          start)
        cv = jax.lax.dynamic_update_slice(cv, v.astype(cv.dtype)[None],
                                          start)
        k_cache = jax.lax.dynamic_index_in_dim(ck, layer_idx, 0,
                                               keepdims=False)
        v_cache = jax.lax.dynamic_index_in_dim(cv, layer_idx, 0,
                                               keepdims=False)
        attn = cached_attention(q, k_cache, v_cache, pos)
        return (_cached_block_tail(h_in, _merge_heads(attn), lp, cfg, cd),
                ck, cv), None

    if cfg.use_layer_scan:
        layer_ids = jnp.arange(cfg.n_layer)
        (x, new_k, new_v), _ = jax.lax.scan(
            body, (x, cache["k"], cache["v"]),
            (params["blocks"], layer_ids))
    else:
        # shallow stacks: unrolled layers fuse/overlap better (same
        # measured rationale as _run_blocks); the static Python index
        # keeps the layer offset a compile-time constant
        carry = (x, cache["k"], cache["v"])
        for i in range(cfg.n_layer):
            lp = jax.tree_util.tree_map(lambda a: a[i], params["blocks"])
            carry, _ = body(carry, (lp, i))
        x, new_k, new_v = carry
    return _decode_head(x, params, cfg, cd), {"k": new_k, "v": new_v}


def _decode_step_packed(params: Params, x, pos, cache, cfg: ModelConfig,
                        cd, allow_pallas: bool
                        ) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """decode_step body for the (L, B, S, C) packed cache layout.

    The fresh K/V rows are written as (B, 1, C) rows — no head split, no
    D-minor tile padding in the carried buffer. Attention reads the
    layer's (B, S, C) slice through the packed decode kernel
    (ops/decode_pallas.py: per-head static lane slices of fully-packed
    rows) on TPU, or the reshape->einsum fallback elsewhere; both attend
    the stale cache masked to positions < pos plus the fresh column,
    which is bit-equivalent to write-then-attend (cache[pos] would hold
    exactly the fresh k/v)."""
    from ..ops.decode_pallas import (_packed_attn_backend_ok,
                                     packed_decode_attention,
                                     packed_decode_supported)
    H = cfg.n_head
    S = cache["k"].shape[2]
    check_in_bounds(pos, 1, S, what="packed decode cache write")
    # same cache-dtype gate as the fused path: the kernel attends the
    # fresh column at compute precision, so write-then-attend
    # bit-equivalence needs the stored value to round-trip losslessly
    use_kernel = (allow_pallas
                  and _packed_attn_backend_ok()
                  and cache["k"].dtype == cd
                  and packed_decode_supported(
                      cfg, jnp.dtype(cache["k"].dtype).itemsize, seq_len=S))

    def body(carry, inputs):
        h_in, ck, cv = carry
        lp, layer_idx = inputs
        q_m, k_m, v_m = _cached_qkv_merged(h_in, lp, cfg, cd)  # (B, 1, C)
        if use_kernel:
            # kernel attends the STALE cache + fresh column, so the
            # write can land after (bit-equivalent final cache)
            k_cache = jax.lax.dynamic_index_in_dim(ck, layer_idx, 0,
                                                   keepdims=False)
            v_cache = jax.lax.dynamic_index_in_dim(cv, layer_idx, 0,
                                                   keepdims=False)
            attn_merged = packed_decode_attention(
                q_m[:, 0, :], k_m[:, 0, :], v_m[:, 0, :],
                k_cache, v_cache, pos, n_head=H)[:, None, :]
            write_first = False
        else:
            write_first = True
        zero = jnp.int32(0)
        start = (layer_idx, zero, pos, zero)
        ck = jax.lax.dynamic_update_slice(ck, k_m.astype(ck.dtype)[None],
                                          start)
        cv = jax.lax.dynamic_update_slice(cv, v_m.astype(cv.dtype)[None],
                                          start)
        if write_first:
            k_cache = jax.lax.dynamic_index_in_dim(ck, layer_idx, 0,
                                                   keepdims=False)
            v_cache = jax.lax.dynamic_index_in_dim(cv, layer_idx, 0,
                                                   keepdims=False)
            attn = cached_attention(_split_heads(q_m, H),
                                    _split_heads(k_cache, H),
                                    _split_heads(v_cache, H), pos)
            attn_merged = _merge_heads(attn)
        return (_cached_block_tail(h_in, attn_merged, lp, cfg, cd),
                ck, cv), None

    if cfg.use_layer_scan:
        layer_ids = jnp.arange(cfg.n_layer)
        (x, new_k, new_v), _ = jax.lax.scan(
            body, (x, cache["k"], cache["v"]),
            (params["blocks"], layer_ids))
    else:
        carry = (x, cache["k"], cache["v"])
        for i in range(cfg.n_layer):
            lp = jax.tree_util.tree_map(lambda a: a[i], params["blocks"])
            carry, _ = body(carry, (lp, i))
        x, new_k, new_v = carry
    return _decode_head(x, params, cfg, cd), {"k": new_k, "v": new_v}


def _decode_head(x, params: Params, cfg: ModelConfig, cd) -> jnp.ndarray:
    """Final layernorm + (tied/untied) head over a (B, 1, C) decode
    state — one source of truth for the fused and XLA decode tails."""
    x = _layer_norm(x, params["ln_f_scale"], params["ln_f_bias"],
                    cfg.layernorm_eps)
    head = (params["wte"].astype(cd).T if cfg.tied_head
            else params["lm_head"].astype(cd))
    return (x[:, 0, :] @ head).astype(jnp.float32)


def prefill(params: Params, idx: jnp.ndarray,
            cache: Dict[str, jnp.ndarray], cfg: ModelConfig
            ) -> Dict[str, jnp.ndarray]:
    """Parallel KV-cache fill: one full-sequence causal forward over the
    (B, P) prompt writing every position's K/V into cache[..., :P, :].
    Replaces P-1 *sequential* ``decode_step`` calls per segment — the
    teacher-forced prompt replay was ~43% of all decode steps on the
    1k-token char workload (window refresh re-prefills block_size//2
    tokens per segment). K/V at position p depends only on tokens
    <= p (causal attention, per-position projections), so positions at
    or beyond the true prompt length may hold padding-derived values —
    harmless: the decode scan overwrites position p before attending it
    and masks everything beyond. Attention core is the einsum path on
    purpose (see the inline comment: the segment is GSPMD-partitioned
    under sharded decode, where a bare pallas_call cannot partition).
    """
    cd = _dtype(cfg.dtype)
    B, P = idx.shape
    # shapes are static, so this guard holds even under jit: a prompt
    # longer than the cache buffer would clamp-corrupt the tail
    check_in_bounds(0, P, cache["k"].shape[cache_seq_axis(cfg)],
                    what="prefill prompt write")
    x = params["wte"].astype(cd)[idx] + params["wpe"].astype(cd)[:P]

    packed = cfg.decode_cache_layout == "packed"

    def body(carry, inputs):
        h_in, ck, cv = carry
        lp, layer_idx = inputs
        q_m, k_m, v_m = _cached_qkv_merged(h_in, lp, cfg, cd)
        q, k, v = (_split_heads(t, cfg.n_head) for t in (q_m, k_m, v_m))
        zero = jnp.int32(0)
        if packed:
            # merged (B, P, C) rows straight into the lane-packed cache
            start = (layer_idx, zero, zero, zero)
            ck = jax.lax.dynamic_update_slice(
                ck, k_m.astype(ck.dtype)[None], start)
            cv = jax.lax.dynamic_update_slice(
                cv, v_m.astype(cv.dtype)[None], start)
        else:
            start = (layer_idx, zero, zero, zero, zero)
            ck = jax.lax.dynamic_update_slice(ck, k.astype(ck.dtype)[None],
                                              start)
            cv = jax.lax.dynamic_update_slice(cv, v.astype(cv.dtype)[None],
                                              start)
        # einsum core on purpose: this runs inside the jitted decode
        # segment, which sharded decodes partition with GSPMD
        # (shard_for_decode) — a bare pallas_call cannot partition
        # (parallel/__init__ policy), and the einsum core is already the
        # decode path's attention everywhere else (cached_attention)
        attn = full_causal_attention(q, k, v, impl="einsum")
        return (_cached_block_tail(h_in, _merge_heads(attn), lp, cfg, cd),
                ck, cv), None

    if cfg.use_layer_scan:
        layer_ids = jnp.arange(cfg.n_layer)
        (_, ck, cv), _ = jax.lax.scan(
            body, (x, cache["k"], cache["v"]),
            (params["blocks"], layer_ids))
    else:
        carry = (x, cache["k"], cache["v"])
        for i in range(cfg.n_layer):
            lp = jax.tree_util.tree_map(lambda a: a[i], params["blocks"])
            carry, _ = body(carry, (lp, i))
        _, ck, cv = carry
    return {"k": ck, "v": cv}


# ---------------------------------------------------------------------------
# Multi-slot decode (continuous batching: per-slot positions)
# ---------------------------------------------------------------------------

def decode_step_multi(params: Params, idx_t: jnp.ndarray, pos: jnp.ndarray,
                      cache: Dict[str, jnp.ndarray], cfg: ModelConfig
                      ) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """One autoregressive step over B independent cache slots at
    PER-SLOT positions. idx_t: (B,) int32 current tokens; pos: (B,)
    int32 per-slot positions. Returns (logits (B, V) float32, updated
    cache).

    This is ``decode_step`` generalized for the continuous-batching
    serving engine (serve/engine.py): each batch row is a pool slot
    decoding its own request at its own offset, so the K/V write is a
    batched scatter at (layer, b, pos[b]) instead of one
    dynamic_update_slice, and the attention mask is per-row
    (ops.attention.cached_attention accepts a (B,) cache_index). The
    per-row math is identical to the scalar-pos XLA path — rows are
    independent through every op — which is what makes the engine's
    greedy output token-identical to offline ``generate`` (pinned in
    tests/test_serve.py). No Pallas route: the fused/packed decode
    kernels assume one shared position; the serving engine is a
    steady-state multi-slot batch where the XLA path is the right tool.
    """
    cd = _dtype(cfg.dtype)
    B = idx_t.shape[0]
    bidx = jnp.arange(B)
    x = params["wte"].astype(cd)[idx_t] + params["wpe"].astype(cd)[pos]
    x = x[:, None, :]  # (B, 1, C)
    packed = cfg.decode_cache_layout == "packed"
    H = cfg.n_head

    def body(carry, inputs):
        h_in, ck, cv = carry
        lp, layer_idx = inputs
        if packed:
            q_m, k_m, v_m = _cached_qkv_merged(h_in, lp, cfg, cd)
            ck = ck.at[layer_idx, bidx, pos, :].set(
                k_m[:, 0, :].astype(ck.dtype))
            cv = cv.at[layer_idx, bidx, pos, :].set(
                v_m[:, 0, :].astype(cv.dtype))
            k_cache = jax.lax.dynamic_index_in_dim(ck, layer_idx, 0,
                                                   keepdims=False)
            v_cache = jax.lax.dynamic_index_in_dim(cv, layer_idx, 0,
                                                   keepdims=False)
            attn = cached_attention(_split_heads(q_m, H),
                                    _split_heads(k_cache, H),
                                    _split_heads(v_cache, H), pos)
        else:
            q, k, v = _cached_qkv(h_in, lp, cfg, cd)  # (B, H, 1, D)
            ck = ck.at[layer_idx, bidx, :, pos, :].set(
                k[:, :, 0, :].astype(ck.dtype))
            cv = cv.at[layer_idx, bidx, :, pos, :].set(
                v[:, :, 0, :].astype(cv.dtype))
            k_cache = jax.lax.dynamic_index_in_dim(ck, layer_idx, 0,
                                                   keepdims=False)
            v_cache = jax.lax.dynamic_index_in_dim(cv, layer_idx, 0,
                                                   keepdims=False)
            attn = cached_attention(q, k_cache, v_cache, pos)
        return (_cached_block_tail(h_in, _merge_heads(attn), lp, cfg, cd),
                ck, cv), None

    if cfg.use_layer_scan:
        layer_ids = jnp.arange(cfg.n_layer)
        (x, new_k, new_v), _ = jax.lax.scan(
            body, (x, cache["k"], cache["v"]),
            (params["blocks"], layer_ids))
    else:
        carry = (x, cache["k"], cache["v"])
        for i in range(cfg.n_layer):
            lp = jax.tree_util.tree_map(lambda a: a[i], params["blocks"])
            carry, _ = body(carry, (lp, i))
        x, new_k, new_v = carry
    return _decode_head(x, params, cfg, cd), {"k": new_k, "v": new_v}


def verify_step_multi(params: Params, window: jnp.ndarray, pos: jnp.ndarray,
                      n_valid: jnp.ndarray, cache: Dict[str, jnp.ndarray],
                      cfg: ModelConfig
                      ) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """The target-side forward of speculative decoding: score a static
    (k+1)-wide token window per slot in ONE pass over the pooled cache.

    window: (B, W) int32 — per slot ``[last_committed, draft_1..draft_k]``;
    pos: (B,) int32 per-slot base positions (window token j sits at
    ``pos[b] + j``); n_valid: (B,) int32 — how many DRAFT positions are
    real for each slot (0..W-1; the base token at j=0 is always real).
    Returns (logits (B, W, V) float32, updated cache): logits[:, j] is
    the next-token distribution after window token j, so j=0 reproduces
    ``decode_step_multi``'s output and j>=1 scores the drafted suffix.

    Cache discipline mirrors ``decode_step_multi``: K/V for window token
    j is scattered at (layer, b, pos[b]+j) and queries attend positions
    <= their own (ops.attention.windowed_cached_attention), i.e.
    write-then-attend. Padding window positions (j > n_valid[b]) route
    their scatter index to S — explicitly out of bounds, where scatter
    drops the update (mode='drop'), so a slot near the end of its buffer
    never clamp-corrupts earlier K/V; their logits are garbage and the
    caller discards them (acceptance is masked by n_valid). Rejected
    drafts leave stale K/V past the committed frontier — harmless under
    the pool invariant (every position is overwritten before any query
    sits at or beyond it). Per-row, per-position math is the decode
    path's exactly, which is what greedy speculative parity rests on
    (tests/test_speculative.py).
    """
    cd = _dtype(cfg.dtype)
    B, W = window.shape
    S = cache["k"].shape[cache_seq_axis(cfg)]
    bidx = jnp.arange(B)[:, None]                       # (B, 1)
    offs = jnp.arange(W, dtype=jnp.int32)[None, :]      # (1, W)
    abs_pos = pos[:, None] + offs                       # (B, W)
    # wpe gather clamps out-of-bounds rows (padding only — real window
    # positions are bounded host-side: pos + n_valid <= S - 1)
    x = (params["wte"].astype(cd)[window]
         + params["wpe"].astype(cd)[jnp.minimum(abs_pos, S - 1)])  # (B, W, C)
    # padding writes go to S where the scatter drops them
    wpos = jnp.where(offs <= n_valid[:, None], abs_pos, S)
    packed = cfg.decode_cache_layout == "packed"
    H = cfg.n_head

    def body(carry, inputs):
        h_in, ck, cv = carry
        lp, layer_idx = inputs
        if packed:
            q_m, k_m, v_m = _cached_qkv_merged(h_in, lp, cfg, cd)  # (B, W, C)
            ck = ck.at[layer_idx, bidx, wpos, :].set(
                k_m.astype(ck.dtype), mode="drop")
            cv = cv.at[layer_idx, bidx, wpos, :].set(
                v_m.astype(cv.dtype), mode="drop")
            k_cache = jax.lax.dynamic_index_in_dim(ck, layer_idx, 0,
                                                   keepdims=False)
            v_cache = jax.lax.dynamic_index_in_dim(cv, layer_idx, 0,
                                                   keepdims=False)
            attn = windowed_cached_attention(
                _split_heads(q_m, H), _split_heads(k_cache, H),
                _split_heads(v_cache, H), pos)
        else:
            q, k, v = _cached_qkv(h_in, lp, cfg, cd)    # (B, H, W, D)
            # scatter value laid out (B, W, H, D): advanced indices
            # (bidx, wpos) broadcast to (B, W) and land first
            ck = ck.at[layer_idx, bidx, :, wpos, :].set(
                k.transpose(0, 2, 1, 3).astype(ck.dtype), mode="drop")
            cv = cv.at[layer_idx, bidx, :, wpos, :].set(
                v.transpose(0, 2, 1, 3).astype(cv.dtype), mode="drop")
            k_cache = jax.lax.dynamic_index_in_dim(ck, layer_idx, 0,
                                                   keepdims=False)
            v_cache = jax.lax.dynamic_index_in_dim(cv, layer_idx, 0,
                                                   keepdims=False)
            attn = windowed_cached_attention(q, k_cache, v_cache, pos)
        return (_cached_block_tail(h_in, _merge_heads(attn), lp, cfg, cd),
                ck, cv), None

    if cfg.use_layer_scan:
        layer_ids = jnp.arange(cfg.n_layer)
        (x, new_k, new_v), _ = jax.lax.scan(
            body, (x, cache["k"], cache["v"]),
            (params["blocks"], layer_ids))
    else:
        carry = (x, cache["k"], cache["v"])
        for i in range(cfg.n_layer):
            lp = jax.tree_util.tree_map(lambda a: a[i], params["blocks"])
            carry, _ = body(carry, (lp, i))
        x, new_k, new_v = carry
    x = _layer_norm(x, params["ln_f_scale"], params["ln_f_bias"],
                    cfg.layernorm_eps)
    head = (params["wte"].astype(cd).T if cfg.tied_head
            else params["lm_head"].astype(cd))
    return (x @ head).astype(jnp.float32), {"k": new_k, "v": new_v}


# ---------------------------------------------------------------------------
# Paged KV pool (serving engine: page tables instead of contiguous slots)
# ---------------------------------------------------------------------------

def _constrain(x, s):
    """``jax.lax.with_sharding_constraint`` when a sharding is given;
    identity when ``s`` is None. The sharded serving engine pins the
    page pool and the per-slot step state to their PartitionSpecs
    (parallel.mesh.ServeShardings) INSIDE every traced program: GSPMD
    left alone may re-layout a scan carry mid-program, and donation
    only aliases input to output when their shardings match — so the
    pool spec must survive every window/verify/prefill body unchanged,
    and the sampled token block must leave fully replicated (the
    engine's one-``np.asarray``-per-window fetch stays a local read)."""
    if s is None:
        return x
    return jax.lax.with_sharding_constraint(x, s)


def pool_entry_sharding(shardings, name: str):
    """Per-entry sharding of a paged pool dict: the K/V page arrays
    take the (data, model) pool spec, the quantization scale arrays
    (``ks``/``vs`` — different rank, no model dim) their own page-axis
    spec (``ServeShardings.scale``). One mapping shared by the traced
    constraints here and the engine's COW page copy."""
    if shardings is None:
        return None
    if name in ("k", "v"):
        return shardings.cache
    return shardings.scale


def _constrain_cache(cache: Dict[str, jnp.ndarray], shardings
                     ) -> Dict[str, jnp.ndarray]:
    if shardings is None:
        return cache
    return {n: _constrain(a, pool_entry_sharding(shardings, n))
            for n, a in cache.items()}


def init_paged_kv_pool(cfg: ModelConfig, n_pages: int, page_size: int,
                       dtype=None, quant=None) -> Dict[str, jnp.ndarray]:
    """Paged KV storage for the serving engine (serve/pages.py): the
    batch/slot axis of ``init_kv_cache`` becomes a PHYSICAL PAGE axis —
    (L, n_pages, page, C) for the packed layout, (L, n_pages, H, page, D)
    for heads. A slot's logical sequence is the concatenation of the
    pages its (host-side) page table maps, so HBM is sized by pages in
    use, not slots*block_size, and pages holding a shared prompt prefix
    appear in many tables while existing once.

    ``quant`` (a quant.QuantConfig with ``kv_dtype`` set) stores the
    pages in int8/fp8 and adds ``ks``/``vs`` scale arrays indexed by
    the same (layer, page, offset) coordinates — halving bytes/page
    (the admission-capacity doubler) at the cost of tiny per-row scale
    metadata. The paged programs derive the quant mode from the dict
    itself (quant.kv.pool_quant_mode), so their traced signatures
    never change."""
    if quant is not None and quant.kv_enabled:
        from ..quant.kv import init_scales, kv_store_dtype
        dt = kv_store_dtype(quant.kv_dtype)
        if cfg.decode_cache_layout == "packed":
            shape = (cfg.n_layer, n_pages, page_size, cfg.n_embd)
        else:
            shape = (cfg.n_layer, n_pages, cfg.n_head, page_size,
                     cfg.head_dim)
        pool = {"k": jnp.zeros(shape, dt), "v": jnp.zeros(shape, dt)}
        pool.update(init_scales(cfg, n_pages, page_size,
                                quant.granularity))
        return pool
    dt = dtype or _dtype(cfg.dtype)
    if cfg.decode_cache_layout == "packed":
        shape = (cfg.n_layer, n_pages, page_size, cfg.n_embd)
    else:
        shape = (cfg.n_layer, n_pages, cfg.n_head, page_size, cfg.head_dim)
    return {"k": jnp.zeros(shape, dt), "v": jnp.zeros(shape, dt)}


def paged_page_size(cfg: ModelConfig, cache: Dict[str, jnp.ndarray]) -> int:
    """Page length of a paged pool — layout-dependent axis, one accessor
    (the paged decode/prefill/verify programs derive it from the arrays
    they are handed, never from config)."""
    return int(cache["k"].shape[
        2 if cfg.decode_cache_layout == "packed" else 3])


def _gather_pages(c_layer: jnp.ndarray, tables: jnp.ndarray,
                  packed: bool, n_head: int, s_layer=None,
                  cd=None) -> jnp.ndarray:
    """Assemble per-slot logical K or V from one layer's page pool.

    c_layer: (N, page, C) packed or (N, H, page, D) heads; tables:
    (B, max_pages) int32 physical-page ids (unmapped entries clamp to 0
    — the positions they cover are beyond every query's mask, so the
    garbage rows get exactly zero softmax weight). Returns the
    (B, H, max_pages*page, D) logical view the attention cores consume.
    This materialized gather streams the same bytes per step as the old
    contiguous (B, S, ...) slot read; the Pallas fast path
    (ops/paged_pallas.py) is the route that skips unmapped pages.

    ``s_layer`` (one layer of a quantized pool's ``ks``/``vs`` scale
    arrays) dequantizes the gathered view to ``cd`` right here — the
    XLA half of the in-kernel dequant contract: every route reads
    quantized pages natively and multiplies scales at the gather, never
    materializing a full-precision pool."""
    g = c_layer[tables]
    if s_layer is not None:
        from ..quant.kv import dequant_gathered
        g = dequant_gathered(g, s_layer[tables], packed, n_head, cd)
    if packed:
        B, mp, psz, C = g.shape
        return _split_heads(g.reshape(B, mp * psz, C), n_head)
    B, mp, H, psz, D = g.shape
    return g.transpose(0, 2, 1, 3, 4).reshape(B, H, mp * psz, D)


def _scatter_kv(cc: Dict[str, jnp.ndarray], layer_idx, phys, woff,
                k_m: jnp.ndarray, v_m: jnp.ndarray, packed: bool,
                n_head: int) -> Dict[str, jnp.ndarray]:
    """Scatter merged fresh K/V rows into one layer of the paged pool
    at (phys, woff) — ONE write discipline for the decode / verify /
    prefill programs, both layouts, quantized or not.

    ``k_m``/``v_m`` carry shape ``phys.shape + (C,)``; out-of-range
    ``woff`` entries (inactive slots, padding, past-``limit``
    positions) route to mode='drop' exactly as before. On a quantized
    pool (``ks`` present) the rows quantize-on-write
    (quant.kv.quantize_rows) and their scales land at the SAME
    coordinates in the ``ks``/``vs`` arrays with the same drop
    routing — a dropped row drops its scale with it."""
    from ..quant.kv import pool_quant_mode, quantize_rows
    kv_dtype, gran = pool_quant_mode(cc)
    ck, cv = cc["k"], cc["v"]
    H = n_head
    if kv_dtype is None:
        if packed:
            ck = ck.at[layer_idx, phys, woff, :].set(
                k_m.astype(ck.dtype), mode="drop")
            cv = cv.at[layer_idx, phys, woff, :].set(
                v_m.astype(cv.dtype), mode="drop")
        else:
            shp = phys.shape + (H, k_m.shape[-1] // H)
            ck = ck.at[layer_idx, phys, :, woff, :].set(
                k_m.reshape(shp).astype(ck.dtype), mode="drop")
            cv = cv.at[layer_idx, phys, :, woff, :].set(
                v_m.reshape(shp).astype(cv.dtype), mode="drop")
        return {**cc, "k": ck, "v": cv}
    kq, ksc = quantize_rows(k_m, kv_dtype, H, gran)
    vq, vsc = quantize_rows(v_m, kv_dtype, H, gran)
    cks, cvs = cc["ks"], cc["vs"]
    if packed:
        ck = ck.at[layer_idx, phys, woff, :].set(
            kq.astype(ck.dtype), mode="drop")
        cv = cv.at[layer_idx, phys, woff, :].set(
            vq.astype(cv.dtype), mode="drop")
        if gran == "head":
            cks = cks.at[layer_idx, phys, woff, :].set(
                ksc.astype(cks.dtype), mode="drop")
            cvs = cvs.at[layer_idx, phys, woff, :].set(
                vsc.astype(cvs.dtype), mode="drop")
        else:
            cks = cks.at[layer_idx, phys, woff].set(
                ksc.astype(cks.dtype), mode="drop")
            cvs = cvs.at[layer_idx, phys, woff].set(
                vsc.astype(cvs.dtype), mode="drop")
    else:
        shp = phys.shape + (H, k_m.shape[-1] // H)
        ck = ck.at[layer_idx, phys, :, woff, :].set(
            kq.reshape(shp).astype(ck.dtype), mode="drop")
        cv = cv.at[layer_idx, phys, :, woff, :].set(
            vq.reshape(shp).astype(cv.dtype), mode="drop")
        if gran == "head":
            cks = cks.at[layer_idx, phys, :, woff].set(
                ksc.astype(cks.dtype), mode="drop")
            cvs = cvs.at[layer_idx, phys, :, woff].set(
                vsc.astype(cvs.dtype), mode="drop")
        else:
            cks = cks.at[layer_idx, phys, woff].set(
                ksc.astype(cks.dtype), mode="drop")
            cvs = cvs.at[layer_idx, phys, woff].set(
                vsc.astype(cvs.dtype), mode="drop")
    return {**cc, "k": ck, "v": cv, "ks": cks, "vs": cvs}


def _gather_kv(cc: Dict[str, jnp.ndarray], layer_idx, tables,
               packed: bool, n_head: int, cd):
    """Per-layer logical K/V views through ``_gather_pages``, with the
    scale layers threaded for quantized pools (dequant at the gather —
    the XLA fallback's half of the in-kernel dequant contract)."""
    quantized = "ks" in cc
    k_l = jax.lax.dynamic_index_in_dim(cc["k"], layer_idx, 0, False)
    v_l = jax.lax.dynamic_index_in_dim(cc["v"], layer_idx, 0, False)
    ks_l = vs_l = None
    if quantized:
        ks_l = jax.lax.dynamic_index_in_dim(cc["ks"], layer_idx, 0, False)
        vs_l = jax.lax.dynamic_index_in_dim(cc["vs"], layer_idx, 0, False)
    return (_gather_pages(k_l, tables, packed, n_head, s_layer=ks_l,
                          cd=cd),
            _gather_pages(v_l, tables, packed, n_head, s_layer=vs_l,
                          cd=cd))


def _serve_kernel_mesh(shardings):
    """The >1-device serve mesh behind a ServeShardings plan, or None
    when the engine is effectively single-device — the static fact the
    paged kernel branches switch on to pick the bare ``pallas_call``
    vs its ``shard_map`` wrapper (shardings ride the jit STATIC args,
    so this resolves at trace time, one program per plan)."""
    if shardings is None:
        return None
    mesh = shardings.cache.mesh
    return mesh if mesh.size > 1 else None


def _paged_window_attn(q_w, k_w, v_w, k_layer, v_layer, tables, pos_eff,
                       n_head, ks_layer, vs_layer, mesh):
    """One layer of windowed paged attention through the unified Pallas
    kernel family (ops/paged_pallas.py): the bare kernel on a single
    device, the ``shard_map`` wrapper on a >1 (data, model) mesh. All
    (B, W, C) in, (B, W, C) out, attending STALE pool + causal fresh
    window — callers scatter the window rows afterwards."""
    if mesh is not None:
        from ..ops.paged_pallas import sharded_paged_window_attention
        return sharded_paged_window_attention(
            q_w, k_w, v_w, k_layer, v_layer, tables, pos_eff,
            n_head=n_head, mesh=mesh, k_scales=ks_layer,
            v_scales=vs_layer)
    from ..ops.paged_pallas import paged_window_attention
    return paged_window_attention(
        q_w, k_w, v_w, k_layer, v_layer, tables, pos_eff,
        n_head=n_head, k_scales=ks_layer, v_scales=vs_layer)


def decode_step_paged(params: Params, idx_t: jnp.ndarray, pos: jnp.ndarray,
                      active: jnp.ndarray, tables: jnp.ndarray,
                      cache: Dict[str, jnp.ndarray], cfg: ModelConfig, *,
                      use_pallas: bool = False, use_fused: bool = False,
                      shardings=None
                      ) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """``decode_step_multi`` over a PAGED pool: per-slot positions are
    logical, and each slot's K/V is gathered through its page table.

    idx_t/pos: (B,) tokens and logical positions; active: (B,) bool;
    tables: (B, max_pages) int32; cache: ``init_paged_kv_pool`` arrays.
    The fresh K/V row for slot b lands at physical page
    ``tables[b, pos//page]``, offset ``pos % page``. INACTIVE rows run
    at position 0 and their writes are routed off the page axis
    (mode='drop'): a released slot's stale table may reference pages
    now owned by another request, so the contiguous pool's
    "next occupant overwrites before attending" invariant does NOT
    carry over — dropping is correctness, not tidiness. Per-row math is
    ``decode_step_multi``'s exactly (the gathered view holds the same
    values at the same logical offsets), which is what keeps the paged
    engine's greedy stream token-identical to offline ``generate``.
    """
    cd = _dtype(cfg.dtype)
    B = idx_t.shape[0]
    packed = cfg.decode_cache_layout == "packed"
    psz = paged_page_size(cfg, cache)
    mp = tables.shape[1]
    H = cfg.n_head
    bidx = jnp.arange(B)
    pos_eff = jnp.where(active, pos, 0)
    # eager calls assert; the engine bounds pos host-side at admission
    check_in_bounds(pos_eff, 1, mp * psz, what="paged decode write")
    x = params["wte"].astype(cd)[idx_t] + params["wpe"].astype(cd)[pos_eff]
    x = x[:, None, :]  # (B, 1, C)
    phys = tables[bidx, jnp.minimum(pos_eff // psz, mp - 1)]
    woff = jnp.where(active, pos_eff % psz, psz)   # inactive -> dropped

    quantized = "ks" in cache
    mesh = _serve_kernel_mesh(shardings)
    if use_fused:
        # ONE Pallas launch for the whole layer stack: the page table
        # rides scalar-prefetch SMEM so each (layer, slot) grid step
        # streams only the slot's LIVE pages (ops/decode_pallas.py,
        # fused_paged_decode_layers). Packed layout only; the caller
        # gates on fused_paged_decode_supported. The kernel attends the
        # STALE pool + fresh column (bit-equivalent to write-then-
        # attend; on a quantized pool it dequants pages in-kernel and
        # fake-quantizes the fresh column to exactly what the store
        # below will dequant to), so every layer's fresh K/V row
        # scatters afterwards — drop-routed exactly like the XLA
        # path's per-layer writes, quantize-on-write included.
        from ..ops.decode_pallas import fused_paged_decode_layers
        x_row, newk, newv = fused_paged_decode_layers(
            x[:, 0, :], params["blocks"], pos_eff, tables, cache, cfg)
        cc = dict(cache)
        if quantized:
            from ..quant.kv import pool_quant_mode, quantize_rows
            kv_dtype, gran = pool_quant_mode(cache)
            kq, ksc = quantize_rows(newk, kv_dtype, H, gran)
            vq, vsc = quantize_rows(newv, kv_dtype, H, gran)
            cc["k"] = cc["k"].at[:, phys, woff, :].set(
                kq.astype(cc["k"].dtype), mode="drop")
            cc["v"] = cc["v"].at[:, phys, woff, :].set(
                vq.astype(cc["v"].dtype), mode="drop")
            cc["ks"] = cc["ks"].at[:, phys, woff].set(
                ksc.astype(cc["ks"].dtype), mode="drop")
            cc["vs"] = cc["vs"].at[:, phys, woff].set(
                vsc.astype(cc["vs"].dtype), mode="drop")
        else:
            cc["k"] = cc["k"].at[:, phys, woff, :].set(
                newk.astype(cc["k"].dtype), mode="drop")
            cc["v"] = cc["v"].at[:, phys, woff, :].set(
                newv.astype(cc["v"].dtype), mode="drop")
        return _decode_head(x_row[:, None, :], params, cfg, cd), cc

    def body(carry, inputs):
        h_in, cc = carry
        lp, layer_idx = inputs
        if packed:
            q_m, k_m, v_m = _cached_qkv_merged(h_in, lp, cfg, cd)
            if use_pallas:
                # kernel attends the STALE pages + fresh column (bit-
                # equivalent to write-then-attend); write lands after.
                # Quantized pools hand the kernel their scale layers
                # (dequant inside the accumulation loop) and a fresh
                # column pre-quantize-dequantized to the exact value
                # the scatter below stores. On a >1 serve mesh the
                # shard_map wrapper runs the same kernel per chip.
                k_layer = jax.lax.dynamic_index_in_dim(cc["k"], layer_idx,
                                                       0, keepdims=False)
                v_layer = jax.lax.dynamic_index_in_dim(cc["v"], layer_idx,
                                                       0, keepdims=False)
                k_new, v_new = k_m, v_m                      # (B, 1, C)
                ks_layer = vs_layer = None
                if quantized:
                    from ..quant.kv import (fake_quantize_rows,
                                            pool_quant_mode)
                    kv_dtype, gran = pool_quant_mode(cc)
                    k_new = fake_quantize_rows(k_new, kv_dtype, H,
                                               gran).astype(cd)
                    v_new = fake_quantize_rows(v_new, kv_dtype, H,
                                               gran).astype(cd)
                    ks_layer = jax.lax.dynamic_index_in_dim(
                        cc["ks"], layer_idx, 0, keepdims=False)
                    vs_layer = jax.lax.dynamic_index_in_dim(
                        cc["vs"], layer_idx, 0, keepdims=False)
                attn_merged = _paged_window_attn(
                    q_m, k_new, v_new, k_layer, v_layer, tables,
                    pos_eff, H, ks_layer, vs_layer, mesh)
                cc = _scatter_kv(cc, layer_idx, phys, woff,
                                 k_m[:, 0, :], v_m[:, 0, :], packed, H)
            else:
                cc = _scatter_kv(cc, layer_idx, phys, woff,
                                 k_m[:, 0, :], v_m[:, 0, :], packed, H)
                k_all, v_all = _gather_kv(cc, layer_idx, tables, packed,
                                          H, cd)
                attn_merged = _merge_heads(cached_attention(
                    _split_heads(q_m, H), k_all, v_all, pos_eff))
        else:
            q_m, k_m, v_m = _cached_qkv_merged(h_in, lp, cfg, cd)
            cc = _scatter_kv(cc, layer_idx, phys, woff,
                             k_m[:, 0, :], v_m[:, 0, :], packed, H)
            k_all, v_all = _gather_kv(cc, layer_idx, tables, packed,
                                      H, cd)
            attn_merged = _merge_heads(cached_attention(
                _split_heads(q_m, H), k_all, v_all, pos_eff))
        return (_cached_block_tail(h_in, attn_merged, lp, cfg, cd),
                cc), None

    if cfg.use_layer_scan:
        layer_ids = jnp.arange(cfg.n_layer)
        (x, cc), _ = jax.lax.scan(
            body, (x, dict(cache)), (params["blocks"], layer_ids))
    else:
        carry = (x, dict(cache))
        for i in range(cfg.n_layer):
            lp = jax.tree_util.tree_map(lambda a: a[i], params["blocks"])
            carry, _ = body(carry, (lp, i))
        x, cc = carry
    return _decode_head(x, params, cfg, cd), cc


def decode_window_paged(params: Params, tok: jnp.ndarray, pos: jnp.ndarray,
                        active: jnp.ndarray, budget: jnp.ndarray,
                        eos: jnp.ndarray, tables: jnp.ndarray,
                        cache: Dict[str, jnp.ndarray], rngs: jnp.ndarray,
                        cfg: ModelConfig, *, sample_fn, length: int,
                        use_pallas: bool = False, use_fused: bool = False,
                        shardings=None):
    """``length`` decode steps over the paged pool in ONE traced program
    — the device-resident loop the async serving engine dispatches once
    per WINDOW instead of once per token (the lax.scan analogue of the
    training loop's steps-per-dispatch amortization; BENCH_r03 measured
    the per-dispatch host tax this removes at 65 ms/step on TPU).

    tok/pos/active: the per-slot step state ``decode_step_paged`` takes;
    budget: (B,) int32 tokens each slot may still emit; eos: (B,) int32
    per-slot stop token (< 0 = disabled); rngs: (B, key) sampling
    streams; ``sample_fn(rngs, logits) -> (tokens, new_rngs)`` is the
    caller's sampler (injected so this module does not depend on
    sample.generate). Per step every ACTIVE slot decodes exactly as a
    standalone ``decode_step_paged`` + sample would — per-row math,
    masking and RNG stream advance are identical, which is what keeps a
    windowed greedy stream byte-identical to the step-at-a-time one —
    then the slot's budget decrements and its on-device active flag
    drops when the budget hits zero or the sampled token == eos. A slot
    that finishes mid-window therefore IDLES inside the window (writes
    dropped, emissions masked off) instead of forcing an early exit: the
    window width is static, so partial windows never compile a second
    program. The window's last real write position is bounded host-side
    by the caller (pos + budget <= logical capacity — the admission
    cap's invariant).

    Returns ``(toks, emitted, tok, pos, active, budget, cache, rngs)``:
    toks/emitted are (length, B) — the sampled token and whether the
    slot was live at each step (``emitted[:, b]`` is a prefix mask: a
    slot deactivates once and never re-arms inside a window); the rest
    is the advanced step state the caller feeds to the NEXT window
    (donated end to end by the engine's jit wrapper).

    ``shardings`` (parallel.mesh.ServeShardings, None = unsharded)
    pins the scan carry on a serving mesh: the page pool to its
    (data, model) PartitionSpec and the step state + per-step token
    outputs to replication, so window-to-window donation aliases and
    the engine's token-block fetch stays a local read (see
    ``_constrain``).
    """
    rep = None if shardings is None else shardings.rep

    def body(carry, _):
        tok, pos, active, budget, cache, rngs = carry
        logits, cache = decode_step_paged(
            params, tok, pos, active, tables, cache, cfg,
            use_pallas=use_pallas, use_fused=use_fused,
            shardings=shardings)
        nxt, rngs = sample_fn(rngs, logits)
        nxt = jnp.where(active, nxt, 0)
        emitted = active
        budget = jnp.where(active, budget - 1, budget)
        hit_eos = active & (eos >= 0) & (nxt == eos)
        pos = jnp.where(emitted, pos + 1, pos)
        tok = jnp.where(emitted, nxt, tok)
        active = active & (budget > 0) & ~hit_eos
        cache = _constrain_cache(cache, shardings)
        tok, pos, active, budget, rngs, nxt, emitted = (
            _constrain(a, rep) for a in (tok, pos, active, budget, rngs,
                                         nxt, emitted))
        return (tok, pos, active, budget, cache, rngs), (nxt, emitted)

    carry = (tok, pos, active, budget, cache, rngs)
    (tok, pos, active, budget, cache, rngs), (toks, emitted) = jax.lax.scan(
        body, carry, None, length=length)
    return toks, emitted, tok, pos, active, budget, cache, rngs


def mixed_window_paged(params: Params, tok: jnp.ndarray, pos: jnp.ndarray,
                       active: jnp.ndarray, budget: jnp.ndarray,
                       eos: jnp.ndarray, pf_left: jnp.ndarray,
                       pf_off: jnp.ndarray, pf_limit: jnp.ndarray,
                       pf_toks: jnp.ndarray, tables: jnp.ndarray,
                       cache: Dict[str, jnp.ndarray], rngs: jnp.ndarray,
                       cfg: ModelConfig, *, sample_fn, length: int,
                       shardings=None, use_kernel: bool = False):
    """``decode_window_paged`` with chunked prefill folded INTO the
    window — the Sarathi-style mixed step the continuous-window engine
    dispatches when an admission landed at the window boundary: newly
    admitted slots prefill their prompt's uncached tail chunk-by-chunk
    while live slots decode, all inside ONE ``length``-step lax.scan,
    so an admission no longer costs a window break (the blocked-k=1
    fallback that used to erase the dispatch amortization exactly when
    traffic peaks).

    Per-slot phase mask: at scan step ``t`` a slot is PREFILLING while
    ``t < pf_left[b]`` (``pf_left``: chunks this window must write for
    the slot; 0 = plain decode) and DECODING afterwards. Each step runs
    one ``verify_step_paged`` forward over a (B, W) token window
    (W = the prefill chunk width, ``pf_toks.shape[-1]``):

    - a prefilling slot's row is its next chunk ``pf_toks[t, b]``,
      written through its page table at absolute positions
      ``pf_off + t*W + j`` (positions >= ``pf_limit`` — the true prompt
      length — are scatter-DROPPED, exactly ``prefill_chunk_paged``'s
      padding discipline); its sampled token is discarded and its rng
      stream does NOT advance, so the first decoded token still uses
      split 0 of the slot's admission-fresh key (stream parity with the
      blocked path, where decode starts the admission step);
    - a decoding slot's row is its current token at window position 0
      (rows past 0 are dropped padding) at its frontier position — the
      same write-then-attend row math as ``decode_step_paged`` via the
      pinned verify<->decode per-row equivalence — and its sample /
      budget / eos bookkeeping is ``decode_window_paged``'s exactly.

    A slot whose prefill exhausts mid-window (``t == pf_left - 1``
    consumed its last chunk) flips to decode at the NEXT scan step with
    no transition math: ``pos``/``tok`` were primed at admission to the
    decode frontier (P-1, last prompt token) and stay untouched while
    prefilling. The caller sizes ``pf_left <= length`` per window and
    carries longer prefills across windows host-side (consumption is
    deterministic, so no device fetch is needed to know the cursor).

    Returns the same ``(toks, emitted, tok, pos, active, budget, cache,
    rngs)`` tuple as ``decode_window_paged`` — ``emitted[:, b]`` is now
    False during b's prefill steps and True from its first decode step
    until deactivation (a suffix-start run, not a prefix: the engine
    commits tokens by mask, not by count).
    """
    rep = None if shardings is None else shardings.rep
    steps = jnp.arange(length, dtype=jnp.int32)
    W = pf_toks.shape[-1]

    def body(carry, xs):
        tok, pos, active, budget, cache, rngs = carry
        chunk_toks, t = xs                       # (B, W), scalar step
        prefilling = active & (t < pf_left)
        cur = pf_off + t * W
        n_tok = jnp.where(prefilling, jnp.clip(pf_limit - cur, 1, W), 1)
        base = jnp.where(prefilling, cur, pos)
        col0 = jnp.zeros_like(chunk_toks).at[:, 0].set(tok)
        window = jnp.where(prefilling[:, None], chunk_toks, col0)
        logits, cache = verify_step_paged(
            params, window, base, n_tok - 1, active, tables, cache, cfg,
            shardings=shardings, logits_rows=1, use_kernel=use_kernel)
        decoding = active & ~prefilling
        nxt, new_rngs = sample_fn(rngs, logits[:, 0, :])
        rngs = jnp.where(decoding[:, None], new_rngs, rngs)
        nxt = jnp.where(decoding, nxt, 0)
        emitted = decoding
        budget = jnp.where(decoding, budget - 1, budget)
        hit_eos = decoding & (eos >= 0) & (nxt == eos)
        pos = jnp.where(decoding, pos + 1, pos)
        tok = jnp.where(decoding, nxt, tok)
        active = active & ~(decoding & ((budget <= 0) | hit_eos))
        cache = _constrain_cache(cache, shardings)
        tok, pos, active, budget, rngs, nxt, emitted = (
            _constrain(a, rep) for a in (tok, pos, active, budget, rngs,
                                         nxt, emitted))
        return (tok, pos, active, budget, cache, rngs), (nxt, emitted)

    carry = (tok, pos, active, budget, cache, rngs)
    (tok, pos, active, budget, cache, rngs), (toks, emitted) = jax.lax.scan(
        body, carry, (pf_toks, steps), length=length)
    return toks, emitted, tok, pos, active, budget, cache, rngs


def verify_step_paged(params: Params, window: jnp.ndarray, pos: jnp.ndarray,
                      n_valid: jnp.ndarray, active: jnp.ndarray,
                      tables: jnp.ndarray, cache: Dict[str, jnp.ndarray],
                      cfg: ModelConfig, *, shardings=None,
                      logits_rows: Optional[int] = None,
                      use_kernel: bool = False
                      ) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """``verify_step_multi`` over a paged pool: the speculative window's
    K/V scatters through each slot's page table and the whole drafted
    window attends the gathered logical view. ``shardings`` pins the
    pool layout per layer on a serving mesh (see ``_constrain``).

    Window token j of slot b sits at logical position pos[b]+j, physical
    page ``tables[b, (pos+j)//page]`` offset ``(pos+j) % page``. Padding
    positions (j > n_valid) AND every position of inactive rows route
    their page offset to ``page`` — out of bounds, where the scatter
    drops the update (a stale table must never be written through; see
    ``decode_step_paged``). Per-row logits are ``verify_step_multi``'s
    exactly, so speculative greedy parity survives paging unchanged.
    ``logits_rows`` limits the final layernorm + vocab head to the
    first that-many window rows (the mixed-window caller samples only
    row 0 — projecting all W rows to the vocab every scan step would
    multiply the head cost by the chunk width for nothing); None keeps
    the full (B, W, V) output the speculative verifier needs.

    ``use_kernel`` routes the attention core through the unified paged
    Pallas kernel (``paged_window_attention`` / its shard_map wrapper):
    the kernel attends the STALE pool (positions < pos) plus the causal
    fresh window in-launch, then the scatter lands AFTER — equivalent to
    this function's scatter-then-gather because valid query rows only
    ever attend valid fresh rows (``valid`` is a prefix mask) and the
    quantized fresh rows are fake-quantized to exactly what the scatter
    stores. Padding rows (j > n_valid) and inactive rows produce
    garbage either way and are discarded by callers (the diagonal
    self-attention keeps them NaN-free). Callers gate on
    ``ops.paged_pallas.mixed_step_kernel_ok`` + packed layout.
    """
    cd = _dtype(cfg.dtype)
    B, W = window.shape
    packed = cfg.decode_cache_layout == "packed"
    psz = paged_page_size(cfg, cache)
    mp = tables.shape[1]
    H = cfg.n_head
    Smax = mp * psz
    offs = jnp.arange(W, dtype=jnp.int32)[None, :]      # (1, W)
    pos_eff = jnp.where(active, pos, 0)
    m_eff = jnp.where(active, n_valid, 0)
    abs_pos = pos_eff[:, None] + offs                   # (B, W)
    # wpe gather clamps padding rows (real window positions are bounded
    # host-side: pos + n_valid <= block_size - 1)
    x = (params["wte"].astype(cd)[window]
         + params["wpe"].astype(cd)[jnp.minimum(abs_pos,
                                                cfg.block_size - 1)])
    valid = (offs <= m_eff[:, None]) & active[:, None]
    lpage = jnp.minimum(abs_pos // psz, mp - 1)
    phys = jnp.take_along_axis(tables, lpage, axis=1)   # (B, W)
    woff = jnp.where(valid & (abs_pos < Smax), abs_pos % psz, psz)
    quantized = "ks" in cache
    mesh = _serve_kernel_mesh(shardings)

    def body(carry, inputs):
        h_in, cc = carry
        lp, layer_idx = inputs
        q_m, k_m, v_m = _cached_qkv_merged(h_in, lp, cfg, cd)  # (B, W, C)
        if use_kernel:
            # attend stale pool + causal fresh window in-kernel, then
            # scatter (write-then-attend equivalence, see docstring)
            k_layer = jax.lax.dynamic_index_in_dim(cc["k"], layer_idx,
                                                   0, keepdims=False)
            v_layer = jax.lax.dynamic_index_in_dim(cc["v"], layer_idx,
                                                   0, keepdims=False)
            k_w, v_w = k_m, v_m
            ks_layer = vs_layer = None
            if quantized:
                from ..quant.kv import (fake_quantize_rows,
                                        pool_quant_mode)
                kv_dtype, gran = pool_quant_mode(cc)
                k_w = fake_quantize_rows(k_m, kv_dtype, H,
                                         gran).astype(cd)
                v_w = fake_quantize_rows(v_m, kv_dtype, H,
                                         gran).astype(cd)
                ks_layer = jax.lax.dynamic_index_in_dim(
                    cc["ks"], layer_idx, 0, keepdims=False)
                vs_layer = jax.lax.dynamic_index_in_dim(
                    cc["vs"], layer_idx, 0, keepdims=False)
            attn_merged = _paged_window_attn(
                q_m, k_w, v_w, k_layer, v_layer, tables, pos_eff, H,
                ks_layer, vs_layer, mesh)
            cc = _scatter_kv(cc, layer_idx, phys, woff, k_m, v_m,
                             packed, H)
        else:
            # scatter values laid out phys.shape-major: advanced
            # indices (phys, woff) broadcast to (B, W) and land first
            cc = _scatter_kv(cc, layer_idx, phys, woff, k_m, v_m,
                             packed, H)
            q_h = _split_heads(q_m, H)
            k_all, v_all = _gather_kv(cc, layer_idx, tables, packed, H,
                                      cd)
            attn_merged = _merge_heads(windowed_cached_attention(
                q_h, k_all, v_all, pos_eff))
        cc = _constrain_cache(cc, shardings)
        return (_cached_block_tail(h_in, attn_merged, lp, cfg, cd),
                cc), None

    if cfg.use_layer_scan:
        layer_ids = jnp.arange(cfg.n_layer)
        (x, cc), _ = jax.lax.scan(
            body, (x, dict(cache)), (params["blocks"], layer_ids))
    else:
        carry = (x, dict(cache))
        for i in range(cfg.n_layer):
            lp = jax.tree_util.tree_map(lambda a: a[i], params["blocks"])
            carry, _ = body(carry, (lp, i))
        x, cc = carry
    if logits_rows is not None:
        x = x[:, :logits_rows, :]
    x = _layer_norm(x, params["ln_f_scale"], params["ln_f_bias"],
                    cfg.layernorm_eps)
    head = (params["wte"].astype(cd).T if cfg.tied_head
            else params["lm_head"].astype(cd))
    return (x @ head).astype(jnp.float32), cc


def prefill_chunk_paged(params: Params, idx: jnp.ndarray,
                        offset: jnp.ndarray, limit: jnp.ndarray,
                        table_row: jnp.ndarray,
                        cache: Dict[str, jnp.ndarray], cfg: ModelConfig, *,
                        shardings=None) -> Dict[str, jnp.ndarray]:
    """Chunked prefill of ONE slot's prompt through its page table.
    ``shardings`` pins the pool layout per layer on a serving mesh
    (see ``_constrain``).

    idx: (1, Pc) chunk of the prompt; offset: scalar int32 first
    absolute position (with a prefix-cache hit the first chunk starts at
    the first UNCACHED token, any position — no chunk-alignment
    requirement); limit: scalar int32 true prompt length — writes at
    positions >= limit are DROPPED. Dropping padding is load-bearing
    here where the contiguous pool merely tolerated it: a padded final
    chunk's tail positions can fall past the slot's reserved pages,
    where the clamped table entry (0) references a page owned by a
    DIFFERENT request. Queries attend the gathered logical view masked
    to k <= offset+i (``windowed_cached_attention`` — write-then-attend
    across chunks, exactly ``prefill_chunk_into_slot``'s discipline);
    padded queries' outputs are garbage and discarded.
    """
    cd = _dtype(cfg.dtype)
    _, Pc = idx.shape
    packed = cfg.decode_cache_layout == "packed"
    psz = paged_page_size(cfg, cache)
    mp = table_row.shape[0]
    H = cfg.n_head
    Smax = mp * psz
    positions = offset + jnp.arange(Pc, dtype=jnp.int32)   # (Pc,)
    # eager calls assert; the engine bounds [offset, limit) at admission
    check_in_bounds(offset, 1, cfg.block_size, what="paged prefill chunk")
    x = (params["wte"].astype(cd)[idx]
         + params["wpe"].astype(cd)[jnp.minimum(positions,
                                                cfg.block_size - 1)][None])
    lpage = jnp.minimum(positions // psz, mp - 1)
    phys = table_row[lpage]                                # (Pc,)
    woff = jnp.where((positions < limit) & (positions < Smax),
                     positions % psz, psz)
    base = jnp.reshape(offset, (1,))

    def body(carry, inputs):
        h_in, cc = carry
        lp, layer_idx = inputs
        q_m, k_m, v_m = _cached_qkv_merged(h_in, lp, cfg, cd)  # (1, Pc, C)
        cc = _scatter_kv(cc, layer_idx, phys, woff, k_m[0], v_m[0],
                         packed, H)
        k_all, v_all = _gather_kv(cc, layer_idx, table_row[None],
                                  packed, H, cd)
        attn = windowed_cached_attention(_split_heads(q_m, H), k_all,
                                         v_all, base)
        cc = _constrain_cache(cc, shardings)
        return (_cached_block_tail(h_in, _merge_heads(attn), lp, cfg, cd),
                cc), None

    if cfg.use_layer_scan:
        layer_ids = jnp.arange(cfg.n_layer)
        (_, cc), _ = jax.lax.scan(
            body, (x, dict(cache)), (params["blocks"], layer_ids))
    else:
        carry = (x, dict(cache))
        for i in range(cfg.n_layer):
            lp = jax.tree_util.tree_map(lambda a: a[i], params["blocks"])
            carry, _ = body(carry, (lp, i))
        _, cc = carry
    return cc


def prefill_chunk_into_slot(params: Params, idx: jnp.ndarray,
                            offset: jnp.ndarray, slot: jnp.ndarray,
                            cache: Dict[str, jnp.ndarray], cfg: ModelConfig
                            ) -> Dict[str, jnp.ndarray]:
    """Chunked prefill into ONE slot of a pooled multi-slot KV cache.

    idx: (1, Pc) int32 — a chunk of the prompt; offset: scalar int32 —
    the chunk's first absolute position; slot: scalar int32 — the pool
    slot. Writes the chunk's K/V rows at cache[:, slot, ..,
    offset:offset+Pc, ..] and runs the block stack with each query at
    position offset+i attending the slot's whole cache buffer masked to
    j <= offset+i (write-then-attend: chunk 2's queries see chunk 1's
    K/V through the buffer, so a long prompt prefills in fixed-size
    chunks under ONE compiled program regardless of prompt length —
    the serving engine's admission path). Positions beyond the true
    prompt inside a right-padded final chunk hold padding-derived K/V;
    same invariant as ``prefill``: decode overwrites position p before
    attending it, and the per-query mask hides everything later.
    Masked-out buffer entries get exactly zero softmax weight (f32
    underflow of NEG_INF), so the math per valid row is the
    ``full_causal_attention`` einsum's.
    """
    cd = _dtype(cfg.dtype)
    _, Pc = idx.shape
    H, S = cfg.n_head, cache["k"].shape[cache_seq_axis(cfg)]
    # THE site of PR 1's clamp bug: a padded final chunk whose offset
    # pushes past the buffer would silently overwrite chunk 1's K/V.
    # Eager calls assert here; the jitted serving path (offset traced)
    # is bounded host-side at admission (Engine._admit) and by
    # EngineConfig.chunk's divisibility invariant.
    check_in_bounds(offset, Pc, S, what="prefill chunk write")
    check_in_bounds(slot, 1, cache["k"].shape[1], what="prefill slot index")
    scale = cfg.head_dim ** -0.5
    x = (params["wte"].astype(cd)[idx]
         + jax.lax.dynamic_slice_in_dim(params["wpe"].astype(cd), offset,
                                        Pc, axis=0))
    packed = cfg.decode_cache_layout == "packed"
    from ..ops.attention import NEG_INF

    def body(carry, inputs):
        h_in, ck, cv = carry
        lp, layer_idx = inputs
        q_m, k_m, v_m = _cached_qkv_merged(h_in, lp, cfg, cd)  # (1, Pc, C)
        zero = jnp.int32(0)
        if packed:
            start = (layer_idx, slot, offset, zero)
            ck = jax.lax.dynamic_update_slice(
                ck, k_m[None].astype(ck.dtype), start)
            cv = jax.lax.dynamic_update_slice(
                cv, v_m[None].astype(cv.dtype), start)
            k_slot = jax.lax.dynamic_index_in_dim(
                jax.lax.dynamic_index_in_dim(ck, layer_idx, 0, False),
                slot, 0, False)          # (S, C)
            v_slot = jax.lax.dynamic_index_in_dim(
                jax.lax.dynamic_index_in_dim(cv, layer_idx, 0, False),
                slot, 0, False)
            k_h = _split_heads(k_slot[None].astype(cd), H)  # (1, H, S, D)
            v_h = _split_heads(v_slot[None].astype(cd), H)
        else:
            k = _split_heads(k_m, H)                        # (1, H, Pc, D)
            v = _split_heads(v_m, H)
            start = (layer_idx, slot, zero, offset, zero)
            ck = jax.lax.dynamic_update_slice(
                ck, k[None].astype(ck.dtype), start)
            cv = jax.lax.dynamic_update_slice(
                cv, v[None].astype(cv.dtype), start)
            k_h = jax.lax.dynamic_index_in_dim(
                jax.lax.dynamic_index_in_dim(ck, layer_idx, 0, False),
                slot, 0, False)[None].astype(cd)            # (1, H, S, D)
            v_h = jax.lax.dynamic_index_in_dim(
                jax.lax.dynamic_index_in_dim(cv, layer_idx, 0, False),
                slot, 0, False)[None].astype(cd)
        q = _split_heads(q_m, H)                            # (1, H, Pc, D)
        logits = jnp.einsum("bhqd,bhkd->bhqk", q, k_h,
                            preferred_element_type=jnp.float32) * scale
        qpos = jax.lax.broadcasted_iota(jnp.int32, (Pc, S), 0) + offset
        kpos = jax.lax.broadcasted_iota(jnp.int32, (Pc, S), 1)
        logits = jnp.where(kpos <= qpos, logits, NEG_INF)
        weights = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
        attn = jnp.einsum("bhqk,bhkd->bhqd", weights.astype(v_h.dtype), v_h)
        return (_cached_block_tail(h_in, _merge_heads(attn), lp, cfg, cd),
                ck, cv), None

    if cfg.use_layer_scan:
        layer_ids = jnp.arange(cfg.n_layer)
        (_, ck, cv), _ = jax.lax.scan(
            body, (x, cache["k"], cache["v"]),
            (params["blocks"], layer_ids))
    else:
        carry = (x, cache["k"], cache["v"])
        for i in range(cfg.n_layer):
            lp = jax.tree_util.tree_map(lambda a: a[i], params["blocks"])
            carry, _ = body(carry, (lp, i))
        _, ck, cv = carry
    return {"k": ck, "v": cv}
