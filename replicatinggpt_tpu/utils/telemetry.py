"""Request-lifecycle tracing + unified telemetry export.

The serving engine's only evidence of where time goes used to be one
end-of-run ``metrics_summary()`` dict and a raw ``jax.profiler`` trace
with no request context. This module is the measurement substrate the
scaling roadmap items lean on (per-phase timelines are how the pjit
TPUv4 and Gemma-on-TPU serving playbooks attribute cost): a
zero-cost-when-disabled event/span recorder plus three exporters.

- :class:`Telemetry` — monotonic-clock span/instant recorder over a
  bounded ring buffer (a soak run must not grow host memory without
  bound — the ``Metrics`` reservoir rationale), with an optional
  append-only JSONL sink whose reader tolerates a torn tail (the crash
  window lands mid-write, exactly like ``serve.journal``). Spans taken
  through :meth:`Telemetry.span` also enter ``profiling.annotate``, so
  the same host region shows up on the XLA device timeline a
  ``jax.profiler`` capture of the run produces — the two traces line
  up by region name.
- Chrome trace-event JSON (:meth:`Telemetry.export_chrome_trace` /
  :func:`chrome_trace_from_jsonl`) — load the file straight into
  Perfetto (ui.perfetto.dev) or ``chrome://tracing``. The serving
  engine lays requests out as one span tree per request on per-slot
  tracks: request B/E envelope, queue/admit/prefill/decode/verify
  complete-events nested inside, prefix-hit/COW/eviction/recovery
  instants on the same timeline.
- Metrics snapshot timeline (:class:`MetricsTimeline`) — a periodic
  JSONL time series of every counter/gauge/histogram in a
  ``utils.logging.Metrics``, for soak runs where one end-of-run
  summary hides the interesting transient.
- Prometheus text exposition (:func:`prometheus_text`) — the scrape
  format an HTTP front door serves from ``/metrics``.

Zero-cost-when-disabled is load-bearing: the :data:`NULL` recorder is
what every instrumented subsystem holds by default, its methods are
no-ops, its ``span()`` returns one shared reusable null context (no
per-call allocation), and nothing in this module performs a
device->host sync — graftlint GL004-clean with zero pragmas (pinned in
tests/test_telemetry.py, along with the no-buffer-growth property).
"""

from __future__ import annotations

import contextlib
import json
import re
import time
from collections import deque
from typing import Any, Callable, Dict, Iterator, List, Optional, TextIO

from .jsonl import load_jsonl

__all__ = [
    "ENGINE_TRACK", "SLOT_TRACK_BASE", "REPLICA_TRACK_STRIDE",
    "ROUTER_TRACK", "ROUTER_TRACK_NAME", "NULL", "NullTelemetry",
    "Telemetry", "MetricsTimeline", "chrome_trace_from_jsonl",
    "load_jsonl", "prometheus_text", "PROM_PINNED_COUNTERS",
]

#: The fleet-dashboard counter schema: every name a Grafana panel or
#: alert rule keys on. ``prometheus_text`` emits each of these at 0
#: even before its first increment, so a freshly started router scrapes
#: a complete series set (a rate() over a counter that APPEARS mid-run
#: is indistinguishable from a restart). graftlint GL021 holds this
#: tuple against the actual ``metrics.inc(...)`` literals — a counter
#: renamed in code without updating this pin (or vice versa) is a
#: silently-flatlined dashboard panel.
PROM_PINNED_COUNTERS = (
    # serve/router.py — fleet lifecycle, routing, disagg, transfers
    "fleet_ledger_recovered", "fleet_requests_submitted",
    "fleet_dedup_rejects", "fleet_replica_downs", "fleet_replicas_added",
    "fleet_requeued_requests", "fleet_ghost_cancels",
    "fleet_replica_attaches", "fleet_drains", "fleet_requests_routed",
    "fleet_route_fallbacks", "fleet_disagg_shortcircuits",
    "fleet_disagg_fallbacks", "fleet_disagg_prefills", "fleet_transfers",
    "fleet_transfer_pages", "fleet_transfer_bytes",
    "fleet_transfer_failures", "fleet_stale_finishes",
    "fleet_ghost_finishes", "fleet_requests_finished",
    "fleet_replica_rejoins", "fleet_replica_wedges", "fleet_replica_kills",
    "fleet_requeue_submits", "fleet_requeue_exhausted",
    "fleet_requeue_retries",
    # faults/procsup.py — autoscaler actions
    "fleet_scale_ups", "fleet_scale_downs",
    # serve/http.py — front-door admission
    "http_rate_limited",
    # serve/router.py — RPC protocol hardening under network faults
    # (serve/rpc.py checksums + idempotency, faults/netchaos.py)
    "rpc_dup_suppressed", "rpc_corrupt_frames", "rpc_partitions_active",
    "rpc_stale_generation_rejects",
)

#: engine-level track (steps, drafts, recovery markers); per-slot
#: request trees live on SLOT_TRACK_BASE + slot
ENGINE_TRACK = 0
SLOT_TRACK_BASE = 1

#: fleet layout: replica ``i``'s engine passes ``track_base = i *
#: REPLICA_TRACK_STRIDE`` so its engine/slot tracks never collide with a
#: neighbor's on the shared fleet recorder (pool sizes are far below the
#: stride). The router's own spans/instants (route decisions, requeues,
#: health transitions) live on ROUTER_TRACK, named ROUTER_TRACK_NAME —
#: tools/trace_check.py recognizes the *name*, so it needs no import.
REPLICA_TRACK_STRIDE = 100
ROUTER_TRACK = 9000
ROUTER_TRACK_NAME = "router"


class _NullSpan:
    """Reusable, reentrant no-op context manager (shared instance)."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class NullTelemetry:
    """The disabled recorder: every method is a no-op, ``span`` hands
    back one shared context manager, and no state ever accumulates.
    Instrumented hot loops additionally guard whole blocks with
    ``if tel.enabled:`` so the disabled step path pays one attribute
    read, not N method calls."""

    enabled = False
    events: tuple = ()

    def span(self, name: str, track: int = ENGINE_TRACK, **args):
        return _NULL_SPAN

    def begin(self, name, track=ENGINE_TRACK, ts_us=None, **args) -> None:
        pass

    def end(self, name, track=ENGINE_TRACK, ts_us=None, **args) -> None:
        pass

    def complete(self, name, track, ts_us, dur_us, **args) -> None:
        pass

    def instant(self, name, track=ENGINE_TRACK, ts_us=None, **args) -> None:
        pass

    def name_track(self, track: int, name: str) -> None:
        pass

    def now_us(self) -> float:
        return 0.0

    def ts_us(self, t: float) -> float:
        return 0.0

    def close(self) -> None:
        pass


#: the module-wide disabled recorder — hold this, not None, so call
#: sites never branch on presence
NULL = NullTelemetry()


class Telemetry:
    """Enabled span/instant recorder.

    Events are Chrome trace-event dicts (``ph`` B/E/X/i) over a
    monotonic clock, appended to a bounded ring buffer and (optionally)
    streamed to a JSONL sink as they happen — a crash preserves the
    prefix, and the tolerant readers below skip the torn final line.
    ``clock`` is injectable for deterministic tests and so the serving
    engine's fake-clock tests keep request timestamps coherent with
    span timestamps.
    """

    enabled = True

    def __init__(self, capacity: int = 1 << 16,
                 jsonl_path: Optional[str] = None,
                 clock: Callable[[], float] = time.monotonic,
                 process_name: str = "replicatinggpt_tpu"):
        self._clock = clock
        self._t0 = clock()
        self.events: deque = deque(maxlen=capacity)
        # 'w', not 'a': each recorder is one run's artifact — appending
        # a rerun onto a reused path would duplicate request envelopes
        # (which trace_check rightly rejects). The journal keeps append
        # semantics; this sink does not want them.
        self._sink: Optional[TextIO] = (open(jsonl_path, "w")
                                        if jsonl_path else None)
        self._track_names: Dict[int, str] = {}
        self.process_name = process_name

    # ------------------------------------------------------------- clock

    def ts_us(self, t: float) -> float:
        """A ``clock()`` reading -> trace microseconds (relative to
        recorder construction, so timestamps stay small and the trace
        starts near 0)."""
        return (t - self._t0) * 1e6

    def now_us(self) -> float:
        return self.ts_us(self._clock())

    # ------------------------------------------------------------ record

    def _emit(self, ev: dict) -> None:
        self.events.append(ev)
        if self._sink is not None:
            # flushed per event: the sink's whole point is surviving a
            # crash mid-run (torn-tail-tolerant readers handle the rest)
            self._sink.write(json.dumps(ev) + "\n")
            self._sink.flush()

    def name_track(self, track: int, name: str) -> None:
        """Register a human-readable track (thread) name once."""
        if self._track_names.get(track) == name:
            return
        self._track_names[track] = name
        if self._sink is not None:
            # the crash-tolerant sink must carry the metadata too: a
            # trace assembled offline (chrome_trace_from_jsonl) needs
            # the thread_name M event for trace_check's router-track
            # envelope exemption
            self._sink.write(json.dumps(
                {"ph": "M", "name": "thread_name", "pid": 0,
                 "tid": track, "args": {"name": name}}) + "\n")
            self._sink.flush()

    def begin(self, name: str, track: int = ENGINE_TRACK,
              ts_us: Optional[float] = None, **args) -> None:
        """Open a span (phase B). ``ts_us`` lets the caller backdate —
        the engine opens a request's envelope at its *submit* time once
        the request is admitted (viewers sort by ts, so out-of-order
        emission is fine)."""
        ev = {"ph": "B", "name": name, "tid": track,
              "ts": self.now_us() if ts_us is None else ts_us}
        if args:
            ev["args"] = args
        self._emit(ev)

    def end(self, name: str, track: int = ENGINE_TRACK,
            ts_us: Optional[float] = None, **args) -> None:
        ev = {"ph": "E", "name": name, "tid": track,
              "ts": self.now_us() if ts_us is None else ts_us}
        if args:
            ev["args"] = args
        self._emit(ev)

    def complete(self, name: str, track: int, ts_us: float,
                 dur_us: float, **args) -> None:
        """One closed span (phase X) with explicit start + duration."""
        ev = {"ph": "X", "name": name, "tid": track, "ts": ts_us,
              "dur": max(dur_us, 0.0)}
        if args:
            ev["args"] = args
        self._emit(ev)

    def instant(self, name: str, track: int = ENGINE_TRACK,
                ts_us: Optional[float] = None, **args) -> None:
        """A point marker (phase i) — recovery events, COW splits,
        evictions, prefix hits land on the timeline as these."""
        ev = {"ph": "i", "name": name, "tid": track, "s": "t",
              "ts": self.now_us() if ts_us is None else ts_us}
        if args:
            ev["args"] = args
        self._emit(ev)

    @contextlib.contextmanager
    def span(self, name: str, track: int = ENGINE_TRACK,
             **args) -> Iterator[None]:
        """Timed region recorded as one X event on exit, wrapped in
        ``profiling.annotate`` so the same region appears on the XLA
        device timeline of a concurrent ``jax.profiler`` capture."""
        from .profiling import annotate    # lazy: keep module import
        t0 = self.now_us()                 # jax-free for the exporters
        try:
            with annotate(name):
                yield
        finally:
            self.complete(name, track, t0, self.now_us() - t0, **args)

    # ------------------------------------------------------------ export

    def chrome_events(self) -> List[dict]:
        """Trace-event list: metadata (process/thread names) + the ring
        buffer's events, normalized with pid and track sort order."""
        meta: List[dict] = [{
            "ph": "M", "name": "process_name", "pid": 0, "tid": 0,
            "args": {"name": self.process_name}}]
        for tid, name in sorted(self._track_names.items()):
            meta.append({"ph": "M", "name": "thread_name", "pid": 0,
                         "tid": tid, "args": {"name": name}})
            meta.append({"ph": "M", "name": "thread_sort_index", "pid": 0,
                         "tid": tid, "args": {"sort_index": tid}})
        return meta + [{**ev, "pid": 0} for ev in self.events]

    def export_chrome_trace(self, path: str) -> int:
        """Write the Perfetto-loadable JSON; returns the event count
        (metadata included). The ring buffer bounds memory, so a very
        long soak exports its most recent window — the JSONL sink is
        the full-history option."""
        events = self.chrome_events()
        with open(path, "w") as f:
            json.dump({"traceEvents": events,
                       "displayTimeUnit": "ms"}, f)
        return len(events)

    def close(self) -> None:
        if self._sink is not None:
            self._sink.close()
            self._sink = None


# ---------------------------------------------------------------------------
# offline Chrome-trace assembly (the torn-tail-tolerant JSONL reader
# itself is utils.jsonl.load_jsonl — one implementation shared with the
# request journal and the fleet router's journal replay; re-exported
# here for existing callers)
# ---------------------------------------------------------------------------

def chrome_trace_from_jsonl(jsonl_path: str, out_path: str,
                            process_name: str = "replicatinggpt_tpu"
                            ) -> int:
    """Assemble a Perfetto-loadable trace from a (possibly torn) event
    sink — the offline path for a crashed run whose in-memory recorder
    died with it."""
    events = [{**ev, "pid": 0} for ev in load_jsonl(jsonl_path)
              if "ph" in ev]
    meta = [{"ph": "M", "name": "process_name", "pid": 0, "tid": 0,
             "args": {"name": process_name}}]
    with open(out_path, "w") as f:
        json.dump({"traceEvents": meta + events,
                   "displayTimeUnit": "ms"}, f)
    return len(events)


# ---------------------------------------------------------------------------
# Metrics snapshot timeline (JSONL time series)
# ---------------------------------------------------------------------------

class MetricsTimeline:
    """Periodic JSONL snapshots of a ``utils.logging.Metrics``.

    One line per snapshot: wall offset, a caller-supplied step counter,
    every counter and gauge, and the histogram summaries. The replay
    driver snapshots on attach, every ``interval_s`` while running, and
    force-snapshots at the end — so even a sub-interval run yields the
    >= 2 points a timeline needs to show direction.
    """

    def __init__(self, metrics, path: str, interval_s: float = 0.5,
                 clock: Callable[[], float] = time.monotonic):
        self.metrics = metrics
        self.path = path
        self.interval_s = interval_s
        self._clock = clock
        self._t0 = clock()
        self._last: Optional[float] = None
        # 'w': one run per timeline file — a reused path must not mix
        # two runs' series (t_s/counters would reset mid-stream)
        self._f: Optional[TextIO] = open(path, "w")
        self.n_snapshots = 0

    def maybe_snapshot(self, step: Optional[int] = None) -> bool:
        now = self._clock()
        if self._last is not None and now - self._last < self.interval_s:
            return False
        self.snapshot(step=step, _now=now)
        return True

    def snapshot(self, step: Optional[int] = None,
                 _now: Optional[float] = None, **extra) -> None:
        assert self._f is not None, "timeline is closed"
        now = self._clock() if _now is None else _now
        self._last = now
        s = self.metrics.summary()
        rec = {"t_s": round(now - self._t0, 6), "step": step,
               "counters": s["counters"], "gauges": s["gauges"],
               "histograms": s["histograms"], **extra}
        self._f.write(json.dumps(rec) + "\n")
        self._f.flush()
        self.n_snapshots += 1

    def close(self, step: Optional[int] = None) -> None:
        """Force a final snapshot (the end-of-run point) and close."""
        if self._f is None:
            return
        self.snapshot(step=step)
        self._f.close()
        self._f = None

    @staticmethod
    def load(path: str) -> List[dict]:
        return load_jsonl(path)


# ---------------------------------------------------------------------------
# Prometheus text exposition
# ---------------------------------------------------------------------------

_PROM_BAD = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name: str, prefix: str) -> str:
    n = _PROM_BAD.sub("_", name)
    if n and n[0].isdigit():
        n = "_" + n
    return f"{prefix}_{n}" if prefix else n


def _prom_value(v) -> str:
    """Full-precision sample value: json.dumps is the shortest string
    that round-trips the number exactly — '%g' would silently collapse
    a 1,234,567-token counter to 1.23457e+06, corrupting every
    rate/delta computed from the scrape."""
    if isinstance(v, bool):
        v = 1 if v else 0
    return json.dumps(v)


def prometheus_text(metrics, prefix: str = "tpu_gpt",
                    extra_gauges: Optional[Dict[str, Any]] = None) -> str:
    """Render a ``Metrics`` in the Prometheus text exposition format
    (v0.0.4 — what a ``/metrics`` scrape endpoint serves): counters as
    ``counter``, gauges as ``gauge``, histograms as ``summary`` with
    p50/p90/p99 quantiles plus ``_sum``/``_count``/``_min``/``_max``
    companions derived from the reservoir summary. ``extra_gauges``
    lets the caller fold in derived values (pages_in_use, spec accept
    rate, ...) without teaching Metrics about them."""
    lines: List[str] = []
    counters = dict(metrics.counters)
    for name in PROM_PINNED_COUNTERS:
        counters.setdefault(name, 0)
    for name in sorted(counters):
        pn = _prom_name(name, prefix)
        lines.append(f"# TYPE {pn} counter")
        lines.append(f"{pn} {_prom_value(counters[name])}")
    gauges = dict(metrics.gauges)
    if extra_gauges:
        gauges.update(extra_gauges)
    for name in sorted(gauges):
        pn = _prom_name(name, prefix)
        lines.append(f"# TYPE {pn} gauge")
        lines.append(f"{pn} {_prom_value(gauges[name])}")
    for name in sorted(metrics.hists):
        pn = _prom_name(name, prefix)
        h = metrics.hist_summary(name)
        lines.append(f"# TYPE {pn} summary")
        for q in ("0.5", "0.9", "0.99"):
            key = {"0.5": "p50", "0.9": "p90", "0.99": "p99"}[q]
            lines.append(f'{pn}{{quantile="{q}"}} {_prom_value(h[key])}')
        lines.append(f"{pn}_sum {_prom_value(h['mean'] * h['n'])}")
        lines.append(f"{pn}_count {_prom_value(h['n'])}")
        lines.append(f"{pn}_min {_prom_value(h['min'])}")
        lines.append(f"{pn}_max {_prom_value(h['max'])}")
    return "\n".join(lines) + "\n"
