from .logging import StepLogger
from .sanitize import (CompileGuard, DonationError, RecompileError,
                       assert_donated, check_in_bounds, donation_report,
                       sanitize_enabled, sanitized)

__all__ = ["CompileGuard", "DonationError", "RecompileError", "StepLogger",
           "assert_donated", "check_in_bounds", "donation_report",
           "sanitize_enabled", "sanitized"]
