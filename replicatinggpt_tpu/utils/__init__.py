from .logging import StepLogger

__all__ = ["StepLogger"]
