"""Runtime sanitizers: the dynamic half of graftlint.

The static rules (analysis/) catch hazards with a syntactic footprint;
these guards catch the ones only visible at run time:

- :class:`CompileGuard` — wraps a jitted callable and fails loudly when
  it compiles more distinct programs than budgeted. Generalizes the
  serve engine's ad-hoc two-program assertion: the engine now guards
  its decode and prefill jits, and the train runner guards the train
  step, so a silent steady-state recompile (shape/dtype drift, a
  weak-type promotion, a committed/uncommitted placement split — the
  exact bug class PR 1 hit) surfaces as an exception naming the
  program instead of as a 40% throughput mystery.
- :func:`check_in_bounds` — the sanctioned guard for
  ``dynamic_update_slice`` starts (lint rule GL006): asserts on
  concrete values, no-op on tracers (jit callers must bound the index
  host-side — the serving engine does, at admission).
- :func:`donation_report` / :func:`assert_donated` — donation is a
  *request*; XLA can decline it (or the backend may not support it at
  all) and the only symptom is doubled peak HBM. These inspect
  ``jax.Array.is_deleted`` after a donating call to verify the old
  buffers actually died.
- :func:`sanitized` / :func:`sanitize_enabled` — ``GRAFT_SANITIZE=1``
  turns on jax's tracer-leak checker and NaN checks around the train
  and serve loops (opt-in: both checks cost compile time and disable
  some fusions, so they are debug equipment, not defaults).
"""

from __future__ import annotations

import contextlib
import os
from typing import Any, Callable, Dict, Iterable, Optional


class RecompileError(RuntimeError):
    """A guarded jit compiled more programs than its budget."""


class DonationError(RuntimeError):
    """Buffers donated to a jitted call are still alive after it."""


class CompileGuard:
    """Budgeted recompile detector around one jitted callable.

    Counts compiled programs via the jit cache size, attributing to
    this guard only the growth observed *across its own calls* —
    module-level jits accumulate programs from every caller (each pool
    shape the serve engine has ever used), so neither the absolute
    size nor cross-call growth means anything to one owner; a compile
    that happened inside a call this guard made is exactly its compile
    count. ``max_programs`` is the number of distinct programs the
    owner expects to trigger (1 for a steady-state step; the first
    compile is legitimate, the second is the bug).

    Raises :class:`RecompileError` from the call that exceeded the
    budget, with the usual suspects listed — by construction the
    offending call is the one that changed something.
    """

    def __init__(self, fn: Callable, name: str, max_programs: int = 1):
        self._fn = fn
        self.name = name
        self.max_programs = max_programs
        self._compiles = 0
        self.calls = 0

    def _cache_size(self) -> int:
        size = getattr(self._fn, "_cache_size", None)
        return int(size()) if callable(size) else 0

    @property
    def compiles(self) -> int:
        """Programs compiled during this guard's own calls."""
        return self._compiles

    def expect(self, n: int) -> "CompileGuard":
        """Widen the budget (e.g. a caller that legitimately runs two
        shapes through one jit)."""
        self.max_programs = n
        return self

    def check(self) -> int:
        n = self.compiles
        if n > self.max_programs:
            raise RecompileError(
                f"CompileGuard[{self.name}]: {n} programs compiled "
                f"(budget {self.max_programs}) over {self.calls} call(s). "
                f"A steady-state jit recompiled — usual causes: an input "
                f"changed shape/dtype, a Python scalar flipped weak-type, "
                f"an input's committed/uncommitted placement changed "
                f"(device_put'd array vs raw numpy), or a static arg got "
                f"a new value. Run with GRAFT_SANITIZE=1 and see "
                f"docs/graftlint_rules.md for the static-side rules.")
        return n

    def __call__(self, *args, **kwargs):
        before = self._cache_size()
        out = self._fn(*args, **kwargs)
        self.calls += 1
        # growth across THIS call only: programs other owners of the
        # same (module-level) jit compile between our calls are theirs
        self._compiles += max(self._cache_size() - before, 0)
        self.check()
        return out

    def stats(self) -> Dict[str, int]:
        return {"calls": self.calls, "compiles": self.compiles,
                "budget": self.max_programs}


# ---------------------------------------------------------------------------
# in-bounds guard (the GL006 sanctioned pattern)
# ---------------------------------------------------------------------------

def _concrete_int(x: Any) -> Optional[int]:
    """Python int of ``x`` when it is host-knowable; None for tracers
    (and anything else that refuses int())."""
    try:
        return int(x)
    except Exception:
        return None


def check_in_bounds(start: Any, length: Any, size: Any,
                    what: str = "dynamic_update_slice") -> bool:
    """Enforce ``0 <= start`` and ``start + length <= size`` when the
    values are concrete; return False (unchecked) under tracing.

    This is the sanctioned guard for ``jax.lax.dynamic_update_slice``
    (lint rule GL006): out-of-bounds starts do not raise, they CLAMP —
    which under a cache write means silently overwriting valid earlier
    entries (PR 1's chunked-prefill corruption). Inside a jit the
    start is a tracer and cannot be checked here; the host-side caller
    owns the bound then (e.g. the serve engine's admission check), and
    eager/debug runs get a hard IndexError (a real exception, not an
    ``assert`` — the guard must survive ``python -O``). ``start`` may
    be a vector (per-slot positions): its min/max are checked.
    """
    sz = _concrete_int(size)
    ln = _concrete_int(length)
    if sz is None or ln is None:
        return False
    lo = hi = None
    try:                      # vector starts: bound the extremes
        import numpy as np
        arr = np.asarray(start)
        if arr.dtype != object and arr.size:
            lo, hi = int(arr.min()), int(arr.max())
    except Exception:
        lo = hi = _concrete_int(start)
    if lo is None or hi is None:
        return False
    if lo < 0 or hi + ln > sz:
        # a real exception, not `assert`: these guards protect against
        # silent cache corruption and must survive `python -O`
        raise IndexError(
            f"{what}: start {lo}..{hi} + length {ln} exceeds size {sz} — "
            f"dynamic_update_slice would CLAMP and corrupt earlier entries")
    return True


# ---------------------------------------------------------------------------
# donation verification
# ---------------------------------------------------------------------------

def donation_supported() -> bool:
    """Whether the default backend honors buffer donation at all (CPU
    ignores it; asserting there would always fail)."""
    import jax
    return jax.default_backend() in ("tpu", "gpu")


def donation_report(tree: Any) -> Dict[str, int]:
    """How many array leaves of ``tree`` have been invalidated.

    Call on the *inputs you donated* after the jitted call: leaves
    still alive mean XLA declined the donation (layout mismatch, or the
    buffer is aliased elsewhere) and peak memory is double what the
    donate_argnums annotation promises."""
    import jax
    deleted = live = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        is_deleted = getattr(leaf, "is_deleted", None)
        if not callable(is_deleted):
            continue
        if is_deleted():
            deleted += 1
        else:
            live += 1
    return {"deleted": deleted, "live": live}


def assert_donated(tree: Any, what: str = "donated input") -> bool:
    """Raise :class:`DonationError` if donated buffers survived the
    call — only on backends that support donation (returns False,
    checked nothing, elsewhere)."""
    if not donation_supported():
        return False
    rep = donation_report(tree)
    if rep["live"]:
        raise DonationError(
            f"{what}: {rep['live']} of {rep['live'] + rep['deleted']} "
            f"donated buffers still alive after the call — XLA declined "
            f"the donation (layout/aliasing mismatch); peak HBM is "
            f"double what donate_argnums promises")
    return True


# ---------------------------------------------------------------------------
# GRAFT_SANITIZE mode
# ---------------------------------------------------------------------------

def sanitize_enabled() -> bool:
    """Opt-in via ``GRAFT_SANITIZE=1`` (any value but ''/'0')."""
    return os.environ.get("GRAFT_SANITIZE", "") not in ("", "0")


@contextlib.contextmanager
def sanitized(enable: Optional[bool] = None):
    """Enable jax tracer-leak checking + NaN checks inside the block
    (both restored on exit). ``enable=None`` follows GRAFT_SANITIZE;
    the train runner and serve engine wrap their loops in this, so
    ``GRAFT_SANITIZE=1 python -m replicatinggpt_tpu train ...`` is a
    full sanitizer run with no code changes."""
    if enable is None:
        enable = sanitize_enabled()
    if not enable:
        yield False
        return
    import jax
    prev_leaks = jax.config.jax_check_tracer_leaks
    prev_nans = jax.config.jax_debug_nans
    jax.config.update("jax_check_tracer_leaks", True)
    jax.config.update("jax_debug_nans", True)
    try:
        yield True
    finally:
        jax.config.update("jax_check_tracer_leaks", prev_leaks)
        jax.config.update("jax_debug_nans", prev_nans)


def check_finite(value: Any, what: str = "value") -> None:
    """Host-side finiteness check for already-fetched scalars (the
    sanitize-mode hook on the train loop's logged loss)."""
    import math
    v = float(value)
    if not math.isfinite(v):
        raise FloatingPointError(f"{what} is {v} — non-finite under "
                                 f"GRAFT_SANITIZE")
