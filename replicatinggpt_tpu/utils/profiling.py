"""Tracing / profiling subsystem.

The reference has no profiler, timers, or even per-step timing (SURVEY.md §5
row 1 — ABSENT). The TPU-native equivalent supplied here:

- ``trace(logdir)``: context manager around ``jax.profiler`` emitting an XLA
  trace viewable in TensorBoard / Perfetto (device timelines, HLO op costs,
  HBM usage).
- ``trace_window``: step-triggered tracing for the hot loop — capture steps
  [start, start+n) of a training run without paying trace overhead elsewhere.
- ``start_server``: on-demand profiling of a live job from TensorBoard.
- ``annotate``: named host-side regions that show up on the trace timeline.
- ``StepTimer``: blocking per-step latency statistics (p50/p90/mean,
  tokens/sec) — used by the latency benchmarks (``bench.py --mode
  generate``, the BASELINE.json "p50 generate latency" metric); every lap
  calls ``jax.block_until_ready`` so async dispatch can't hide device
  time. Throughput benchmarks deliberately time an unsynchronized span
  instead, since a per-step device sync over a tunneled TPU would
  dominate small step times.
"""

from __future__ import annotations

import contextlib
import time
from typing import Any, Dict, List, Optional

import jax


def start_server(port: int = 9012):
    """Start the profiler RPC server so TensorBoard can capture on demand."""
    return jax.profiler.start_server(port)


@contextlib.contextmanager
def trace(logdir: str):
    """Trace everything inside the block into ``logdir``."""
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named region on the profiler timeline (host + linked device ops)."""
    return jax.profiler.TraceAnnotation(name)


class trace_window:
    """Step-triggered tracing: trace steps [start, start + n_steps).

    Usage in a loop::

        win = trace_window(logdir, start=10, n_steps=5)
        for it in range(max_iters):
            win.step(it)        # starts/stops the trace at the boundaries
            ...
        win.close()             # in case the loop ended mid-window

    ``step`` may be called with strides > 1 (the runner's multi-step scan
    dispatches advance K steps at a time): the window opens at the first
    call at-or-past ``start`` and closes at the first call at-or-past
    ``stop_at`` after opening, then never reopens — a jumped-over window
    still produces a trace of at least one dispatch.
    """

    def __init__(self, logdir: Optional[str], start: int = 10,
                 n_steps: int = 5):
        self.logdir = logdir
        self.start = start
        self.stop_at = start + n_steps
        self._active = False
        self._done = False

    def step(self, it: int) -> None:
        if not self.logdir or self._done:
            return
        if self._active and it >= self.stop_at:
            jax.profiler.stop_trace()
            self._active = False
            self._done = True
        elif not self._active and it >= self.start:
            jax.profiler.start_trace(self.logdir)
            self._active = True

    def close(self) -> None:
        if self._active:
            jax.profiler.stop_trace()
            self._active = False
            self._done = True


class StepTimer:
    """Blocking wall-clock statistics for jitted steps."""

    def __init__(self) -> None:
        self.laps: List[float] = []
        self._t0: Optional[float] = None

    def start(self) -> None:
        self._t0 = time.perf_counter()

    def lap(self, *block_on: Any) -> float:
        """End the current lap, blocking on ``block_on`` first. Returns
        the lap time and immediately starts the next lap.

        Blocking is a real device->host fetch (``jax.device_get``), not
        ``block_until_ready``: some tunneled PJRT backends (axon) return
        from block_until_ready before device execution finishes, which
        makes latency laps impossibly fast. A fetch cannot lie.
        """
        if block_on:
            jax.device_get(block_on)
        now = time.perf_counter()
        assert self._t0 is not None, "call start() before lap()"
        dt = now - self._t0
        self.laps.append(dt)
        self._t0 = now
        return dt

    @staticmethod
    def _pct(sorted_laps: List[float], q: float) -> float:
        if not sorted_laps:
            return 0.0
        i = min(int(q * (len(sorted_laps) - 1) + 0.5), len(sorted_laps) - 1)
        return sorted_laps[i]

    def summary(self, tokens_per_step: int = 0, n_chips: int = 1,
                skip: int = 0) -> Dict[str, float]:
        """Stats over laps[skip:] (skip warmup/compile laps)."""
        laps = self.laps[skip:]
        if not laps:
            return {"n": 0, "mean_s": 0.0, "p50_s": 0.0, "p90_s": 0.0,
                    "tokens_per_sec_per_chip": 0.0}
        s = sorted(laps)
        mean = sum(laps) / len(laps)
        p50 = self._pct(s, 0.50)
        out = {"n": float(len(laps)), "mean_s": mean, "p50_s": p50,
               "p90_s": self._pct(s, 0.90),
               "tokens_per_sec_per_chip": 0.0}
        if tokens_per_step and p50 > 0:
            out["tokens_per_sec_per_chip"] = (
                tokens_per_step / p50 / max(n_chips, 1))
        return out
