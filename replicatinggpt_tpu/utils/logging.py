"""Structured step logging.

The reference's observability is bare print() calls (SURVEY.md §5): periodic
``step {i} : train loss X, val loss = Y`` (GPT1.py:225) and per-step
``Step {i}, Loss: L`` (GPT-2.py:229). This logger keeps those exact
human-readable formats (for parity eyeballing) and adds a JSONL stream with
throughput (tokens/sec/chip — the BASELINE.json primary metric).
"""

from __future__ import annotations

import json
import sys
import time
from typing import Optional, TextIO


class StepLogger:
    def __init__(self, jsonl_path: Optional[str] = None,
                 stream: TextIO = sys.stdout, quiet: bool = False):
        self.stream = stream
        self.jsonl = open(jsonl_path, "a") if jsonl_path else None
        self.t_last = time.perf_counter()
        # quiet silences everything (set on non-coordinator hosts of a
        # multi-process run so the pod logs once, not n_proc times)
        self.quiet = quiet

    def log_step(self, step: int, loss: float, tokens: int,
                 n_chips: int = 1, lr: Optional[float] = None) -> None:
        if self.quiet:
            return
        now = time.perf_counter()
        dt = max(now - self.t_last, 1e-9)
        self.t_last = now
        tps = tokens / dt / max(n_chips, 1)
        # GPT-2.py:229 format, extended
        print(f"Step {step}, Loss: {loss:.6f} | {tps:,.0f} tok/s/chip",
              file=self.stream)
        self._jsonl({"event": "step", "step": step, "loss": float(loss),
                     "tokens_per_sec_per_chip": tps, "lr": lr,
                     "time": time.time()})

    def log_eval(self, step: int, train_loss: float, val_loss: float) -> None:
        if self.quiet:
            return
        # GPT1.py:225 format
        print(f"step {step} : train loss {train_loss:.4f}, "
              f"val loss = {val_loss:.4f}", file=self.stream)
        self._jsonl({"event": "eval", "step": step,
                     "train_loss": float(train_loss),
                     "val_loss": float(val_loss), "time": time.time()})

    def log(self, msg: str, **fields) -> None:
        if self.quiet:
            return
        print(msg, file=self.stream)
        if fields:
            self._jsonl({"event": "info", "msg": msg, **fields,
                         "time": time.time()})

    def _jsonl(self, obj: dict) -> None:
        if self.jsonl:
            self.jsonl.write(json.dumps(obj) + "\n")
            self.jsonl.flush()

    def reset_timer(self) -> None:
        self.t_last = time.perf_counter()
