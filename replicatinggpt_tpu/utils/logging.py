"""Structured step logging.

The reference's observability is bare print() calls (SURVEY.md §5): periodic
``step {i} : train loss X, val loss = Y`` (GPT1.py:225) and per-step
``Step {i}, Loss: L`` (GPT-2.py:229). This logger keeps those exact
human-readable formats (for parity eyeballing) and adds a JSONL stream with
throughput (tokens/sec/chip — the BASELINE.json primary metric).
"""

from __future__ import annotations

import json
import sys
import time
from typing import Dict, List, Optional, TextIO


class StepLogger:
    def __init__(self, jsonl_path: Optional[str] = None,
                 stream: TextIO = sys.stdout, quiet: bool = False):
        self.stream = stream
        self.jsonl = open(jsonl_path, "a") if jsonl_path else None
        self.t_last = time.perf_counter()
        # quiet silences everything (set on non-coordinator hosts of a
        # multi-process run so the pod logs once, not n_proc times)
        self.quiet = quiet

    def log_step(self, step: int, loss: float, tokens: int,
                 n_chips: int = 1, lr: Optional[float] = None) -> None:
        if self.quiet:
            return
        now = time.perf_counter()
        dt = max(now - self.t_last, 1e-9)
        self.t_last = now
        tps = tokens / dt / max(n_chips, 1)
        # GPT-2.py:229 format, extended
        print(f"Step {step}, Loss: {loss:.6f} | {tps:,.0f} tok/s/chip",
              file=self.stream)
        self._jsonl({"event": "step", "step": step, "loss": float(loss),
                     "tokens_per_sec_per_chip": tps, "lr": lr,
                     "time": time.time()})

    def log_eval(self, step: int, train_loss: float, val_loss: float) -> None:
        if self.quiet:
            return
        # GPT1.py:225 format
        print(f"step {step} : train loss {train_loss:.4f}, "
              f"val loss = {val_loss:.4f}", file=self.stream)
        self._jsonl({"event": "eval", "step": step,
                     "train_loss": float(train_loss),
                     "val_loss": float(val_loss), "time": time.time()})

    def log(self, msg: str, **fields) -> None:
        if self.quiet:
            return
        print(msg, file=self.stream)
        if fields:
            self._jsonl({"event": "info", "msg": msg, **fields,
                         "time": time.time()})

    def _jsonl(self, obj: dict) -> None:
        if self.jsonl:
            self.jsonl.write(json.dumps(obj) + "\n")
            self.jsonl.flush()

    def reset_timer(self) -> None:
        self.t_last = time.perf_counter()


class Metrics:
    """Process-local serving metrics: monotone counters, point-in-time
    gauges, and bounded-reservoir histograms with percentile summaries.

    The serving engine (serve/engine.py) is the first consumer: request
    counters (admitted/completed/rejected/...), occupancy gauges, and
    TTFT / decode-throughput / batch-fill histograms all land here, and
    ``summary()`` is the dict the ``serve-replay`` driver prints.
    Reservoirs keep the most recent ``reservoir`` observations (a soak
    run must not grow host memory without bound); percentiles use the
    same nearest-rank convention as profiling.StepTimer.
    """

    def __init__(self, reservoir: int = 8192):
        self.reservoir = reservoir
        self.counters: Dict[str, float] = {}
        self.gauges: Dict[str, float] = {}
        self.hists: Dict[str, List[float]] = {}

    def inc(self, name: str, n: float = 1) -> None:
        self.counters[name] = self.counters.get(name, 0) + n

    def gauge(self, name: str, value: float) -> None:
        self.gauges[name] = value

    def observe(self, name: str, value: float) -> None:
        h = self.hists.setdefault(name, [])
        h.append(float(value))
        if len(h) > self.reservoir:
            del h[: len(h) - self.reservoir]

    def percentile(self, name: str, q: float) -> float:
        h = sorted(self.hists.get(name, []))
        if not h:
            return 0.0
        i = min(int(q * (len(h) - 1) + 0.5), len(h) - 1)
        return h[i]

    #: pinned hist_summary key schema (tests/test_telemetry.py) — the
    #: telemetry exporters (Prometheus summaries, the metrics timeline)
    #: index these keys directly, so a silent rename breaks a scrape
    HIST_KEYS = ("n", "mean", "min", "p50", "p90", "p99", "max")

    def hist_summary(self, name: str) -> Dict[str, float]:
        h = self.hists.get(name, [])
        if not h:
            return {"n": 0, "mean": 0.0, "min": 0.0, "p50": 0.0,
                    "p90": 0.0, "p99": 0.0, "max": 0.0}
        return {"n": len(h), "mean": sum(h) / len(h), "min": min(h),
                "p50": self.percentile(name, 0.50),
                "p90": self.percentile(name, 0.90),
                "p99": self.percentile(name, 0.99), "max": max(h)}

    def summary(self) -> dict:
        return {"counters": dict(self.counters),
                "gauges": dict(self.gauges),
                "histograms": {k: self.hist_summary(k) for k in self.hists}}
