"""Torn-tail-tolerant JSONL reading — the one shared reader.

Three subsystems write append-only (or per-run) JSONL whose most
interesting files are the ones a crash tore mid-line: the request
journal (serve/journal.py), the telemetry event sink and metrics
timeline (utils/telemetry.py), and the per-replica journals the fleet
router replays after a replica death (serve/router.py). They used to
carry private copies of the same skip-blank/skip-torn loop; this module
is the single implementation all of them call.

The contract: blank lines are skipped, a line that does not parse as
JSON is skipped (the torn final record a crash leaves mid-write — by
construction at most the tail can be torn, and silently dropping an
*interior* corrupt line is still the right call for recovery readers:
every record is independently meaningful and a reader that refuses the
whole file loses everything instead of one record).
"""

from __future__ import annotations

import json
import os
from typing import Iterator, List


def iter_jsonl(path: str) -> Iterator[dict]:
    """Yield each parseable JSON object in ``path``, skipping blank and
    torn lines. Streams — callers that may read very large soak
    artifacts should prefer this over :func:`load_jsonl`."""
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                yield json.loads(line)
            except json.JSONDecodeError:
                continue              # torn record (crash mid-write)


def load_jsonl(path: str) -> List[dict]:
    """Read a whole JSONL file tolerantly (see :func:`iter_jsonl`)."""
    return list(iter_jsonl(path))


def load_jsonl_if_exists(path: str) -> List[dict]:
    """Recovery-reader convenience: a journal that was never created
    (engine died before its first write) is an empty history, not an
    error."""
    if not os.path.exists(path):
        return []
    return load_jsonl(path)
