"""Fused / flash attention entry point.

``flash_attention(q, k, v)`` is the memory-efficient attention core used when
``cfg.attention_impl == 'flash'`` (and by 'auto' on TPU): it avoids
materializing the (T, T) weight matrix in HBM that the einsum path (and the
reference, GPT1.py:114-116) allocates.

Current implementation: a Pallas TPU kernel (blockwise online-softmax) with
an XLA-SDPA fallback on non-TPU backends / unsupported shapes. The kernel
lives in :mod:`.flash_pallas`.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

# Measured 'auto' flash/einsum crossover on v5e with auto-sized tiles
# (benchmarks/RESULTS.md): flash wins from T=256 up. Single source of
# truth for the local policy in models.gpt._block AND the mesh wrapper
# in parallel/sharded_flash.py — re-tune it here only.
FLASH_MIN_T = 256


def _xla_sdpa(q, k, v, scale, causal):
    # (B,H,T,D) -> jax.nn.dot_product_attention wants (B,T,H,D)
    qt, kt, vt = (t.transpose(0, 2, 1, 3) for t in (q, k, v))
    out = jax.nn.dot_product_attention(qt, kt, vt, scale=scale,
                                       is_causal=causal)
    return out.transpose(0, 2, 1, 3)


def _pallas_supported(q) -> bool:
    if jax.default_backend() != "tpu":
        return False
    *_, T, D = q.shape
    # kernel tiles: lane dim 128, sequence blocks of 128
    return D in (32, 64, 128, 256) and T % 128 == 0 and T >= 128


def _packed_backend_ok() -> bool:
    """Pallas lowering gate for the packed family (tests monkeypatch this
    to exercise the interpret-mode kernel on CPU). One site — the local
    routing and the mesh packed hook both go through it."""
    return jax.default_backend() == "tpu"


def packed_envelope_ok(qkv: jnp.ndarray, n_head: int) -> bool:
    """THE packed-family gate: backend + shape/residency envelope. Both
    packed entry points — the local routing below and the mesh hook's
    precheck (parallel/sharded_flash.py) — must use this one predicate,
    so a gate added here can never diverge the two paths."""
    if not _packed_backend_ok():
        return False
    from . import flash_pallas as fp
    _, T, C3 = qkv.shape
    itemsize = jnp.dtype(qkv.dtype).itemsize
    # group_stream joins the envelope only behind its hardware-validation
    # gate (fp.GROUP_STREAM_AUTOROUTE) — read dynamically so flipping the
    # gate (hw_validate passing, or a test) takes effect here too
    return (fp.packed_supported(T, C3 // 3, n_head, itemsize)
            or fp.packed_group_supported(T, C3 // 3, n_head, itemsize)
            or (fp.GROUP_STREAM_AUTOROUTE
                and fp.packed_group_stream_supported(T, C3 // 3, n_head,
                                                     itemsize)))


def packed_qkv_attention(qkv: jnp.ndarray, n_head: int, *,
                         scale: Optional[float] = None,
                         dropout_rate: float = 0.0,
                         rng: Optional[jax.Array] = None,
                         train: bool = False) -> Optional[jnp.ndarray]:
    """Attention straight off the fused (B, T, 3C) QKV projection via the
    packed-heads kernel (flash_pallas packed family): returns the merged
    (B, T, C) output, or None when the kernel does not apply (non-TPU
    backend or off the residency/shape envelope) — callers then take the
    split-heads path. Skipping the (B,T,H,D)<->(B,H,T,D) layout round
    trip is worth ~18% of attention fwd+bwd at char-GPT shapes on v5e
    (benchmarks/RESULTS.md)."""
    if not packed_envelope_ok(qkv, n_head):
        return None
    from .flash_pallas import pallas_flash_attention_packed
    training_dropout = train and dropout_rate > 0.0 and rng is not None
    return pallas_flash_attention_packed(
        qkv, n_head, scale=scale, causal=True,
        dropout_rate=dropout_rate if training_dropout else 0.0,
        dropout_rng=rng if training_dropout else None)


def supports_dropout(q) -> bool:
    """Attention-weight dropout is implemented in the Pallas kernel only
    (counter-based in-kernel mask); the XLA-SDPA fallback has no hook for
    it — callers route dropout-training to the einsum path elsewhere."""
    return _pallas_supported(q)


def flash_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, *,
                    scale: Optional[float] = None,
                    causal: bool = True,
                    dropout_rate: float = 0.0,
                    dropout_rng: Optional[jax.Array] = None) -> jnp.ndarray:
    """q, k, v: (B, H, T, D). Returns (B, H, T, D)."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if _pallas_supported(q):
        from .flash_pallas import pallas_flash_attention
        return pallas_flash_attention(q, k, v, scale=scale, causal=causal,
                                      dropout_rate=dropout_rate,
                                      dropout_rng=dropout_rng)
    if dropout_rate > 0.0:
        raise ValueError(
            "attention-weight dropout needs the Pallas kernel (TPU, "
            "lane-aligned shapes); use the einsum path here")
    return _xla_sdpa(q, k, v, scale, causal)
