from .attention import full_causal_attention, cached_attention

__all__ = ["full_causal_attention", "cached_attention"]
