"""Fused single-stream decode-step kernel: every transformer layer of one
autoregressive token in ONE Pallas call.

Why: the XLA decode step at char-GPT scale is op-issue-latency-bound, not
bandwidth-bound — ~125 device ops per token (per-layer ln/matvec/attention
/mlp fusions) at ~0.4 us issue latency each ≈ 102 us/token against a
~28 us parameter-byte floor (benchmarks/RESULTS.md decode roofline,
round 4). This kernel replaces the whole layer loop with one launch:
grid over layers ("arbitrary" = sequential), the residual-stream row
carried in VMEM scratch across grid steps, per-layer weights and the
layer's KV cache fetched as double-buffered blocks — so the per-token
cost approaches the parameter stream time instead of the op count. The
reference's decode ancestry is the O(T^2) full re-forward per token
(GPT1.py:196-212); the XLA cache path replaced the re-forward, this
kernel replaces the op soup.

Scope: B == 1 (the single-stream latency workload, BASELINE config 5);
batched decode stays on the XLA path where per-op work is large enough
to hide issue latency. The kernel computes attention against the STALE
cache block masked to positions < pos plus an explicit fresh-KV column
(bit-equivalent to write-then-attend: cache[pos] would equal the fresh
k/v), and emits the fresh per-layer K/V rows; the caller scatters them
into the cache at ``pos`` with one dynamic_update_slice over all layers.

Numerics mirror the XLA decode body (models/gpt.py decode_step /
ops/attention.cached_attention): LN statistics in f32, matmuls on
compute-dtype operands with f32 accumulation, attention scores and
softmax in f32, probabilities cast to the cache dtype for the PV
product. Parity with decode_step is asserted in tests/test_generate.py.
"""

from __future__ import annotations

import functools
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ..quant.kv import fake_quantize_row_body as _fake_quant_row
from .flash_pallas import (LANES, NEG_INF, _compiler_params,
                           _interpret_mode, _smem_spec, _vmem_spec, pltpu)

# Per-layer VMEM budget for the fused kernel: weights (qkv C*3C + proj
# C*C + mlp 2*C*4C), the (H, S, D) k/v cache blocks, and the (S, lanes)
# score temporaries, double-buffered across layer grid steps. 6 MiB
# covers char-GPT (3.7 MiB at C=384, S=256 bf16) with margin and
# excludes GPT-2 124M (14+ MiB), whose decode is byte-floor-bound on
# the XLA path anyway (RESULTS.md roofline: 1.29x of floor).
FUSED_LAYER_BYTES = 6 * 1024 * 1024


def fused_decode_supported(cfg, batch: int, itemsize: int = 2,
                           seq_len: int = 0) -> bool:
    """Envelope: single stream, lane-aligned head dim, per-layer weights
    + cache within FUSED_LAYER_BYTES. ``seq_len`` is the ACTUAL cache
    length (init_kv_cache callers may override max_len past
    cfg.block_size); 0 means cfg.block_size."""
    C, H = cfg.n_embd, cfg.n_head
    S = seq_len or cfg.block_size
    if batch != 1 or C % H != 0:
        return False
    D = C // H
    if D not in (32, 64, 128, 256) or S % 8 != 0:
        return False
    weights = (C * 3 * C + C * C + 2 * C * 4 * C) * itemsize
    cache = 2 * H * S * D * itemsize
    return weights + cache <= FUSED_LAYER_BYTES


# VMEM budget for the per-layer packed decode-attention kernel: one
# (S, C) K and V block per grid step plus (1, C)/(S, 1) temporaries.
# 8 MiB covers GPT-2 124M at S=1024 bf16 (2 * 1.5 MiB) with margin and
# S up to ~2048 at C=768.
PACKED_DECODE_BYTES = 8 * 1024 * 1024


def _packed_attn_backend_ok() -> bool:
    """Pallas lowering gate for the packed decode-attention kernel
    (tests monkeypatch this to exercise the interpret-mode kernel on
    CPU). Sharding safety (a bare pallas_call cannot be partitioned by
    GSPMD) is the caller's allow_pallas gate — models.gpt.decode_step."""
    return jax.default_backend() == "tpu"


def packed_decode_supported(cfg, itemsize: int = 2,
                            seq_len: int = 0) -> bool:
    """Envelope for the packed-layout decode attention kernel: head dim
    lane-sliceable and both (S, C) cache blocks within
    PACKED_DECODE_BYTES."""
    C, H = cfg.n_embd, cfg.n_head
    S = seq_len or cfg.block_size
    if C % H != 0:
        return False
    D = C // H
    if D not in (32, 64, 128, 256) or S % 8 != 0:
        return False
    return 2 * S * C * itemsize <= PACKED_DECODE_BYTES


def _packed_attn_kernel(pos_ref, q_ref, knew_ref, vnew_ref, kc_ref, vc_ref,
                        out_ref, *, n_head, head_dim, seq_len, scale):
    """One batch row's decode attention over the lane-packed (S, C)
    cache: heads are static D-wide lane slices of the packed row
    (exactly the packed-flash trick, flash_pallas.py packed section),
    so the cache block streams fully packed — no D-minor tile padding.
    Numerics per head mirror the fused decode kernel above (stale cache
    masked to < pos + explicit fresh column; f32 scores/softmax, probs
    cast to the cache dtype for PV)."""
    pos = pos_ref[0]
    S, D = seq_len, head_dim
    kpos = jax.lax.broadcasted_iota(jnp.int32, (S, 1), 0)
    for i in range(n_head):
        sl = slice(i * D, (i + 1) * D)
        q = q_ref[:, sl].astype(jnp.float32)                    # (1, D)
        k_new = knew_ref[:, sl]
        v_new = vnew_ref[:, sl]
        kc = kc_ref[:, sl]                                      # (S, D)
        vc = vc_ref[:, sl]
        s = jnp.sum(kc.astype(jnp.float32) * q, axis=-1,
                    keepdims=True) * scale                      # (S, 1)
        s = jnp.where(kpos < pos, s, NEG_INF)
        s_new = jnp.sum(k_new.astype(jnp.float32) * q) * scale  # scalar
        m = jnp.maximum(jnp.max(s), s_new)
        p = jnp.exp(s - m)
        p_new = jnp.exp(s_new - m)
        denom = jnp.sum(p) + p_new
        w = (p / denom).astype(vc.dtype)
        pv = jnp.sum(w * vc, axis=0, keepdims=True)             # (1, D)
        out = pv + (p_new / denom).astype(v_new.dtype) * v_new
        out_ref[:, sl] = out.astype(out_ref.dtype)


def packed_decode_attention(q: jnp.ndarray, k_new: jnp.ndarray,
                            v_new: jnp.ndarray, k_cache: jnp.ndarray,
                            v_cache: jnp.ndarray, pos: jnp.ndarray, *,
                            n_head: int) -> jnp.ndarray:
    """Decode attention for the packed (B, S, C) cache layout.

    q, k_new, v_new: (B, C) fresh merged rows; caches: (B, S, C) STALE
    (position ``pos`` not yet written). Returns the merged (B, C)
    attention output — bit-equivalent to writing k_new/v_new at ``pos``
    and attending positions <= pos (models.gpt._decode_step_packed does
    the write afterwards). Grid over B (parallel); each step streams one
    row's fully-packed cache blocks."""
    B, S, C = k_cache.shape
    D = C // n_head
    kernel = functools.partial(
        _packed_attn_kernel, n_head=n_head, head_dim=D, seq_len=S,
        scale=D ** -0.5)
    row = _vmem_spec((None, 1, C), lambda b: (b, 0, 0))
    kw = {}
    cp = _compiler_params(1, 1)
    if cp is not None:
        kw["compiler_params"] = cp
    out = pl.pallas_call(
        kernel,
        grid=(B,),
        in_specs=[
            _smem_spec(),
            row, row, row,
            _vmem_spec((None, S, C), lambda b: (b, 0, 0)),
            _vmem_spec((None, S, C), lambda b: (b, 0, 0)),
        ],
        out_specs=row,
        out_shape=jax.ShapeDtypeStruct((B, 1, C), q.dtype),
        interpret=_interpret_mode(),
        **kw,
    )(jnp.asarray(pos, jnp.int32).reshape(1), q[:, None, :],
      k_new[:, None, :], v_new[:, None, :], k_cache, v_cache)
    return out[:, 0, :]


def _ln_row(x, scale, bias, eps):
    """(1, C) layernorm, f32 statistics, result in x.dtype — mirrors
    models.gpt._layer_norm."""
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(xf - mu), axis=-1, keepdims=True)
    y = (xf - mu) * jax.lax.rsqrt(var + eps)
    return (y * scale.astype(jnp.float32)
            + bias.astype(jnp.float32)).astype(x.dtype)


def _row_matmul(h, w, b):
    """(1, Cin) @ (Cin, Cout) + (1, Cout) on compute-dtype operands with
    f32 accumulation, result in h.dtype — mirrors `h @ W + b`."""
    y = jax.lax.dot_general(h, w, (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)
    return (y + b.astype(jnp.float32)).astype(h.dtype)


def _decode_kernel(pos_ref, x0_ref, ln1s_ref, ln1b_ref, wqkv_ref, bqkv_ref,
                   wproj_ref, bproj_ref, ln2s_ref, ln2b_ref, wup_ref,
                   bup_ref, wdown_ref, bdown_ref, kc_ref, vc_ref,
                   xout_ref, newk_ref, newv_ref, x_ref, *, n_layer, n_head,
                   head_dim, seq_len, eps, scale, activation, packed_cache):
    l = pl.program_id(0)
    H, D, S = n_head, head_dim, seq_len
    C = H * D
    pos = pos_ref[0]

    @pl.when(l == 0)
    def _init():
        x_ref[...] = x0_ref[...]

    x = x_ref[...]                                   # (1, C) compute dtype
    h = _ln_row(x, ln1s_ref[...], ln1b_ref[...], eps)
    qkv = _row_matmul(h, wqkv_ref[...], bqkv_ref[...])   # (1, 3C)

    kpos = jax.lax.broadcasted_iota(jnp.int32, (S, 1), 0)
    outs = []
    for i in range(H):
        q = qkv[:, i * D:(i + 1) * D].astype(jnp.float32)       # (1, D)
        k_new = qkv[:, C + i * D:C + (i + 1) * D]               # (1, D)
        v_new = qkv[:, 2 * C + i * D:2 * C + (i + 1) * D]
        newk_ref[:, i * D:(i + 1) * D] = k_new
        newv_ref[:, i * D:(i + 1) * D] = v_new
        if packed_cache:
            # lane slice of the (S, C) packed row — same trick as
            # packed_decode_attention below; fully-packed cache stream
            kc = kc_ref[:, i * D:(i + 1) * D]                   # (S, D)
            vc = vc_ref[:, i * D:(i + 1) * D]
        else:
            kc = kc_ref[i]                                      # (S, D)
            vc = vc_ref[i]
        # scores vs the stale cache, masked to positions < pos; the
        # fresh position's score rides a separate column (write-then-
        # attend equivalence: cache[pos] would hold exactly k_new)
        s = jnp.sum(kc.astype(jnp.float32) * q, axis=-1,
                    keepdims=True) * scale                      # (S, 1)
        s = jnp.where(kpos < pos, s, NEG_INF)
        s_new = jnp.sum(k_new.astype(jnp.float32) * q) * scale  # scalar
        m = jnp.maximum(jnp.max(s), s_new)
        p = jnp.exp(s - m)                                      # (S, 1)
        p_new = jnp.exp(s_new - m)
        denom = jnp.sum(p) + p_new
        w = (p / denom).astype(vc.dtype)
        pv = jnp.sum(w * vc, axis=0, keepdims=True)             # (1, D)
        out = pv + ((p_new / denom).astype(v_new.dtype) * v_new)
        outs.append(out.astype(x.dtype))
    attn = jnp.concatenate(outs, axis=1)                        # (1, C)
    attn = _row_matmul(attn, wproj_ref[...], bproj_ref[...])
    x_mid = x + attn
    h = _ln_row(x_mid, ln2s_ref[...], ln2b_ref[...], eps)
    h = _row_matmul(h, wup_ref[...], bup_ref[...])
    h = (jax.nn.gelu(h) if activation == "gelu" else jax.nn.relu(h))
    h = _row_matmul(h.astype(x.dtype), wdown_ref[...], bdown_ref[...])
    x_ref[...] = x_mid + h

    @pl.when(l == n_layer - 1)
    def _finalize():
        xout_ref[...] = x_ref[...]


def fused_decode_layers(x0: jnp.ndarray, blocks: Dict[str, jnp.ndarray],
                        pos: jnp.ndarray, cache: Dict[str, jnp.ndarray],
                        cfg) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """Run all n_layer blocks for one (B=1) decode token in one Pallas
    call. x0: (1, C) embedded input row (compute dtype); blocks: the
    layer-stacked param dict (weights will be cast to x0.dtype —
    hoisted out of the token scan by XLA exactly like the unfused
    path's per-use casts); cache: {"k","v"} — (L, 1, H, S, D) heads
    layout or (L, 1, S, C) packed layout, per
    ``cfg.decode_cache_layout``. Returns (x_out (1, C), updated
    cache)."""
    packed = cfg.decode_cache_layout == "packed"
    if packed:
        L, _, S, C = cache["k"].shape
        H = cfg.n_head
        D = C // H
    else:
        L, _, H, S, D = cache["k"].shape
        C = H * D
    cd = x0.dtype
    w = {k: v.astype(cd) for k, v in blocks.items()}
    # (L, width) row vectors -> (L, 1, width) so in-kernel refs are 2-d
    vec = lambda name: w[name].reshape(L, 1, -1)
    kernel = functools.partial(
        _decode_kernel, n_layer=L, n_head=H, head_dim=D, seq_len=S,
        eps=cfg.layernorm_eps, scale=D ** -0.5, activation=cfg.activation,
        packed_cache=packed)
    row = lambda width: _vmem_spec((None, 1, width), lambda l: (l, 0, 0))
    mat = lambda a, b: _vmem_spec((None, a, b), lambda l: (l, 0, 0))
    cache_spec = (_vmem_spec((None, None, S, C), lambda l: (l, 0, 0, 0))
                  if packed else
                  _vmem_spec((None, None, H, S, D),
                             lambda l: (l, 0, 0, 0, 0)))
    kw = {}
    cp = _compiler_params(0, 1)
    if cp is not None:
        kw["compiler_params"] = cp
    xout, newk, newv = pl.pallas_call(
        kernel,
        grid=(L,),
        in_specs=[
            _smem_spec(),
            _vmem_spec((1, C), lambda l: (0, 0)),
            row(C), row(C), mat(C, 3 * C), row(3 * C),
            mat(C, C), row(C), row(C), row(C),
            mat(C, 4 * C), row(4 * C), mat(4 * C, C), row(C),
            cache_spec, cache_spec,
        ],
        out_specs=[
            _vmem_spec((1, C), lambda l: (0, 0)),
            row(C), row(C),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, C), cd),
            jax.ShapeDtypeStruct((L, 1, C), cd),
            jax.ShapeDtypeStruct((L, 1, C), cd),
        ],
        scratch_shapes=[pltpu.VMEM((1, C), cd) if pltpu is not None
                        else None],
        interpret=_interpret_mode(),
        **kw,
    )(jnp.asarray(pos, jnp.int32).reshape(1), x0,
      vec("ln1_scale"), vec("ln1_bias"), w["qkv_kernel"], vec("qkv_bias"),
      w["attn_out_kernel"], vec("attn_out_bias"), vec("ln2_scale"),
      vec("ln2_bias"), w["mlp_up_kernel"], vec("mlp_up_bias"),
      w["mlp_down_kernel"], vec("mlp_down_bias"), cache["k"], cache["v"])
    # scatter every layer's fresh K/V row into the cache at pos — ONE
    # dynamic_update_slice per array for all layers. An out-of-range pos
    # would CLAMP onto the last valid row (lint GL006); eager calls
    # assert, jitted callers bound pos host-side (decode_step's guard
    # already ran on this pos before dispatching here).
    from ..utils.sanitize import check_in_bounds
    seq_axis = 2 if packed else 3
    check_in_bounds(pos, 1, cache["k"].shape[seq_axis],
                    what="fused decode cache write")
    zero = jnp.int32(0)
    p = jnp.asarray(pos, jnp.int32)
    if packed:
        newk_u = newk.reshape(L, 1, 1, C)
        newv_u = newv.reshape(L, 1, 1, C)
        start = (zero, zero, p, zero)
    else:
        newk_u = newk.reshape(L, 1, H, 1, D)
        newv_u = newv.reshape(L, 1, H, 1, D)
        start = (zero, zero, zero, p, zero)
    ck = jax.lax.dynamic_update_slice(
        cache["k"], newk_u.astype(cache["k"].dtype), start)
    cv = jax.lax.dynamic_update_slice(
        cache["v"], newv_u.astype(cache["v"].dtype), start)
    return xout, {"k": ck, "v": cv}


# ---------------------------------------------------------------------------
# Fused PAGED decode: the all-layers kernel above, page-table-aware
# ---------------------------------------------------------------------------

def fused_paged_decode_supported(cfg, n_slots: int, page_size: int,
                                 itemsize: int = 2, mesh=None,
                                 kv_quant: str = "none",
                                 granularity: str = "page") -> bool:
    """Envelope for ``fused_paged_decode_layers``: packed cache layout,
    lane-sliceable heads, sublane-aligned pages, per-head accumulator
    lanes available, and one layer's weights + a double-buffered page
    pair + the (n_slots, C) residual scratch within FUSED_LAYER_BYTES.
    The serve engine prefers this route over the per-layer paged kernel
    (ops/paged_pallas.py) whenever it fits — one launch per decode step
    instead of one per layer. The shape/quant checks are the SHARED
    envelope (``ops.paged_pallas.paged_attention_envelope`` — int8 AND
    fp8, page AND head granularity all dequant in the accumulation
    loop now); this predicate layers the fused-only gates on top:
    packed cache layout, a 1x1 mesh (the fused kernel streams whole
    weight matrices per layer step, which tensor parallelism shards —
    sharded engines route the per-layer kernel's shard_map wrapper
    instead), and one layer's weights + a double-buffered page pair +
    the (n_slots, C) residual scratch within FUSED_LAYER_BYTES."""
    from .paged_pallas import paged_attention_envelope
    if mesh is not None and mesh.size > 1:
        return False
    if cfg.decode_cache_layout != "packed":
        return False
    C, H = cfg.n_embd, cfg.n_head
    if C % H != 0:
        return False
    D = C // H
    ok, _ = paged_attention_envelope(
        H, D, page_size, itemsize=itemsize, kv_quant=kv_quant,
        granularity=granularity)
    if not ok:
        return False
    if pltpu is None:
        return False
    weights = (C * 3 * C + C * C + 2 * C * 4 * C) * itemsize
    pages = 2 * page_size * C * itemsize
    scratch = (n_slots + 3) * C * itemsize + C * 4 + 2 * LANES * 4
    return weights + pages + scratch <= FUSED_LAYER_BYTES


def _paged_fused_kernel(tables_ref, pos_ref, x0_ref, ln1s_ref, ln1b_ref,
                        wqkv_ref, bqkv_ref, wproj_ref, bproj_ref, ln2s_ref,
                        ln2b_ref, wup_ref, bup_ref, wdown_ref, bdown_ref,
                        kp_ref, vp_ref, *rest, n_layer, n_head, head_dim,
                        page_size, n_pages_per_slot, eps, scale,
                        activation, quantized, kv_dtype, head_gran):
    """Grid (layer, slot, logical page), all sequential: the residual
    row of every slot is carried across layer steps in VMEM scratch
    (exactly ``_decode_kernel``'s trick, widened to B rows), each
    slot's QKV projection runs once at its first page step, attention
    accumulates online-softmax across its LIVE pages (the block index
    map repeats the previous physical page past the frontier, skipping
    the DMA — ops/paged_pallas.clamped_live_page), and the block tail
    (proj/ln2/MLP/residual) lands at the last page step. Layer weights
    keep a constant block index across the whole (slot, page) subgrid,
    so they stream exactly once per layer.

    ``quantized`` (int8 OR fp8 pool): two extra f32 scale blocks —
    (psz, 1) page granularity, (psz, H) head granularity with the
    per-head lane column selected in the loop — ride the page index
    map and dequant the K/V pages inside the accumulation loop, and
    the fresh K/V rows are FAKE-QUANTIZED (``_fake_quant_row`` —
    bit-identical math to quant.kv, including fp8's saturating e4m3
    round-trip) before attending, so the fresh column scores exactly
    what the caller's quantize-on-write scatter will store; the raw
    rows still leave through newk/newv for that scatter."""
    if quantized:
        (ksp_ref, vsp_ref, xout_ref, newk_ref, newv_ref, x_scr, q_scr,
         knew_scr, vnew_scr, acc_ref, m_ref, l_ref) = rest
    else:
        (xout_ref, newk_ref, newv_ref, x_scr, q_scr, knew_scr,
         vnew_scr, acc_ref, m_ref, l_ref) = rest
    l = pl.program_id(0)
    b = pl.program_id(1)
    p = pl.program_id(2)
    H, D, psz = n_head, head_dim, page_size
    C = H * D
    pos = pos_ref[b]
    live = (pos + psz - 1) // psz        # pages holding positions < pos

    @pl.when((l == 0) & (p == 0))
    def _seed():
        x_scr[pl.ds(b, 1), :] = x0_ref[...]

    @pl.when(p == 0)
    def _project():
        x = x_scr[pl.ds(b, 1), :]
        h = _ln_row(x, ln1s_ref[...], ln1b_ref[...], eps)
        qkv = _row_matmul(h, wqkv_ref[...], bqkv_ref[...])   # (1, 3C)
        q_scr[...] = qkv[:, :C]
        k_row = qkv[:, C:2 * C]
        v_row = qkv[:, 2 * C:]
        if quantized:
            # attend the value the pool will actually hold (docstring)
            kdq = _fake_quant_row(k_row, kv_dtype, n_head,
                                  "head" if head_gran else "page")
            vdq = _fake_quant_row(v_row, kv_dtype, n_head,
                                  "head" if head_gran else "page")
            knew_scr[...] = kdq.astype(knew_scr.dtype)
            vnew_scr[...] = vdq.astype(vnew_scr.dtype)
        else:
            knew_scr[...] = k_row
            vnew_scr[...] = v_row
        newk_ref[...] = k_row
        newv_ref[...] = v_row
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    @pl.when(p < live)
    def _accumulate():
        kpos = jax.lax.broadcasted_iota(jnp.int32, (psz, 1), 0) + p * psz
        if quantized:
            ksc = ksp_ref[...]           # (psz, 1) page / (psz, H) head
            vsc = vsp_ref[...]
        for i in range(H):
            sl = slice(i * D, (i + 1) * D)
            q = q_scr[:, sl].astype(jnp.float32)                 # (1, D)
            kc = kp_ref[:, sl]                                   # (psz, D)
            vc = vp_ref[:, sl]
            kcf = kc.astype(jnp.float32)
            vcf = vc.astype(jnp.float32)
            if quantized:
                kcf = kcf * (ksc[:, i:i + 1] if head_gran else ksc)
                vcf = vcf * (vsc[:, i:i + 1] if head_gran else vsc)
            s = jnp.sum(kcf * q, axis=-1,
                        keepdims=True) * scale                   # (psz, 1)
            s = jnp.where(kpos < pos, s, NEG_INF)
            m_prev = m_ref[0, i]
            m_new = jnp.maximum(m_prev, jnp.max(s))
            alpha = jnp.exp(m_prev - m_new)
            # masked rows contribute EXACTLY zero (not exp(0)): with a
            # fully-masked page m_new stays NEG_INF and s - m_new == 0
            pexp = jnp.where(s > NEG_INF / 2, jnp.exp(s - m_new), 0.0)
            l_ref[0, i] = l_ref[0, i] * alpha + jnp.sum(pexp)
            acc_ref[:, sl] = (acc_ref[:, sl] * alpha
                              + jnp.sum(pexp * vcf,
                                        axis=0, keepdims=True))
            m_ref[0, i] = m_new

    @pl.when(p == n_pages_per_slot - 1)
    def _finalize():
        outs = []
        for i in range(H):
            sl = slice(i * D, (i + 1) * D)
            q = q_scr[:, sl].astype(jnp.float32)
            s_new = jnp.sum(knew_scr[:, sl].astype(jnp.float32)
                            * q) * scale                         # scalar
            m2 = jnp.maximum(m_ref[0, i], s_new)
            alpha = jnp.exp(m_ref[0, i] - m2)
            p_new = jnp.exp(s_new - m2)
            denom = l_ref[0, i] * alpha + p_new   # >= p_new > 0 always
            outs.append((acc_ref[:, sl] * alpha
                         + p_new * vnew_scr[:, sl].astype(jnp.float32))
                        / denom)
        x = x_scr[pl.ds(b, 1), :]
        attn = jnp.concatenate(outs, axis=1).astype(x.dtype)
        attn = _row_matmul(attn, wproj_ref[...], bproj_ref[...])
        x_mid = x + attn
        h = _ln_row(x_mid, ln2s_ref[...], ln2b_ref[...], eps)
        h = _row_matmul(h, wup_ref[...], bup_ref[...])
        h = (jax.nn.gelu(h) if activation == "gelu" else jax.nn.relu(h))
        h = _row_matmul(h.astype(x.dtype), wdown_ref[...], bdown_ref[...])
        x_new = x_mid + h
        x_scr[pl.ds(b, 1), :] = x_new
        xout_ref[...] = x_new


def fused_paged_decode_layers(x0: jnp.ndarray,
                              blocks: Dict[str, jnp.ndarray],
                              pos: jnp.ndarray, tables: jnp.ndarray,
                              cache: Dict[str, jnp.ndarray], cfg
                              ) -> Tuple[jnp.ndarray, jnp.ndarray,
                                         jnp.ndarray]:
    """Every transformer layer of one multi-slot PAGED decode step in
    ONE Pallas call. x0: (B, C) embedded rows (compute dtype); pos:
    (B,) int32 effective logical positions (inactive slots at 0);
    tables: (B, max_pages) int32; cache: packed ``init_paged_kv_pool``
    arrays (L, n_pages, page, C), STALE at ``pos``. Returns
    ``(x (B, C), newk (L, B, C), newv (L, B, C))`` — the caller
    scatters the fresh K/V rows through the page tables (drop-routed
    for inactive slots), mirroring ``fused_decode_layers``'s
    attend-stale-then-write contract."""
    from ..quant.kv import pool_quant_mode
    from .paged_pallas import clamped_live_page
    L, N, psz, C = cache["k"].shape
    H = cfg.n_head
    D = C // H
    B, mp = tables.shape
    cd = x0.dtype
    kv_dtype, gran = pool_quant_mode(cache)
    quantized = kv_dtype is not None
    head_gran = gran == "head"
    w = {k: v.astype(cd) for k, v in blocks.items()}
    vec = lambda name: w[name].reshape(L, 1, -1)
    kernel = functools.partial(
        _paged_fused_kernel, n_layer=L, n_head=H, head_dim=D,
        page_size=psz, n_pages_per_slot=mp, eps=cfg.layernorm_eps,
        scale=D ** -0.5, activation=cfg.activation,
        quantized=quantized, kv_dtype=kv_dtype, head_gran=head_gran)
    lrow = lambda width: _vmem_spec((None, 1, width),
                                    lambda l, b, p, t, q: (l, 0, 0))
    lmat = lambda a, c: _vmem_spec((None, a, c),
                                   lambda l, b, p, t, q: (l, 0, 0))
    brow = _vmem_spec((None, 1, C), lambda l, b, p, t, q: (b, 0, 0))

    def page_map(l, b, p, tables, pos):
        return (l, tables[b, clamped_live_page(p, pos[b], psz)], 0, 0)

    page_spec = _vmem_spec((None, None, psz, C), page_map)
    if pltpu is None:  # pragma: no cover — gated by
        # fused_paged_decode_supported; explicit error over a pallas
        # internals traceback
        raise RuntimeError("fused_paged_decode_layers needs pallas TPU "
                           "memory spaces "
                           "(jax.experimental.pallas.tpu)")
    scratch = [pltpu.VMEM((B, C), cd), pltpu.VMEM((1, C), cd),
               pltpu.VMEM((1, C), cd), pltpu.VMEM((1, C), cd),
               pltpu.VMEM((1, C), jnp.float32),
               pltpu.VMEM((1, LANES), jnp.float32),
               pltpu.VMEM((1, LANES), jnp.float32)]
    kw = {}
    cp = _compiler_params(0, 3)
    if cp is not None:
        kw["compiler_params"] = cp
    in_specs = [brow,
                lrow(C), lrow(C), lmat(C, 3 * C), lrow(3 * C),
                lmat(C, C), lrow(C), lrow(C), lrow(C),
                lmat(C, 4 * C), lrow(4 * C), lmat(4 * C, C), lrow(C),
                page_spec, page_spec]
    inputs = [x0[:, None, :],
              vec("ln1_scale"), vec("ln1_bias"), w["qkv_kernel"],
              vec("qkv_bias"), w["attn_out_kernel"],
              vec("attn_out_bias"), vec("ln2_scale"), vec("ln2_bias"),
              w["mlp_up_kernel"], vec("mlp_up_bias"),
              w["mlp_down_kernel"], vec("mlp_down_bias"),
              cache["k"], cache["v"]]
    if quantized:
        # (L, N, psz) page-granularity scales -> (psz, 1) blocks, or
        # packed head-granularity (L, N, psz, H) -> (psz, H) blocks,
        # per (layer, physical page) on the same fetch-skip index map
        swidth = H if head_gran else 1
        scale_spec = _vmem_spec((None, None, psz, swidth), page_map)
        in_specs += [scale_spec, scale_spec]
        inputs += [cache["ks"].reshape(L, N, psz, swidth),
                   cache["vs"].reshape(L, N, psz, swidth)]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(L, B, mp),
        in_specs=in_specs,
        out_specs=[brow,
                   _vmem_spec((None, None, 1, C),
                              lambda l, b, p, t, q: (l, b, 0, 0)),
                   _vmem_spec((None, None, 1, C),
                              lambda l, b, p, t, q: (l, b, 0, 0))],
        scratch_shapes=scratch,
    )
    xout, newk, newv = pl.pallas_call(
        kernel, grid_spec=grid_spec,
        out_shape=[jax.ShapeDtypeStruct((B, 1, C), cd),
                   jax.ShapeDtypeStruct((L, B, 1, C), cd),
                   jax.ShapeDtypeStruct((L, B, 1, C), cd)],
        interpret=_interpret_mode(), **kw,
    )(jnp.asarray(tables, jnp.int32), jnp.asarray(pos, jnp.int32),
      *inputs)
    return xout[:, 0, :], newk[:, :, 0, :], newv[:, :, 0, :]
