"""Paged decode attention: one Pallas kernel whose scalar-prefetched
page table streams ONLY a slot's mapped pages.

The XLA paged decode path (models/gpt.py decode_step_paged) gathers
every slot's full (max_pages, page, C) view each layer each step —
simple and parity-exact, but it fetches max_pages pages per slot
regardless of how short the slot's sequence actually is. This kernel
puts the page table in scalar-prefetch SMEM and lets the BLOCK INDEX
MAP translate (slot, logical page) -> physical page right before the
DMA: grid (B, max_pages), page minor, and logical pages past the slot's
live frontier map to the SAME physical page as the previous grid step —
Pallas skips the re-fetch for a repeated block index (the exact trick
the streamed flash kernels' triangular tile map uses for fully-masked
tiles), so a slot at position p streams ceil(p/page) pages, not
max_pages. Accumulation is online softmax across page steps (f32
running max / denominator per head in VMEM scratch); the fresh K/V
column rides separately and folds in at the final page step, so the
kernel attends the STALE pool bit-equivalently to write-then-attend
(cache[pos] would hold exactly the fresh k/v) — the caller scatters the
fresh row afterwards, mirroring ops/decode_pallas.py's packed kernel.

Packed (page, C) layout only: heads are static D-wide lane slices of
the fully-packed row (no D-minor tile padding in the stream). Gated to
TPU (`_paged_attn_backend_ok`, monkeypatched by tests to exercise the
interpreter on CPU) and to shapes inside `paged_decode_supported`.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ..parallel.compat import shard_map
from .flash_pallas import (LANES, NEG_INF, _compiler_params,
                           _interpret_mode, _vmem_spec, pltpu)

# VMEM budget: one (page, C) K and V block per grid step, double-
# buffered, plus the (1, C) rows and f32 accumulators. 4 MiB covers
# C=768 pages of 1024 tokens bf16 with margin.
PAGED_DECODE_BYTES = 4 * 1024 * 1024


def _paged_attn_backend_ok() -> bool:
    """Pallas lowering gate (tests monkeypatch this to run the
    interpret-mode kernel on CPU). Sharding safety is a SEPARATE gate:
    ``paged_kernel_mesh_ok`` — the serve engine may now run on a
    (data, model) mesh, where a bare pallas_call cannot partition."""
    return jax.default_backend() == "tpu"


def paged_kernel_mesh_ok(mesh, n_pages=None, n_embd=None,
                         n_head=None) -> bool:
    """Sharding-aware kernel routing. A bare ``pallas_call`` cannot be
    GSPMD-partitioned, but the per-layer windowed kernel now ships a
    ``shard_map`` wrapper (``sharded_paged_window_attention``): each
    chip runs the kernel on its own contiguous page block with the
    scalar-prefetched table localized per shard, partial online-softmax
    state merged across 'data' and heads fully local over 'model'. The
    wrapper needs clean per-shard blocks, so a >1 mesh routes the
    kernel iff the page axis divides over 'data' and channels AND heads
    divide over 'model' (the same divisibility-drop rule
    parallel.mesh.page_pool_pspec applies to the pool specs). Callers
    that cannot supply the geometry get the conservative answer for a
    >1 mesh. The FUSED all-layers kernel stays 1x1-only — it streams
    whole weight matrices per layer step, which TP shards."""
    if mesh is None or mesh.size == 1:
        return True
    if n_pages is None or n_embd is None or n_head is None:
        return False
    shape = dict(getattr(mesh, "shape", {}))
    data = int(shape.get("data", 1))
    model = int(shape.get("model", 1))
    if data * model != mesh.size:
        return False
    return (n_pages % data == 0 and n_embd % model == 0
            and n_head % model == 0)


def mixed_step_kernel_ok(n_head: int, head_dim: int, page_size: int,
                         itemsize: int = 2, mesh=None,
                         kv_quant: str = "none",
                         granularity: str = "page",
                         n_pages=None) -> bool:
    """Kernel routing for the MIXED prefill+decode window step and the
    speculative verify forward (models.gpt.verify_step_paged): the seam
    PR 12 documented is now FLIPPED — ``paged_window_attention`` walks
    a (W, C) query block per slot, so prefilling slots scatter chunk
    rows through their page tables and decoding slots do the
    verify<->decode row math in ONE kernel launch per layer (same
    ``mode='drop'`` routing as the XLA path; the scatter itself stays
    outside the kernel, exactly like the decode kernels'
    attend-stale-then-write contract). Same envelope as the decode
    kernel — the window width W is a block-shape parameter, not an
    envelope axis (Pallas pads the sublane dim)."""
    ok, _ = paged_attention_envelope(
        n_head, head_dim, page_size, itemsize=itemsize, mesh=mesh,
        kv_quant=kv_quant, granularity=granularity, n_pages=n_pages)
    return ok


def clamped_live_page(p, pos, page_size: int):
    """The fetch-skip trick, shared by every paged block index map
    (this file's per-layer kernel and the fused all-layers kernel in
    ops/decode_pallas.py): logical pages past a slot's live frontier
    map to the SAME logical page as the previous grid step, and Pallas
    skips the DMA for a repeated block index — so a slot at position
    ``pos`` streams ceil(pos/page) pages regardless of max_pages. An
    idle slot (pos == 0) clamps to page 0; its zero live pages are
    never read (the accumulation loop is gated on ``p < live``)."""
    live = (pos + page_size - 1) // page_size
    return jnp.where(p < live, p, jnp.maximum(live - 1, 0))


def paged_attention_envelope(n_head: int, head_dim: int, page_size: int,
                             *, itemsize: int = 2, mesh=None,
                             kv_quant: str = "none",
                             granularity: str = "page",
                             n_pages=None) -> tuple:
    """THE shared kernel envelope — one set of gate checks consumed by
    every route predicate (``paged_decode_supported``,
    ``mixed_step_kernel_ok`` here; ``fused_paged_decode_supported`` in
    ops/decode_pallas.py layers its VMEM/weight checks on top), so the
    mesh/quant/shape logic cannot drift between the fused and per-layer
    kernels. Returns ``(ok, reasons)`` — ``reasons`` names every failed
    check (the engine's kernel-route export surfaces them, so a silent
    XLA fallback is observable, not asserted).

    What the unified kernel family now accepts: int8 AND fp8 pools at
    page AND head granularity (per-head scale-lane selection + the
    saturating e4m3 cast run inside the accumulation loop), and >1
    (data, model) meshes through the shard_map wrapper when the pool
    geometry divides (``paged_kernel_mesh_ok``)."""
    reasons = []
    if not paged_kernel_mesh_ok(mesh, n_pages=n_pages,
                                n_embd=n_head * head_dim,
                                n_head=n_head):
        reasons.append("mesh_indivisible")
    if kv_quant not in ("none", "int8", "fp8"):
        reasons.append("kv_quant_unknown")
    if granularity not in ("page", "head"):
        reasons.append("granularity_unknown")
    if head_dim not in (32, 64, 128, 256):
        reasons.append("head_dim")
    if n_head > LANES:
        reasons.append("n_head_gt_lanes")
    if page_size % 8 != 0:
        reasons.append("page_align")
    if pltpu is None and not _interpret_mode():
        reasons.append("no_pltpu")
    C = n_head * head_dim
    if 2 * page_size * C * itemsize > PAGED_DECODE_BYTES:
        reasons.append("vmem_budget")
    return (not reasons), tuple(reasons)


def paged_decode_supported(n_head: int, head_dim: int, page_size: int,
                           itemsize: int = 2, mesh=None,
                           kv_quant: str = "none",
                           granularity: str = "page",
                           n_pages=None) -> bool:
    """Per-layer decode-kernel envelope — a thin view over
    ``paged_attention_envelope`` (one shared gate, no drift)."""
    ok, _ = paged_attention_envelope(
        n_head, head_dim, page_size, itemsize=itemsize, mesh=mesh,
        kv_quant=kv_quant, granularity=granularity, n_pages=n_pages)
    return ok


def _fill_last_owned(phys: jnp.ndarray, owned: jnp.ndarray) -> jnp.ndarray:
    """Localize a page table for the kernel's fetch-skip contract:
    positions the kernel must not read (``~owned``) repeat the LAST
    owned physical index to their left (a repeated block index skips
    the DMA — the generalization of ``clamped_live_page`` to the
    sharded case, where a shard's owned pages can be any subset of the
    logical walk, not just a prefix). Slots with no owned page at all
    clamp to physical 0 (never accumulated — the kernel gates on the
    owned mask)."""
    marked = jnp.where(owned, phys, -1)
    filled = jax.lax.associative_scan(
        lambda a, b: jnp.where(b >= 0, b, a), marked, axis=1)
    return jnp.maximum(filled, 0).astype(jnp.int32)


def effective_tables(tables: jnp.ndarray, pos: jnp.ndarray,
                     page_size: int) -> tuple:
    """(effective table, owned mask) for the UNSHARDED kernel call:
    owned = the prefix of pages holding positions < pos, effective
    table = ``clamped_live_page`` materialized host^Wtrace-side so the
    kernel's index map is a plain (B, max_pages) lookup shared with the
    sharded wrapper's localized tables."""
    mp = tables.shape[1]
    live = (pos + page_size - 1) // page_size
    p_idx = jnp.arange(mp, dtype=jnp.int32)[None, :]
    owned = p_idx < live[:, None]
    return (_fill_last_owned(jnp.asarray(tables, jnp.int32), owned),
            owned)


def _paged_window_kernel(tables_ref, pos_ref, owned_ref, q_ref, knew_ref,
                         vnew_ref, kp_ref, vp_ref, *rest, n_head,
                         head_dim, page_size, n_pages_per_slot, window,
                         scale, quantized, head_gran, fold):
    """ONE kernel body for the whole paged-attention family.

    W = ``window`` query rows per slot (W=1 is plain decode; W>1 is the
    mixed prefill+decode / speculative-verify step, where row j sits at
    logical position pos+j). Stale pool pages accumulate online-softmax
    gated on the scalar-prefetched OWNED mask (per-slot page prefix
    unsharded; an arbitrary owned subset under the shard_map wrapper),
    masked to positions < pos — identical for every query row, since
    rows 0..W-1 attend the fresh window via the causal fold. Quantized
    pools stream (psz, 1) page-granularity or (psz, H) head-granularity
    scale blocks through the same fetch-skip index map; the per-head
    lane column dequants in the accumulation loop (int8 AND fp8 — the
    e4m3 block ``astype``s to f32 like any other storage dtype).

    ``fold=True`` folds the fresh causal (W, W) block per head at the
    last page step and writes normalized output; ``fold=False`` emits
    the raw (acc, m, l) partials instead — the shard_map wrapper merges
    them across the 'data' axis (pmax/psum softmax merge) and folds the
    fresh window outside, where the collective lives."""
    if quantized:
        ksp_ref, vsp_ref, *rest = rest
    if fold:
        out_ref, acc_ref, m_ref, l_ref = rest
    else:
        accout_ref, mout_ref, lout_ref, acc_ref, m_ref, l_ref = rest
    b = pl.program_id(0)
    p = pl.program_id(1)
    D, psz, W = head_dim, page_size, window
    pos = pos_ref[b]

    @pl.when(p == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    @pl.when(owned_ref[b, p] > 0)
    def _accumulate():
        kpos = jax.lax.broadcasted_iota(jnp.int32, (1, psz), 1) + p * psz
        if quantized:
            ksc = ksp_ref[...]           # (psz, 1) page / (psz, H) head
            vsc = vsp_ref[...]
        for i in range(n_head):
            sl = slice(i * D, (i + 1) * D)
            q = q_ref[:, sl].astype(jnp.float32)                 # (W, D)
            kcf = kp_ref[:, sl].astype(jnp.float32)              # (psz, D)
            vcf = vp_ref[:, sl].astype(jnp.float32)
            if quantized:
                kcf = kcf * (ksc[:, i:i + 1] if head_gran else ksc)
                vcf = vcf * (vsc[:, i:i + 1] if head_gran else vsc)
            s = jax.lax.dot_general(
                q, kcf, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * scale      # (W, psz)
            s = jnp.where(kpos < pos, s, NEG_INF)
            m_prev = m_ref[:, i:i + 1]                           # (W, 1)
            m_new = jnp.maximum(m_prev,
                                jnp.max(s, axis=1, keepdims=True))
            alpha = jnp.exp(m_prev - m_new)
            # masked rows contribute EXACTLY zero (not exp(0)): with a
            # fully-masked page m_new stays NEG_INF and s - m_new == 0
            pexp = jnp.where(s > NEG_INF / 2, jnp.exp(s - m_new), 0.0)
            l_ref[:, i:i + 1] = (l_ref[:, i:i + 1] * alpha
                                 + jnp.sum(pexp, axis=1, keepdims=True))
            acc_ref[:, sl] = (acc_ref[:, sl] * alpha
                              + jax.lax.dot_general(
                                  pexp, vcf, (((1,), (0,)), ((), ())),
                                  preferred_element_type=jnp.float32))
            m_ref[:, i:i + 1] = m_new

    @pl.when(p == n_pages_per_slot - 1)
    def _finalize():
        if not fold:
            accout_ref[...] = acc_ref[...]
            mout_ref[...] = m_ref[...]
            lout_ref[...] = l_ref[...]
            return
        row = jax.lax.broadcasted_iota(jnp.int32, (W, W), 0)
        col = jax.lax.broadcasted_iota(jnp.int32, (W, W), 1)
        causal = col <= row            # fresh row j attends rows 0..j
        for i in range(n_head):
            sl = slice(i * D, (i + 1) * D)
            q = q_ref[:, sl].astype(jnp.float32)
            kn = knew_ref[:, sl].astype(jnp.float32)
            vn = vnew_ref[:, sl].astype(jnp.float32)
            s_new = jax.lax.dot_general(
                q, kn, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * scale      # (W, W)
            s_new = jnp.where(causal, s_new, NEG_INF)
            m_prev = m_ref[:, i:i + 1]
            m2 = jnp.maximum(m_prev,
                             jnp.max(s_new, axis=1, keepdims=True))
            alpha = jnp.exp(m_prev - m2)
            p_new = jnp.where(causal, jnp.exp(s_new - m2), 0.0)
            # denom >= diagonal term > 0 always (row j attends itself)
            denom = (l_ref[:, i:i + 1] * alpha
                     + jnp.sum(p_new, axis=1, keepdims=True))
            out = (acc_ref[:, sl] * alpha
                   + jax.lax.dot_general(
                       p_new, vn, (((1,), (0,)), ((), ())),
                       preferred_element_type=jnp.float32)) / denom
            out_ref[:, sl] = out.astype(out_ref.dtype)


def paged_window_attention(q: jnp.ndarray, k_new: jnp.ndarray,
                           v_new: jnp.ndarray, k_pages: jnp.ndarray,
                           v_pages: jnp.ndarray, tables: jnp.ndarray,
                           pos: jnp.ndarray, *, n_head: int,
                           k_scales=None, v_scales=None, owned=None,
                           fold: bool = True):
    """Windowed paged attention for one layer of a packed pool — the
    SINGLE entry point behind every per-layer engine route.

    q, k_new, v_new: (B, W, C) fresh merged window rows (row j of slot
    b sits at logical position ``pos[b] + j``; callers pad dead rows —
    garbage-in-garbage-out, the diagonal fold keeps them NaN-free);
    k_pages/v_pages: (n_pages, page, C) STALE pool (positions >= pos
    not yet written); tables: (B, max_pages) int32; pos: (B,) int32.
    Returns (B, W, C) — bit-equivalent to scattering the window rows at
    pos..pos+W-1 and attending causally, because stale-pool history is
    masked to positions < pos and the in-window positions are covered
    by the causal fresh fold (write-then-attend == attend-stale-then-
    fold, the same contract the W=1 decode kernel always had).

    ``k_scales``/``v_scales`` mark a QUANTIZED pool — (n_pages, page)
    f32 at page granularity or (n_pages, page, H) at head granularity
    (int8 or fp8 storage; the kernel only ever sees f32 scale blocks
    and ``astype``s the e4m3 pages like any storage dtype). The caller
    passes window rows already fake-quantized so the fresh fold attends
    exactly what the post-kernel scatter stores.

    ``owned``/pre-localized ``tables`` are the shard_map wrapper's
    seam (with ``fold=False`` it returns raw (acc, m, l) partials for
    the cross-'data' softmax merge); plain callers leave both unset and
    get the ``effective_tables`` prefix mask."""
    N, psz, C = k_pages.shape
    B, W, _ = q.shape
    mp = tables.shape[1]
    D = C // n_head
    quantized = k_scales is not None
    head_gran = quantized and k_scales.ndim == 3
    if owned is None:
        tables, owned = effective_tables(tables, pos, psz)
    kernel = functools.partial(
        _paged_window_kernel, n_head=n_head, head_dim=D, page_size=psz,
        n_pages_per_slot=mp, window=W, scale=D ** -0.5,
        quantized=quantized, head_gran=head_gran, fold=fold)

    def row_map(b, p, tables, pos, owned):
        return (b, 0, 0)

    def page_map(b, p, tables, pos, owned):
        # unowned steps repeat an already-fetched physical page (the
        # table is pre-filled by _fill_last_owned) — a repeated block
        # index skips the DMA (the fetch-skip trick)
        return (tables[b, p], 0, 0)

    if pltpu is None:  # pragma: no cover — pltpu-less installs are
        # gated out by the envelope; kept so an explicit call errors
        # with a clear message instead of a pallas internals traceback
        raise RuntimeError("paged_window_attention needs pallas TPU "
                           "memory spaces (jax.experimental.pallas.tpu)")
    row = _vmem_spec((None, W, C), row_map)
    kw = {}
    cp = _compiler_params(0, 2)
    if cp is not None:
        kw["compiler_params"] = cp
    scratch = [pltpu.VMEM((W, C), jnp.float32),
               pltpu.VMEM((W, LANES), jnp.float32),
               pltpu.VMEM((W, LANES), jnp.float32)]
    in_specs = [row, row, row,
                _vmem_spec((None, psz, C), page_map),
                _vmem_spec((None, psz, C), page_map)]
    inputs = [q, k_new, v_new, k_pages, v_pages]
    if quantized:
        swidth = n_head if head_gran else 1
        in_specs += [_vmem_spec((None, psz, swidth), page_map),
                     _vmem_spec((None, psz, swidth), page_map)]
        inputs += [k_scales.reshape(N, psz, swidth),
                   v_scales.reshape(N, psz, swidth)]
    if fold:
        out_specs = row
        out_shape = jax.ShapeDtypeStruct((B, W, C), q.dtype)
    else:
        rowL = _vmem_spec((None, W, LANES), row_map)
        out_specs = [_vmem_spec((None, W, C), row_map), rowL, rowL]
        out_shape = [jax.ShapeDtypeStruct((B, W, C), jnp.float32),
                     jax.ShapeDtypeStruct((B, W, LANES), jnp.float32),
                     jax.ShapeDtypeStruct((B, W, LANES), jnp.float32)]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(B, mp),
        in_specs=in_specs,
        out_specs=out_specs,
        scratch_shapes=scratch,
    )
    return pl.pallas_call(
        kernel, grid_spec=grid_spec, out_shape=out_shape,
        interpret=_interpret_mode(), **kw,
    )(jnp.asarray(tables, jnp.int32), jnp.asarray(pos, jnp.int32),
      jnp.asarray(owned, jnp.int32), *inputs)


def paged_decode_attention(q: jnp.ndarray, k_new: jnp.ndarray,
                           v_new: jnp.ndarray, k_pages: jnp.ndarray,
                           v_pages: jnp.ndarray, tables: jnp.ndarray,
                           pos: jnp.ndarray, *, n_head: int,
                           k_scales=None, v_scales=None) -> jnp.ndarray:
    """Decode attention for one layer of a paged packed pool — the
    W=1 view of :func:`paged_window_attention` (kept as the named
    decode entry point; a single-row window's causal fold degenerates
    to the scalar fresh-column fold of the original decode kernel).

    q, k_new, v_new: (B, C) fresh merged rows. Returns (B, C) —
    bit-equivalent to scattering k_new/v_new at ``pos`` and attending
    positions <= pos; the caller scatters afterwards."""
    return paged_window_attention(
        q[:, None, :], k_new[:, None, :], v_new[:, None, :],
        k_pages, v_pages, tables, pos, n_head=n_head,
        k_scales=k_scales, v_scales=v_scales)[:, 0, :]


def _fold_fresh_window(acc: jnp.ndarray, m: jnp.ndarray, l: jnp.ndarray,
                       q: jnp.ndarray, k_new: jnp.ndarray,
                       v_new: jnp.ndarray, n_head: int) -> jnp.ndarray:
    """Fold the fresh causal (W, W) window into raw kernel partials —
    the jnp twin of the kernel's ``fold=True`` finalize, run by the
    shard_map wrapper AFTER the cross-'data' merge (the fresh rows are
    replicated over 'data'; folding them per shard before the psum
    would double-count). acc: (B, W, C) f32; m/l: (B, W, LANES) f32
    with per-head state in columns :n_head."""
    B, W, C = q.shape
    D = C // n_head
    qh = q.astype(jnp.float32).reshape(B, W, n_head, D)
    knh = k_new.astype(jnp.float32).reshape(B, W, n_head, D)
    vnh = v_new.astype(jnp.float32).reshape(B, W, n_head, D)
    s_new = jnp.einsum("bwhd,bjhd->bhwj", qh, knh) * D ** -0.5
    causal = (jnp.arange(W)[None, :]
              <= jnp.arange(W)[:, None])[None, None]   # col <= row
    s_new = jnp.where(causal, s_new, NEG_INF)
    m_h = jnp.swapaxes(m[..., :n_head], 1, 2)          # (B, H, W)
    l_h = jnp.swapaxes(l[..., :n_head], 1, 2)
    m2 = jnp.maximum(m_h, jnp.max(s_new, axis=-1))
    alpha = jnp.exp(m_h - m2)
    p_new = jnp.where(causal, jnp.exp(s_new - m2[..., None]), 0.0)
    # denom >= diagonal term > 0 always (row j attends itself)
    denom = l_h * alpha + jnp.sum(p_new, axis=-1)
    acch = jnp.swapaxes(acc.reshape(B, W, n_head, D), 1, 2)
    out = (acch * alpha[..., None]
           + jnp.einsum("bhwj,bjhd->bhwd", p_new, vnh)) / denom[..., None]
    return jnp.swapaxes(out, 1, 2).reshape(B, W, C)


def sharded_paged_window_attention(q: jnp.ndarray, k_new: jnp.ndarray,
                                   v_new: jnp.ndarray,
                                   k_pages: jnp.ndarray,
                                   v_pages: jnp.ndarray,
                                   tables: jnp.ndarray, pos: jnp.ndarray,
                                   *, n_head: int, mesh,
                                   k_scales=None, v_scales=None):
    """:func:`paged_window_attention` over a (data, model) serve mesh.

    ``shard_map`` runs the kernel per chip: the pool's page axis splits
    over 'data' (each shard holds a contiguous physical block of
    ``n_pages // data`` pages), channels/heads split over 'model'
    (heads are whole per shard — ``paged_kernel_mesh_ok`` gates on
    that), and the replicated page table is LOCALIZED per shard — a
    shard owns a logical page iff its physical index lands in the
    shard's block, the owned mask gates accumulation, and
    ``_fill_last_owned`` rewrites unowned steps to repeat an owned
    block index so the fetch-skip contract survives arbitrary owned
    subsets (a slot's pages interleave across shards under allocation
    churn). Each shard emits raw (acc, m, l) partials (``fold=False``);
    the online-softmax merge across 'data' is exact — pmax the maxima,
    rescale, psum — and the fresh causal window folds once afterwards
    on the merged state ('model' needs no collective: heads are fully
    local). Output matches the unsharded kernel to f32 merge order."""
    P = jax.sharding.PartitionSpec
    shape = dict(mesh.shape)
    data = int(shape.get("data", 1))
    model = int(shape.get("model", 1))
    N, psz, C = k_pages.shape
    mp = tables.shape[1]
    N_loc = N // data
    H_loc = n_head // model
    quantized = k_scales is not None
    head_gran = quantized and k_scales.ndim == 3
    d_ax = "data" if data > 1 else None
    m_ax = "model" if model > 1 else None
    qspec = P(None, None, m_ax)
    pspec = P(d_ax, None, m_ax)

    def local_fn(q_l, kn_l, vn_l, kp_l, vp_l, tab, pos_l, *scales):
        ks_l, vs_l = scales if scales else (None, None)
        lo = jax.lax.axis_index("data") * N_loc
        live = (pos_l + psz - 1) // psz
        p_idx = jnp.arange(mp, dtype=jnp.int32)[None, :]
        tab = jnp.asarray(tab, jnp.int32)
        owned = ((p_idx < live[:, None]) & (tab >= lo)
                 & (tab < lo + N_loc))
        eff = _fill_last_owned(tab - lo, owned)
        acc, m_, l_ = paged_window_attention(
            q_l, kn_l, vn_l, kp_l, vp_l, eff, pos_l, n_head=H_loc,
            k_scales=ks_l, v_scales=vs_l, owned=owned, fold=False)
        # exact cross-shard online-softmax merge: max, rescale, sum
        m_g = jax.lax.pmax(m_, "data")
        corr = jnp.exp(m_ - m_g)      # 1 where both stayed NEG_INF
        l_g = jax.lax.psum(l_ * corr, "data")
        D = (C // model) // H_loc
        corr_c = jnp.repeat(corr[..., :H_loc], D, axis=-1)
        acc_g = jax.lax.psum(acc * corr_c, "data")
        return _fold_fresh_window(acc_g, m_g, l_g, q_l, kn_l, vn_l,
                                  H_loc).astype(q_l.dtype)

    in_specs = [qspec, qspec, qspec, pspec, pspec, P(), P()]
    args = [q, k_new, v_new, k_pages, v_pages,
            jnp.asarray(tables, jnp.int32), jnp.asarray(pos, jnp.int32)]
    if quantized:
        sspec = P(d_ax, None, m_ax) if head_gran else P(d_ax, None)
        in_specs += [sspec, sspec]
        args += [k_scales, v_scales]
    return shard_map(local_fn, mesh=mesh, in_specs=tuple(in_specs),
                     out_specs=qspec, check_vma=False)(*args)
