"""Paged decode attention: one Pallas kernel whose scalar-prefetched
page table streams ONLY a slot's mapped pages.

The XLA paged decode path (models/gpt.py decode_step_paged) gathers
every slot's full (max_pages, page, C) view each layer each step —
simple and parity-exact, but it fetches max_pages pages per slot
regardless of how short the slot's sequence actually is. This kernel
puts the page table in scalar-prefetch SMEM and lets the BLOCK INDEX
MAP translate (slot, logical page) -> physical page right before the
DMA: grid (B, max_pages), page minor, and logical pages past the slot's
live frontier map to the SAME physical page as the previous grid step —
Pallas skips the re-fetch for a repeated block index (the exact trick
the streamed flash kernels' triangular tile map uses for fully-masked
tiles), so a slot at position p streams ceil(p/page) pages, not
max_pages. Accumulation is online softmax across page steps (f32
running max / denominator per head in VMEM scratch); the fresh K/V
column rides separately and folds in at the final page step, so the
kernel attends the STALE pool bit-equivalently to write-then-attend
(cache[pos] would hold exactly the fresh k/v) — the caller scatters the
fresh row afterwards, mirroring ops/decode_pallas.py's packed kernel.

Packed (page, C) layout only: heads are static D-wide lane slices of
the fully-packed row (no D-minor tile padding in the stream). Gated to
TPU (`_paged_attn_backend_ok`, monkeypatched by tests to exercise the
interpreter on CPU) and to shapes inside `paged_decode_supported`.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .flash_pallas import (LANES, NEG_INF, _compiler_params,
                           _interpret_mode, _vmem_spec, pltpu)

# VMEM budget: one (page, C) K and V block per grid step, double-
# buffered, plus the (1, C) rows and f32 accumulators. 4 MiB covers
# C=768 pages of 1024 tokens bf16 with margin.
PAGED_DECODE_BYTES = 4 * 1024 * 1024


def _paged_attn_backend_ok() -> bool:
    """Pallas lowering gate (tests monkeypatch this to run the
    interpret-mode kernel on CPU). Sharding safety is a SEPARATE gate:
    ``paged_kernel_mesh_ok`` — the serve engine may now run on a
    (data, model) mesh, where a bare pallas_call cannot partition."""
    return jax.default_backend() == "tpu"


def paged_kernel_mesh_ok(mesh) -> bool:
    """Sharding-aware kernel routing: a bare ``pallas_call`` cannot be
    GSPMD-partitioned, so on a >1-device serving mesh both this file's
    per-layer paged-attention kernel and the fused all-layers kernel
    (ops/decode_pallas.py) must route to the XLA gather path inside
    ``models.gpt.decode_step_paged`` — that path is plain gather/
    scatter/einsum, which the partitioner handles. A future shard_map
    wrapper (per-shard kernel over the chip's local page block, specs
    from parallel.mesh.page_pool_pspec) would lift this gate; until
    then falling back IS the routing decision, made once per engine at
    construction (never inside a traced program)."""
    return mesh is None or mesh.size == 1


def mixed_step_kernel_ok() -> bool:
    """Kernel routing for the MIXED prefill+decode window step
    (models.gpt.mixed_window_paged): always False today. Both paged
    Pallas kernels here and in ops/decode_pallas.py are single-token
    decode kernels — their grid walks one fresh column per slot, while
    a mixed scan step writes up to a whole chunk of K/V rows per slot
    and attends a (W, S) score tile per head. The mixed window
    therefore routes the XLA gather path unconditionally (the same
    per-row math, partitioner-friendly), and this seam is where a
    mixed-phase kernel — per-slot chunk scatter + windowed flash tile,
    the Sarathi-style fused step — would flip the decision. Kept as a
    function, not a constant, so the engine's routing reads as a
    decision point and a future kernel lands without touching the
    engine."""
    return False


def clamped_live_page(p, pos, page_size: int):
    """The fetch-skip trick, shared by every paged block index map
    (this file's per-layer kernel and the fused all-layers kernel in
    ops/decode_pallas.py): logical pages past a slot's live frontier
    map to the SAME logical page as the previous grid step, and Pallas
    skips the DMA for a repeated block index — so a slot at position
    ``pos`` streams ceil(pos/page) pages regardless of max_pages. An
    idle slot (pos == 0) clamps to page 0; its zero live pages are
    never read (the accumulation loop is gated on ``p < live``)."""
    live = (pos + page_size - 1) // page_size
    return jnp.where(p < live, p, jnp.maximum(live - 1, 0))


def paged_decode_supported(n_head: int, head_dim: int, page_size: int,
                           itemsize: int = 2, mesh=None,
                           kv_quant: str = "none",
                           granularity: str = "page") -> bool:
    """Envelope: lane-sliceable heads, sublane-aligned page length,
    per-head accumulator lanes available, both page blocks in budget —
    and no serving mesh (``paged_kernel_mesh_ok``). Quantized pools
    (quant/): int8 at PAGE granularity streams its (page, 1) scale
    blocks alongside the K/V pages and dequants in the accumulation
    loop; fp8 and head granularity route the XLA gather path (fp8
    in-kernel casts and per-head scale lane selection are not lowered
    here yet — the gather fallback is the sharding-style escape
    hatch, decided once per engine)."""
    if not paged_kernel_mesh_ok(mesh):
        return False
    if kv_quant not in ("none", "int8") or granularity != "page":
        return False
    if head_dim not in (32, 64, 128, 256) or n_head > LANES:
        return False
    if page_size % 8 != 0:
        return False
    if pltpu is None and not _interpret_mode():
        return False
    C = n_head * head_dim
    return 2 * page_size * C * itemsize <= PAGED_DECODE_BYTES


def _paged_kernel(tables_ref, pos_ref, q_ref, knew_ref, vnew_ref,
                  kp_ref, vp_ref, *rest, n_head, head_dim, page_size,
                  n_pages_per_slot, scale, quantized):
    # quantized pools append two (psz, 1) f32 scale blocks streamed
    # through the same page index map as the K/V blocks — dequant is
    # one broadcast multiply inside the accumulation loop (the
    # "in-kernel dequant" half of quant/kv.py's contract)
    if quantized:
        ksp_ref, vsp_ref, out_ref, acc_ref, m_ref, l_ref = rest
    else:
        out_ref, acc_ref, m_ref, l_ref = rest
    b = pl.program_id(0)
    p = pl.program_id(1)
    D, psz = head_dim, page_size
    pos = pos_ref[b]
    live = (pos + psz - 1) // psz        # pages holding positions < pos

    @pl.when(p == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    @pl.when(p < live)
    def _accumulate():
        kpos = jax.lax.broadcasted_iota(jnp.int32, (psz, 1), 0) + p * psz
        if quantized:
            ksc = ksp_ref[...]                               # (psz, 1)
            vsc = vsp_ref[...]
        for i in range(n_head):
            sl = slice(i * D, (i + 1) * D)
            q = q_ref[:, sl].astype(jnp.float32)                 # (1, D)
            kc = kp_ref[:, sl]                                   # (psz, D)
            vc = vp_ref[:, sl]
            kcf = kc.astype(jnp.float32)
            vcf = vc.astype(jnp.float32)
            if quantized:
                kcf = kcf * ksc
                vcf = vcf * vsc
            s = jnp.sum(kcf * q, axis=-1,
                        keepdims=True) * scale                   # (psz, 1)
            s = jnp.where(kpos < pos, s, NEG_INF)
            m_prev = m_ref[0, i]
            m_new = jnp.maximum(m_prev, jnp.max(s))
            alpha = jnp.exp(m_prev - m_new)
            # masked rows contribute EXACTLY zero (not exp(0)): with a
            # fully-masked page m_new stays NEG_INF and s - m_new == 0
            pexp = jnp.where(s > NEG_INF / 2, jnp.exp(s - m_new), 0.0)
            l_ref[0, i] = l_ref[0, i] * alpha + jnp.sum(pexp)
            acc_ref[:, sl] = (acc_ref[:, sl] * alpha
                              + jnp.sum(pexp * vcf,
                                        axis=0, keepdims=True))
            m_ref[0, i] = m_new

    @pl.when(p == n_pages_per_slot - 1)
    def _finalize():
        for i in range(n_head):
            sl = slice(i * D, (i + 1) * D)
            q = q_ref[:, sl].astype(jnp.float32)
            s_new = jnp.sum(knew_ref[:, sl].astype(jnp.float32)
                            * q) * scale                         # scalar
            m2 = jnp.maximum(m_ref[0, i], s_new)
            alpha = jnp.exp(m_ref[0, i] - m2)
            p_new = jnp.exp(s_new - m2)
            denom = l_ref[0, i] * alpha + p_new   # >= p_new > 0 always
            out = (acc_ref[:, sl] * alpha
                   + p_new * vnew_ref[:, sl].astype(jnp.float32)) / denom
            out_ref[:, sl] = out.astype(out_ref.dtype)


def paged_decode_attention(q: jnp.ndarray, k_new: jnp.ndarray,
                           v_new: jnp.ndarray, k_pages: jnp.ndarray,
                           v_pages: jnp.ndarray, tables: jnp.ndarray,
                           pos: jnp.ndarray, *, n_head: int,
                           k_scales=None, v_scales=None) -> jnp.ndarray:
    """Decode attention for one layer of a paged packed pool.

    q, k_new, v_new: (B, C) fresh merged rows; k_pages/v_pages:
    (n_pages, page, C) STALE pool (position ``pos`` not yet written);
    tables: (B, max_pages) int32; pos: (B,) int32 logical positions.
    Returns the merged (B, C) attention output — bit-equivalent to
    scattering k_new/v_new at ``pos`` and attending positions <= pos.

    ``k_scales``/``v_scales`` ((n_pages, page) f32, page granularity)
    mark a QUANTIZED pool: the scale blocks ride the same page index
    map and dequant inside the accumulation loop, and the caller
    passes ``k_new``/``v_new`` already fake-quantized
    (quant.kv.fake_quantize_rows) so the fresh column attends exactly
    what the post-kernel scatter will store.
    """
    N, psz, C = k_pages.shape
    B, mp = tables.shape
    D = C // n_head
    quantized = k_scales is not None
    kernel = functools.partial(
        _paged_kernel, n_head=n_head, head_dim=D, page_size=psz,
        n_pages_per_slot=mp, scale=D ** -0.5, quantized=quantized)

    def row_map(b, p, tables, pos):
        return (b, 0, 0)

    def page_map(b, p, tables, pos):
        # past the frontier: repeat the previous step's physical page —
        # a repeated block index skips the DMA (the fetch-skip trick)
        return (tables[b, clamped_live_page(p, pos[b], psz)], 0, 0)

    row = _vmem_spec((None, 1, C), row_map)
    kw = {}
    cp = _compiler_params(0, 2)
    if cp is not None:
        kw["compiler_params"] = cp
    if pltpu is not None:
        scratch = [pltpu.VMEM((1, C), jnp.float32),
                   pltpu.VMEM((1, LANES), jnp.float32),
                   pltpu.VMEM((1, LANES), jnp.float32)]
        in_specs = [row, row, row,
                    _vmem_spec((None, psz, C), page_map),
                    _vmem_spec((None, psz, C), page_map)]
        inputs = [q[:, None, :], k_new[:, None, :], v_new[:, None, :],
                  k_pages, v_pages]
        if quantized:
            in_specs += [_vmem_spec((None, psz, 1), page_map),
                         _vmem_spec((None, psz, 1), page_map)]
            inputs += [k_scales.reshape(N, psz, 1),
                       v_scales.reshape(N, psz, 1)]
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(B, mp),
            in_specs=in_specs,
            out_specs=row,
            scratch_shapes=scratch,
        )
        out = pl.pallas_call(
            kernel, grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((B, 1, C), q.dtype),
            interpret=_interpret_mode(), **kw,
        )(jnp.asarray(tables, jnp.int32), jnp.asarray(pos, jnp.int32),
          *inputs)
    else:  # pragma: no cover — pltpu-less installs are gated out by
        # paged_decode_supported; kept so an explicit call still errors
        # with a clear message instead of a pallas internals traceback
        raise RuntimeError("paged_decode_attention needs pallas TPU "
                           "memory spaces (jax.experimental.pallas.tpu)")
    return out[:, 0, :]
