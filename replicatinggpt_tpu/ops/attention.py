"""Attention cores.

The reference materializes a full (T, T) attention matrix per head in a
Python loop over heads (GPT1.py:109-123, 130-136) or calls torch SDPA
(GPT-2.py:46). Here the batched multi-head core is a single einsum pair so
XLA can tile it onto the MXU; a Pallas flash kernel (ops/flash_attention.py)
replaces it on TPU for long sequences, and a ring variant
(parallel/ring_attention.py) shards the sequence axis across chips.

Conventions: q, k, v are (B, H, T, D); softmax runs in float32 regardless of
compute dtype; scaling is by head_dim**-0.5 (the correct scaling — the
reference's GPT1 path scales by n_embd**-0.5, SURVEY.md §8-Q1, reproducible
via the ``scale`` argument if bit-parity with that quirk is ever needed).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e30  # large-negative instead of -inf: keeps softmax NaN-free
                 # for fully-masked rows under bf16


def quantize_dropout_rate(rate: float) -> float:
    """Quantize a dropout rate to 1/256 granularity (clamped to
    [1/256, 255/256]).

    Every dropout site — residual (models.gpt._dropout), einsum
    attention weights (_softmax_dropout), and the Pallas flash kernel's
    in-kernel mask (flash_pallas._dropout_mult) — quantizes through this
    one function, so a config rate means the same effective rate on
    every path the 'auto' router can pick, and the inverted scaling
    below stays exactly unbiased for it.
    """
    return min(max(int(round(rate * 256)), 1), 255) / 256.0


def uint8_inverted_dropout(x: jnp.ndarray, rate: float,
                           rng: jax.Array) -> jnp.ndarray:
    """Inverted dropout from 8-bit random draws: a quarter of the random
    bits of bernoulli() and no float conversion (measured 13.2 -> 9.8 ms
    for 12 (64,256,384) masks on v5e). Drop iff bits < 256*q; kept
    entries scale by 1/(1-q); E[out] == x exactly."""
    q = quantize_dropout_rate(rate)
    bits = jax.random.bits(rng, x.shape, jnp.uint8)
    return jnp.where(bits >= int(q * 256), x / (1.0 - q), 0.0)


def _softmax_dropout(weights: jnp.ndarray, rate: float,
                     rng: Optional[jax.Array], train: bool) -> jnp.ndarray:
    # Dropout on attention weights (GPT1.py:117), at the (B,H,T,T) mask
    # size this path materializes.
    if not train or rate <= 0.0 or rng is None:
        return weights
    return uint8_inverted_dropout(weights, rate, rng)


def full_causal_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, *,
                          scale: Optional[float] = None,
                          dropout_rate: float = 0.0,
                          rng: Optional[jax.Array] = None,
                          train: bool = False,
                          impl: str = "einsum") -> jnp.ndarray:
    """Causal self-attention over a full sequence. q,k,v: (B, H, T, D)."""
    if impl == "flash":
        from .flash_attention import flash_attention, supports_dropout
        training_dropout = train and dropout_rate > 0.0 and rng is not None
        if not training_dropout:
            return flash_attention(q, k, v, scale=scale, causal=True)
        if supports_dropout(q):
            # in-kernel attention-weight dropout (Pallas): the dense path's
            # _softmax_dropout semantics without the (T,T) materialization
            return flash_attention(q, k, v, scale=scale, causal=True,
                                   dropout_rate=dropout_rate,
                                   dropout_rng=rng)
        # non-Pallas backends: fall through to einsum (which can apply
        # dropout on materialized weights) — semantics preserved
    *_, T, D = q.shape
    if scale is None:
        scale = D ** -0.5
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    qpos = jax.lax.broadcasted_iota(jnp.int32, (T, T), 0)
    kpos = jax.lax.broadcasted_iota(jnp.int32, (T, T), 1)
    logits = jnp.where(kpos <= qpos, logits, NEG_INF)
    weights = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    weights = _softmax_dropout(weights, dropout_rate, rng, train)
    return jnp.einsum("bhqk,bhkd->bhqd", weights.astype(v.dtype), v)


def windowed_cached_attention(q: jnp.ndarray, k_cache: jnp.ndarray,
                              v_cache: jnp.ndarray, base_index: jnp.ndarray,
                              *, scale: Optional[float] = None) -> jnp.ndarray:
    """Multi-position decode attention against a KV cache — the verify
    core of speculative decoding (serve/speculative.py).

    q: (B, H, W, D) — W window queries per row, query j sitting at
    absolute position ``base_index[b] + j``; caches: (B, H, S, D);
    base_index: (B,) int32 per-row base positions. Query j attends cache
    positions <= base_index[b] + j — the same write-then-attend masking
    as ``cached_attention`` (W=1 reduces to it exactly), widened so one
    forward scores a whole drafted window per slot. Stale cache entries
    past each query's own position (rejected drafts from an earlier
    speculative step) get NEG_INF before the softmax, so they carry
    exactly zero weight.
    """
    *_, S, D = k_cache.shape
    W = q.shape[2]
    if scale is None:
        scale = D ** -0.5
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k_cache,
                        preferred_element_type=jnp.float32) * scale
    qj = jax.lax.broadcasted_iota(jnp.int32, (W, S), 0)
    kpos = jax.lax.broadcasted_iota(jnp.int32, (W, S), 1)
    limit = jnp.asarray(base_index)[:, None, None, None] + qj  # (B,1,W,S)
    logits = jnp.where(kpos <= limit, logits, NEG_INF)
    weights = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", weights.astype(v_cache.dtype),
                      v_cache)


def cached_attention(q: jnp.ndarray, k_cache: jnp.ndarray,
                     v_cache: jnp.ndarray, cache_index: jnp.ndarray, *,
                     scale: Optional[float] = None) -> jnp.ndarray:
    """Single-position decode attention against a KV cache.

    q: (B, H, 1, D); caches: (B, H, S, D); cache_index: scalar int32 — the
    position just written — or a (B,) vector of per-row positions (the
    continuous-batching engine decodes slots at independent offsets).
    Attends over positions <= cache_index. This is the inner op of the
    lax.scan decode loop that replaces the reference's O(T^2)-per-token
    re-forward generate (GPT1.py:196-212).
    """
    *_, S, D = k_cache.shape
    if scale is None:
        scale = D ** -0.5
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k_cache,
                        preferred_element_type=jnp.float32) * scale
    kpos = jax.lax.broadcasted_iota(jnp.int32, (1, S), 1)
    ci = jnp.asarray(cache_index)
    if ci.ndim == 1:
        ci = ci[:, None, None, None]  # (B,1,1,1) against (B,H,1,S) logits
    logits = jnp.where(kpos <= ci, logits, NEG_INF)
    weights = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", weights.astype(v_cache.dtype), v_cache)
