"""Pallas TPU flash attention: blockwise online-softmax, fwd + custom-VJP bwd.

Replaces the O(T^2)-HBM attention the reference materializes per head
(GPT1.py:114-116) with a fused kernel that keeps only (block_q, block_k)
score tiles in VMEM. Forward follows the standard flash algorithm (running
max m, running normalizer l, rescaled accumulator); backward recomputes
score tiles blockwise from the saved logsumexp, producing dq in a q-major
kernel and dk/dv in a kv-major kernel (no stored attention matrix anywhere).

Layout notes (TPU): q/do tiles are (block, D) with D in {32, 64, 128,
256} and block auto-sized to the largest of {512, 256, 128} dividing T
(``_auto_block`` — 512x512 score tiles measured 2.3x faster fwd+bwd than
128x128 on v5e; callers may override). LSE/delta are per-row scalars,
which Mosaic cannot tile as a bare (T,) lane — they are carried
broadcast across a LANES-wide trailing dim ((BH, T, LANES) arrays,
(block_q, LANES) tiles), the same layout the reference TPU flash kernel
in jax.experimental.pallas.ops.tpu uses for its m/l stats.
Causal masking skips fully-masked kv blocks entirely (the fori_loop upper
bound is derived from the q-block index), so the kernel does ~half the
FLOPs of the dense path on causal workloads.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # TPU memory spaces; absent on pure-CPU installs
    from jax.experimental.pallas import tpu as pltpu
    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

BLOCK = 128
LANES = 128  # trailing width for per-row stats (Mosaic lane alignment)
NEG_INF = -1e30


def _vmem_spec(block_shape, index_map):
    kw = {"memory_space": _VMEM} if _VMEM is not None else {}
    return pl.BlockSpec(block_shape, index_map, **kw)


def _smem_spec():
    kw = {"memory_space": pltpu.SMEM} if pltpu is not None else {}
    return pl.BlockSpec(**kw)


# ---------------------------------------------------------------------------
# in-kernel dropout bits
#
# Counter-based hash instead of pltpu.prng_*: the mask for tile
# (bh, q-block, k-block) must be regenerated bit-identically by three
# different kernels (fwd, bwd-dq, bwd-dkv) whose loop structures differ,
# and must also run under the CPU interpreter (prng_seed has no CPU
# lowering). Two murmur3 fmix32 rounds chained over (seed^bh, qpos, kpos)
# give full avalanche per element at a handful of VPU integer ops — noise
# quality is plenty for dropout, and tests pin the keep-rate statistics.
# ---------------------------------------------------------------------------

def _fmix32(x: jnp.ndarray) -> jnp.ndarray:
    # murmur3 finalizer; uint32 arithmetic wraps mod 2^32
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x85EBCA6B)
    x = x ^ (x >> 13)
    x = x * jnp.uint32(0xC2B2AE35)
    x = x ^ (x >> 16)
    return x


def _dropout_mult(seed, bh, q_first, k_first, block_q, block_k, rate):
    """(block_q, block_k) float32 tile of {0, 1/(1-rate)} — inverted
    dropout on attention weights, deterministic in (seed, bh, q, k)."""
    qpos = (jnp.asarray(q_first).astype(jnp.uint32)
            + jax.lax.broadcasted_iota(jnp.uint32, (block_q, block_k), 0))
    kpos = (jnp.asarray(k_first).astype(jnp.uint32)
            + jax.lax.broadcasted_iota(jnp.uint32, (block_q, block_k), 1))
    h = _fmix32(jnp.asarray(seed).astype(jnp.uint32)
                ^ (jnp.asarray(bh).astype(jnp.uint32)
                   * jnp.uint32(0x9E3779B9)))
    y = _fmix32(_fmix32(h ^ qpos) ^ kpos)
    threshold = jnp.uint32(min(int(rate * 2**32), 2**32 - 1))
    return jnp.where(y > threshold, jnp.float32(1.0 / (1.0 - rate)),
                     jnp.float32(0.0))


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _fwd_kernel(seed_ref, q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale,
                causal, seq_len, block_q, block_k, dropout_rate):
    i = pl.program_id(0)
    j = pl.program_id(1)
    q = q_ref[...].astype(jnp.float32) * scale          # (bq, D)
    D = q.shape[-1]
    q_first = j * block_q

    if causal:
        n_kv = (q_first + block_q + block_k - 1) // block_k
    else:
        n_kv = seq_len // block_k

    def body(kb, carry):
        acc, m, l = carry
        k = k_ref[pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)          # (bq, bk)
        if causal:
            qpos = q_first + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            kpos = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(kpos <= qpos, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        # the softmax normalizer l is dropout-free (dense-path semantics:
        # dropout applies to the normalized weights); only the V
        # accumulation sees the inverted-dropout multiplier
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        if dropout_rate > 0.0:
            p_v = p * _dropout_mult(seed_ref[0], i, q_first, kb * block_k,
                                    block_q, block_k, dropout_rate)
        else:
            p_v = p
        acc_new = acc * alpha + jnp.dot(
            p_v, v, preferred_element_type=jnp.float32)
        return acc_new, m_new, l_new

    acc = jnp.zeros((block_q, D), jnp.float32)
    m0 = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc, m, l = jax.lax.fori_loop(0, n_kv, body, (acc, m0, l0))
    l = jnp.maximum(l, 1e-30)
    o_ref[...] = (acc / l).astype(o_ref.dtype)
    lse_ref[...] = jnp.broadcast_to(m + jnp.log(l), (block_q, LANES))


def _flash_fwd(q, k, v, seed, scale, causal, block_q, block_k,
               dropout_rate):
    B, H, T, D = q.shape
    BH = B * H
    qf = q.reshape(BH, T, D)
    kf = k.reshape(BH, T, D)
    vf = v.reshape(BH, T, D)
    grid = (BH, T // block_q)
    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               seq_len=T, block_q=block_q, block_k=block_k,
                               dropout_rate=dropout_rate)
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            _smem_spec(),
            _vmem_spec((None, block_q, D), lambda i, j: (i, j, 0)),
            _vmem_spec((None, T, D), lambda i, j: (i, 0, 0)),
            _vmem_spec((None, T, D), lambda i, j: (i, 0, 0)),
        ],
        out_specs=[
            _vmem_spec((None, block_q, D), lambda i, j: (i, j, 0)),
            _vmem_spec((None, block_q, LANES), lambda i, j: (i, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, T, D), q.dtype),
            jax.ShapeDtypeStruct((BH, T, LANES), jnp.float32),
        ],
        interpret=_interpret_mode(),
    )(seed, qf, kf, vf)
    return o.reshape(B, H, T, D), lse


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------

def _bwd_dq_kernel(seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                   delta_ref, dq_ref, *, scale, causal, seq_len, block_q,
                   block_k, dropout_rate):
    i = pl.program_id(0)
    j = pl.program_id(1)
    q = q_ref[...].astype(jnp.float32)                   # (bq, D)
    do = do_ref[...].astype(jnp.float32)
    lse = lse_ref[...][:, :1]                            # (bq, 1) of (bq, LANES)
    delta = delta_ref[...][:, :1]
    q_first = j * block_q
    if causal:
        n_kv = (q_first + block_q + block_k - 1) // block_k
    else:
        n_kv = seq_len // block_k

    def body(kb, dq):
        k = k_ref[pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = scale * jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if causal:
            qpos = q_first + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            kpos = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(kpos <= qpos, s, NEG_INF)
        p = jnp.exp(s - lse)                             # (bq, bk)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if dropout_rate > 0.0:
            # d(softmax): ds_ij = p_ij (z_ij dp_ij - delta_i); delta (the
            # do.o rowsum) already absorbs the dropout mask z from forward
            dp = dp * _dropout_mult(seed_ref[0], i, q_first, kb * block_k,
                                    block_q, block_k, dropout_rate)
        ds = p * (dp - delta) * scale
        return dq + jnp.dot(ds, k, preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(0, n_kv,
                           body, jnp.zeros_like(q))
    dq_ref[...] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel(seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                    delta_ref, dk_ref, dv_ref, *, scale, causal, seq_len,
                    block_q, block_k, dropout_rate):
    i = pl.program_id(0)
    kb = pl.program_id(1)
    k = k_ref[...].astype(jnp.float32)                   # (bk, D)
    v = v_ref[...].astype(jnp.float32)
    k_first = kb * block_k
    n_q = seq_len // block_q
    first_q = (k_first // block_q) if causal else 0

    def body(jb, carry):
        dk, dv = carry
        q = q_ref[pl.ds(jb * block_q, block_q), :].astype(jnp.float32)
        do = do_ref[pl.ds(jb * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[pl.ds(jb * block_q, block_q), :][:, :1]
        delta = delta_ref[pl.ds(jb * block_q, block_q), :][:, :1]
        s = scale * jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)          # (bq, bk)
        if causal:
            qpos = jb * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            kpos = k_first + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(kpos <= qpos, s, NEG_INF)
        p = jnp.exp(s - lse)
        if dropout_rate > 0.0:
            # same (seed, bh, qpos, kpos) stream as the forward kernel —
            # tile coords are absolute, so the kv-major loop regenerates
            # the exact fwd mask
            z = _dropout_mult(seed_ref[0], i, jb * block_q, k_first,
                              block_q, block_k, dropout_rate)
        else:
            z = None
        dv = dv + jax.lax.dot_general(
            p * z if z is not None else p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)          # (bk, D)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)          # (bq, bk)
        if z is not None:
            dp = dp * z
        ds = p * (dp - delta) * scale
        dk = dk + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)          # (bk, D)
        return dk, dv

    dk0 = jnp.zeros((block_k, k.shape[-1]), jnp.float32)
    dv0 = jnp.zeros_like(dk0)
    dk, dv = jax.lax.fori_loop(first_q, n_q, body, (dk0, dv0))
    dk_ref[...] = dk.astype(dk_ref.dtype)
    dv_ref[...] = dv.astype(dv_ref.dtype)


def _flash_bwd(scale, causal, block_q, block_k, dropout_rate, residuals, g):
    q, k, v, seed, o, lse = residuals  # lse: (BH, T) — see _flash_fwd_rule
    B, H, T, D = q.shape
    BH = B * H
    delta = jnp.sum(o.astype(jnp.float32) * g.astype(jnp.float32),
                    axis=-1).reshape(BH, T)
    # stats ride a LANES-wide trailing dim (see module docstring) — but
    # only transiently, materialized here just before the kernels; the
    # per-layer residual that lives across the whole backward pass is the
    # compact (BH, T) form (128x less HBM)
    delta = jnp.broadcast_to(delta[:, :, None], (BH, T, LANES))
    lse = jnp.broadcast_to(lse[:, :, None], (BH, T, LANES))
    qf, kf, vf = (t.reshape(BH, T, D) for t in (q, k, v))
    gf = g.reshape(BH, T, D)

    dq_kernel = functools.partial(
        _bwd_dq_kernel, scale=scale, causal=causal, seq_len=T,
        block_q=block_q, block_k=block_k, dropout_rate=dropout_rate)
    dq = pl.pallas_call(
        dq_kernel,
        grid=(BH, T // block_q),
        in_specs=[
            _smem_spec(),
            _vmem_spec((None, block_q, D), lambda i, j: (i, j, 0)),
            _vmem_spec((None, T, D), lambda i, j: (i, 0, 0)),
            _vmem_spec((None, T, D), lambda i, j: (i, 0, 0)),
            _vmem_spec((None, block_q, D), lambda i, j: (i, j, 0)),
            _vmem_spec((None, block_q, LANES), lambda i, j: (i, j, 0)),
            _vmem_spec((None, block_q, LANES), lambda i, j: (i, j, 0)),
        ],
        out_specs=_vmem_spec((None, block_q, D), lambda i, j: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((BH, T, D), q.dtype),
        interpret=_interpret_mode(),
    )(seed, qf, kf, vf, gf, lse, delta)

    dkv_kernel = functools.partial(
        _bwd_dkv_kernel, scale=scale, causal=causal, seq_len=T,
        block_q=block_q, block_k=block_k, dropout_rate=dropout_rate)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(BH, T // block_k),
        in_specs=[
            _smem_spec(),
            _vmem_spec((None, T, D), lambda i, j: (i, 0, 0)),
            _vmem_spec((None, block_k, D), lambda i, j: (i, j, 0)),
            _vmem_spec((None, block_k, D), lambda i, j: (i, j, 0)),
            _vmem_spec((None, T, D), lambda i, j: (i, 0, 0)),
            _vmem_spec((None, T, LANES), lambda i, j: (i, 0, 0)),
            _vmem_spec((None, T, LANES), lambda i, j: (i, 0, 0)),
        ],
        out_specs=[
            _vmem_spec((None, block_k, D), lambda i, j: (i, j, 0)),
            _vmem_spec((None, block_k, D), lambda i, j: (i, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, T, D), q.dtype),
            jax.ShapeDtypeStruct((BH, T, D), q.dtype),
        ],
        interpret=_interpret_mode(),
    )(seed, qf, kf, vf, gf, lse, delta)

    shape = (B, H, T, D)
    return dq.reshape(shape), dk.reshape(shape), dv.reshape(shape), None


# ---------------------------------------------------------------------------
# public entry with custom VJP
# ---------------------------------------------------------------------------

_INTERPRET = False


def _interpret_mode() -> bool:
    return _INTERPRET or jax.default_backend() != "tpu"


def set_interpret(flag: bool) -> None:
    """Force interpreter mode (CPU testing)."""
    global _INTERPRET
    _INTERPRET = flag


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _flash(q, k, v, seed, scale, causal, block_q, block_k, dropout_rate):
    o, _ = _flash_fwd(q, k, v, seed, scale, causal, block_q, block_k,
                      dropout_rate)
    return o


def _flash_fwd_rule(q, k, v, seed, scale, causal, block_q, block_k,
                    dropout_rate):
    o, lse = _flash_fwd(q, k, v, seed, scale, causal, block_q, block_k,
                        dropout_rate)
    # keep the residual compact: the kernel emits lse LANES-broadcast
    # ((BH,T,LANES), a Mosaic tiling requirement), but storing that per
    # layer until the backward pass wastes 128x the HBM — save (BH, T)
    # and rebroadcast in _flash_bwd
    return o, (q, k, v, seed, o, lse[..., 0])


def _flash_bwd_rule(scale, causal, block_q, block_k, dropout_rate,
                    residuals, g):
    return _flash_bwd(scale, causal, block_q, block_k, dropout_rate,
                      residuals, g)


_flash.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def _auto_block(T: int) -> int:
    """Largest tile size in {512, 256, 128} dividing T. 512x512 tiles
    measured 18.2 TF/s fwd+bwd vs 7.9 at 128x128 on v5e (T=1024, D=64) —
    bigger tiles amortize the kv fori_loop and feed the MXU longer
    contractions; past 512 returns flatten (1024 measured 17.5)."""
    for b in (512, 256, 128):
        if T % b == 0:
            return b
    return BLOCK


def pallas_flash_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, *,
                           scale: Optional[float] = None,
                           causal: bool = True,
                           block_q: Optional[int] = None,
                           block_k: Optional[int] = None,
                           dropout_rate: float = 0.0,
                           dropout_rng: Optional[jax.Array] = None
                           ) -> jnp.ndarray:
    """Flash attention. q,k,v: (B, H, T, D); T must be a multiple of the
    block sizes (callers pad or fall back to the einsum path otherwise).

    ``dropout_rate`` > 0 (with ``dropout_rng``) applies inverted dropout to
    the normalized attention weights inside the kernel — the capability the
    dense path gets from _softmax_dropout (GPT1.py:117 semantics) without
    materializing the (T, T) weight matrix. The mask derives from a
    counter-based hash of (rng-derived seed, head, q-pos, k-pos), so the
    backward kernels regenerate it exactly.
    """
    B, H, T, D = q.shape
    if scale is None:
        scale = D ** -0.5
    block_q = min(block_q if block_q is not None else _auto_block(T), T)
    block_k = min(block_k if block_k is not None else _auto_block(T), T)
    assert T % block_q == 0 and T % block_k == 0, (T, block_q, block_k)
    rate = float(dropout_rate)
    if rate > 0.0 and dropout_rng is None:
        raise ValueError("dropout_rate > 0 requires dropout_rng")
    if dropout_rng is not None and rate > 0.0:
        seed = jax.random.randint(dropout_rng, (1,), 0, 2**31 - 1,
                                  dtype=jnp.int32)
    else:
        rate = 0.0
        seed = jnp.zeros((1,), jnp.int32)
    return _flash(q, k, v, seed, float(scale), bool(causal), block_q,
                  block_k, rate)
